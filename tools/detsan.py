#!/usr/bin/env python3
"""Determinism sanitizer driver: run-twice chaos sim + leak bisection.

The dynamic half of the determinism plane (the static half is
``tools/lint.py``'s ``sim-taint`` rule).  One invocation produces the
``DETSAN_rNN.json`` trend artifact by exercising every layer:

1. **clean** — the seeded N-node chaos sim runs twice under a
   :class:`~mysticeti_tpu.detsan.DetsanRecorder`; the per-event digest
   chains must match exactly (``identical: true``).
2. **planted** — the same sim with a deliberately wall-clock-derived
   timer cadence injected via the chaos ``extra_fault`` seam; the
   bisector must report ``identical: false`` and name the first
   diverging event.
3. **fixtures** — the two historical leak shapes (PR 11 ``wal_backlog``
   thread-progress admission signal, PR 12 wall-clock dispatch EMA
   arming a virtual flush timer) are re-checked against the *static*
   ``sim-taint`` rule, proving the lint still catches both.
4. **tripwire** — the strict-mode wall-clock tripwire is self-tested:
   counting mode must attribute a read to its call-site (and tick
   ``mysticeti_detsan_wallclock_reads_total``); strict mode must raise
   :class:`~mysticeti_tpu.detsan.WallClockLeak`.

Usage:
    python tools/detsan.py                         # run, print verdicts
    python tools/detsan.py --out DETSAN_r16.json   # also write the artifact
    python tools/detsan.py --append-trend          # fold into BENCH_TREND.json
    python tools/detsan.py --nodes 10 --duration 3 --seed 42

Exit code 0 when every section passes, 1 otherwise.
"""
from __future__ import annotations

import argparse
import ast
import asyncio
import json
import os
import sys
import tempfile
import textwrap
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from mysticeti_tpu import detsan  # noqa: E402
from mysticeti_tpu.analysis import checker, detflow  # noqa: E402
from mysticeti_tpu.chaos import FaultPlan, run_chaos_sim  # noqa: E402
from mysticeti_tpu.metrics import Metrics  # noqa: E402
from mysticeti_tpu.runtime.simulated import run_simulation  # noqa: E402


# ---------------------------------------------------------------------------
# Historical-leak fixtures (mirrored in tests/test_static_analysis.py): the
# exact dataflow shapes that shipped in PR 11 and PR 12 before being reverted.

PR11_FIXTURE = textwrap.dedent(
    """
    class HealthProbe:
        def __init__(self, core):
            self.core = core

        def sample(self):
            signals = {}
            # real drain-thread progress observed into a sim-visible signal
            signals["wal_backlog"] = bool(self.core.wal_writer.pending())
            return signals


    class AdmissionController:
        def admit(self, signals):
            if signals.get("wal_backlog"):
                return False
            return True
    """
)

PR12_FIXTURE = textwrap.dedent(
    """
    import time


    class BatchedVerifier:
        def __init__(self, loop):
            self.loop = loop
            self._dispatch_ema_s = 0.001

        def _observe_dispatch(self, started):
            wall = time.monotonic() - started
            self._dispatch_ema_s = 0.9 * self._dispatch_ema_s + 0.1 * wall

        def _effective_delay_s(self):
            return min(0.05, self._dispatch_ema_s * 4.0)

        def _arm_flush(self):
            self.loop.call_later(self._effective_delay_s(), self._flush)

        def _flush(self):
            pass
    """
)


def _fixture_detected(source: str) -> bool:
    tree = ast.parse(source)
    aliases = checker._collect_aliases(tree)
    return bool(detflow.check_sim_taint(tree, aliases))


# ---------------------------------------------------------------------------
# The planted leak: host-clock-derived virtual timer cadence, injected
# through the chaos extra_fault seam so the sim itself stays untouched.


async def _planted_leak(harness):
    # The exact bug class detsan exists for: a timer delay derived from the
    # HOST clock inside a virtual-time run.  Two same-seed runs draw
    # different jitter, so their event schedules fork.
    while True:
        jitter = (time.perf_counter_ns() % 997) / 1e5
        await asyncio.sleep(0.05 + jitter)


def _run_recorded(nodes, duration_s, seed, cap, extra_fault=None):
    recorder = detsan.DetsanRecorder(cap)
    with tempfile.TemporaryDirectory(prefix="detsan-wal-") as wal_dir:
        run_chaos_sim(
            FaultPlan(seed=seed),
            nodes,
            duration_s,
            wal_dir,
            extra_fault=extra_fault,
            detsan=recorder,
        )
    return recorder


def _run_twice(nodes, duration_s, seed, cap, extra_fault=None):
    a = _run_recorded(nodes, duration_s, seed, cap, extra_fault)
    b = _run_recorded(nodes, duration_s, seed, cap, extra_fault)
    return detsan.find_divergence(a, b)


# ---------------------------------------------------------------------------
# Tripwire self-test: a synthetic 'package module' reads the wall clock
# under simulation; counting mode must attribute it, strict mode must raise.

_TRIPWIRE_PROBE = textwrap.dedent(
    """
    import time


    def read_clock():
        return time.monotonic()
    """
)


def _tripwire_selftest() -> dict:
    namespace = {"__name__": "mysticeti_tpu._detsan_probe"}
    exec(compile(_TRIPWIRE_PROBE, "<detsan-probe>", "exec"), namespace)
    read_clock = namespace["read_clock"]

    async def main():
        return read_clock()

    metrics = Metrics()
    counting = detsan.Tripwire(metrics=metrics, strict=False)
    with counting:
        run_simulation(main())

    raised = False
    try:
        with detsan.Tripwire(strict=True):
            run_simulation(main())
    except detsan.WallClockLeak:
        raised = True

    return {
        "counted_reads": counting.total_reads,
        "sites": dict(counting.reads),
        "strict_mode_raised": raised,
        "metric": "mysticeti_detsan_wallclock_reads_total",
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--nodes", type=int, default=10)
    parser.add_argument("--duration", type=float, default=3.0,
                        help="virtual seconds per run")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--cap", type=int, default=detsan.DEFAULT_TRACE_CAP,
                        help="max stored trace events per run")
    parser.add_argument("--out", default=None,
                        help="write the DETSAN artifact JSON here")
    parser.add_argument("--append-trend", action="store_true",
                        help="fold the artifact into BENCH_TREND.json")
    args = parser.parse_args(argv)

    print(f"detsan: clean run-twice ({args.nodes} nodes, "
          f"{args.duration}s virtual, seed {args.seed}) ...")
    clean = _run_twice(args.nodes, args.duration, args.seed, args.cap)
    print(f"  identical={clean.identical} events={clean.events_a}")

    print("detsan: planted wall-clock leak run-twice ...")
    planted = _run_twice(
        args.nodes, args.duration, args.seed, args.cap,
        extra_fault=_planted_leak,
    )
    print(f"  identical={planted.identical} "
          f"first_divergence={planted.first_divergence}")

    fixtures = {
        "pr11_wal_backlog": _fixture_detected(PR11_FIXTURE),
        "pr12_dispatch_ema": _fixture_detected(PR12_FIXTURE),
    }
    print(f"detsan: static fixtures detected: {fixtures}")

    tripwire = _tripwire_selftest()
    print(f"detsan: tripwire counted={tripwire['counted_reads']} "
          f"strict_raised={tripwire['strict_mode_raised']}")

    passed = (
        clean.identical
        and not planted.identical
        and planted.first_divergence is not None
        and all(fixtures.values())
        and tripwire["counted_reads"] > 0
        and tripwire["strict_mode_raised"]
    )

    artifact = {
        "metric": "detsan",
        "nodes": args.nodes,
        "duration_s": args.duration,
        "seed": args.seed,
        "trace_cap": args.cap,
        "clean": clean.to_dict(),
        "planted": planted.to_dict(),
        "fixtures": fixtures,
        "tripwire": tripwire,
        "passed": passed,
    }

    if args.out:
        path = (args.out if os.path.isabs(args.out)
                else os.path.join(_REPO_ROOT, args.out))
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        print(f"detsan: artifact -> {os.path.relpath(path, _REPO_ROOT)}")

    if args.append_trend:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_trend

        bench_trend.main(["--repo", _REPO_ROOT])

    print(f"detsan: {'PASS' if passed else 'FAIL'}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
