#!/usr/bin/env python3
"""Max-sustainable-load search over a local fleet, per verifier backend.

Reproducible generator of the MAXLOAD artifacts: runs the orchestrator's
binary search (benchmark.rs:202-271 semantics — double until out-of-capacity,
then bisect; out-of-capacity = avg latency > 5x previous or tps < 2/3
offered) with the chosen --verifier and records every probe.

Weather pinning (VERDICT r5 #8): the same box moves 20k->32k tx/s across
hours, so a lone peak is not evidence.  Every probe embeds the hostmon
weather summary AND wall-clock window, and every non-cpu run is followed
immediately by a fixed-load cpu reference probe at that run's peak — so each
artifact is a self-contained same-window A/B, and round-over-round deltas
never need to reach across windows.

Usage:
  python tools/maxload_bench.py --verifier cpu --out MAXLOAD_r03.json
  python tools/maxload_bench.py --verifiers cpu tpu --out MAXLOAD_TPU_r03.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _probe_dicts(collections) -> list:
    probes = []
    for c in collections:
        probe = {
            "offered_load_tx_s": c.parameters["load"],
            "tps": round(c.aggregate_tps(), 1),
            "avg_latency_s": round(c.aggregate_average_latency_s(), 4),
            "stdev_latency_s": round(c.aggregate_stdev_latency_s(), 4),
        }
        host = c.host_summary()
        if host is not None:
            probe["host"] = host
        health = c.health_summary()
        if health is not None:
            # The probe ships with its own diagnosis (health plane): a peak
            # taken while participation dipped or SLO alerts fired is
            # visible in the artifact itself, not just in hindsight.
            probe["health"] = health
        probes.append(probe)
    return probes


async def run_fixed_probe(verifier: str, nodes: int, load: int,
                          duration: float, workdir: str) -> dict:
    """One fixed-load probe — the same-window reference leg of the A/B."""
    from mysticeti_tpu.orchestrator.benchmark import LoadType, ParametersGenerator
    from mysticeti_tpu.orchestrator.orchestrator import Orchestrator
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    runner = LocalProcessRunner(
        os.path.join(workdir, f"fleet-ref-{verifier}"), verifier=verifier
    )
    generator = ParametersGenerator(
        nodes, LoadType.fixed([load]), duration_s=duration
    )
    orch = Orchestrator(
        runner,
        generator,
        results_dir=os.path.join(workdir, f"results-ref-{verifier}"),
        scrape_interval_s=duration / 3,
    )
    started = time.time()
    collections = await orch.run_benchmarks()
    probes = _probe_dicts(collections)
    probe = probes[0] if probes else {"error": "reference probe recorded nothing"}
    probe["verifier"] = verifier
    probe["window_utc"] = [round(started, 1), round(time.time(), 1)]
    return probe


async def search_one(verifier: str, nodes: int, start_load: int,
                     duration: float, iterations: int, workdir: str) -> dict:
    from mysticeti_tpu.orchestrator.benchmark import LoadType, ParametersGenerator
    from mysticeti_tpu.orchestrator.orchestrator import Orchestrator
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    # The shared verifier service removed the per-node warmup that used to
    # force 240 s tpu probe windows (the runner blocks until the service is
    # warm BEFORE booting nodes; validators are jax-free and seed their
    # routers from HELLO_OK).  Identical delays keep probes comparable.
    os.environ["INITIAL_DELAY"] = "1"
    if verifier.startswith("tpu") and os.environ.get(
        "MYSTICETI_NO_VERIFIER_SERVICE"
    ):
        # Service opted out: every node builds a cold JAX runtime again —
        # the probe window must outlast the old ~2-3 min contended warmup
        # or each probe measures zero tx and the search bisects down.
        os.environ["INITIAL_DELAY"] = "10"
        duration = max(duration, 240.0)
    runner = LocalProcessRunner(
        os.path.join(workdir, f"fleet-{verifier}"), verifier=verifier
    )
    generator = ParametersGenerator(
        nodes,
        LoadType.search(start_load, max_iterations=iterations),
        duration_s=duration,
    )
    orch = Orchestrator(
        runner,
        generator,
        results_dir=os.path.join(workdir, f"results-{verifier}"),
        scrape_interval_s=duration / 3,
    )
    started = time.time()
    collections = await orch.run_benchmarks()
    probes = _probe_dicts(collections)
    peak = max((p["tps"] for p in probes), default=0.0)
    return {
        "verifier": verifier,
        "nodes": nodes,
        "max_sustainable_load_tx_s": generator.max_sustainable_load(),
        "peak_committed_tx_s": round(peak, 1),
        "window_utc": [round(started, 1), round(time.time(), 1)],
        "probes": probes,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--start-load", type=int, default=400)
    parser.add_argument("--duration", type=float, default=30.0)
    parser.add_argument("--iterations", type=int, default=7)
    parser.add_argument("--workdir", default="/tmp/mysticeti-maxload")
    parser.add_argument("--out", default="MAXLOAD.json")
    parser.add_argument(
        "--verifiers", nargs="+", default=["cpu"],
        choices=["accept", "cpu", "tpu", "tpu-only", "cpu-agg", "tpu-agg"],
    )
    args = parser.parse_args()

    if any(v.startswith("tpu") for v in args.verifiers):
        # Compile every kernel flavor a node will touch into the persistent
        # cache once, in THIS process, so the fleet's per-node warmups are
        # cache loads instead of four contending ~40 s compiles.  Keys via
        # mysticeti_tpu.crypto (pure-Python RFC 8032 fallback): hosts
        # without the `cryptography` package still prewarm.
        print("prewarming kernel cache...", flush=True)
        from mysticeti_tpu import crypto
        from mysticeti_tpu.block_validator import TpuSignatureVerifier

        signers = [
            crypto.Signer.from_seed(bytes([i] * 32))
            for i in range(args.nodes)
        ]
        TpuSignatureVerifier(
            committee_keys=[s.public_key.bytes for s in signers]
        ).warmup()

    runs = []
    for verifier in args.verifiers:
        print(f"max-load search verifier={verifier}...", flush=True)
        run = asyncio.run(
            search_one(verifier, args.nodes, args.start_load, args.duration,
                       args.iterations, args.workdir)
        )
        if verifier != "cpu":
            # Same-window reference leg: a cpu probe at THIS run's peak,
            # back-to-back so both legs share the box's current weather.
            ref_load = int(run["peak_committed_tx_s"]) or args.start_load
            print(
                f"  same-window cpu reference probe at {ref_load} tx/s...",
                flush=True,
            )
            run["cpu_reference_probe"] = asyncio.run(
                run_fixed_probe("cpu", args.nodes, ref_load, args.duration,
                                args.workdir)
            )
            ref_tps = run["cpu_reference_probe"].get("tps")
            if ref_tps:
                run["peak_vs_same_window_cpu"] = round(
                    run["peak_committed_tx_s"] / ref_tps, 3
                )
        runs.append(run)
        print(json.dumps(run), flush=True)

    artifact = {
        "metric": "max_sustainable_load_tx_s",
        "host": "single-core CI box (all validators + load generators share one core)",
        "search_rule": (
            "double until out-of-capacity (latency>5x prev or tps<2/3 "
            "offered), then bisect (benchmark.rs:202-271 semantics)"
        ),
        "ab_rule": (
            "every non-cpu run carries a cpu_reference_probe at its peak "
            "load, run back-to-back in the same weather window; "
            "peak_vs_same_window_cpu is the self-contained A/B ratio"
        ),
        "runs": runs,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
