#!/usr/bin/env python3
"""Max-sustainable-load search over a local fleet, per verifier backend.

Reproducible generator of the MAXLOAD artifacts: runs the orchestrator's
binary search (benchmark.rs:202-271 semantics — double until out-of-capacity,
then bisect; out-of-capacity = avg latency > 5x previous or tps < 2/3
offered) with the chosen --verifier and records every probe.

Usage:
  python tools/maxload_bench.py --verifier cpu --out MAXLOAD_r03.json
  python tools/maxload_bench.py --verifiers cpu tpu --out MAXLOAD_TPU_r03.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def search_one(verifier: str, nodes: int, start_load: int,
                     duration: float, iterations: int, workdir: str) -> dict:
    from mysticeti_tpu.orchestrator.benchmark import LoadType, ParametersGenerator
    from mysticeti_tpu.orchestrator.orchestrator import Orchestrator
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    # The shared verifier service removed the per-node warmup that used to
    # force 240 s tpu probe windows (the runner blocks until the service is
    # warm BEFORE booting nodes; validators are jax-free and seed their
    # routers from HELLO_OK).  Identical delays keep probes comparable.
    os.environ["INITIAL_DELAY"] = "1"
    if verifier.startswith("tpu") and os.environ.get(
        "MYSTICETI_NO_VERIFIER_SERVICE"
    ):
        # Service opted out: every node builds a cold JAX runtime again —
        # the probe window must outlast the old ~2-3 min contended warmup
        # or each probe measures zero tx and the search bisects down.
        os.environ["INITIAL_DELAY"] = "10"
        duration = max(duration, 240.0)
    runner = LocalProcessRunner(
        os.path.join(workdir, f"fleet-{verifier}"), verifier=verifier
    )
    generator = ParametersGenerator(
        nodes,
        LoadType.search(start_load, max_iterations=iterations),
        duration_s=duration,
    )
    orch = Orchestrator(
        runner,
        generator,
        results_dir=os.path.join(workdir, f"results-{verifier}"),
        scrape_interval_s=duration / 3,
    )
    collections = await orch.run_benchmarks()
    probes = []
    peak = 0.0
    for c in collections:
        tps = c.aggregate_tps()
        peak = max(peak, tps)
        probe = {
            "offered_load_tx_s": c.parameters["load"],
            "tps": round(tps, 1),
            "avg_latency_s": round(c.aggregate_average_latency_s(), 4),
            "stdev_latency_s": round(c.aggregate_stdev_latency_s(), 4),
        }
        host = c.host_summary()
        if host is not None:
            probe["host"] = host
        probes.append(probe)
    return {
        "verifier": verifier,
        "nodes": nodes,
        "max_sustainable_load_tx_s": generator.max_sustainable_load(),
        "peak_committed_tx_s": round(peak, 1),
        "probes": probes,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--start-load", type=int, default=400)
    parser.add_argument("--duration", type=float, default=30.0)
    parser.add_argument("--iterations", type=int, default=7)
    parser.add_argument("--workdir", default="/tmp/mysticeti-maxload")
    parser.add_argument("--out", default="MAXLOAD.json")
    parser.add_argument(
        "--verifiers", nargs="+", default=["cpu"],
        choices=["accept", "cpu", "tpu", "tpu-only", "cpu-agg", "tpu-agg"],
    )
    args = parser.parse_args()

    if any(v.startswith("tpu") for v in args.verifiers):
        # Compile every kernel flavor a node will touch into the persistent
        # cache once, in THIS process, so the fleet's per-node warmups are
        # cache loads instead of four contending ~40 s compiles.
        print("prewarming kernel cache...", flush=True)
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        from mysticeti_tpu.block_validator import TpuSignatureVerifier

        keys = [
            Ed25519PrivateKey.from_private_bytes(bytes([i] * 32))
            for i in range(args.nodes)
        ]
        TpuSignatureVerifier(
            committee_keys=[k.public_key().public_bytes_raw() for k in keys]
        ).warmup()

    runs = []
    for verifier in args.verifiers:
        print(f"max-load search verifier={verifier}...", flush=True)
        run = asyncio.run(
            search_one(verifier, args.nodes, args.start_load, args.duration,
                       args.iterations, args.workdir)
        )
        runs.append(run)
        print(json.dumps(run), flush=True)

    artifact = {
        "metric": "max_sustainable_load_tx_s",
        "host": "single-core CI box (all validators + load generators share one core)",
        "search_rule": (
            "double until out-of-capacity (latency>5x prev or tps<2/3 "
            "offered), then bisect (benchmark.rs:202-271 semantics)"
        ),
        "runs": runs,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
