#!/usr/bin/env python3
"""OVERLOAD artifact generator: graceful degradation under 2-5x overload.

Rides the maxload harness (LocalProcessRunner fleet + /metrics scrapes) to
measure committed-vs-offered throughput at 1x/2x/3x/5x the 1x-saturation
load, with per-rung shed accounting (`mysticeti_ingress_shed_total`) and a
fleet health diagnosis; then runs the seeded deterministic overload sim
twice and records the byte-identical shed-schedule digests.  Appended to
BENCH_TREND.json under the OVERLOAD family (tools/bench_trend.py).

The acceptance gate (ROADMAP item 3, ISSUE 11): committed tx/s at 3x and
5x offered >= 80% of the 1x-saturation peak — the pre-ingress fleet
COLLAPSED past saturation (MAXLOAD r4: 40.3k committed at 57.6k offered).

Usage:
  python tools/overload_bench.py --out OVERLOAD_r11.json
  python tools/overload_bench.py --base-load 3000 --duration 30
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MULTIPLIERS = (1, 2, 3, 5)

INGRESS_SERIES = (
    "mysticeti_ingress_shed_total",
    "mysticeti_ingress_admitted_total",
    "mysticeti_ingress_admitted_rate",
    "mysticeti_ingress_mempool_transactions",
    "mysticeti_ingress_shed_mode",
)


def _parse_ingress(texts) -> dict:
    """Sum the ingress counters across the fleet's raw /metrics scrapes."""
    from mysticeti_tpu.orchestrator.measurement import iter_series

    shed: dict = {}
    admitted = 0.0
    shed_mode_nodes = 0
    for text in texts:
        if text is None:
            continue
        for name, labels, value in iter_series(text):
            if name == "mysticeti_ingress_shed_total":
                reason = labels.get("reason", "?")
                shed[reason] = shed.get(reason, 0.0) + value
            elif name == "mysticeti_ingress_admitted_total":
                admitted += value
            elif name == "mysticeti_ingress_shed_mode" and value >= 1.0:
                shed_mode_nodes += 1
    return {
        "admitted_total": int(admitted),
        "shed_total": {k: int(v) for k, v in sorted(shed.items())},
        "shed_mode_nodes": shed_mode_nodes,
    }


_FINALITY_GAUGES = {
    "mysticeti_e2e_finality_p50_seconds": "server_p50_s",
    "mysticeti_e2e_finality_p99_seconds": "server_p99_s",
    "mysticeti_client_finality_p50_seconds": "client_p50_s",
    "mysticeti_client_finality_p99_seconds": "client_p99_s",
}


def _parse_finality(texts) -> dict:
    """Worst-node finality percentiles (server e2e + client-observed) from
    the fleet's raw /metrics scrapes — the per-rung finality columns."""
    from mysticeti_tpu.orchestrator.measurement import iter_series

    out = {key: 0.0 for key in _FINALITY_GAUGES.values()}
    for text in texts:
        if text is None:
            continue
        for name, _labels, value in iter_series(text):
            key = _FINALITY_GAUGES.get(name)
            if key is not None:
                out[key] = max(out[key], value)
    return {k: round(v, 4) for k, v in out.items()}


async def run_rung(nodes: int, load: int, duration: float, workdir: str,
                   label: str) -> dict:
    """One fixed-offered-load fleet run; returns the rung record."""
    from mysticeti_tpu.health import cluster_snapshot_from_texts
    from mysticeti_tpu.orchestrator.measurement import Measurement
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    os.environ["INITIAL_DELAY"] = "1"
    runner = LocalProcessRunner(
        os.path.join(workdir, f"fleet-{label}"), verifier="cpu"
    )
    started = time.time()
    await runner.configure(nodes, load)
    try:
        for authority in range(nodes):
            await runner.boot_node(authority)
        await asyncio.sleep(duration)
        texts = [await runner.scrape(a) for a in range(nodes)]
    finally:
        await runner.cleanup()
    # Every validator observes every committed shared tx, so per-node
    # counts are N views of ONE total: aggregate as max(count) over the
    # common duration (measurement.rs:236-250 semantics), never a sum.
    measurements = [
        Measurement.from_prometheus(text) for text in texts if text is not None
    ]
    duration_s = max(
        (m.benchmark_duration_s for m in measurements), default=0.0
    )
    tps = (
        max((m.count for m in measurements), default=0) / duration_s
        if duration_s
        else 0.0
    )
    latencies = [m.avg_latency_s() for m in measurements if m.count]
    rung = {
        "offered_tx_s": load,
        "committed_tx_s": round(tps, 1),
        "committed_over_offered": round(tps / load, 3) if load else None,
        "avg_latency_s": (
            round(sum(latencies) / len(latencies), 4) if latencies else None
        ),
        "scraped_nodes": sum(1 for t in texts if t is not None),
        "window_utc": [round(started, 1), round(time.time(), 1)],
    }
    rung.update(_parse_ingress(texts))
    rung["finality"] = _parse_finality(texts)
    health = cluster_snapshot_from_texts(
        {f"node-{a}": texts[a] for a in range(nodes)}, nodes
    )
    rung["health"] = {
        "status": health["status"],
        "quorum_participation": health["quorum_participation"],
        "degraded_reasons": health["degraded_reasons"],
    }
    return rung


def run_determinism_leg() -> dict:
    """The seeded sim twice at 3x (byte-identical shed schedule) + a 1x
    reference — the virtual-time twin of the fleet rungs."""
    from mysticeti_tpu.ingress import OverloadScenario, run_overload_sim

    def scenario(mult):
        return OverloadScenario(
            seed=11, nodes=6, duration_s=8.0, base_tps=300,
            max_per_proposal=30, mempool_max_transactions=600,
            multiplier_schedule=[(0.0, float(mult))], clients_per_node=3,
            duplicate_flood=True,
        )

    r1 = run_overload_sim(scenario(1))
    r3a = run_overload_sim(scenario(3))
    r3b = run_overload_sim(scenario(3))
    return {
        "scenario": scenario(3).to_dict(),
        "sim_committed_1x": r1.committed_tx,
        "sim_committed_3x": r3a.committed_tx,
        "sim_committed_3x_over_1x": round(
            r3a.committed_tx / r1.committed_tx, 3
        ),
        "sim_shed_by_reason_3x": r3a.shed_by_reason,
        "sim_offered_3x": r3a.offered_tx,
        "sim_admitted_3x": r3a.admitted_tx,
        "sim_fully_accounted": (
            sum(r3a.shed_by_reason.values()) + r3a.admitted_tx
            == r3a.offered_tx
        ),
        "shed_schedule_digest_run1": r3a.shed_schedule_digest,
        "shed_schedule_digest_run2": r3b.shed_schedule_digest,
        "byte_identical": r3a.shed_log_bytes == r3b.shed_log_bytes,
        # Client-perceived finality under 3x overload (finality.py): the
        # sim's closed-loop generators vs the server-side trackers.
        "sim_server_finality_3x": r3a.server_finality,
        "sim_client_finality_3x": r3a.client_finality,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--base-load", type=int, default=0,
                        help="the 1x offered load (tx/s); 0 = calibrate by "
                        "running one saturating rung and taking its "
                        "committed rate as the saturation point")
    parser.add_argument("--calibrate-load", type=int, default=120000,
                        help="offered load of the calibration rung (should "
                        "comfortably exceed the box's saturation point so "
                        "committed = saturation)")
    parser.add_argument("--duration", type=float, default=30.0)
    parser.add_argument("--multipliers", type=int, nargs="+",
                        default=list(MULTIPLIERS))
    parser.add_argument("--workdir", default="/tmp/mysticeti-overload")
    parser.add_argument("--out", default="OVERLOAD.json")
    parser.add_argument("--skip-fleet", action="store_true",
                        help="determinism/sim leg only (no process fleet)")
    args = parser.parse_args()

    rungs = []
    base = args.base_load
    calibration = None
    if not args.skip_fleet:
        if base <= 0:
            print(
                f"calibrating: saturating rung at {args.calibrate_load} tx/s"
                " offered...", flush=True,
            )
            calibration = asyncio.run(run_rung(
                args.nodes, args.calibrate_load, args.duration, args.workdir,
                "calibrate",
            ))
            print(json.dumps(calibration), flush=True)
            base = max(200, int(calibration["committed_tx_s"]))
        print(f"1x saturation load: {base} tx/s", flush=True)
        for mult in args.multipliers:
            load = base * mult
            print(f"rung {mult}x: offered {load} tx/s...", flush=True)
            rung = asyncio.run(run_rung(
                args.nodes, load, args.duration, args.workdir, f"{mult}x"
            ))
            rung["multiplier"] = mult
            rungs.append(rung)
            print(json.dumps(rung), flush=True)

    print("determinism leg: seeded overload sim x2...", flush=True)
    determinism = run_determinism_leg()
    print(json.dumps(determinism), flush=True)

    acceptance = {}
    if rungs:
        by_mult = {r["multiplier"]: r for r in rungs}
        peak_1x = by_mult.get(1, {}).get("committed_tx_s") or 0.0
        for mult in (3, 5):
            rung = by_mult.get(mult)
            if rung and peak_1x:
                acceptance[f"committed_{mult}x_over_1x"] = round(
                    rung["committed_tx_s"] / peak_1x, 3
                )
        acceptance["no_collapse"] = all(
            v >= 0.8 for k, v in acceptance.items() if k.endswith("_over_1x")
        )
    acceptance["sim_no_collapse"] = (
        determinism["sim_committed_3x_over_1x"] >= 0.8
    )
    acceptance["shed_schedule_byte_identical"] = determinism["byte_identical"]
    acceptance["sim_fully_accounted"] = determinism["sim_fully_accounted"]

    artifact = {
        "metric": "overload_committed_vs_offered",
        "nodes": args.nodes,
        "verifier": "cpu",
        "host": "single-core CI box (all validators + load generators share "
                "one core)",
        "base_load_tx_s": base,
        "calibration": calibration,
        "rule": (
            "rungs at 1x/2x/3x/5x the 1x-saturation load; acceptance: "
            "committed tx/s at 3x and 5x >= 80% of the 1x peak (no "
            "collapse), every rejection on mysticeti_ingress_shed_total, "
            "seeded sim shed schedule byte-identical across runs"
        ),
        "rungs": rungs,
        "determinism": determinism,
        "acceptance": acceptance,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    ok = acceptance.get("no_collapse", True) and acceptance[
        "sim_no_collapse"
    ] and acceptance["shed_schedule_byte_identical"]
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
