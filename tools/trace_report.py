#!/usr/bin/env python3
"""Per-stage latency breakdown from a MYSTICETI_TRACE Chrome trace file.

Usage:
    python tools/trace_report.py trace.json [--by-track]

Reads the trace-event JSON written by ``mysticeti_tpu.spans`` (set
``MYSTICETI_TRACE=/path/out.json`` on a node or testbed run, or load the
same file in Perfetto for the visual timeline) and prints count / p50 / p90 /
p99 / max duration per pipeline stage — the "which stage ate the commit
latency" table.  ``--by-track`` splits the breakdown per authority track.
"""
import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.spans import STAGES  # noqa: E402


def load_events(path: str) -> List[dict]:
    """All events from a Chrome trace-event JSON file (parsed once — a
    MAX_EVENTS-capped production trace is hundreds of MB)."""
    with open(path) as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def load_spans(events: List[dict]) -> List[dict]:
    """Complete ("X") span events."""
    return [e for e in events if e.get("ph") == "X"]


def _track_names(events: List[dict]) -> Dict[Tuple[int, int], str]:
    return {
        (e.get("pid", 0), e.get("tid", 0)): e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }


def _pct(ordered: List[float], pct: float) -> float:
    idx = min(len(ordered) - 1, int(len(ordered) * pct / 100))
    return ordered[idx]


def _stage_order(name: str) -> Tuple[int, str]:
    try:
        return (STAGES.index(name), name)
    except ValueError:
        return (len(STAGES), name)


def build_report(spans: List[dict], by_track: bool = False,
                 track_names: Dict[Tuple[int, int], str] = {}) -> str:
    groups: Dict[Tuple, List[float]] = defaultdict(list)
    for e in spans:
        key = (e["name"],)
        if by_track:
            pid_tid = (e.get("pid", 0), e.get("tid", 0))
            key = (e["name"], track_names.get(pid_tid, str(pid_tid[1])))
        groups[key].append(e.get("dur", 0) / 1e3)  # µs -> ms
    if not groups:
        return "no spans in trace"
    header = f"{'stage':<16}" + (f"{'track':<10}" if by_track else "") + (
        f"{'count':>8}{'p50_ms':>10}{'p90_ms':>10}{'p99_ms':>10}{'max_ms':>10}"
    )
    lines = [header]
    for key in sorted(groups, key=lambda k: (_stage_order(k[0]),) + k[1:]):
        durs = sorted(groups[key])
        row = f"{key[0]:<16}"
        if by_track:
            row += f"{key[1]:<10}"
        row += (
            f"{len(durs):>8}"
            f"{_pct(durs, 50):>10.3f}"
            f"{_pct(durs, 90):>10.3f}"
            f"{_pct(durs, 99):>10.3f}"
            f"{durs[-1]:>10.3f}"
        )
        lines.append(row)
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("trace", help="Chrome trace-event JSON (MYSTICETI_TRACE output)")
    parser.add_argument(
        "--by-track", action="store_true",
        help="split the breakdown per authority track",
    )
    args = parser.parse_args(argv)
    try:
        events = load_events(args.trace)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: cannot read trace {args.trace}: {exc}", file=sys.stderr)
        return 2
    names = _track_names(events) if args.by_track else {}
    print(build_report(load_spans(events), by_track=args.by_track,
                       track_names=names))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
