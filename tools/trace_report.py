#!/usr/bin/env python3
"""Per-stage latency breakdown from a MYSTICETI_TRACE Chrome trace file.

Usage:
    python tools/trace_report.py trace.json [--by-track] [--critical-path]

Reads the trace-event JSON written by ``mysticeti_tpu.spans`` (set
``MYSTICETI_TRACE=/path/out.json`` on a node or testbed run, or load the
same file in Perfetto for the visual timeline) and prints count / p50 / p90 /
p99 / max duration per pipeline stage — the "which stage ate the commit
latency" table.  ``--by-track`` splits the breakdown per authority track.

``--critical-path`` prints commit critical-path attribution instead: per
committed leader (a block with a ``commit`` span), which pipeline stage
dominated its receive -> verify -> dag_add -> proposal_wait -> commit ->
finalize chain, attributed to the leader's authoring authority — the top
blocking (stage, authority) pairs are the fleet's slow edges.

A truncated trace (node SIGKILLed mid-flush, or a live ``.tmp``) is
salvaged: complete events before the tear are recovered with a note
instead of a traceback, and empty stages / span-free traces report
themselves and exit 0.
"""
import argparse
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.spans import (  # noqa: E402
    PIPELINE_STAGES,
    STAGES,
    complete_spans,
    load_trace_events,
    stage_chains,
    track_names,
)


def load_events(path: str) -> Tuple[List[dict], str]:
    """All events from a Chrome trace-event JSON file; salvage + extraction
    live in ``mysticeti_tpu.spans`` now, SHARED with tools/fleet_trace.py —
    the two offline consumers must never disagree about where a truncated
    trace's stage boundaries are."""
    events, note, _other = load_trace_events(path)
    return events, note


def load_spans(events: List[dict]) -> List[dict]:
    """Complete ("X") span events."""
    return complete_spans(events)


def _track_names(events: List[dict]) -> Dict[Tuple[int, int], str]:
    return track_names(events)


def _pct(ordered: List[float], pct: float) -> float:
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(len(ordered) * pct / 100))
    return ordered[idx]


def _stage_order(name: str) -> Tuple[int, str]:
    try:
        return (STAGES.index(name), name)
    except ValueError:
        return (len(STAGES), name)


def build_report(spans: List[dict], by_track: bool = False,
                 track_names: Dict[Tuple[int, int], str] = {}) -> str:
    groups: Dict[Tuple, List[float]] = defaultdict(list)
    for e in spans:
        key = (e["name"],)
        if by_track:
            pid_tid = (e.get("pid", 0), e.get("tid", 0))
            key = (e["name"], track_names.get(pid_tid, str(pid_tid[1])))
        groups[key].append(e.get("dur", 0) / 1e3)  # µs -> ms
    if not groups:
        return "no spans in trace"
    header = f"{'stage':<16}" + (f"{'track':<10}" if by_track else "") + (
        f"{'count':>8}{'p50_ms':>10}{'p90_ms':>10}{'p99_ms':>10}{'max_ms':>10}"
    )
    lines = [header]
    for key in sorted(groups, key=lambda k: (_stage_order(k[0]),) + k[1:]):
        durs = sorted(groups[key])
        row = f"{key[0]:<16}"
        if by_track:
            row += f"{key[1]:<10}"
        row += (
            f"{len(durs):>8}"
            f"{_pct(durs, 50):>10.3f}"
            f"{_pct(durs, 90):>10.3f}"
            f"{_pct(durs, 99):>10.3f}"
            f"{(durs[-1] if durs else 0.0):>10.3f}"
        )
        lines.append(row)
    return "\n".join(lines)


# -- commit critical-path attribution ----------------------------------------

_REF_RE = re.compile(r"^A(\d+)R(\d+)#")


def attribute_critical_paths(spans: List[dict]) -> List[dict]:
    """Offline twin of ``health.CriticalPathAnalyzer``: per committed leader
    (block label with a ``commit`` span) and observing track, the pipeline
    stage with the largest duration is THE critical-path edge, attributed to
    the leader's authoring authority.  Returns one record per (leader,
    track).  Stage extraction is the SHARED ``spans.stage_chains`` helper
    (also under tools/fleet_trace.py), so a trace tail truncated mid-flush
    lands on the same stage boundaries in both tools."""
    chains = stage_chains(spans, stages=PIPELINE_STAGES)
    out: List[dict] = []
    for (track, label), chain in chains.items():
        stages = {name: dur / 1e6 for name, (_ts, dur) in chain.items()}
        if "commit" not in stages:
            continue  # never committed (or commit fell past the trace cap)
        match = _REF_RE.match(label)
        blocking = max(stages, key=lambda s: (stages[s], s))
        out.append(
            {
                "leader": label,
                "authority": int(match.group(1)) if match else None,
                "round": int(match.group(2)) if match else None,
                "track": track,
                "blocking_stage": blocking,
                "blocking_s": stages[blocking],
                "stages": stages,
            }
        )
    return out


def build_critical_path_report(spans: List[dict]) -> str:
    attributed = attribute_critical_paths(spans)
    if not attributed:
        return (
            "no committed leaders in trace (nothing reached a `commit` "
            "span); critical-path attribution needs a run that commits"
        )
    # Top blocking (stage, authority) pairs by total blocked seconds.
    pairs: Dict[Tuple[str, int], List[float]] = defaultdict(list)
    per_stage: Dict[str, List[float]] = defaultdict(list)
    for rec in attributed:
        if rec["authority"] is not None:
            pairs[(rec["blocking_stage"], rec["authority"])].append(
                rec["blocking_s"]
            )
        for stage, dur in rec["stages"].items():
            per_stage[stage].append(dur * 1e3)
    lines = [
        f"critical-path attribution over {len(attributed)} committed "
        "leader observation(s)",
        "",
        f"{'stage':<16}{'authority':>10}{'leaders':>9}{'blocked_s':>11}"
        f"{'mean_ms':>10}",
    ]
    ranked = sorted(
        pairs.items(), key=lambda kv: (-sum(kv[1]), kv[0])
    )
    for (stage, authority), durs in ranked[:10]:
        lines.append(
            f"{stage:<16}{authority:>10}{len(durs):>9}"
            f"{sum(durs):>11.3f}{sum(durs) / len(durs) * 1e3:>10.3f}"
        )
    lines.append("")
    lines.append(
        f"{'stage (on path)':<16}{'count':>8}{'p50_ms':>10}{'p90_ms':>10}"
        f"{'max_ms':>10}"
    )
    for stage in sorted(per_stage, key=lambda s: _stage_order(s)):
        durs = sorted(per_stage[stage])
        lines.append(
            f"{stage:<16}{len(durs):>8}{_pct(durs, 50):>10.3f}"
            f"{_pct(durs, 90):>10.3f}{durs[-1]:>10.3f}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("trace", help="Chrome trace-event JSON (MYSTICETI_TRACE output)")
    parser.add_argument(
        "--by-track", action="store_true",
        help="split the breakdown per authority track",
    )
    parser.add_argument(
        "--critical-path", action="store_true",
        help="commit critical-path attribution: top blocking "
        "(stage, authority) pairs per committed leader",
    )
    args = parser.parse_args(argv)
    try:
        events, note = load_events(args.trace)
    except OSError as exc:
        print(f"error: cannot read trace {args.trace}: {exc}", file=sys.stderr)
        return 2
    if note:
        print(note, file=sys.stderr)
    spans = load_spans(events)
    if args.critical_path:
        print(build_critical_path_report(spans))
        return 0
    names = _track_names(events) if args.by_track else {}
    print(build_report(spans, by_track=args.by_track, track_names=names))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
