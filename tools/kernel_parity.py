#!/usr/bin/env python3
"""On-device kernel correctness artifact (KERNEL_PARITY).

Runs the DEPLOYED verification paths on the real attached accelerator (the
Pallas backend auto-selects on TPU — ops/ed25519.py `_backend`) and checks
them against RFC 8032 vectors and the OpenSSL oracle over >= 10k randomized
sign/verify/corrupt cases.  This is the evidence the bench numbers alone
cannot give: a wrong-but-fast lane would still post high throughput; here
every accept/reject bit is compared.

Covered paths:
  * verify_batch            — raw-bytes fused path (unknown signer set)
  * verify_batch_table      — committee-indexed path (keyed-tile kernel via
                              grouped dispatch, the fleet/bench hot path)
Case classes: valid, corrupted R, corrupted s, corrupted message, wrong key,
non-canonical s (s+L), corrupted pk (table path: unknown-key fallback).

Usage: python tools/kernel_parity.py --n 12288 --out KERNEL_PARITY_r04.json
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RFC8032_VECTORS = [
    (
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249015"
        "55fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]

L = (1 << 252) + 27742317777372353535851937790883648493


def oracle_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    try:
        Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
        return True
    except (InvalidSignature, ValueError):
        return False


def build_cases(n: int, seed: int):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    rng = random.Random(seed)
    n_keys = 16
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(n_keys)
    ]
    raw_pks = [k.public_key().public_bytes_raw() for k in keys]
    classes = [
        "valid", "valid", "valid", "valid",
        "corrupt_R", "corrupt_s", "corrupt_msg", "wrong_key",
        "noncanonical_s", "corrupt_pk",
    ]
    pks, msgs, sigs, labels = [], [], [], []
    for i in range(n):
        ki = rng.randrange(n_keys)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = keys[ki].sign(msg)
        pk = raw_pks[ki]
        cls = classes[rng.randrange(len(classes))]
        if cls == "corrupt_R":
            pos = rng.randrange(32)
            sig = sig[:pos] + bytes([sig[pos] ^ (1 << rng.randrange(8))]) + sig[pos + 1:]
        elif cls == "corrupt_s":
            pos = 32 + rng.randrange(32)
            sig = sig[:pos] + bytes([sig[pos] ^ (1 << rng.randrange(8))]) + sig[pos + 1:]
        elif cls == "corrupt_msg":
            pos = rng.randrange(32)
            msg = msg[:pos] + bytes([msg[pos] ^ 1]) + msg[pos + 1:]
        elif cls == "wrong_key":
            pk = raw_pks[(ki + 1) % n_keys]
        elif cls == "noncanonical_s":
            s = int.from_bytes(sig[32:], "little") + L
            if s < (1 << 256):
                sig = sig[:32] + s.to_bytes(32, "little")
            else:  # unrepresentable: fall back to a plain valid case
                cls = "valid"
        elif cls == "corrupt_pk":
            pos = rng.randrange(32)
            pk = pk[:pos] + bytes([pk[pos] ^ 1]) + pk[pos + 1:]
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
        labels.append(cls)
    return raw_pks, pks, msgs, sigs, labels


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=12288)
    parser.add_argument("--seed", type=int, default=2026)
    parser.add_argument("--out", default="KERNEL_PARITY.json")
    args = parser.parse_args()

    import numpy as np

    import jax

    from mysticeti_tpu.ops import ed25519 as E

    device = jax.devices()[0]
    out = {
        "metric": "kernel_parity_on_device",
        "device": f"{device.platform}:{device.device_kind}",
        "backend": E._backend(),
        "seed": args.seed,
        "n_randomized": args.n,
    }

    # RFC 8032 vectors (variable-length messages -> host-hash packing, the
    # same device ladder) + corrupted variants.
    pks = [bytes.fromhex(pk) for pk, _, _ in RFC8032_VECTORS]
    msgs = [bytes.fromhex(m) for _, m, _ in RFC8032_VECTORS]
    sigs = [bytes.fromhex(s) for _, _, s in RFC8032_VECTORS]
    rfc_ok = bool(E.verify_batch(pks, msgs, sigs).all())
    bad_sigs = [bytearray(s) for s in sigs]
    bad_sigs[0][3] ^= 0x40
    bad_sigs[1][40] ^= 0x01
    bad_msgs = list(msgs)
    bad_msgs[2] = msgs[2] + b"x"
    rfc_rej = not E.verify_batch(
        pks, bad_msgs, [bytes(s) for s in bad_sigs]
    ).any()
    out["rfc8032"] = {"accept_all_valid": rfc_ok, "reject_all_corrupt": rfc_rej}

    committee_keys, pks, msgs, sigs, labels = build_cases(args.n, args.seed)
    expected = np.array(
        [oracle_verify(pk, m, s) for pk, m, s in zip(pks, msgs, sigs)]
    )

    table = E.KeyTable(committee_keys)
    results = {}
    for name, got in (
        ("verify_batch_raw", np.asarray(E.verify_batch(pks, msgs, sigs))),
        (
            "verify_batch_table_keyed",
            np.asarray(E.verify_batch_table(table, pks, msgs, sigs)),
        ),
    ):
        mism = np.nonzero(got != expected)[0]
        per_class = {}
        for lbl in set(labels):
            sel = [i for i, l in enumerate(labels) if l == lbl]
            per_class[lbl] = {
                "cases": len(sel),
                "mismatches": int(sum(got[i] != expected[i] for i in sel)),
            }
        results[name] = {
            "cases": args.n,
            "mismatches": int(mism.size),
            "first_mismatches": mism[:5].tolist(),
            "per_class": per_class,
        }
    out["randomized"] = results
    out["pass"] = (
        rfc_ok
        and rfc_rej
        and all(r["mismatches"] == 0 for r in results.values())
    )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({k: out[k] for k in ("device", "backend", "pass")}))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
