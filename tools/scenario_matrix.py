#!/usr/bin/env python3
"""Resilience scenario-matrix probe -> SCENARIO_r12.json.

Runs the declarative Byzantine scenario matrix (mysticeti_tpu/scenarios.py)
— every entry an attacked seeded sim plus a same-seed clean twin — and pins
the per-scenario verdicts into the ``SCENARIO_rNN.json`` artifact family
consumed by ``tools/bench_trend.py``:

* **safety** — zero honest-node SafetyChecker violations per scenario;
* **liveness** — honest-authored committed throughput >= the scenario's
  ``min_ratio`` x the clean twin;
* **detection** — every injected attack detected on its counter surface
  (equivocation / invalid-signature / malformed) or accounted in the
  attack ledger (the silence-shaped behaviors);
* **reproducibility** — schedule / attack / detection / sequence digests
  recorded per scenario, so a same-seed re-run is byte-checkable.

A ``--determinism`` pass re-runs the first scenario on the same seed and
asserts the digests match — the artifact then carries the proof, not just
the claim.

Usage::

    python tools/scenario_matrix.py [--out SCENARIO_r12.json] [--quick]
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.scenarios import (  # noqa: E402
    default_matrix,
    run_matrix,
    run_scenario,
    scenario_by_name,
)


def determinism_leg(name: str, quick: bool) -> dict:
    """Same scenario, same seed, twice: the digests must be identical."""
    import tempfile

    scenario = scenario_by_name(name)
    if quick:
        scenario = dataclasses.replace(scenario, duration_s=6.0)
    digests = []
    for run in range(2):
        with tempfile.TemporaryDirectory(prefix="scenario-det-") as root:
            verdict = run_scenario(scenario, root)
        digests.append(verdict["digests"])
    return {
        "scenario": name,
        "runs": digests,
        "byte_identical": digests[0] == digests[1],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="SCENARIO_r12.json")
    parser.add_argument("--quick", action="store_true",
                        help="shortened scenarios (smoke, not acceptance)")
    parser.add_argument("--scenario", default=None,
                        help="run only this named scenario")
    parser.add_argument("--no-determinism", action="store_true",
                        help="skip the same-seed re-run leg")
    parser.add_argument("--real-crypto", action="store_true",
                        help="genuine per-node Ed25519 verification instead "
                        "of the sim re-sign oracle (same semantics; minutes "
                        "per scenario on the pure-Python fallback)")
    args = parser.parse_args(argv)

    scenarios = default_matrix()
    if args.scenario:
        scenarios = [scenario_by_name(args.scenario)]
    if args.quick:
        scenarios = [
            dataclasses.replace(s, duration_s=min(s.duration_s, 8.0))
            for s in scenarios
        ]
    t0 = time.monotonic()
    doc = run_matrix(scenarios, real_crypto=args.real_crypto)
    doc.update(
        probe="resilience-scenario-matrix",
        revision="r12",
        quick=bool(args.quick),
        wall_s=round(time.monotonic() - t0, 1),
    )
    for verdict in doc["scenarios"]:
        name = verdict["scenario"]["name"]
        print(
            f"{name:<24} {'PASS' if verdict['passed'] else 'FAIL'}  "
            f"ratio={verdict.get('throughput_ratio', 0.0):.2f}  "
            f"attacks={sum(verdict.get('attack_counts', {}).values())}",
            flush=True,
        )
    if not args.no_determinism:
        print("== determinism leg ==", flush=True)
        doc["determinism"] = determinism_leg(
            scenarios[0].name, args.quick
        )
        print(f"byte_identical: {doc['determinism']['byte_identical']}")
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out} ({doc['passed']} passed, {doc['failed']} failed)")
    # The determinism leg gates the exit code too: a byte_identical=false
    # run is a regression even when every scenario verdict passes.
    deterministic = (doc.get("determinism") or {}).get("byte_identical", True)
    return 0 if doc["all_pass"] and deterministic else 1


if __name__ == "__main__":
    sys.exit(main())
