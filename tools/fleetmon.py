#!/usr/bin/env python3
"""Fleet health monitor: scrape every node, diagnose, record, render.

The fleet-level half of the health plane (``mysticeti_tpu/health.py``):
scrapes every node's ``/metrics`` endpoint on an interval (the same
prometheus parsing the orchestrator's measurement scraper uses), computes
cluster health — quorum participation, per-authority straggler scores,
cross-node commit skew, SLO alert totals — embeds the hostmon weather
snapshot, flushes a JSON health timeline ATOMICALLY every tick (a killed
run keeps its last complete snapshot), and renders a live terminal
dashboard.

Usage:
    # explicit targets
    python tools/fleetmon.py --targets 127.0.0.1:1600 127.0.0.1:1601 \
        --out fleetmon.json --interval 2 --duration 60

    # or point it at an orchestrator/testbed working directory (reads the
    # metrics addresses from parameters.yaml)
    python tools/fleetmon.py --fleet-dir benchmark-fleet --out fleetmon.json

``--once`` takes a single snapshot and exits (CI artifact mode);
``--no-dashboard`` suppresses the terminal rendering for headless runs.
Exit status is 0 when the final snapshot is healthy, 3 when degraded —
scriptable as a fleet readiness gate.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.health import (  # noqa: E402
    SLOThresholds,
    cluster_snapshot_from_texts,
)
from mysticeti_tpu.orchestrator.runner import _http_get_metrics  # noqa: E402


def resolve_targets(args) -> List[Tuple[str, int]]:
    if args.targets:
        out = []
        for t in args.targets:
            host, _, port = t.rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
        return out
    if args.fleet_dir:
        from mysticeti_tpu.config import Parameters

        parameters = Parameters.load(
            os.path.join(args.fleet_dir, "parameters.yaml")
        )
        return [
            parameters.metrics_address(a)
            for a in range(len(parameters.identifiers))
        ]
    raise SystemExit("need --targets or --fleet-dir")


async def scrape_all(targets) -> Dict[str, Optional[str]]:
    texts = await asyncio.gather(
        *(_http_get_metrics(host, port) for host, port in targets)
    )
    return {str(i): text for i, text in enumerate(texts)}


async def fetch_recorders(targets) -> Dict[str, Optional[dict]]:
    """Every node's live flight-recorder document (flight_recorder.py) from
    the ``/debug/flight-recorder`` route; None for unreachable nodes or
    pre-r9 nodes without the route."""
    texts = await asyncio.gather(
        *(
            _http_get_metrics(host, port, path="/debug/flight-recorder")
            for host, port in targets
        )
    )
    docs: Dict[str, Optional[dict]] = {}
    for i, text in enumerate(texts):
        doc = None
        if text:
            try:
                parsed = json.loads(text)
                if isinstance(parsed, dict) and "events" in parsed:
                    doc = parsed
            except ValueError:
                pass
        docs[str(i)] = doc
    return docs


def recorder_summary(
    docs: Dict[str, Optional[dict]], last: int = 10
) -> Dict[str, Optional[dict]]:
    """The artifact-embedded view: last N events + dump ledger per node."""
    out: Dict[str, Optional[dict]] = {}
    for node, doc in sorted(docs.items()):
        if doc is None:
            out[node] = None
            continue
        out[node] = {
            "recorded": doc.get("recorded"),
            "dropped": doc.get("dropped"),
            "last_events": (doc.get("events") or [])[-last:],
            "dumps": doc.get("dumps") or [],
        }
    return out


def weather_sample(sampler) -> Optional[dict]:
    if sampler is None:
        return None
    sample = sampler.sample()
    return {
        k: sample[k]
        for k in (
            "cpu_pct", "load_1m", "load_5m", "load_15m", "mem_available_mb",
            "cpu_steal_pct", "switch_interval_s",
        )
        if k in sample
    }


def atomic_write(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


def render_dashboard(snapshot: dict, targets, tick: int) -> str:
    """One frame of the terminal dashboard (ANSI home+clear per tick)."""
    lines = [
        f"fleetmon  tick {tick}  status: {snapshot['status'].upper()}"
        f"  participation {snapshot['quorum_participation']:.2f}"
        f"  commit skew {snapshot['commit_skew_rounds']}r"
        f"  max commit round {snapshot['max_commit_round']}",
    ]
    weather = snapshot.get("weather")
    if weather:
        lines.append(
            "weather: "
            + "  ".join(f"{k}={v}" for k, v in sorted(weather.items()))
        )
    lines.append(
        f"{'node':<6}{'state':<12}{'epoch':>7}{'commit/s':>10}"
        f"{'straggler':>12}{'lag p99':>10}{'fin p99':>10}  "
        f"{'top cpu subsystems':<32}"
    )
    stragglers = snapshot.get("straggler_score", {})
    rates = snapshot.get("commit_rate_by_node", {})
    lags = snapshot.get("loop_lag_p99_by_node", {})
    finality = snapshot.get("finality_p99_by_node", {})
    top_subs = snapshot.get("top_cpu_subsystems", {})
    epochs = snapshot.get("epochs_by_node", {})
    for i in range(len(targets)):
        node = str(i)
        if node in snapshot["unreachable"]:
            state = "UNREACHABLE"
        elif node in snapshot.get("degraded_nodes", []):
            state = "degraded"
        elif node in snapshot.get("yellow_nodes", []):
            state = "yellow"
        else:
            state = "ok"
        lag_ms = lags.get(node, 0.0) * 1e3
        fin_ms = finality.get(node, 0.0) * 1e3
        lines.append(
            f"{node:<6}{state:<12}{epochs.get(node, 0):>7}"
            f"{rates.get(node, 0.0):>10.3f}"
            f"{stragglers.get(node, 0):>12}"
            f"{lag_ms:>8.1f}ms"
            f"{fin_ms:>8.0f}ms  "
            f"{','.join(top_subs.get(node, []) or ['-']):<32}"
        )
    # Mixed-epoch readiness warning: nodes disagreeing on the consensus
    # epoch is EXPECTED for the seconds around a reconfiguration boundary
    # but a lagging straggler beyond that — surface it without tripping
    # the red machinery (commit-skew and participation gates own "red").
    distinct_epochs = {e for e in epochs.values()}
    if len(distinct_epochs) > 1:
        by_epoch: Dict[int, List[str]] = {}
        for node, e in sorted(epochs.items()):
            by_epoch.setdefault(int(e), []).append(node)
        lines.append(
            "WARNING mixed epochs: "
            + "  ".join(
                f"epoch {e}: nodes {','.join(nodes)}"
                for e, nodes in sorted(by_epoch.items())
            )
        )
    alerts = snapshot.get("slo_alert_totals", {})
    if alerts:
        lines.append(
            "alerts: "
            + "  ".join(f"{k}={v:.0f}" for k, v in sorted(alerts.items()))
        )
    if snapshot.get("degraded_reasons"):
        lines.append("degraded: " + "; ".join(snapshot["degraded_reasons"]))
    return "\n".join(lines)


async def run(args) -> int:
    targets = resolve_targets(args)
    slo = SLOThresholds(
        min_participation=args.min_participation,
        max_loop_lag_s=args.max_loop_lag,
        # getattr: programmatic callers build a bare Namespace (the
        # fleet-trace test does) and must keep working with old arg sets.
        max_finality_p99_s=getattr(args, "max_finality_p99", 0.0),
    )
    sampler = None
    try:
        from mysticeti_tpu.orchestrator.hostmon import HostSampler

        sampler = HostSampler()
    except ImportError:  # no psutil: timeline rides without weather
        pass
    # Bounded history: run-forever mode must not grow memory (or the
    # per-tick rewrite) without limit — beyond the cap the oldest ticks
    # roll off and the artifact says how many it dropped.
    max_ticks = max(1, args.max_ticks)
    timeline: List[dict] = []
    dropped_ticks = 0
    started = time.time()
    tick = 0
    last_snapshot: Optional[dict] = None
    recorders: Dict[str, Optional[dict]] = {}
    dump_paths: List[str] = []

    def artifact_doc() -> dict:
        return {
            "targets": [f"{h}:{p}" for h, p in targets],
            "interval_s": args.interval,
            "window_utc": [round(started, 1), round(time.time(), 1)],
            "slo": slo.to_dict(),
            "dropped_ticks": dropped_ticks,
            # Flight-recorder summary (flight_recorder.py): the last few
            # incident-ring events per node + each node's dump ledger.
            # Refreshed on the first tick, on every red transition, and at
            # exit — NOT per tick: the debug route returns the FULL ring
            # (up to ~1 MB/node) and polling it continuously would cost
            # megabytes per interval to keep a 10-event slice fresh.
            "flight_recorder": recorder_summary(recorders),
            "flight_recorder_dumps": dump_paths,
            "timeline": timeline,
        }

    async def write_red_dumps() -> None:
        """Preserve every node's full incident ring on disk NOW — the
        operator's first question is "what happened in the seconds before
        red", and that window rolls off the bounded ring."""
        nonlocal recorders
        recorders = await fetch_recorders(targets)
        base = args.out or "fleetmon.json"
        for node, doc in sorted(recorders.items()):
            if doc is None:
                continue
            path = f"{base}.flight-{node}.json"
            atomic_write(path, doc)
            if os.path.basename(path) not in dump_paths:
                dump_paths.append(os.path.basename(path))
            print(f"flight recorder of node {node} dumped to {path}",
                  file=sys.stderr)

    prev_degraded = False
    while True:
        tick += 1
        texts = await scrape_all(targets)
        snapshot = cluster_snapshot_from_texts(texts, len(targets), slo=slo)
        snapshot["t"] = round(time.time() - started, 3)
        weather = weather_sample(sampler)
        if weather is not None:
            snapshot["weather"] = weather
        timeline.append(snapshot)
        if len(timeline) > max_ticks:
            timeline.pop(0)
            dropped_ticks += 1
        last_snapshot = snapshot
        # Yellow (a loop-lag SLO breach: the fleet is committing but some
        # node's event loop runs hot) warns on the dashboard without
        # tripping the red machinery — only "degraded" dumps rings/exits 3.
        degraded_now = snapshot["status"] == "degraded"
        if degraded_now and not prev_degraded and args.dump_on_red:
            # Dump AT the red transition, mid-run included: a fleet that
            # goes red at minute 10 of an hour-long watch must not wait
            # for loop exit (the ring would have rolled past the incident,
            # or a recovery would skip the dump entirely).
            await write_red_dumps()
        elif tick == 1:
            recorders = await fetch_recorders(targets)
        prev_degraded = degraded_now
        if args.out:
            atomic_write(args.out, artifact_doc())
        if not args.no_dashboard:
            frame = render_dashboard(snapshot, targets, tick)
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
        if args.once or (
            args.duration and time.time() - started >= args.duration
        ):
            break
        await asyncio.sleep(args.interval)
    if args.no_dashboard and last_snapshot is not None:
        print(render_dashboard(last_snapshot, targets, tick))
    degraded = not (
        last_snapshot and last_snapshot["status"] in ("ok", "yellow")
    )
    if degraded and args.dump_on_red:
        # Exit while red: refresh the dumps so the gate failure always
        # leaves the freshest rings (idempotent if the transition already
        # dumped this red period).
        await write_red_dumps()
    else:
        recorders = await fetch_recorders(targets)
    if args.out:
        atomic_write(args.out, artifact_doc())
    return 3 if degraded else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleetmon", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--targets", nargs="*", default=None,
                        help="metrics endpoints as host:port")
    parser.add_argument("--fleet-dir", default=None,
                        help="orchestrator working dir (reads parameters.yaml)")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--duration", type=float, default=0.0,
                        help="stop after this many seconds (0 = forever)")
    parser.add_argument("--once", action="store_true",
                        help="one snapshot, then exit")
    parser.add_argument("--out", default=None,
                        help="JSON health-timeline path (atomically rewritten "
                        "every tick)")
    parser.add_argument("--min-participation", type=float, default=0.67)
    parser.add_argument("--max-loop-lag", type=float, default=0.25,
                        help="loop-lag p99 (s) past which a node shows "
                        "yellow on the readiness gate (0 disables)")
    parser.add_argument("--max-finality-p99", type=float, default=0.0,
                        help="submit→finalized p99 (s) past which a node "
                        "shows yellow on the readiness gate (0 disables; "
                        "reads mysticeti_e2e_finality_p99_seconds)")
    parser.add_argument("--max-ticks", type=int, default=2880,
                        help="keep at most this many timeline ticks in "
                        "memory/on disk (oldest roll off; default = 4h at "
                        "the 5s interval)")
    parser.add_argument("--no-dashboard", action="store_true")
    parser.add_argument("--dump-on-red", action="store_true",
                        help="when the readiness gate fails, pull "
                        "/debug/flight-recorder from every node and write "
                        "<out>.flight-<node>.json dumps")
    args = parser.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    raise SystemExit(main())
