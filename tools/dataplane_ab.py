#!/usr/bin/env python3
"""Same-box A/B of the native data plane (r19): extension vs pure fallback.

Runs the perf_attr subprocess fleet twice — once with the native extension
loaded (the r19 batched frame encode/parse + digest offload path) and once
with ``MYSTICETI_NO_NATIVE=1`` pinning the pure-Python twin everywhere.
``MYSTICETI_NO_NATIVE`` is read at import time, so the mode toggle lives in
the NODE subprocess environment; the two fleets are otherwise identical
(same box, same load, ABBA-interleaved repeats so drift cancels).

Acceptance evidence written to ``DATAPLANE_rNN.json``:

* committed leaders (fleet mean) — native must be >= the fallback baseline;
* PERF_ATTR-attributed hot-path CPU per committed leader — the
  mesh-encode + mesh-parse + digest subsystem sum must drop >= 25%;
* the ``/health`` host block's ``native_active`` inventory per mode — the
  artifact records which path each fleet actually measured;
* the data-plane microbench (tools/node_bench.py --dataplane-bench)
  embedded for context — native batched encode+parse+digest must be
  >= 2x the pure per-block path.

Results are appended to BENCH_TREND.json under the NODE_DATAPLANE family
as higher-is-better rows (leaders per attributed hot-path CPU second — the
PERF_ATTR budget-row inversion), so the stock >10% regression gate guards
the win round-over-round.

Usage: JAX_PLATFORMS=cpu python tools/dataplane_ab.py --round 19
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The attributed subsystems the r19 native path accelerates: whole-frame
# encode (synchronizer/network), whole-frame parse + block decode
# (net_sync/serde/types.from_bytes*), and the digest pair (crypto).
HOT_SUBSYSTEMS = ("mesh-encode", "mesh-parse", "digest")


def run_mode(mode: str, rep: int, args) -> dict:
    """One fleet run in the given mode; returns the perf_attr doc."""
    import perf_attr

    env_key = "MYSTICETI_NO_NATIVE"
    saved = os.environ.get(env_key)
    saved_tx = os.environ.get("TRANSACTION_SIZE")
    if mode == "fallback":
        os.environ[env_key] = "1"
    else:
        os.environ.pop(env_key, None)
    # run_fleet copies os.environ into every node subprocess.  Load
    # starts at the stock INITIAL_DELAY: holding the generators back any
    # longer lets empty rounds race ahead (~10/s idle vs ~2/s loaded on
    # a small host), which pads the committed-leader totals of both arms
    # with cheap leaders and buries the throughput signal.
    os.environ["TRANSACTION_SIZE"] = str(args.tx_size)
    try:
        fleet_args = argparse.Namespace(
            committee_size=args.committee,
            duration=args.duration,
            tps=args.tps,
            verifier=args.verifier,
            working_dir=os.path.join(args.workdir, f"{mode}-{rep}"),
            scrape_interval=args.scrape_interval,
            round=args.round,
        )
        doc = perf_attr.run_fleet(fleet_args)
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
        if saved_tx is None:
            os.environ.pop("TRANSACTION_SIZE", None)
        else:
            os.environ["TRANSACTION_SIZE"] = saved_tx
    doc["mode"] = mode
    doc["rep"] = rep
    return doc


def subsystem_us_per_leader(doc: dict, sub: str) -> float:
    """One subsystem's attributed µs per committed leader, fleet-averaged.

    Prefers perf_attr's windowed view (counter deltas between the boot
    probe and the last scrape) — the node's own cumulative gauge counts
    the cheap empty rounds committed before the transaction generators
    start, which dilutes a load A/B.  Falls back to the cumulative gauge
    when no window was captured."""
    windowed = doc.get("windowed_us_per_leader_by_node") or {}
    if windowed:
        return statistics.mean(
            node.get(sub, 0.0) for node in windowed.values()
        )
    return doc["subsystems"].get(sub, {}).get("us_per_leader") or 0.0


def hotpath_us_per_leader(doc: dict) -> float:
    """mesh-encode + mesh-parse + digest attributed µs per committed
    leader, fleet-averaged (the quantity the 25% gate is about)."""
    return sum(subsystem_us_per_leader(doc, sub) for sub in HOT_SUBSYSTEMS)


def mean_leaders(doc: dict) -> float:
    leaders = list(doc["committed_leaders_by_node"].values())
    return statistics.mean(leaders) if leaders else 0.0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dataplane_ab", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--committee", type=int, default=4)
    parser.add_argument("--duration", type=float, default=45.0)
    parser.add_argument("--tps", type=int, default=800)
    parser.add_argument(
        "--tx-size", type=int, default=4096,
        help="transaction payload bytes (TRANSACTION_SIZE in the node env): "
        "the data-plane win scales with frame bytes, so the A/B runs a "
        "byte-heavy load where codec+digest work is a visible fraction of "
        "the hot path",
    )
    parser.add_argument(
        "--verifier", default="cpu",
        help="node verifier for both fleets; the production-shaped 'cpu' "
        "path paces rounds realistically ('accept' lets empty rounds race "
        "and spreads the byte-work over 3-4x the leaders, hiding the "
        "data-plane cost the A/B is about)",
    )
    parser.add_argument("--workdir", default="/tmp/mysticeti-dataplane-ab")
    parser.add_argument("--scrape-interval", type=float, default=5.0)
    parser.add_argument(
        "--repeats", type=int, default=2,
        help="fleet runs per mode, ABBA-interleaved so same-box drift "
        "cancels",
    )
    parser.add_argument("--round", type=int, default=19)
    parser.add_argument("--out", default=None)
    parser.add_argument("--no-trend", action="store_true")
    args = parser.parse_args(argv)
    out = args.out or f"DATAPLANE_r{args.round:02d}.json"
    out = out if os.path.isabs(out) else os.path.join(_REPO, out)

    from mysticeti_tpu.native import native

    if native is None:
        print(
            "native extension unavailable: the A/B has no treatment arm",
            file=sys.stderr,
        )
        return 2

    # Microbench first, while the box is quiet: fleet teardown (WAL
    # flush, exiting nodes) contends with timing loops for a while after
    # a run, which skews the per-call speedups on small hosts.  Best of
    # three passes — the classic interference guard for a timing loop on
    # a shared 1-core host; each pass is already iters-averaged inside.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from node_bench import append_dataplane_trend, dataplane_bench

    def combined_of(bench):
        return (
            (bench.get("speedups") or {}).get("combined_encode_parse_digest")
            or 0.0
        )

    passes = [dataplane_bench() for _ in range(3)]
    microbench = max(passes, key=combined_of)
    microbench["combined_speedup_passes"] = [
        round(combined_of(b), 2) for b in passes
    ]
    combined = combined_of(microbench)
    print(f"microbench combined speedup: {combined} "
          f"(passes {microbench['combined_speedup_passes']})", flush=True)

    schedule = []
    for i in range(args.repeats):
        pair = (
            ["fallback", "native"] if i % 2 == 0 else ["native", "fallback"]
        )
        schedule += [(mode, i) for mode in pair]
    runs = {"fallback": [], "native": []}
    for mode, rep in schedule:
        print(f"running {mode} fleet rep {rep} ({args.duration:.0f}s)...",
              flush=True)
        doc = run_mode(mode, rep, args)
        print(json.dumps(
            {
                "mode": mode,
                "leaders": doc["committed_leaders_by_node"],
                "hotpath_us_per_leader": round(hotpath_us_per_leader(doc), 1),
            },
        ), flush=True)
        runs[mode].append(doc)

    def mode_hotpath(mode):
        return round(statistics.mean(
            hotpath_us_per_leader(doc) for doc in runs[mode]
        ), 2)

    def mode_leaders(mode):
        return round(statistics.mean(
            mean_leaders(doc) for doc in runs[mode]
        ), 1)

    def native_inventory(mode):
        # Under saturating load a /health scrape can miss; take the first
        # node whose host block was actually captured.
        for doc in runs[mode]:
            for node in (doc.get("native_active_by_node") or {}).values():
                if node is not None:
                    return node
        return None

    comparison = {
        "committed_leaders_mean": {m: mode_leaders(m) for m in runs},
        "hotpath_us_per_leader": {m: mode_hotpath(m) for m in runs},
        "hotpath_subsystems": {
            m: {
                sub: round(statistics.mean(
                    subsystem_us_per_leader(doc, sub) for doc in runs[m]
                ), 2)
                for sub in HOT_SUBSYSTEMS
            }
            for m in runs
        },
        "native_active": {m: native_inventory(m) for m in runs},
    }
    fallback_cost = comparison["hotpath_us_per_leader"]["fallback"]
    native_cost = comparison["hotpath_us_per_leader"]["native"]
    reduction_pct = (
        round(100.0 * (1.0 - native_cost / fallback_cost), 1)
        if fallback_cost > 0
        else 0.0
    )
    comparison["hotpath_cpu_reduction_pct"] = reduction_pct

    acceptance = {
        "committed_leaders_not_worse": (
            comparison["committed_leaders_mean"]["native"]
            >= comparison["committed_leaders_mean"]["fallback"]
        ),
        "hotpath_cpu_reduced_25pct": reduction_pct >= 25.0,
        "microbench_combined_2x": combined >= 2.0,
    }

    artifact = {
        "metric": "native_dataplane_ab",
        "round": args.round,
        "committee": args.committee,
        "duration_s": args.duration,
        "tps_per_node": args.tps,
        "transaction_size": args.tx_size,
        "verifier": args.verifier,
        "repeats": args.repeats,
        "note": (
            "same-box ABBA fleets: fallback = MYSTICETI_NO_NATIVE=1 in the "
            "node subprocess env (pure-Python frame codecs + per-block "
            "hashlib digests); native = r19 extension (batched GIL-free "
            "encode/parse/digest + offload executor).  Wire frames are "
            "byte-identical across modes (golden corpus + parity suite); "
            "only the CPU cost per committed leader moves."
        ),
        "comparison": comparison,
        "acceptance": acceptance,
        "microbench": microbench,
        "runs": runs,
    }
    tmp = f"{out}.tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)
    print(f"wrote {out}")
    print(json.dumps({"comparison": comparison, "acceptance": acceptance},
                     indent=1))

    if not args.no_trend:
        append_dataplane_trend(microbench, args.round)
        import bench_trend

        source = f"DATAPLANE_r{args.round:02d}.json"
        fresh = []
        for mode in ("fallback", "native"):
            cost = comparison["hotpath_us_per_leader"][mode]
            if cost > 0:
                # PERF_ATTR budget-row inversion: leaders per attributed
                # hot-path CPU second — HIGHER is better, so cost creep
                # fires the stock >10% trend gate.
                fresh.append(bench_trend._record(
                    args.round, source,
                    f"NODE_DATAPLANE.ab_{mode}_leaders_per_hotpath_cpu_s",
                    round(1e6 / cost, 3), "ldr/cpu-s",
                ))
        fresh.append(bench_trend._record(
            args.round, source, "NODE_DATAPLANE.ab_hotpath_cpu_reduction",
            reduction_pct, "%",
        ))
        path = os.environ.get(
            "BENCH_TREND_PATH", os.path.join(_REPO, "BENCH_TREND.json")
        )
        index = bench_trend.load_index(path)
        if bench_trend.merge_index(index, fresh):
            bench_trend.write_index(index, path)
        print("appended NODE_DATAPLANE A/B records to BENCH_TREND.json")

    return 0 if all(acceptance.values()) else 3


if __name__ == "__main__":
    raise SystemExit(main())
