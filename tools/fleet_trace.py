#!/usr/bin/env python3
"""Fleet causal trace merge: stitch N nodes' span traces into block journeys.

Per-node traces (``MYSTICETI_TRACE``) explain where a block's latency went
INSIDE one validator; the latency the paper actually claims spans the fleet:

    propose @ author -> wire transit -> receive/verify/dag_add @ every peer
    -> proposal_wait -> commit -> finalize

This tool joins any number of trace files on block reference into one causal
timeline per committed leader, with cross-node timestamps made comparable by
a **clock-skew estimator**:

* every trace carries a ``(runtime, wall)`` clock anchor (``otherData``),
  mapping its span timestamps to that node's wall clock;
* the ``transit`` spans recorded from the tag-12 frame timestamps carry the
  RAW signed one-way transit per link (``raw_us``) plus the link's smoothed
  RTT (``rtt_us``) from the existing ping/pong exchange in ``network.py``;
* per-link offsets come from **min-transit alignment** — for a link
  observed in both directions, ``(min raw(a->b) - min raw(b->a)) / 2`` is
  the clock offset under a symmetric minimum path delay — refined by the
  RTT/2 rule (``min raw - rtt/2``) when only one direction was observed;
* offsets are propagated from the lowest observed authority over the link
  graph, and the resulting **skew table is embedded in the merged
  artifact** so cross-node deltas in it are meaningful.

Outputs: per-stage fleet percentiles, per-link wire latency, a "slowest
journeys" table, and the merged JSON artifact (``--out``).  ``--block REF``
prints a per-node waterfall for one journey.  Truncated/mid-flush traces
are salvaged through the SAME loader + stage extraction the critical-path
report uses (``mysticeti_tpu.spans``), so the two tools can never disagree
about a torn file's stage boundaries.  The merged output is a pure function
of the inputs (no wall-clock-of-now anywhere), so merging a seeded sim's
trace is byte-identical across same-seed runs.

Usage:
    python tools/fleet_trace.py trace-*.json --out TRACE.json
    python tools/fleet_trace.py trace-*.json --block A2R141
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.spans import (  # noqa: E402
    PIPELINE_STAGES,
    STAGES,
    complete_spans,
    load_trace_events,
    stage_chains,
    track_names,
)

_REF_RE = re.compile(r"^A(\d+)R(\d+)#")
_TRACK_RE = re.compile(r"^A(\d+)$")

# Stages that participate in a journey timeline (everything per-block).
JOURNEY_STAGES = ("propose", "transit") + PIPELINE_STAGES


def _pct(ordered: List[float], pct: float) -> float:
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(len(ordered) * pct / 100))
    return ordered[idx]


# ---------------------------------------------------------------------------
# Loading


def load_fleet(paths: List[str]):
    """Load every trace; returns (per-(authority,label) stage chains with
    wall-converted start timestamps, transit observations, notes).

    ``chains[(authority, label)] = {stage: (wall_ts_us, dur_us)}`` merged
    across files (earliest start, longest duration — the shared extraction
    rule).  ``transits`` is a list of ``{src, dst, label, raw_us, rtt_us,
    arrival_us}``.
    """
    chains: Dict[Tuple[int, str], Dict[str, Tuple[float, int]]] = {}
    transits: List[dict] = []
    notes: List[str] = []
    anchored = 0
    for path in paths:
        events, note, other = load_trace_events(path)
        if note:
            notes.append(f"{os.path.basename(path)}: {note}")
        spans = complete_spans(events)
        names = track_names(events)
        offset_us = 0.0
        if other.get("clock_wall_us") is not None:
            offset_us = other["clock_wall_us"] - other.get(
                "clock_runtime_us", 0
            )
            anchored += 1
        else:
            notes.append(
                f"{os.path.basename(path)}: no clock anchor (pre-r9 trace?);"
                " timestamps used as-is"
            )

        def authority_of(track: Tuple[int, int]) -> Optional[int]:
            match = _TRACK_RE.match(names.get(track, ""))
            if match:
                return int(match.group(1))
            return track[1] if track[1] < (1 << 20) else None

        for (track, label), chain in stage_chains(spans).items():
            authority = authority_of(track)
            if authority is None:
                continue
            merged = chains.setdefault((authority, label), {})
            for stage, (ts, dur) in chain.items():
                wall = ts + offset_us
                prev = merged.get(stage)
                if prev is None:
                    merged[stage] = (wall, dur)
                else:
                    merged[stage] = (min(prev[0], wall), max(prev[1], dur))
        for e in spans:
            if e.get("name") != "transit":
                continue
            args = e.get("args") or {}
            dst = authority_of((e.get("pid", 0), e.get("tid", 0)))
            src = args.get("src")
            raw_us = args.get("raw_us")
            if dst is None or src is None or raw_us is None:
                continue
            transits.append(
                {
                    "src": int(src),
                    "dst": int(dst),
                    "label": args.get("block"),
                    "raw_us": int(raw_us),
                    "rtt_us": args.get("rtt_us"),
                    "arrival_us": e.get("ts", 0) + e.get("dur", 0) + offset_us,
                }
            )
    return chains, transits, notes, anchored


# ---------------------------------------------------------------------------
# Clock-skew estimation


def estimate_skew(transits: List[dict], authorities: List[int]) -> dict:
    """Per-authority wall-clock offsets from the transit observations.

    ``offset[a]`` is authority a's clock error relative to the reference
    (lowest observed authority); subtract it from a's timestamps to land on
    the fleet-common clock.  Links observed both ways use min-transit
    alignment; one-way links fall back to ``min raw - rtt/2``.
    """
    links: Dict[Tuple[int, int], List[int]] = defaultdict(list)
    rtts: Dict[Tuple[int, int], List[int]] = defaultdict(list)
    for obs in transits:
        key = (obs["src"], obs["dst"])
        links[key].append(obs["raw_us"])
        if obs.get("rtt_us") is not None:
            rtts[key].append(obs["rtt_us"])

    # Pairwise estimates: delta[(a, b)] = offset[b] - offset[a].
    delta: Dict[Tuple[int, int], Tuple[float, str]] = {}
    for (a, b) in sorted(links):
        if (b, a) in delta or (a, b) in delta:
            continue
        fwd = min(links[(a, b)])
        if (b, a) in links:
            rev = min(links[(b, a)])
            delta[(a, b)] = ((fwd - rev) / 2.0, "min-transit")
        else:
            rtt = min(rtts[(a, b)]) if rtts.get((a, b)) else None
            if rtt is not None:
                delta[(a, b)] = (fwd - rtt / 2.0, "rtt-half")
            else:
                # Last resort: assume the observed minimum IS the offset
                # plus zero delay — still better than nothing for ordering.
                delta[(a, b)] = (float(fwd), "min-raw")

    # Propagate from the reference over the link graph (deterministic BFS).
    adjacency: Dict[int, List[Tuple[int, float]]] = defaultdict(list)
    for (a, b), (d, _method) in delta.items():
        adjacency[a].append((b, d))
        adjacency[b].append((a, -d))
    offsets: Dict[int, float] = {}
    methods: Dict[int, str] = {}
    for start in sorted(authorities):
        if start in offsets:
            continue
        offsets[start] = 0.0
        methods[start] = "reference"
        queue = [start]
        while queue:
            node = queue.pop(0)
            for peer, d in sorted(adjacency.get(node, [])):
                if peer in offsets:
                    continue
                offsets[peer] = offsets[node] + d
                methods[peer] = "derived"
                queue.append(peer)
    for a in authorities:
        if a not in offsets:
            offsets[a] = 0.0
            methods[a] = "unobserved"

    link_stats = {}
    for (a, b), raws in sorted(links.items()):
        ordered = sorted(raws)
        corrected = sorted(
            r - (offsets.get(b, 0.0) - offsets.get(a, 0.0)) for r in raws
        )
        link_stats[f"{a}->{b}"] = {
            "samples": len(ordered),
            "raw_min_ms": round(ordered[0] / 1e3, 3),
            "raw_p50_ms": round(_pct(ordered, 50) / 1e3, 3),
            "latency_min_ms": round(corrected[0] / 1e3, 3),
            "latency_p50_ms": round(_pct(corrected, 50) / 1e3, 3),
            "latency_p99_ms": round(_pct(corrected, 99) / 1e3, 3),
            "rtt_min_ms": (
                round(min(rtts[(a, b)]) / 1e3, 3) if rtts.get((a, b)) else None
            ),
        }
    return {
        "reference": min(authorities) if authorities else None,
        "offsets_us": {
            str(a): round(offsets[a], 1) for a in sorted(offsets)
        },
        "method": {str(a): methods[a] for a in sorted(methods)},
        "pairwise": {
            f"{a}->{b}": {"delta_us": round(d, 1), "method": m}
            for (a, b), (d, m) in sorted(delta.items())
        },
        "links": link_stats,
    }


# ---------------------------------------------------------------------------
# Journey stitching


def build_journeys(chains, transits, offsets_us: Dict[str, float]):
    """One causal record per block that committed anywhere.

    Timestamps are skew-corrected (observer's offset subtracted) and made
    relative to the journey's ``propose`` edge (or its earliest observed
    event when the author's trace is missing)."""
    by_label: Dict[str, Dict[int, Dict[str, Tuple[float, int]]]] = defaultdict(dict)
    for (authority, label), chain in chains.items():
        by_label[label][authority] = chain
    transit_by_label: Dict[str, List[dict]] = defaultdict(list)
    for obs in transits:
        if obs.get("label"):
            transit_by_label[obs["label"]].append(obs)

    have_transit_data = bool(transits)
    journeys: List[dict] = []
    for label in sorted(
        by_label,
        key=lambda lbl: (
            (int(m.group(2)), int(m.group(1)), lbl)
            if (m := _REF_RE.match(lbl))
            else (1 << 62, 0, lbl)
        ),
    ):
        nodes = by_label[label]
        if not any("commit" in chain for chain in nodes.values()):
            continue  # never committed: not a journey (yet)
        match = _REF_RE.match(label)
        author = int(match.group(1)) if match else None
        round_ = int(match.group(2)) if match else None

        def corrected(authority: int, ts: float) -> float:
            return ts - offsets_us.get(str(authority), 0.0)

        propose_t: Optional[float] = None
        if author is not None and author in nodes:
            entry = nodes[author].get("propose")
            if entry is not None:
                propose_t = corrected(author, entry[0])
        earliest = min(
            corrected(a, ts)
            for a, chain in nodes.items()
            for ts, _dur in chain.values()
        )
        t0 = propose_t if propose_t is not None else earliest
        end = max(
            corrected(a, ts) + dur
            for a, chain in nodes.items()
            for ts, dur in chain.values()
        )
        per_node = {}
        stages_present = set()
        for a in sorted(nodes):
            chain = nodes[a]
            stages_present.update(chain)
            per_node[str(a)] = {
                stage: [
                    round((corrected(a, ts) - t0) / 1e3, 3),
                    round(dur / 1e3, 3),
                ]
                for stage, (ts, dur) in sorted(chain.items())
            }
        transit_ms = {}
        for obs in transit_by_label.get(label, []):
            latency = obs["raw_us"] - (
                offsets_us.get(str(obs["dst"]), 0.0)
                - offsets_us.get(str(obs["src"]), 0.0)
            )
            key = f"{obs['src']}->{obs['dst']}"
            prev = transit_ms.get(key)
            value = round(latency / 1e3, 3)
            transit_ms[key] = value if prev is None else min(prev, value)
        fully_stitched = (
            propose_t is not None
            and set(PIPELINE_STAGES) <= stages_present
            and (bool(transit_ms) or not have_transit_data)
        )
        journeys.append(
            {
                "block": label,
                "author": author,
                "round": round_,
                "observers": sorted(int(a) for a in nodes),
                "e2e_ms": round((end - t0) / 1e3, 3),
                "propose_anchored": propose_t is not None,
                "fully_stitched": bool(fully_stitched),
                "transit_ms": transit_ms,
                "nodes": per_node,
            }
        )
    return journeys


def stage_percentiles(chains) -> Dict[str, dict]:
    per_stage: Dict[str, List[float]] = defaultdict(list)
    for (_authority, _label), chain in chains.items():
        for stage, (_ts, dur) in chain.items():
            per_stage[stage].append(dur / 1e3)
    out = {}
    for stage in sorted(per_stage, key=lambda s: (
        STAGES.index(s) if s in STAGES else len(STAGES), s
    )):
        durs = sorted(per_stage[stage])
        out[stage] = {
            "count": len(durs),
            "p50_ms": round(_pct(durs, 50), 3),
            "p90_ms": round(_pct(durs, 90), 3),
            "p99_ms": round(_pct(durs, 99), 3),
            "max_ms": round(durs[-1], 3),
        }
    return out


# ---------------------------------------------------------------------------
# Rendering


def render_summary(doc: dict) -> str:
    lines = [
        f"fleet trace: {doc['journeys_total']} committed journey(s) from "
        f"{len(doc['inputs'])} trace(s), {doc['fully_stitched']} fully "
        "stitched end-to-end",
    ]
    skew = doc["skew"]
    if skew["offsets_us"]:
        lines.append(
            "skew table (us vs A%s): " % skew["reference"]
            + "  ".join(
                f"A{a}={v:+.0f}" for a, v in skew["offsets_us"].items()
            )
        )
    lines.append("")
    lines.append(f"{'stage':<16}{'count':>8}{'p50_ms':>10}{'p90_ms':>10}"
                 f"{'p99_ms':>10}{'max_ms':>10}")
    for stage, row in doc["stage_percentiles"].items():
        lines.append(
            f"{stage:<16}{row['count']:>8}{row['p50_ms']:>10.3f}"
            f"{row['p90_ms']:>10.3f}{row['p99_ms']:>10.3f}"
            f"{row['max_ms']:>10.3f}"
        )
    if skew["links"]:
        lines.append("")
        lines.append(f"{'link':<10}{'frames':>8}{'min_ms':>10}{'p50_ms':>10}"
                     f"{'p99_ms':>10}{'rtt_min':>10}")
        for link, row in skew["links"].items():
            rtt = row["rtt_min_ms"]
            lines.append(
                f"{link:<10}{row['samples']:>8}{row['latency_min_ms']:>10.3f}"
                f"{row['latency_p50_ms']:>10.3f}{row['latency_p99_ms']:>10.3f}"
                f"{(f'{rtt:.3f}' if rtt is not None else '-'):>10}"
            )
    if doc["slowest"]:
        lines.append("")
        lines.append("slowest journeys:")
        lines.append(f"{'block':<22}{'author':>7}{'e2e_ms':>10}  stages")
        for j in doc["slowest"]:
            worst = ""
            durs = [
                (node[stage][1], stage)
                for node in j["nodes"].values()
                for stage in node
            ]
            if durs:
                top = max(durs)
                worst = f"{top[1]}={top[0]:.1f}ms"
            lines.append(
                f"{j['block']:<22}{j['author'] if j['author'] is not None else '?':>7}"
                f"{j['e2e_ms']:>10.3f}  {worst}"
            )
    return "\n".join(lines)


def render_waterfall(journey: dict) -> str:
    lines = [
        f"journey {journey['block']} (author A{journey['author']}, "
        f"e2e {journey['e2e_ms']:.3f} ms, "
        f"{'fully stitched' if journey['fully_stitched'] else 'partial'})",
        f"{'authority':<10}{'stage':<16}{'start_ms':>10}{'dur_ms':>10}",
    ]
    rows = []
    for a, stages in journey["nodes"].items():
        for stage, (start, dur) in stages.items():
            rows.append((start, int(a), stage, dur))
    for start, a, stage, dur in sorted(rows):
        lines.append(f"A{a:<9}{stage:<16}{start:>10.3f}{dur:>10.3f}")
    if journey["transit_ms"]:
        lines.append("wire transit (skew-corrected): " + "  ".join(
            f"{k}={v:.3f}ms" for k, v in sorted(journey["transit_ms"].items())
        ))
    return "\n".join(lines)


# ---------------------------------------------------------------------------


def merge(paths: List[str], max_journeys: int = 2000, slowest: int = 10) -> dict:
    chains, transits, notes, anchored = load_fleet(paths)
    authorities = sorted({a for (a, _label) in chains})
    skew = estimate_skew(transits, authorities)
    journeys = build_journeys(chains, transits, skew["offsets_us"])
    fully = [j for j in journeys if j["fully_stitched"]]
    ranked = sorted(journeys, key=lambda j: (-j["e2e_ms"], j["block"]))
    if max_journeys and len(journeys) > max_journeys:
        notes = notes + [
            f"note: journeys list capped at {max_journeys} of "
            f"{len(journeys)} (slowest kept; totals cover everything)"
        ]
        kept = set(
            j["block"] for j in ranked[:max_journeys]
        )
        emitted = [j for j in journeys if j["block"] in kept]
    else:
        emitted = journeys
    return {
        "kind": "mysticeti-fleet-trace",
        "inputs": [os.path.basename(p) for p in paths],
        "anchored_inputs": anchored,
        "notes": notes,
        "authorities": authorities,
        "skew": skew,
        "stage_percentiles": stage_percentiles(chains),
        "journeys_total": len(journeys),
        "fully_stitched": len(fully),
        "transit_observations": len(transits),
        "slowest": ranked[:slowest],
        "journeys": emitted,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleet_trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("traces", nargs="+",
                        help="MYSTICETI_TRACE output files, one per node "
                        "(or one multi-track testbed/sim trace)")
    parser.add_argument("--out", default=None,
                        help="write the merged fleet-trace JSON artifact")
    parser.add_argument("--block", default=None,
                        help="print the waterfall view of one journey "
                        "(block label or its A<n>R<m> prefix)")
    parser.add_argument("--slowest", type=int, default=10)
    parser.add_argument("--max-journeys", type=int, default=2000,
                        help="cap the journeys list in the artifact "
                        "(slowest kept; summary totals always cover all)")
    args = parser.parse_args(argv)
    try:
        doc = merge(args.traces, max_journeys=args.max_journeys,
                    slowest=args.slowest)
    except OSError as exc:
        print(f"error: cannot read trace: {exc}", file=sys.stderr)
        return 2
    for note in doc["notes"]:
        print(note, file=sys.stderr)
    if args.block:
        matches = [
            j for j in doc["journeys"]
            if j["block"] == args.block or j["block"].startswith(args.block)
        ]
        if not matches:
            print(f"no committed journey matches {args.block!r}",
                  file=sys.stderr)
            return 1
        for journey in matches[:3]:
            print(render_waterfall(journey))
    else:
        print(render_summary(doc))
    if args.out:
        tmp = f"{args.out}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")
        os.replace(tmp, args.out)
        print(f"merged fleet trace written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
