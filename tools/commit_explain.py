#!/usr/bin/env python3
"""Explain why a leader slot committed or skipped, from a decision ledger.

The committer's decision ledger (``mysticeti_tpu/decisions.py``) records
one structured record per decided leader slot: the commit rule that
decided it, the certificate/blame stake tallies with the contributing
authorities, the anchor used by indirect decisions, and how far behind
the DAG frontier the decision landed.  This tool renders those records
as the human-readable causal explanation
(:func:`mysticeti_tpu.decisions.explain_record`), from either a live
node's ``/debug/consensus`` route or a dumped ledger JSON.

Usage:
    # explain one slot from a live node ("A3R42" = authority 3, round 42)
    python tools/commit_explain.py --url http://127.0.0.1:1600 A3R42

    # explain the last N records (default 10) when no slot is named
    python tools/commit_explain.py --url http://127.0.0.1:1600 --last 20

    # or from a saved /debug/consensus document / ledger dump
    python tools/commit_explain.py --file consensus.json A3R42

    # skips only (the records an operator actually asks about)
    python tools/commit_explain.py --file consensus.json --skips

Exit status: 0 when every requested slot had a record, 1 when a named
slot has no record in the (bounded) ledger window.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import urllib.request
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.decisions import explain_record  # noqa: E402

_SLOT_RE = re.compile(r"^[aA](\d+)[rR](\d+)$")
_LEDGER_SLOT_RE = re.compile(r"^([A-Za-z])(\d+)$")


def parse_slot(text: str) -> Optional[tuple]:
    """Slot name -> (authority, round).

    Accepts the explicit ``A3R42`` form (authority 3, round 42) and the
    ledger's own letter-coded ``repr(AuthorityRound)`` form ``D42``
    (authority D = index 3, round 42).  None when neither matches.
    """
    text = text.strip()
    match = _SLOT_RE.match(text)
    if match:
        return int(match.group(1)), int(match.group(2))
    match = _LEDGER_SLOT_RE.match(text)
    if match:
        return ord(match.group(1).upper()) - ord("A"), int(match.group(2))
    return None


def load_records(args) -> tuple:
    """(records, context) from --file or --url.  Accepts either the full
    ``/debug/consensus`` document or a bare list of records (a dumped
    ``DecisionLedger.records()``)."""
    if args.file:
        with open(args.file) as f:
            doc = json.load(f)
    else:
        url = args.url.rstrip("/") + "/debug/consensus"
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            doc = json.loads(resp.read().decode())
    if isinstance(doc, list):
        return doc, {}
    if isinstance(doc, dict):
        records = doc.get("records")
        if isinstance(records, list):
            context = {k: v for k, v in doc.items() if k != "records"}
            return records, context
    raise SystemExit("unrecognized ledger document shape")


def render_context(context: dict) -> str:
    parts = []
    for key in ("authority", "threshold_clock_round", "highest_round",
                "last_decided", "recorded", "dropped"):
        if key in context:
            parts.append(f"{key}={context[key]}")
    undecided = context.get("undecided")
    if undecided:
        parts.append(f"undecided={','.join(undecided)}")
    return "  ".join(parts)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="commit_explain", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("slots", nargs="*",
                        help="leader slots to explain: A3R42 (authority 3, "
                        "round 42) or the ledger's letter form D42; "
                        "empty = the last --last")
    parser.add_argument("--url", default=None,
                        help="node metrics endpoint (reads /debug/consensus)")
    parser.add_argument("--file", default=None,
                        help="saved /debug/consensus document or a dumped "
                        "record list")
    parser.add_argument("--last", type=int, default=10,
                        help="with no slots named: explain the newest N "
                        "records (default 10)")
    parser.add_argument("--skips", action="store_true",
                        help="restrict the no-slots listing to skips")
    parser.add_argument("--timeout", type=float, default=5.0)
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.file):
        parser.error("need exactly one of --url or --file")

    slots: List[tuple] = []
    for text in args.slots:
        slot = parse_slot(text)
        if slot is None:
            parser.error(f"not a slot name: {text!r} (expected e.g. A3R42)")
        slots.append(slot)

    records, context = load_records(args)
    header = render_context(context)
    if header:
        print(f"# {header}")

    missing = 0
    if slots:
        for authority, round_ in slots:
            record = next(
                (
                    r
                    for r in reversed(records)
                    if r.get("authority") == authority
                    and r.get("round") == round_
                ),
                None,
            )
            if record is None:
                print(f"slot A{authority}R{round_}: no record in the ledger "
                      "window (undecided, pre-ring, or rolled off)")
                missing += 1
            else:
                print(explain_record(record))
    else:
        chosen = [
            r for r in records
            if not args.skips or r.get("outcome") == "skip"
        ][-args.last:]
        if not chosen:
            print("no matching records in the ledger window")
        for record in chosen:
            print(explain_record(record))
    return 1 if missing else 0


if __name__ == "__main__":
    raise SystemExit(main())
