#!/usr/bin/env python3
"""Same-box back-to-back A/B of the broadcast-once mesh data plane (r10).

Runs the in-process 4-node testbed twice over real localhost TCP sockets —
first with ``MYSTICETI_MESH_LEGACY=1`` (the pre-r10 path: per-peer encode,
per-frame write+drain, StreamReader receive), then with the broadcast-once
plane (encode-once fan-out, scatter-gather coalescing, zero-copy receive).
Same box, same duration, tag-12 timestamp frames and span tracing on for
both runs.  Writes one JSON artifact (default ``MESH_r10.json``) carrying
the acceptance evidence:

* per-node committed leaders — the mesh run must be >= the baseline;
* ``dissemination_encode_reuse_total`` — must be > 0 on every node;
* mesh encode CPU (``utilization_timer{net:mesh_encode}``, normalized per
  committed leader) — must be measurably reduced;
* the PR 9 critical-path stage p50s for ``receive``/``transit`` from the
  traces — must be no worse than baseline;
* the mesh-serialization microbench rung (tools/node_bench.py) embedded
  for context.

Usage: JAX_PLATFORMS=cpu python tools/mesh_ab.py --duration 60 --out MESH_r10.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


async def run_mode(mode: str, working_dir: str, committee_size: int,
                   duration_s: float, tps: int, verifier: str) -> dict:
    """One testbed run; returns per-node rows + the trace path."""
    from mysticeti_tpu import spans
    from mysticeti_tpu.cli import benchmark_genesis
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.config import Parameters, PrivateConfig
    from mysticeti_tpu.validator import Validator

    os.makedirs(working_dir, exist_ok=True)
    trace_path = os.path.join(working_dir, f"trace-{mode}.json")
    if mode == "legacy":
        os.environ["MYSTICETI_MESH_LEGACY"] = "1"
    else:
        os.environ.pop("MYSTICETI_MESH_LEGACY", None)
    os.environ["MYSTICETI_TRACE"] = trace_path
    os.environ["TPS"] = str(tps)
    os.environ["INITIAL_DELAY"] = "1"

    spans.start_from_env()
    try:
        ips = ["127.0.0.1"] * committee_size
        benchmark_genesis(ips, working_dir)
        committee = Committee.load(os.path.join(working_dir, "committee.yaml"))
        parameters = Parameters.load(
            os.path.join(working_dir, "parameters.yaml")
        )
        # Tag-12 stamps feed the per-link transit stage the critical-path
        # comparison reads.
        parameters.synchronizer.timestamp_frames = True
        signers = Committee.benchmark_signers(committee_size)
        validators = []
        for i in range(committee_size):
            private = PrivateConfig.new_in_dir(
                i, os.path.join(working_dir, f"validator-{i}")
            )
            validators.append(
                await Validator.start_benchmarking(
                    i, committee, parameters, private, signer=signers[i],
                    serve_metrics_endpoint=False, verifier=verifier,
                )
            )
        await asyncio.sleep(duration_s)
        nodes = []
        for i, v in enumerate(validators):
            m = v.metrics

            def timer_us(proc):
                return int(
                    m.utilization_timer_us.labels(proc)._value.get()
                )

            nodes.append({
                "authority": i,
                "committed_leaders": len(v.committed_leaders()),
                "encode_reuse_total": int(
                    m.dissemination_encode_reuse_total._value.get()
                ),
                "mesh_encode_us": timer_us("net:mesh_encode"),
                "net_decode_us": timer_us("net:decode"),
                "frames_coalesced_total": int(
                    m.mesh_frames_coalesced_total._value.get()
                ),
                "wire_bytes_sent": int(
                    m.mesh_wire_bytes_total.labels("sent")._value.get()
                ),
                "wire_bytes_received": int(
                    m.mesh_wire_bytes_total.labels("received")._value.get()
                ),
            })
        for v in validators:
            await v.stop()
    finally:
        spans.stop_from_env()
        os.environ.pop("MYSTICETI_MESH_LEGACY", None)
    return {"mode": mode, "trace": trace_path, "nodes": nodes}


def stage_p50s(trace_path: str, stages=("receive", "transit")) -> dict:
    """Per-stage p50 duration (µs) across every block chain in the trace —
    the same extraction rule trace_report --critical-path uses."""
    from mysticeti_tpu import spans

    events, note, _ = spans.load_trace_events(trace_path)
    chains = spans.stage_chains(spans.complete_spans(events), tuple(stages))
    durs = {s: [] for s in stages}
    for stage_map in chains.values():
        for stage, (_ts, dur) in stage_map.items():
            durs[stage].append(dur)
    out = {}
    for stage, values in durs.items():
        out[stage] = {
            "n": len(values),
            "p50_us": int(statistics.median(values)) if values else None,
        }
    if note:
        out["note"] = note
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--committee", type=int, default=4)
    parser.add_argument("--duration", type=float, default=60.0)
    parser.add_argument("--tps", type=int, default=300,
                        help="per-node offered load (tx/s)")
    parser.add_argument("--verifier", default="cpu")
    parser.add_argument("--workdir", default="/tmp/mysticeti-mesh-ab")
    parser.add_argument("--out", default="MESH_r10.json")
    parser.add_argument(
        "--repeats", type=int, default=2,
        help="testbed runs per mode, ABBA-interleaved (legacy, mesh, mesh, "
        "legacy, ...) so same-box drift cancels; a single same-box run "
        "varies by ~10%% on committed leaders, larger than the effect "
        "under test",
    )
    args = parser.parse_args()

    # ABBA interleave: pairs of (legacy, mesh) alternating which goes
    # first, so thermal/contention drift hits both modes symmetrically.
    schedule = []
    for i in range(args.repeats):
        pair = ["legacy", "mesh"] if i % 2 == 0 else ["mesh", "legacy"]
        schedule += [(mode, i) for mode in pair]
    runs = {"legacy": [], "mesh": []}
    for mode, rep in schedule:
        print(
            f"running {mode} testbed rep {rep} ({args.duration:.0f}s)...",
            flush=True,
        )
        run = asyncio.run(
            run_mode(
                mode, os.path.join(args.workdir, f"{mode}-{rep}"),
                args.committee, args.duration, args.tps, args.verifier,
            )
        )
        print(json.dumps(run["nodes"], indent=2), flush=True)
        runs[mode].append(run)

    critical_path = {
        mode: [stage_p50s(run["trace"]) for run in mode_runs]
        for mode, mode_runs in runs.items()
    }

    def mean_leaders(mode):
        per_run = [
            statistics.mean(n["committed_leaders"] for n in run["nodes"])
            for run in runs[mode]
        ]
        return round(statistics.mean(per_run), 1)

    def encode_us_per_leader(mode):
        leaders = sum(
            n["committed_leaders"] for run in runs[mode] for n in run["nodes"]
        )
        encode = sum(
            n["mesh_encode_us"] for run in runs[mode] for n in run["nodes"]
        )
        return round(encode / max(1, leaders), 2)

    def mean_p50(mode, stage):
        values = [
            rep[stage]["p50_us"]
            for rep in critical_path[mode]
            if rep.get(stage, {}).get("p50_us")
        ]
        return int(statistics.mean(values)) if values else None

    comparison = {
        "committed_leaders_mean": {m: mean_leaders(m) for m in runs},
        "committed_leaders": {
            m: [
                [n["committed_leaders"] for n in run["nodes"]]
                for run in runs[m]
            ]
            for m in runs
        },
        "encode_reuse_total": {
            m: [
                [n["encode_reuse_total"] for n in run["nodes"]]
                for run in runs[m]
            ]
            for m in runs
        },
        "mesh_encode_us_per_leader": {
            m: encode_us_per_leader(m) for m in runs
        },
        "critical_path_p50_us": {
            m: {
                stage: mean_p50(m, stage)
                for stage in ("receive", "transit")
            }
            for m in runs
        },
        "critical_path_per_run": critical_path,
    }

    def p50_not_worse(stage):
        legacy_p50 = comparison["critical_path_p50_us"]["legacy"][stage]
        mesh_p50 = comparison["critical_path_p50_us"]["mesh"][stage]
        if not legacy_p50 or not mesh_p50:
            return True  # stage absent in one trace: nothing to compare
        return mesh_p50 <= legacy_p50 * 1.1  # no worse (10% noise band)

    acceptance = {
        "committed_leaders_not_worse": (
            comparison["committed_leaders_mean"]["mesh"]
            >= comparison["committed_leaders_mean"]["legacy"]
        ),
        "encode_reuse_on_every_node": all(
            n["encode_reuse_total"] > 0
            for run in runs["mesh"]
            for n in run["nodes"]
        ),
        "encode_cpu_reduced": (
            comparison["mesh_encode_us_per_leader"]["mesh"]
            < comparison["mesh_encode_us_per_leader"]["legacy"]
        ),
        "receive_p50_not_worse": p50_not_worse("receive"),
        "transit_p50_not_worse": p50_not_worse("transit"),
    }

    from node_bench import mesh_serialization

    artifact = {
        "metric": "mesh_broadcast_once_ab",
        "committee": args.committee,
        "duration_s": args.duration,
        "tps_per_node": args.tps,
        "verifier": args.verifier,
        "note": (
            "same-box back-to-back: legacy = MYSTICETI_MESH_LEGACY=1 "
            "(per-peer encode, per-frame write+drain, stream receive); "
            "mesh = encode-once fan-out + scatter-gather coalescing + "
            "zero-copy receive.  Wire bytes are counted by the mesh mode "
            "only (the legacy path predates the counters); frames are "
            "byte-identical by the golden-corpus test."
        ),
        "runs": runs,
        "comparison": comparison,
        "acceptance": acceptance,
        "microbench": mesh_serialization(),
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    print(json.dumps(acceptance, indent=2))
    return 0 if all(acceptance.values()) else 3


if __name__ == "__main__":
    sys.exit(main())
