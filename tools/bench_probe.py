#!/usr/bin/env python3
"""Decompose the e2e bench into its serial components on the live device:
null RTT, host->device bandwidth, kernel-only time, and the current e2e
number — the measurement discipline that separates chip weather from real
regressions (see BENCH_SAMPLES_r02.json).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from mysticeti_tpu.ops import ed25519 as E

    out = {"device": jax.devices()[0].platform}

    # 1. null RTT: tiny jitted op, block on result
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(f(x))
    out["null_rtt_ms"] = round((time.perf_counter() - t0) / 5 * 1e3, 1)

    # 2. h->d bandwidth: 8 MB transfer forced by a reduction fetch
    big = np.random.randint(0, 2**31, size=(2 * 1024 * 1024,), dtype=np.int32)
    g = jax.jit(lambda x: x.sum())
    np.asarray(g(jnp.asarray(big)))
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(g(jnp.asarray(big)))
    dt = (time.perf_counter() - t0) / 3
    out["h2d_MBps"] = round(big.nbytes / dt / 1e6, 1)

    # 3. kernel-only: batch resident on device, dispatch N, block on last
    # (same batch construction as the headline bench — single source)
    from bench import _build_batch

    batch = int(os.environ.get("BENCH_BATCH", "16384"))
    table, pks, msgs, sigs = _build_batch(batch, seed=0)
    idx = table.indices_for(pks)
    blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
    dev_blob = jnp.asarray(blob)
    h = E._dispatch_indexed(dev_blob, table.words)
    np.asarray(h)  # warm
    iters = 8
    t0 = time.perf_counter()
    hs = [E._dispatch_indexed(dev_blob, table.words) for _ in range(iters)]
    np.asarray(hs[-1])
    dt = time.perf_counter() - t0
    out["kernel_ms_per_batch"] = round(dt / iters * 1e3, 1)
    out["kernel_only_sig_s"] = round(batch * iters / dt, 0)
    out["blob_bytes_per_batch"] = int(blob.nbytes)

    # 4. transfer+kernel serial estimate vs measured e2e (one bench trial)
    t0 = time.perf_counter()
    handles = []
    for _ in range(16):
        i2 = table.indices_for(pks)
        b2 = E.pack_blob_indexed(i2, msgs, sigs, num_keys=len(table))
        handles.extend(E.dispatch_indexed_chunks(b2, table))
    res = E.fetch_handles(handles)
    dt = time.perf_counter() - t0
    assert res.all()
    out["e2e_sig_s_16iters"] = round(batch * 16 / dt, 0)
    out["e2e_ms_per_batch"] = round(dt / 16 * 1e3, 1)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
