#!/usr/bin/env python3
"""Multi-chip sharding scaling curve over virtual device meshes.

Extends the driver's one-shot ``dryrun_multichip`` into a measured curve
(VERDICT r4 #8): for each mesh size, the SAME fixed global batch is sharded
over an n-device ``jax.sharding.Mesh`` through the deployed committee-
indexed path (``parallel/mesh.py:sharded_verify_batch_indexed``), asserting
per-shard shapes and the psum'd global valid count, and timing the jitted
step.  Each mesh size runs in its own subprocess because XLA parses the
virtual-device-count flag once per process.

HONESTY NOTE (recorded in the artifact): virtual CPU devices share this
host's single physical core, so wall-clock here measures shard_map +
collective LOWERING overhead at fixed total work — flat-or-slowly-rising
wall time with correct psum totals is the pass criterion, NOT a speedup
claim.  On a real TPU slice the same code path shards over ICI; run with
``--real`` on multi-chip hardware to measure actual scaling (bench.py
accepts BENCH_MESH=N for the same thing fleet-shaped).

Usage:
  python tools/mesh_scaling.py --out MULTICHIP_SCALING_r05.json
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", os.environ.get("MESH_PLATFORM", "cpu"))
import random
from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
from mysticeti_tpu.ops import ed25519 as E
from mysticeti_tpu.parallel.mesh import make_mesh, sharded_verify_batch_indexed

n = int(os.environ["MESH_DEVICES"])
batch = int(os.environ["MESH_BATCH"])
devices = jax.devices()
assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
mesh = make_mesh(n, devices=devices[:n])

rng = random.Random(5)
keys = [
    Ed25519PrivateKey.from_private_bytes(bytes(rng.randrange(256) for _ in range(32)))
    for _ in range(16)
]
table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
pks, msgs, sigs = [], [], []
for i in range(batch):
    k = keys[i % 16]
    m = bytes(rng.randrange(256) for _ in range(32))
    pks.append(k.public_key().public_bytes_raw())
    msgs.append(m)
    sigs.append(k.sign(m))

# Warm/compile, and the correctness assertions the dryrun makes.
ok, total = sharded_verify_batch_indexed(mesh, table, pks, msgs, sigs)
ok = np.asarray(ok)
assert ok.shape == (batch,), ok.shape
assert ok.all(), "all signatures must verify"
assert int(total) == batch, f"psum'd valid count {total} != {batch}"

iters = 3
t0 = time.perf_counter()
for _ in range(iters):
    ok, total = sharded_verify_batch_indexed(mesh, table, pks, msgs, sigs)
    assert int(total) == batch
elapsed = (time.perf_counter() - t0) / iters
print(json.dumps({
    "devices": n,
    "global_batch": batch,
    "per_shard_batch": batch // n,
    "psum_total_ok": True,
    "step_s": round(elapsed, 4),
    "sig_per_s": round(batch / elapsed, 1),
}))
"""


def run_point(n: int, batch: int, real: bool) -> dict:
    env = dict(os.environ)
    env["MESH_DEVICES"] = str(n)
    env["MESH_BATCH"] = str(batch)
    if not real:
        env["MESH_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    out = subprocess.run(
        [sys.executable, "-c", CHILD],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"mesh point n={n} failed:\n{out.stderr[-2000:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=int, nargs="+", default=[1, 2, 4, 8])
    parser.add_argument("--batch", type=int, default=4096)
    parser.add_argument("--real", action="store_true",
                        help="use the real attached devices (TPU slice) "
                        "instead of virtual CPU devices")
    parser.add_argument("--out", default="MULTICHIP_SCALING.json")
    args = parser.parse_args()

    points = []
    for n in args.sizes:
        print(f"mesh point: {n} device(s), global batch {args.batch}...",
              flush=True)
        point = run_point(n, args.batch, args.real)
        points.append(point)
        print(json.dumps(point), flush=True)

    artifact = {
        "metric": "sharded_verify_scaling_curve",
        "config": {
            "path": "parallel/mesh.py:sharded_verify_batch_indexed "
                    "(committee-indexed blob, batch-axis sharding, psum "
                    "valid-count reduction)",
            "global_batch_fixed": args.batch,
            "platform": "real devices" if args.real else
                        "virtual CPU devices (one physical core)",
            "note": (
                "Virtual-device points validate shard_map lowering, "
                "per-shard shapes and psum totals at fixed global work; "
                "they share one physical core, so step_s measures "
                "partitioning overhead, not speedup.  On a real slice the "
                "same code path shards over ICI (run with --real, or "
                "BENCH_MESH=N through bench.py)."
            ),
        },
        "points": points,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
