#!/usr/bin/env python3
"""Storage lifecycle acceptance probe -> STORAGE_r08.json.

Two deterministic sims on the virtual-time loop (no accelerator, no real
network), exercising the storage plane end-to-end at the scale the
acceptance criteria name:

1. **bounded-disk**: a 4-node fleet under load with small segments,
   checkpoints every few commits, and an aggressive GC depth; one node
   crash-restarts mid-run.  Evidence: segments below the GC round are
   deleted while the fleet keeps committing (live bytes << lifetime bytes
   written), and the restarted node boots from a checkpoint, replaying only
   post-checkpoint segments (replay bytes << lifetime WAL bytes).

2. **snapshot-catchup**: a node is absent for >= 1000 rounds (its history
   GC'd fleet-wide, so block-by-block pull from round zero is impossible),
   rejoins via the snapshot stream (wire tags 9/10), and commits the same
   leader sequence as the fleet — asserted at every shared height, plus the
   adopted anchor.  Catch-up wall-clock (virtual and host), blocks, and
   bytes are recorded.

Usage::

    python tools/storage_probe.py [--out STORAGE_r08.json] [--quick]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim  # noqa: E402
from mysticeti_tpu.config import Parameters, StorageParameters  # noqa: E402


def bounded_disk_scenario(quick: bool) -> dict:
    duration = 20.0 if quick else 60.0
    parameters = Parameters(
        leader_timeout_s=1.0,
        storage=StorageParameters(
            segment_bytes=16 * 1024, checkpoint_interval=10, gc_depth=30
        ),
    )
    plan = FaultPlan(
        seed=8,
        crashes=[CrashFault(node=2, at_s=duration * 0.6, downtime_s=2.0)],
    )
    wal_dir = tempfile.mkdtemp(prefix="storage-probe-disk-")
    t0 = time.monotonic()
    report, harness = run_chaos_sim(
        plan, 4, duration, wal_dir, parameters=parameters, with_metrics=True
    )
    wall = time.monotonic() - t0
    nodes = {}
    for authority in range(4):
        node = harness.nodes[authority]
        writer = node.core.wal_writer
        metrics = harness.metrics[authority]
        nodes[str(authority)] = {
            "commit_height": harness.committed_height(authority),
            "lifetime_wal_bytes": writer.position(),
            "live_wal_bytes": writer.size_bytes(),
            "live_segments": writer.segment_count(),
            "first_live_offset": writer.first_base(),
            "reclaimed_bytes": metrics.wal_reclaimed_bytes_total._value.get(),
            "last_checkpoint_height": metrics.checkpoint_last_commit_index._value.get(),
            "retired_round": node.core.storage.retired_round,
        }
    restarted = harness.nodes[2].core.storage
    lifetime = harness.nodes[2].core.wal_writer.position()
    result = {
        "virtual_duration_s": duration,
        "wall_s": round(wall, 2),
        "nodes": nodes,
        "restart": {
            "node": 2,
            "recovered_checkpoint_height": restarted.recovered_checkpoint_height,
            "replay_start": restarted.replay_start,
            "replayed_bytes": restarted.replayed_bytes,
            "lifetime_wal_bytes": lifetime,
            "replay_fraction": round(restarted.replayed_bytes / lifetime, 4),
        },
    }
    # Acceptance: disk bounded + checkpoint boot replays only the tail.
    assert all(n["reclaimed_bytes"] > 0 for n in nodes.values()), "GC never ran"
    assert all(
        n["live_wal_bytes"] < n["lifetime_wal_bytes"] for n in nodes.values()
    ), "disk not bounded"
    assert restarted.recovered_checkpoint_height > 0, "restart missed the checkpoint"
    assert restarted.replayed_bytes * 5 < lifetime, "replay not << lifetime bytes"
    result["pass"] = True
    return result


def snapshot_catchup_scenario(quick: bool) -> dict:
    # With one node down, every 4th round waits out the 1 s leader timeout,
    # so rounds advance ~2.2/s of virtual time; >= 1000 rounds of absence
    # needs ~480 virtual seconds of downtime.
    downtime = 60.0 if quick else 480.0
    duration = downtime + 60.0
    parameters = Parameters(
        leader_timeout_s=1.0,
        storage=StorageParameters(
            segment_bytes=32 * 1024,
            checkpoint_interval=10,
            gc_depth=40,
            snapshot_catchup=True,
            catchup_threshold_commits=60,
        ),
    )
    plan = FaultPlan(
        seed=21, crashes=[CrashFault(node=3, at_s=4.0, downtime_s=downtime)]
    )
    wal_dir = tempfile.mkdtemp(prefix="storage-probe-catchup-")
    t0 = time.monotonic()
    report, harness = run_chaos_sim(
        plan, 4, duration, wal_dir, parameters=parameters, with_metrics=True
    )
    wall = time.monotonic() - t0
    node3 = harness.nodes[3]
    lifecycle = node3.core.storage
    crash_event = report.crash_events[0]
    anchors_fleet = harness.checker._anchors[0]
    anchors_rejoined = harness.checker._anchors[3]
    rejoined_heights = sorted(anchors_rejoined)
    crashed_at = crash_event["committed_height"]
    resumed_at = min(h for h in rejoined_heights if h > crashed_at)
    shared = sorted(set(anchors_fleet) & set(anchors_rejoined))
    mismatches = [h for h in shared if anchors_fleet[h] != anchors_rejoined[h]]
    served_blocks = sum(
        harness.nodes[a].snapshot_blocks_served
        + sum(
            d.snapshot_blocks_sent
            for d in harness.nodes[a]._disseminators.values()
        )
        for a in range(4)
        if harness.nodes[a] is not None
    )
    served_bytes = sum(
        harness.nodes[a].snapshot_bytes_served
        + sum(
            d.snapshot_bytes_sent
            for d in harness.nodes[a]._disseminators.values()
        )
        for a in range(4)
        if harness.nodes[a] is not None
    )
    adopted_leader_round = (
        lifecycle.last_committed_leader.round
        if lifecycle.last_committed_leader
        else 0
    )
    # Rounds absent, measured on the committed-anchor ROUNDS themselves:
    # first anchor committed after rejoining minus last anchor committed
    # before the crash.
    rounds_absent = (
        anchors_rejoined[resumed_at].round
        - anchors_rejoined[crashed_at].round
    )
    result = {
        "virtual_duration_s": duration,
        "downtime_s": downtime,
        "wall_s": round(wall, 2),
        "crashed_at_height": crashed_at,
        "resumed_at_height": resumed_at,
        "adopted_heights_skipped": resumed_at - crashed_at - 1,
        "rounds_absent": rounds_absent,
        "final_heights": {
            str(a): harness.committed_height(a) for a in range(4)
        },
        "snapshots_adopted": lifecycle.snapshots_adopted,
        "adopted_floor_round": lifecycle.retired_round,
        "adopted_leader_round": adopted_leader_round,
        "snapshot_blocks_served": served_blocks,
        "snapshot_bytes_served": served_bytes,
        "shared_heights_checked": len(shared),
        "prefix_mismatches": len(mismatches),
    }
    assert lifecycle.snapshots_adopted >= 1, "snapshot never adopted"
    assert result["rounds_absent"] >= (
        100 if quick else 1000
    ), f"absence too short: {result['rounds_absent']}"
    assert not mismatches, f"prefix divergence at heights {mismatches[:5]}"
    assert harness.committed_height(3) > resumed_at + 20, "rejoined node stalled"
    result["pass"] = True
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="STORAGE_r08.json")
    parser.add_argument("--quick", action="store_true",
                        help="shortened scenarios (smoke, not acceptance)")
    args = parser.parse_args(argv)
    artifact = {
        "probe": "storage-lifecycle",
        "revision": "r08",
        "quick": bool(args.quick),
        "config_defaults": {
            "segment_bytes": StorageParameters().segment_bytes,
            "checkpoint_interval": StorageParameters().checkpoint_interval,
            "gc_depth": StorageParameters().gc_depth,
        },
    }
    print("== bounded-disk scenario ==", flush=True)
    artifact["bounded_disk"] = bounded_disk_scenario(args.quick)
    print(json.dumps(artifact["bounded_disk"], indent=1))
    print("== snapshot catch-up scenario ==", flush=True)
    artifact["snapshot_catchup"] = snapshot_catchup_scenario(args.quick)
    print(json.dumps(artifact["snapshot_catchup"], indent=1))
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
