#!/usr/bin/env python3
"""Crash-recovery catch-up race per verifier backend.

A validator rejoining after downtime must verify its whole missed backlog —
deep, multi-author batches arriving as fast as peers can stream them.  This
is the fleet-level regime where signature verification (not the consensus
engine) binds, i.e. the regime BASELINE configs #4/#5 describe: the
threshold-aggregate verifier skips quorum-endorsed interior blocks
(crypto.rs:77-84's layering licenses the skip) and the TPU path batches the
frontier, while the CPU oracle pays ~125 µs per signature serially.

Reference anchors: crash-recovery faults (orchestrator/src/faults.rs:104-160),
WAL replay recovery (state.rs:23-95), the verifier seam
(block_validator.rs:10-14).

Measured per verifier {cpu, cpu-agg, tpu, tpu-agg}:
  * reboot_to_metrics_s       — process boot + WAL replay until /metrics serves
  * reboot_to_first_verify_s  — until the first peer block passes verification
    (for tpu flavors this includes the persistent-cache kernel load, the
    number VERDICT r3 item 3 asks to be recorded)
  * reboot_to_caught_up_s     — until the rebooted node's commit_round reaches
    the live fleet's (within MARGIN rounds)
  * catchup verification counters — direct vs aggregate-skipped

Usage:
  python tools/catchup_bench.py --verifiers cpu cpu-agg tpu-agg --down 45 \
      --out CATCHUP_r04.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MARGIN_ROUNDS = 20


def parse_metrics(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name_labels, _, rest = line.partition(" ")
        try:
            value = float(rest.split()[0])
        except ValueError:
            continue
        out[name_labels] = value
    return out


def metric(samples: dict, name: str, default=0.0) -> float:
    return samples.get(name, default)


def sig_counters(samples: dict) -> dict:
    direct = skipped = rejected = 0.0
    for key, value in samples.items():
        if not key.startswith("verified_signatures_total{"):
            continue
        if 'outcome="skipped"' in key:
            skipped += value
        elif 'outcome="rejected"' in key:
            rejected += value
        elif 'outcome="accepted"' in key:
            direct += value
    total = direct + skipped
    return {
        "direct": int(direct),
        "skipped": int(skipped),
        "rejected": int(rejected),
        "skip_frac": round(skipped / total, 3) if total else 0.0,
    }


async def scrape_parsed(runner, authority):
    text = await runner.scrape(authority)
    return parse_metrics(text) if text is not None else None


async def wait_for(predicate, timeout_s: float, interval_s: float = 0.5):
    """Poll an async predicate; returns (elapsed_s, value) or (None, None)."""
    started = time.monotonic()
    while time.monotonic() - started < timeout_s:
        value = await predicate()
        if value is not None:
            return time.monotonic() - started, value
        await asyncio.sleep(interval_s)
    return None, None


async def run_one(verifier: str, nodes: int, load: int, down_s: float,
                  workdir: str) -> dict:
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    # The shared verifier service made tpu warmup a non-event (the runner
    # blocks until the service is warm before booting nodes), so the load
    # delay no longer needs a tpu asymmetry.  Pinned (not setdefault) so an
    # ambient INITIAL_DELAY cannot skew one flavor's steady window.
    os.environ["INITIAL_DELAY"] = "1"
    runner = LocalProcessRunner(
        os.path.join(workdir, f"fleet-{verifier}"), verifier=verifier
    )
    result = {"verifier": verifier, "nodes": nodes,
              "offered_load_tx_s": load, "down_s": down_s}
    await runner.configure(nodes, load)
    for a in range(nodes):
        await runner.boot_node(a)

    # Steady state: commits flowing on node 0 (tpu flavors pay their one-time
    # warmup here, against the persistent compile cache).
    async def committing():
        m = await scrape_parsed(runner, 0)
        # Steady = consensus cadence AND transaction flow: opening the
        # window on commit_round alone can catch the pre-generator phase
        # (boot contention delays tx flow ~tens of seconds on a 1-core
        # host), recording steady_tps=0 for a fleet that is fine.
        if (
            m
            and metric(m, "commit_round") > 30
            and metric(m, 'latency_s_count{workload="shared"}') > 0
        ):
            return m
        return None

    elapsed, m0 = await wait_for(committing, timeout_s=300, interval_s=1.0)
    if m0 is None:
        await runner.cleanup()
        result["error"] = "fleet never reached steady commits"
        return result
    result["boot_to_steady_s"] = round(elapsed, 1)

    # Fleet commit cadence + tps over a short steady window.
    r_start = metric(m0, "commit_round")
    c_start = metric(m0, 'latency_s_count{workload="shared"}')
    window_t0 = time.monotonic()
    await asyncio.sleep(10)
    # A single transient scrape failure must not abort the whole verifier
    # run — retry briefly instead of calling metric(None, ...).
    _, m0 = await wait_for(
        lambda: scrape_parsed(runner, 0), timeout_s=30, interval_s=0.5
    )
    if m0 is None:
        await runner.cleanup()
        result["error"] = "steady-window scrape failed"
        return result
    # Divide by the MEASURED window: scrape retries can stretch it past the
    # nominal 10 s, and dividing by 10 would inflate the degraded runs.
    window_s = time.monotonic() - window_t0
    r_now = metric(m0, "commit_round")
    result["steady_rounds_per_s"] = round((r_now - r_start) / window_s, 1)
    result["steady_tps"] = round(
        (metric(m0, 'latency_s_count{workload="shared"}') - c_start)
        / window_s, 1
    )

    victim = nodes - 1
    await runner.kill_node(victim)
    round_at_kill = r_now
    await asyncio.sleep(down_s)
    _, m0 = await wait_for(
        lambda: scrape_parsed(runner, 0), timeout_s=30, interval_s=0.5
    )
    if m0 is None:
        await runner.cleanup()
        result["error"] = "reboot-backlog scrape failed"
        return result
    fleet_round_at_reboot = metric(m0, "commit_round")
    result["backlog_rounds"] = int(fleet_round_at_reboot - round_at_kill)

    t0 = time.monotonic()
    await runner.boot_node(victim)

    async def metrics_up():
        return await scrape_parsed(runner, victim)

    elapsed, mv = await wait_for(metrics_up, timeout_s=120, interval_s=0.25)
    result["reboot_to_metrics_s"] = (
        round(elapsed, 2) if elapsed is not None else None
    )

    async def first_verify():
        m = await scrape_parsed(runner, victim)
        if m is None:
            return None
        c = sig_counters(m)
        return c if (c["direct"] + c["skipped"]) > 0 else None

    elapsed, _ = await wait_for(first_verify, timeout_s=240, interval_s=0.25)
    result["reboot_to_first_verify_s"] = (
        round(time.monotonic() - t0, 2) if elapsed is not None else None
    )

    async def caught_up():
        mv = await scrape_parsed(runner, victim)
        m0 = await scrape_parsed(runner, 0)
        if mv is None or m0 is None:
            return None
        lead = metric(m0, "commit_round")
        own = metric(mv, "commit_round")
        if own > 0 and lead - own <= MARGIN_ROUNDS:
            return mv
        return None

    elapsed, mv = await wait_for(caught_up, timeout_s=600, interval_s=0.5)
    result["reboot_to_caught_up_s"] = (
        round(time.monotonic() - t0, 2) if elapsed is not None else None
    )
    if mv is not None:
        result["catchup_verification"] = sig_counters(mv)
    host = await runner.host_sample()
    if host is not None:
        result["host_after_recovery"] = {
            k: host[k] for k in ("cpu_pct", "load_1m") if k in host
        }
    # Fleet health snapshot at the finish line (health plane): the artifact
    # says whether the recovered fleet is actually green — participation,
    # stragglers, SLO alerts — not just that the victim's round caught up.
    from mysticeti_tpu.health import cluster_snapshot_from_texts

    texts = {}
    for authority in range(nodes):
        texts[str(authority)] = await runner.scrape(authority)
    result["health_after_recovery"] = cluster_snapshot_from_texts(texts, nodes)
    await runner.cleanup()
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    # 7 nodes so one crash leaves the fleet well above quorum (5): commits
    # keep pace during the downtime and a real backlog accumulates (4 nodes
    # minus one is EXACTLY quorum — the fleet crawls and there is nothing to
    # catch up on).  More than ~7 JAX client processes thrash this 1-core
    # host for the tpu flavors.
    parser.add_argument("--nodes", type=int, default=7)
    parser.add_argument("--load", type=int, default=3200)
    parser.add_argument("--down", type=float, default=45.0)
    parser.add_argument("--workdir", default="/tmp/mysticeti-catchup")
    parser.add_argument("--out", default="CATCHUP.json")
    parser.add_argument("--max-block-tx", type=int, default=16)
    parser.add_argument(
        "--verifiers", nargs="+", default=["cpu", "cpu-agg", "tpu-agg"],
        choices=["accept", "cpu", "tpu", "tpu-only", "cpu-agg", "tpu-agg"],
    )
    args = parser.parse_args()

    # Genesis-time + node env: small blocks raise the block (= signature)
    # rate, and the retain window must cover the whole downtime's rounds or
    # peers prune the backlog the victim needs to fetch.
    os.environ["MYSTICETI_MAX_BLOCK_TX"] = str(args.max_block_tx)
    os.environ["MYSTICETI_RETAIN_ROUNDS"] = "100000"
    os.environ["MYSTICETI_LEADER_TIMEOUT"] = "0.25"

    if any(v.startswith("tpu") for v in args.verifiers):
        # Keys via mysticeti_tpu.crypto (pure-Python RFC 8032 fallback):
        # hosts without the `cryptography` package still prewarm.
        print("prewarming kernel cache...", flush=True)
        from mysticeti_tpu import crypto
        from mysticeti_tpu.block_validator import TpuSignatureVerifier

        signers = [
            crypto.Signer.from_seed(bytes([i] * 32))
            for i in range(args.nodes)
        ]
        TpuSignatureVerifier(
            committee_keys=[s.public_key.bytes for s in signers]
        ).warmup()

    runs = []
    for verifier in args.verifiers:
        print(f"catch-up race verifier={verifier}...", flush=True)
        run = asyncio.run(
            run_one(verifier, args.nodes, args.load, args.down, args.workdir)
        )
        runs.append(run)
        print(json.dumps(run), flush=True)

    artifact = {
        "metric": "crash_recovery_catchup_by_verifier",
        "config": {
            "nodes": args.nodes,
            "offered_load_tx_s": args.load,
            "down_s": args.down,
            "max_block_tx": args.max_block_tx,
            "note": (
                "A rebooted validator must verify its missed backlog in deep"
                " multi-author batches — the fleet-level regime where"
                " signature verification binds (BASELINE #4/#5). Caught-up ="
                f" commit_round within {MARGIN_ROUNDS} rounds of the live"
                " fleet."
            ),
        },
        "host": "single-core CI box; TPU via ~100 ms-RTT tunnel",
        "runs": runs,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
