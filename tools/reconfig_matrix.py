#!/usr/bin/env python3
"""Epoch-reconfiguration probe -> RECONFIG_r18.json.

Three legs, pinned into the ``RECONFIG_rNN.json`` artifact family consumed
by ``tools/bench_trend.py``:

* **continuous-churn matrix** — the reconfig scenario family
  (mysticeti_tpu/scenarios.py::reconfig_matrix): seeded 10-node sims with
  live adversaries where the committee reweights, a registered-at-zero
  authority joins via snapshot catch-up, and a member departs — every
  honest node must agree on each epoch boundary (height, digest), joiners
  must commit, and throughput must hold against the same-churn clean twin;
* **determinism** — the continuous-churn scenario re-run on the same seed
  must be byte-identical (schedule / attack / detection / sequence
  digests) across BOTH epoch boundaries;
* **live** — a real-socket 4-node localhost testbed under generator load
  performs one add-node epoch (a registered stake-0 validator is activated
  by a committed change, then boots and catches up) and one remove-node
  epoch (an active member is deactivated and retired); every surviving
  validator must land on epoch 2 with prefix-consistent commits.

Usage::

    python tools/reconfig_matrix.py [--out RECONFIG_r18.json] [--quick]
"""
from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.reconfig import (  # noqa: E402
    CHANGE_ADD,
    CHANGE_REMOVE,
    CommitteeChange,
)
from mysticeti_tpu.scenarios import (  # noqa: E402
    reconfig_matrix,
    run_reconfig_matrix,
    run_scenario,
    scenario_by_name,
)


def determinism_leg(name: str) -> dict:
    """Same churn scenario, same seed, twice: digests must be identical."""
    scenario = scenario_by_name(name)
    digests = []
    for _ in range(2):
        with tempfile.TemporaryDirectory(prefix="reconfig-det-") as root:
            verdict = run_scenario(scenario, root)
        digests.append(verdict["digests"])
    return {
        "scenario": name,
        "runs": digests,
        "byte_identical": digests[0] == digests[1],
    }


# ---------------------------------------------------------------------------
# Live testbed leg: one add-node and one remove-node epoch under load


async def _live_epoch_cycle(working_dir: str, tps: int) -> dict:
    from mysticeti_tpu.cli import benchmark_genesis
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.config import Parameters, PrivateConfig
    from mysticeti_tpu.validator import Validator

    n = 4
    add_authority, remove_authority = 3, 2
    benchmark_genesis(["127.0.0.1"] * n, working_dir)
    registry = Committee.load(os.path.join(working_dir, "committee.yaml"))
    # Stable-index registration: validator 3's key is in the genesis
    # registry at stake 0 — the ADD activates it, no key onboarding.
    genesis = registry.with_stakes([1, 1, 1, 0], 0)
    parameters = Parameters.load(os.path.join(working_dir, "parameters.yaml"))
    parameters.reconfig = True
    signers = Committee.benchmark_signers(n)

    async def boot(i: int) -> Validator:
        private = PrivateConfig.new_in_dir(
            i, os.path.join(working_dir, f"validator-{i}")
        )
        return await Validator.start_benchmarking(
            i, genesis, parameters, private,
            signer=signers[i], tps=tps, serve_metrics_endpoint=False,
        )

    validators: dict[int, Validator] = {}
    commits: dict[int, list] = {}

    def epoch_of(i: int) -> int:
        core = validators[i].core
        return core.reconfig.epoch if core.reconfig is not None else 0

    try:
        for i in range(n):
            if i != add_authority:
                validators[i] = await boot(i)
        await asyncio.sleep(6.0)  # generator warm-up + steady commits

        # Epoch 1: activate the registered stake-0 validator.  The change
        # rides validator 0's next proposal as an ordinary Share.
        validators[0].core.block_handler.submit(
            [CommitteeChange(CHANGE_ADD, add_authority, 1).to_bytes()]
        )
        await asyncio.sleep(3.0)
        # The joiner boots from an empty WAL and catches up block-by-block,
        # re-deriving the boundary from the committed sequence itself.
        validators[add_authority] = await boot(add_authority)
        await asyncio.sleep(6.0)

        # Epoch 2: deactivate an active member, then retire its process.
        validators[0].core.block_handler.submit(
            [CommitteeChange(CHANGE_REMOVE, remove_authority).to_bytes()]
        )
        await asyncio.sleep(5.0)
        removed_epoch = epoch_of(remove_authority)
        commits[remove_authority] = validators[remove_authority].committed_leaders()
        await validators[remove_authority].stop()
        departed = validators.pop(remove_authority)
        del departed
        await asyncio.sleep(5.0)

        epochs = {i: epoch_of(i) for i in sorted(validators)}
        epochs[remove_authority] = removed_epoch
        for i in sorted(validators):
            commits[i] = validators[i].committed_leaders()
    finally:
        for v in validators.values():
            await v.stop()

    survivors = [i for i in range(n) if i != remove_authority]
    sequences = {i: commits.get(i, []) for i in commits}
    longest = max(sequences.values(), key=len, default=[])
    prefix_ok = all(seq == longest[: len(seq)] for seq in sequences.values())
    joiner_commits = len(sequences.get(add_authority, []))
    epochs_reached = min(epochs.get(i, 0) for i in survivors)
    passed = (
        epochs_reached >= 2
        and epochs.get(remove_authority, 0) >= 1
        and joiner_commits > 0
        and prefix_ok
    )
    return {
        "passed": passed,
        "nodes": n,
        "epochs_reached": epochs_reached,
        "epochs": {str(i): e for i, e in sorted(epochs.items())},
        "add_authority": add_authority,
        "remove_authority": remove_authority,
        "joiner_commits": joiner_commits,
        "commits": {str(i): len(seq) for i, seq in sorted(sequences.items())},
        "prefix_consistent": prefix_ok,
        "tps": tps,
    }


def live_leg(tps: int) -> dict:
    with tempfile.TemporaryDirectory(prefix="reconfig-live-") as root:
        return asyncio.run(_live_epoch_cycle(root, tps))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="RECONFIG_r18.json")
    parser.add_argument("--quick", action="store_true",
                        help="shortened scenarios (smoke, not acceptance: "
                        "short runs may not reach every min_epoch gate)")
    parser.add_argument("--scenario", default=None,
                        help="run only this named scenario")
    parser.add_argument("--no-matrix", action="store_true",
                        help="skip the scenario matrix (run only the other "
                        "legs; a wrapper merges the per-leg documents when "
                        "one wall-clock budget cannot fit all three)")
    parser.add_argument("--no-determinism", action="store_true",
                        help="skip the same-seed re-run leg")
    parser.add_argument("--no-live", action="store_true",
                        help="skip the real-socket 4-node testbed leg")
    parser.add_argument("--tps", type=int, default=20,
                        help="per-validator generator load for the live leg")
    parser.add_argument("--real-crypto", action="store_true",
                        help="genuine per-node Ed25519 verification instead "
                        "of the sim re-sign oracle")
    args = parser.parse_args(argv)

    scenarios = reconfig_matrix()
    if args.scenario:
        scenarios = [scenario_by_name(args.scenario)]
    if args.quick:
        scenarios = [
            dataclasses.replace(s, duration_s=min(s.duration_s, 12.0))
            for s in scenarios
        ]
    t0 = time.monotonic()
    if args.no_matrix:
        doc = {
            "kind": "mysticeti-reconfig-matrix",
            "metric": "reconfig",
            "scenarios": [],
            "passed": 0,
            "failed": 0,
            "all_pass": True,
        }
    else:
        doc = run_reconfig_matrix(scenarios, real_crypto=args.real_crypto)
    doc.update(
        probe="epoch-reconfig-matrix",
        revision="r18",
        quick=bool(args.quick),
    )
    for verdict in doc["scenarios"]:
        name = verdict["scenario"]["name"]
        print(
            f"{name:<32} {'PASS' if verdict['passed'] else 'FAIL'}  "
            f"ratio={verdict.get('throughput_ratio', 0.0):.2f}  "
            f"epochs={verdict.get('max_epoch', 0)}",
            flush=True,
        )
    if not args.no_determinism:
        print("== determinism leg ==", flush=True)
        doc["determinism"] = determinism_leg(scenarios[0].name)
        print(f"byte_identical: {doc['determinism']['byte_identical']}")
    if not args.no_live:
        print("== live testbed leg (4 nodes, add + remove epoch) ==",
              flush=True)
        doc["live"] = live_leg(args.tps)
        print(
            f"live: {'PASS' if doc['live']['passed'] else 'FAIL'}  "
            f"epochs={doc['live']['epochs']}  "
            f"joiner_commits={doc['live']['joiner_commits']}"
        )
    doc["wall_s"] = round(time.monotonic() - t0, 1)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out} ({doc['passed']} passed, {doc['failed']} failed)")
    deterministic = (doc.get("determinism") or {}).get("byte_identical", True)
    live_ok = (doc.get("live") or {}).get("passed", True)
    return 0 if doc["all_pass"] and deterministic and live_ok else 1


if __name__ == "__main__":
    sys.exit(main())
