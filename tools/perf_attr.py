#!/usr/bin/env python3
"""One-command host attribution probe: testbed fleet -> PERF_ATTR artifact.

Boots a local N-node benchmark fleet (subprocess nodes, so each has its own
GIL, sampler, and /metrics endpoint), runs it under load with the
per-subsystem accountant on (MYSTICETI_PROFILE + MYSTICETI_PERF_REPORT),
scrapes the host attribution series over /metrics, and reduces everything
into one ``PERF_ATTR_rNN.json`` artifact:

* per-subsystem CPU seconds and µs per committed leader (the budget rows
  the generic bench_trend >10% regression gate evaluates),
* loop-lag percentiles and the GIL convoy ratio,
* verifier dispatch occupancy fractions (device-busy / host-pack /
  fetch-wait) + JAX compile/cache/transfer counters,
* the hostmon weather block (load averages, CPU steal, GIL switch
  interval) the run was measured under.

Usage:
    python tools/perf_attr.py --round 14                 # 4 nodes, 45 s
    python tools/perf_attr.py --committee-size 4 --duration 60 \
        --verifier cpu --out PERF_ATTR_r14.json

The artifact lands in the repo root and is appended to BENCH_TREND.json
(one ``PERF_ATTR.<subsystem>.leaders_per_cpu_s`` row per subsystem —
HIGHER is better, so cost creep fires the gate).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from mysticeti_tpu.config import Parameters  # noqa: E402
from mysticeti_tpu.orchestrator.measurement import iter_series  # noqa: E402


def _http_get(host: str, port: int, path: str, timeout: float = 3.0):
    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}{path}", timeout=timeout
        ) as resp:
            return resp.read().decode(errors="replace")
    except Exception:  # noqa: BLE001 - unreachable nodes scrape as None
        return None


def scrape_node(host: str, port: int) -> Optional[dict]:
    """One node's attribution view from its /metrics + /health routes."""
    text = _http_get(host, port, "/metrics")
    if text is None:
        return None
    out: dict = {
        "leaders": 0.0,
        "cpu_seconds": {},  # subsystem -> s (summed over thread classes)
        "us_per_leader": {},  # subsystem -> µs (the node's own gauge)
        "loop_lag_p99_s": 0.0,
        "gil_convoy_ratio": 0.0,
        "occupancy": {},
        "jax": {},
        "transfer_bytes": {},
        "blocking_calls": 0.0,
        "slo_alerts": {},
    }
    for name, labels, value in iter_series(text):
        if name == "committed_leaders_total":
            if "commit" in labels.get("status", ""):
                out["leaders"] += value
        elif name == "mysticeti_cpu_seconds_total":
            sub = labels.get("subsystem", "?")
            out["cpu_seconds"][sub] = out["cpu_seconds"].get(sub, 0.0) + value
        elif name == "mysticeti_cpu_us_per_leader":
            out["us_per_leader"][labels.get("subsystem", "?")] = value
        elif name == "mysticeti_loop_lag_p99_seconds":
            out["loop_lag_p99_s"] = value
        elif name == "mysticeti_gil_convoy_ratio":
            out["gil_convoy_ratio"] = value
        elif name == "mysticeti_verify_occupancy_fraction":
            out["occupancy"][labels.get("phase", "?")] = value
        elif name in (
            "mysticeti_jax_compiles_total",
            "mysticeti_jax_compile_seconds_total",
            "mysticeti_jax_cache_hits_total",
            "mysticeti_jax_cache_misses_total",
        ):
            out["jax"][name.replace("mysticeti_jax_", "")] = value
        elif name == "mysticeti_device_transfer_bytes_total":
            out["transfer_bytes"][labels.get("direction", "?")] = value
        elif name == "mysticeti_blocking_calls_total":
            out["blocking_calls"] += value
        elif name == "mysticeti_health_slo_alerts_total":
            kind = labels.get("kind", "?")
            out["slo_alerts"][kind] = out["slo_alerts"].get(kind, 0.0) + value
    # /health is served on the node's event loop, so under saturating load
    # it lags far behind the thread-served /metrics route — give it room.
    health = _http_get(host, port, "/health", timeout=10.0)
    if health:
        try:
            doc = json.loads(health)
            out["host"] = (doc.get("signals") or {}).get("host")
        except ValueError:
            pass
    return out


def aggregate(
    scrapes: Dict[str, Optional[dict]],
    reports: Dict[str, Optional[dict]],
) -> dict:
    """Reduce per-node scrapes + shutdown attribution reports into the
    artifact's fleet view (per-node numbers averaged, counters summed)."""
    live = {k: v for k, v in scrapes.items() if v is not None}
    n = max(1, len(live))
    subsystems: Dict[str, dict] = {}
    attributed: List[float] = []
    convoy: List[float] = []
    for node, scrape in sorted(live.items()):
        report = reports.get(node)
        seconds = (
            report["subsystem_seconds"] if report else scrape["cpu_seconds"]
        )
        leaders = scrape["leaders"]
        for sub, cpu_s in seconds.items():
            slot = subsystems.setdefault(
                sub, {"cpu_s": 0.0, "us_per_leader": 0.0, "nodes": 0}
            )
            slot["cpu_s"] += cpu_s
            if leaders > 0 and sub != "event-loop-idle":
                slot["us_per_leader"] += cpu_s * 1e6 / leaders
                slot["nodes"] += 1
        if report:
            attributed.append(report["attributed_ratio"])
            convoy.append(report["gil_convoy_ratio"])
        else:
            convoy.append(scrape["gil_convoy_ratio"])
    for slot in subsystems.values():
        if slot["nodes"]:
            slot["us_per_leader"] = round(
                slot["us_per_leader"] / slot["nodes"], 3
            )
        else:
            slot.pop("us_per_leader", None)
        slot["cpu_s"] = round(slot["cpu_s"], 6)
        slot.pop("nodes", None)
    # event-loop-idle is parked time, not a budget: no per-leader row.
    idle = subsystems.get("event-loop-idle")
    if idle is not None:
        idle.pop("us_per_leader", None)
    lag_p50 = [
        (s.get("host") or {}).get("loop_lag_p50_s", 0.0) for s in live.values()
    ]
    lag_p99 = [s["loop_lag_p99_s"] for s in live.values()]
    occupancy: Dict[str, float] = {}
    for s in live.values():
        for phase, frac in s["occupancy"].items():
            occupancy[phase] = occupancy.get(phase, 0.0) + frac / n
    jax: Dict[str, float] = {}
    transfer: Dict[str, float] = {}
    for s in live.values():
        for key, value in s["jax"].items():
            jax[key] = jax.get(key, 0.0) + value
        for direction, value in s["transfer_bytes"].items():
            transfer[direction] = transfer.get(direction, 0.0) + value
    alert_totals: Dict[str, float] = {}
    for s in live.values():
        for kind, count in s["slo_alerts"].items():
            alert_totals[kind] = alert_totals.get(kind, 0.0) + count
    return {
        "subsystems": dict(sorted(subsystems.items())),
        "attributed_ratio": (
            round(sum(attributed) / len(attributed), 6) if attributed else None
        ),
        "loop_lag": {
            "p50_s_mean": round(sum(lag_p50) / n, 6),
            "p99_s_mean": round(sum(lag_p99) / n, 6),
            "p99_s_max": round(max(lag_p99, default=0.0), 6),
        },
        "gil_convoy_ratio": (
            round(sum(convoy) / len(convoy), 6) if convoy else 0.0
        ),
        "device": {
            "occupancy_fractions": {
                k: round(v, 6) for k, v in sorted(occupancy.items())
            },
            "jax": {k: round(v, 3) for k, v in sorted(jax.items())},
            "transfer_bytes": {
                k: int(v) for k, v in sorted(transfer.items())
            },
        },
        "blocking_calls": int(sum(s["blocking_calls"] for s in live.values())),
        "slo_alert_totals": dict(sorted(alert_totals.items())),
        "committed_leaders_by_node": {
            k: int(v["leaders"]) for k, v in sorted(live.items())
        },
        # Which native data-plane functions each node resolved: A/B
        # artifacts (tools/dataplane_ab.py) record which path a fleet
        # actually measured.  The shutdown report is authoritative (it is
        # written even when load kept /health from ever answering); the
        # live scrape's host block is the fallback.
        "native_active_by_node": {
            k: (
                (reports.get(k) or {}).get("native_active")
                if (reports.get(k) or {}).get("native_active") is not None
                else (v.get("host") or {}).get("native_active")
            )
            for k, v in sorted(live.items())
        },
    }


def run_fleet(args) -> dict:
    wd = os.path.abspath(args.working_dir)
    os.makedirs(wd, exist_ok=True)
    subprocess.run(
        [
            sys.executable, "-m", "mysticeti_tpu", "benchmark-genesis",
            "--ips", *(["127.0.0.1"] * args.committee_size),
            "--working-directory", wd,
        ],
        check=True, cwd=_REPO,
    )
    parameters = Parameters.load(os.path.join(wd, "parameters.yaml"))
    targets = [
        parameters.metrics_address(a) for a in range(args.committee_size)
    ]
    procs = []
    logs = []
    for i in range(args.committee_size):
        node_dir = os.path.join(wd, f"validator-{i}")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(
            MYSTICETI_EXIT_AFTER=str(args.duration),
            MYSTICETI_PROFILE=os.path.join(node_dir, "profile.folded"),
            MYSTICETI_PERF_REPORT=os.path.join(node_dir, "perf_report.json"),
            TPS=str(args.tps),
        )
        log = open(os.path.join(node_dir, "node.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [
                sys.executable, "-m", "mysticeti_tpu", "run",
                "--authority", str(i),
                "--committee-path", os.path.join(wd, "committee.yaml"),
                "--parameters-path", os.path.join(wd, "parameters.yaml"),
                "--private-config-path", node_dir,
                "--verifier", args.verifier,
            ],
            cwd=_REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
        ))
    scrapes: Dict[str, Optional[dict]] = {}
    # Boot probe: one scrape right after launch, before any INITIAL_DELAY
    # load lands.  It snapshots the counter window start (so cumulative
    # cpu/leader gauges can be re-windowed to the loaded interval) and is
    # usually the only /health capture that succeeds when the load later
    # saturates the event loop.
    first_scrapes: Dict[str, dict] = {}
    deadline = time.time() + args.duration
    try:
        # Two probe passes: the first often lands mid-boot (verifier
        # warmup keeps the loop too busy for /health), so the second is
        # both the /health retry and the real window start — each pass
        # overwrites first_scrapes, carrying any captured host block.
        for _ in range(2):
            time.sleep(min(3.0, max(0.5, args.duration / 8.0)))
            for idx, (host, port) in enumerate(targets):
                scrape = scrape_node(host, port)
                if scrape is None:
                    continue
                prev = first_scrapes.get(str(idx))
                if scrape.get("host") is None and prev is not None:
                    scrape["host"] = prev.get("host")
                first_scrapes[str(idx)] = scrape
                scrapes[str(idx)] = scrape
        while time.time() < deadline - 1.0:
            time.sleep(min(args.scrape_interval, max(0.5, deadline - time.time() - 1.0)))
            for idx, (host, port) in enumerate(targets):
                scrape = scrape_node(host, port)
                if scrape is not None:
                    prev = scrapes.get(str(idx))
                    if scrape.get("host") is None and prev is not None:
                        # /health can still time out when the loop is
                        # saturated; the host block (native inventory,
                        # thread census) barely moves, so keep the last
                        # one we captured rather than dropping it.
                        scrape["host"] = prev.get("host")
                    scrapes[str(idx)] = scrape  # keep the freshest
    finally:
        for proc in procs:
            try:
                proc.wait(timeout=args.duration + 60)
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in logs:
            log.close()
    reports: Dict[str, Optional[dict]] = {}
    for i in range(args.committee_size):
        path = os.path.join(wd, f"validator-{i}", "perf_report.json")
        try:
            with open(path) as f:
                reports[str(i)] = json.load(f)
        except (OSError, ValueError):
            reports[str(i)] = None
    doc = aggregate(scrapes, reports)
    # Re-window the cumulative cpu/leader counters to [boot probe, last
    # scrape]: the node's own us_per_leader gauge averages from process
    # start, so cheap pre-load boot rounds dilute it.  The windowed view
    # is what load A/Bs (tools/dataplane_ab.py) compare.
    windowed: Dict[str, Dict[str, float]] = {}
    for key, last in scrapes.items():
        head = first_scrapes.get(key)
        if not last or not head or last is head:
            continue
        dleaders = last["leaders"] - head["leaders"]
        if dleaders <= 0:
            continue
        windowed[key] = {
            sub: round(
                1e6 * (cpu - head["cpu_seconds"].get(sub, 0.0)) / dleaders, 1
            )
            for sub, cpu in last["cpu_seconds"].items()
        }
    doc["windowed_us_per_leader_by_node"] = windowed
    doc.update(
        metric="perf_attr",
        nodes=args.committee_size,
        duration_s=args.duration,
        verifier=args.verifier,
        tps_per_node=args.tps,
        scraped_nodes=len(scrapes),
        reports_written=sum(1 for r in reports.values() if r is not None),
    )
    if args.round is not None:
        doc["round"] = args.round
    try:
        from mysticeti_tpu.orchestrator.hostmon import HostSampler

        doc["weather"] = {
            k: v
            for k, v in HostSampler().sample().items()
            if k != "per_process"
        }
    except Exception:  # noqa: BLE001 - no psutil: artifact rides without
        pass
    return doc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_attr", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--committee-size", type=int, default=4)
    parser.add_argument("--duration", type=float, default=45.0)
    parser.add_argument("--tps", type=int, default=100,
                        help="offered load per node (generator tx/s)")
    parser.add_argument("--verifier", default="cpu")
    parser.add_argument("--working-dir", default="perf-attr-testbed")
    parser.add_argument("--scrape-interval", type=float, default=5.0)
    parser.add_argument("--round", type=int, default=None,
                        help="bench round number (names the artifact "
                        "PERF_ATTR_rNN.json)")
    parser.add_argument("--out", default=None)
    parser.add_argument("--no-trend", action="store_true",
                        help="skip the BENCH_TREND.json refresh")
    args = parser.parse_args(argv)
    out = args.out
    if out is None:
        out = (
            f"PERF_ATTR_r{args.round:02d}.json"
            if args.round is not None
            else "PERF_ATTR.json"
        )
    out = os.path.join(_REPO, out) if not os.path.isabs(out) else out

    doc = run_fleet(args)
    tmp = f"{out}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)
    print(f"wrote {out}", file=sys.stderr)

    ratio = doc.get("attributed_ratio")
    print(json.dumps(
        {
            "attributed_ratio": ratio,
            "loop_lag_p99_s_max": doc["loop_lag"]["p99_s_max"],
            "gil_convoy_ratio": doc["gil_convoy_ratio"],
            "occupancy": doc["device"]["occupancy_fractions"],
            "subsystems": {
                k: v.get("us_per_leader")
                for k, v in doc["subsystems"].items()
                if v.get("us_per_leader")
            },
        },
        indent=1, sort_keys=True,
    ))
    if not args.no_trend:
        from bench_trend import main as trend_main

        trend_main(["--repo", _REPO])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
