#!/usr/bin/env python3
"""Node-level verifier comparison: committed-tx/sec with verifier=cpu vs tpu.

Runs the SAME fixed load through the local orchestrator twice — once with the
serial OpenSSL verifier (reference behavior) and once with the batched
TPU kernel — and records both (BASELINE configs #3/#5 measurement semantics:
tps = latency_s_count / benchmark_duration, orchestrator/src/measurement.rs:92-142).

Writes one JSON artifact (default NODE_BENCH.json) with both runs.

Caveat recorded in the artifact: under the axon tunnel the TPU sits behind a
high-latency network link (~100-300 ms per synchronous dispatch), which taxes
the per-batch verification path in a way co-located TPU hosts do not.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

# Persistent compilation cache: mysticeti_tpu.ops.ed25519 sets a per-uid,
# ownership-checked default when JAX_COMPILATION_CACHE_DIR is unset.

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def prewarm() -> None:
    """Compile the fused bucket kernels into the persistent cache so node
    subprocesses hit warm compiles."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from mysticeti_tpu.ops import ed25519 as E

    key = Ed25519PrivateKey.from_private_bytes(bytes(32))
    pk = key.public_key().public_bytes_raw()
    msg = bytes(32)
    sig = key.sign(msg)
    for bucket in E.BUCKETS[:2]:  # 256 and 1024 cover node-sized batches
        E.verify_batch([pk] * bucket, [msg] * bucket, [sig] * bucket)


async def run_one(verifier: str, nodes: int, load: int, duration: float,
                  workdir: str) -> dict:
    from mysticeti_tpu.orchestrator.benchmark import LoadType, ParametersGenerator
    from mysticeti_tpu.orchestrator.logs import analyze_logs
    from mysticeti_tpu.orchestrator.orchestrator import Orchestrator
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    fleet = os.path.join(workdir, f"fleet-{verifier}")
    results = os.path.join(workdir, f"results-{verifier}")
    # The shared verifier service removed the tpu warmup asymmetry: the
    # runner blocks until the service is warm before booting nodes, and
    # nodes seed their routers from the service's HELLO_OK calibration
    # instead of probing.  Identical delays keep the rows comparable.
    os.environ["INITIAL_DELAY"] = "1"
    if verifier.startswith("tpu") and os.environ.get(
        "MYSTICETI_NO_VERIFIER_SERVICE"
    ):
        # Service opted out: per-node cold JAX runtimes are back, and the
        # window must outlast their ~2-3 min contended warmup.
        os.environ["INITIAL_DELAY"] = "10"
        duration = max(duration, 240.0)
    runner = LocalProcessRunner(fleet, verifier=verifier)
    generator = ParametersGenerator(
        nodes, LoadType.fixed([load]), duration_s=duration
    )
    orch = Orchestrator(
        runner, generator, results_dir=results, scrape_interval_s=duration / 4
    )
    collections = await orch.run_benchmarks()
    c = collections[0]
    logs = analyze_logs(fleet)
    return {
        "verifier": verifier,
        "nodes": nodes,
        "offered_load_tx_s": load,
        "duration_s": c.benchmark_duration(),
        "committed_tx_s": round(c.aggregate_tps(), 1),
        "avg_latency_s": round(c.aggregate_average_latency_s(), 4),
        "stdev_latency_s": round(c.aggregate_stdev_latency_s(), 4),
        "log_errors": logs.total_errors,
        "log_crashes": logs.total_crashes,
    }


def saturation(verifier: str, batch: int = 4096, iters: int = 5) -> dict:
    """Sustained throughput of the SignatureVerifier backend itself — the
    number that caps a node's verification rate once consensus stops being
    the bottleneck (large committees / per-certificate checks)."""
    import random
    import time

    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from mysticeti_tpu.block_validator import CpuSignatureVerifier, TpuSignatureVerifier

    rng = random.Random(1)
    keys = [
        Ed25519PrivateKey.from_private_bytes(bytes(rng.randrange(256) for _ in range(32)))
        for _ in range(8)
    ]
    pks, msgs, sigs = [], [], []
    for i in range(batch):
        k = keys[i % 8]
        m = bytes(rng.getrandbits(8) for _ in range(32))
        pks.append(k.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(k.sign(m))
    # Deployed semantics: the signer set is the committee, keys ride as
    # indices into a device-resident table (validator._make_verifier).  The
    # hybrid ("tpu") routes a saturation-sized batch to the kernel, so the
    # pure TPU backend measures both flavors.
    backend = (
        CpuSignatureVerifier()
        if verifier == "cpu"
        else TpuSignatureVerifier(
            committee_keys=[k.public_key().public_bytes_raw() for k in keys]
        )
    )
    assert all(backend.verify_signatures(pks, msgs, sigs))  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        backend.verify_signatures(pks, msgs, sigs)
    elapsed = time.perf_counter() - t0
    return {
        "verifier": verifier,
        "batch": batch,
        "sig_per_sec": round(batch * iters / elapsed, 1),
    }


def mesh_serialization(peers: int = 9, blocks: int = 50, txs: int = 16,
                       iters: int = 30) -> dict:
    """Mesh-serialization microbench: encode-once fan-out vs per-peer.

    Measures exactly the work ``write_loop`` does when a dissemination
    frame fans out to ``peers`` subscribers: the legacy path encodes the
    frame once PER PEER; the broadcast-once path encodes once and ships the
    cached payload (``EncodedFrame``).  Reports MB/s of fan-out payload
    production plus interpreter allocation counts — the second number is
    the GC-pressure story the throughput number hides.  Uses the
    ``mysticeti_tpu.crypto`` signers (pure-Python RFC 8032 fallback) so the
    rung runs on hosts without the ``cryptography`` package.
    """
    import time

    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.network import Blocks, EncodedFrame, encode_message, frame_payload
    from mysticeti_tpu.types import Share, StatementBlock

    signers = Committee.benchmark_signers(4)
    genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
    batch = tuple(
        StatementBlock.build(
            0, 1 + i, genesis, [Share(bytes(128) + i.to_bytes(4, "little"))] * txs,
            signer=signers[0],
        ).to_bytes()
        for i in range(blocks)
    )
    msg = Blocks(batch)
    frame_bytes = len(encode_message(msg))
    shipped = frame_bytes * peers * iters

    def measure(fn, encodes_per_fanout):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        elapsed = time.perf_counter() - t0
        return {
            "mb_per_s": round(shipped / 1e6 / elapsed, 1),
            # Every encoded byte is an allocated byte: the fan-out's
            # allocation volume is encodes × frame size (the GC-pressure
            # story the throughput number hides).
            "encodes_per_fanout": encodes_per_fanout,
            "alloc_bytes_per_fanout": encodes_per_fanout * frame_bytes,
            "elapsed_s": round(elapsed, 4),
        }

    def per_peer():
        for _ in range(peers):
            encode_message(msg)

    def encode_once():
        frame = EncodedFrame(msg)
        for _ in range(peers):
            frame_payload(frame)

    per_peer_row = measure(per_peer, peers)
    encode_once_row = measure(encode_once, 1)
    return {
        "metric": "mesh_serialization_fanout",
        "peers": peers,
        "blocks_per_frame": blocks,
        "frame_bytes": frame_bytes,
        "iters": iters,
        "per_peer": per_peer_row,
        "encode_once": encode_once_row,
        "speedup": round(
            encode_once_row["mb_per_s"] / max(per_peer_row["mb_per_s"], 1e-9), 2
        ),
    }


def dataplane_bench(blocks: int = 64, txs: int = 16, iters: int = 40) -> dict:
    """Native data-plane microbench: frame encode / parse / digest, native
    batched vs pure-Python per-block, on one realistic dissemination frame.

    The three stages are exactly the receive/send hot path the r19 native
    batch helpers cover: whole-frame encode (``encode_message`` on a
    ``Blocks`` fan-out), whole-frame parse (``decode_message`` splitting the
    payload into per-block views), and the per-block digest pair
    (block digest + signature pre-hash, batched into ONE native call).  The
    fallback rows force the pure interpreter path in-process by nulling the
    module-level native aliases — same bytes, same objects, so the ratio is
    the GIL-free batching win and nothing else.  Without the extension the
    native rows are absent and the artifact records fallback-only numbers.
    """
    import time

    import mysticeti_tpu.network as network_mod
    import mysticeti_tpu.types as types_mod
    from mysticeti_tpu import crypto
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.native import native
    from mysticeti_tpu.network import Blocks, decode_message, encode_message
    from mysticeti_tpu.types import Share, StatementBlock

    signers = Committee.benchmark_signers(4)
    genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
    parts = tuple(
        StatementBlock.build(
            0, 1 + i, genesis,
            [Share(bytes(128) + i.to_bytes(4, "little"))] * txs,
            signer=signers[0],
        ).to_bytes()
        for i in range(blocks)
    )
    msg = Blocks(parts)
    payload = encode_message(msg)
    frame_bytes = len(payload)
    total_bytes = sum(len(p) for p in parts)

    def timed(fn):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters

    def py_digest_per_block():
        for p in parts:
            crypto.blake2b_256(p)
            crypto.blake2b_256(p[:-64])

    saved = (
        network_mod._native_encode_frame,
        network_mod._native_parse_spans,
        types_mod._native_decode,
        types_mod._native_block_digests,
    )
    try:
        network_mod._native_encode_frame = None
        network_mod._native_parse_spans = None
        types_mod._native_decode = None
        types_mod._native_block_digests = None
        fb_encode = timed(lambda: encode_message(msg))
        fb_parse = timed(lambda: decode_message(payload))
        fb_digest = timed(py_digest_per_block)
        fb_decode_many = timed(
            lambda: StatementBlock.from_bytes_many(parts)
        )
    finally:
        (network_mod._native_encode_frame, network_mod._native_parse_spans,
         types_mod._native_decode, types_mod._native_block_digests) = saved

    def us(seconds):
        return round(seconds * 1e6, 1)

    row = {
        "metric": "native_dataplane",
        "native_active": native is not None,
        "blocks_per_frame": blocks,
        "txs_per_block": txs,
        "frame_bytes": frame_bytes,
        "iters": iters,
        "fallback": {
            "encode_us": us(fb_encode),
            "parse_us": us(fb_parse),
            "digest_per_block_us": us(fb_digest),
            "decode_many_us": us(fb_decode_many),
            "encode_mb_s": round(frame_bytes / 1e6 / fb_encode, 1),
            "parse_mb_s": round(frame_bytes / 1e6 / fb_parse, 1),
            "digest_mb_s": round(total_bytes / 1e6 / fb_digest, 1),
        },
    }
    if native is None:
        return row

    nat_encode = timed(lambda: encode_message(msg))
    nat_parse = timed(lambda: decode_message(payload))
    nat_digest = timed(lambda: native.block_digests(parts))
    nat_digest_per_block = timed(
        lambda: [native.block_digests([p]) for p in parts]
    )
    nat_decode_many = timed(lambda: StatementBlock.from_bytes_many(parts))
    combined = (fb_encode + fb_parse + fb_digest) / max(
        nat_encode + nat_parse + nat_digest, 1e-12
    )
    row["native"] = {
        "encode_us": us(nat_encode),
        "parse_us": us(nat_parse),
        "digest_batched_us": us(nat_digest),
        "digest_per_block_us": us(nat_digest_per_block),
        "decode_many_us": us(nat_decode_many),
        "encode_mb_s": round(frame_bytes / 1e6 / nat_encode, 1),
        "parse_mb_s": round(frame_bytes / 1e6 / nat_parse, 1),
        "digest_mb_s": round(total_bytes / 1e6 / nat_digest, 1),
    }
    row["speedups"] = {
        "encode": round(fb_encode / nat_encode, 2),
        "parse": round(fb_parse / nat_parse, 2),
        "digest": round(fb_digest / nat_digest, 2),
        # One GIL round-trip per frame vs one per block, both native: the
        # batching win isolated from the C-vs-interpreter win.
        "digest_batched_vs_per_block": round(
            nat_digest_per_block / nat_digest, 2
        ),
        "decode_many": round(fb_decode_many / nat_decode_many, 2),
        # The acceptance ratio: whole native hot path vs pure per-block.
        "combined_encode_parse_digest": round(combined, 2),
    }
    return row


def append_dataplane_trend(row: dict, round_: int) -> None:
    """NODE_DATAPLANE trend family: every recorded value is higher-is-better
    (MB/s and speedup ratios — the budget-row inversion PERF_ATTR uses for
    per-leader costs), so the stock >10%-below-best regression gate applies
    directly round-over-round."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_trend

    source = f"DATAPLANE_r{round_:02d}.json"
    fresh = []

    def rec(metric, value, unit):
        fresh.append(bench_trend._record(
            round_, source, f"NODE_DATAPLANE.{metric}", value, unit,
        ))

    rec("fallback_encode_mb_s", row["fallback"]["encode_mb_s"], "MB/s")
    rec("fallback_parse_mb_s", row["fallback"]["parse_mb_s"], "MB/s")
    rec("fallback_digest_mb_s", row["fallback"]["digest_mb_s"], "MB/s")
    if row.get("native"):
        rec("native_encode_mb_s", row["native"]["encode_mb_s"], "MB/s")
        rec("native_parse_mb_s", row["native"]["parse_mb_s"], "MB/s")
        rec("native_digest_mb_s", row["native"]["digest_mb_s"], "MB/s")
        sp = row["speedups"]
        rec("encode_speedup", sp["encode"], "x")
        rec("parse_speedup", sp["parse"], "x")
        rec("digest_speedup", sp["digest"], "x")
        rec("digest_batched_vs_per_block", sp["digest_batched_vs_per_block"],
            "x")
        rec("decode_many_speedup", sp["decode_many"], "x")
        rec("combined_speedup", sp["combined_encode_parse_digest"], "x")
    path = os.environ.get("BENCH_TREND_PATH", "BENCH_TREND.json")
    index = bench_trend.load_index(path)
    if bench_trend.merge_index(index, fresh):
        bench_trend.write_index(index, path)


def append_mesh_trend(row: dict, round_: int) -> None:
    """Track the fan-out win round-over-round in BENCH_TREND.json under its
    own MESH_SERIALIZATION family (never mixed with the fleet families —
    a serialization microbench must not gate a fleet search and vice
    versa)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_trend

    source = f"MESH_SERIALIZATION_r{round_:02d}.json"
    fresh = [
        bench_trend._record(
            round_, source, "MESH_SERIALIZATION.encode_once_mb_s",
            row["encode_once"]["mb_per_s"], "MB/s",
        ),
        bench_trend._record(
            round_, source, "MESH_SERIALIZATION.per_peer_mb_s",
            row["per_peer"]["mb_per_s"], "MB/s",
        ),
        bench_trend._record(
            round_, source, "MESH_SERIALIZATION.fanout_speedup",
            row["speedup"], "x",
        ),
    ]
    path = os.environ.get("BENCH_TREND_PATH", "BENCH_TREND.json")
    index = bench_trend.load_index(path)
    if bench_trend.merge_index(index, fresh):
        bench_trend.write_index(index, path)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--load", type=int, default=200)
    parser.add_argument("--duration", type=float, default=40.0)
    parser.add_argument("--workdir", default="/tmp/mysticeti-node-bench")
    parser.add_argument("--out", default="NODE_BENCH.json")
    parser.add_argument(
        "--verifiers", nargs="+", default=["cpu", "tpu"],
        choices=["accept", "cpu", "tpu", "tpu-only"],
    )
    parser.add_argument(
        "--mesh-bench", action="store_true",
        help="run ONLY the mesh-serialization microbench (encode-once vs "
        "per-peer fan-out) and append it to BENCH_TREND.json under the "
        "MESH_SERIALIZATION family",
    )
    parser.add_argument(
        "--dataplane-bench", action="store_true",
        help="run ONLY the native data-plane microbench (frame encode/parse"
        "/digest, native batched vs pure-Python per-block) and append it to "
        "BENCH_TREND.json under the NODE_DATAPLANE family",
    )
    parser.add_argument(
        "--round", type=int, default=10,
        help="PR round recorded with --mesh-bench/--dataplane-bench trend "
        "records",
    )
    args = parser.parse_args()

    if args.dataplane_bench:
        row = dataplane_bench()
        print(json.dumps(row, indent=2))
        append_dataplane_trend(row, args.round)
        print("appended NODE_DATAPLANE records to BENCH_TREND.json")
        return

    if args.mesh_bench:
        row = mesh_serialization()
        print(json.dumps(row, indent=2))
        append_mesh_trend(row, args.round)
        print("appended MESH_SERIALIZATION records to BENCH_TREND.json")
        return

    if any(v.startswith("tpu") for v in args.verifiers):
        print("prewarming fused kernel cache...", flush=True)
        prewarm()

    runs = []
    for verifier in args.verifiers:
        print(f"running verifier={verifier}...", flush=True)
        for attempt in range(2):
            run = asyncio.run(
                run_one(verifier, args.nodes, args.load, args.duration, args.workdir)
            )
            if run["committed_tx_s"] > 0 or attempt == 1:
                break
            print("no commits (warmup overran the window); retrying", flush=True)
        runs.append(run)
        print(json.dumps(runs[-1]), flush=True)

    saturation_rows = []
    for verifier in args.verifiers:
        if verifier == "accept":
            continue
        print(f"saturation verifier={verifier}...", flush=True)
        saturation_rows.append(saturation(verifier))
        print(json.dumps(saturation_rows[-1]), flush=True)

    import jax

    artifact = {
        "metric": "committed_tx_per_sec_by_verifier",
        "backend": jax.default_backend(),
        "verifier_saturation": saturation_rows,
        "environment_note": (
            "TPU reached through the axon tunnel: each synchronous device "
            "round-trip costs ~100-300 ms, penalizing small per-batch node "
            "dispatches; co-located hosts do not pay this."
            if jax.default_backend() == "tpu"
            else "JAX backend degraded to CPU (no accelerator attached): "
            "'tpu' rows exercise the verifier-service architecture with "
            "jax-on-CPU XLA behind it — saturation rows are NOT chip rates."
        ),
        "runs": runs,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
