#!/usr/bin/env python3
"""FINALITY artifact generator: submit→finality SLIs + decision-ledger audit.

Two legs, one artifact (``FINALITY_rNN.json``, appended to
``BENCH_TREND.json`` under the FINALITY family by ``tools/bench_trend.py``):

* **Fleet leg** — a real local fleet (LocalProcessRunner, gateway/ingress
  plane on) under closed-loop load.  Scrapes the server-side
  ``mysticeti_e2e_finality_p{50,99}_seconds`` gauges and the
  CLIENT-observed ``mysticeti_client_finality_p{50,99}_seconds`` gauges
  from every node, pulls each node's ``/debug/consensus`` decision
  ledger, and cross-checks the two ends: the client's number is the
  server's ``total`` plus the notification hop, so the percentiles must
  agree within ``--cross-check-tolerance`` (default 20%).
* **Sim leg** — the seeded ``byzantine-at-f`` scenario (10 nodes, f=3
  attacking) run twice with the same seed: every decided leader slot in
  every honest node's ledger must carry an explaining record (slot
  coverage audited against the committer's own leader schedule), and the
  canonical ledgers must be byte-identical across the two runs.

Usage:
  python tools/finality_bench.py --out FINALITY_r17.json
  python tools/finality_bench.py --skip-fleet --out FINALITY_sim.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FINALITY_GAUGES = (
    "mysticeti_e2e_finality_p50_seconds",
    "mysticeti_e2e_finality_p99_seconds",
    "mysticeti_client_finality_p50_seconds",
    "mysticeti_client_finality_p99_seconds",
)


def _node_gauges(text) -> dict:
    from mysticeti_tpu.orchestrator.measurement import iter_series

    out = {name: 0.0 for name in FINALITY_GAUGES}
    if text:
        for name, _labels, value in iter_series(text):
            if name in out:
                out[name] = value
    return out


def _percentile(values, q):
    from mysticeti_tpu.finality import percentile

    return percentile(list(values), q)


def _within(a: float, b: float, tolerance: float) -> bool:
    """|a-b| within tolerance of the larger (sub-ms values always agree —
    at that scale both ends are measuring scheduler noise)."""
    hi = max(a, b)
    if hi < 1e-3:
        return True
    return abs(a - b) <= tolerance * hi


def _fresh_dir(path: str) -> str:
    # Stale WALs from a previous invocation replay into the new run and
    # the safety checker (rightly) reports the replayed prefix as a commit
    # gap — every leg must start from an empty directory.
    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    return path


async def run_fleet_leg(args) -> dict:
    """One closed-loop fleet run; returns the fleet-side record."""
    from mysticeti_tpu.orchestrator.runner import (
        LocalProcessRunner,
        _http_get_metrics,
    )

    os.environ["INITIAL_DELAY"] = "1"
    runner = LocalProcessRunner(
        _fresh_dir(os.path.join(args.workdir, "fleet")), verifier="cpu"
    )
    started = time.time()
    await runner.configure(args.nodes, args.load)
    ledgers = {}
    try:
        for authority in range(args.nodes):
            await runner.boot_node(authority)
        await asyncio.sleep(args.duration)
        texts = [await runner.scrape(a) for a in range(args.nodes)]
        for authority in range(args.nodes):
            host, port = runner.parameters.metrics_address(authority)
            doc = await _http_get_metrics(
                host, port, path="/debug/consensus"
            )
            try:
                ledgers[str(authority)] = json.loads(doc) if doc else None
            except ValueError:
                ledgers[str(authority)] = None
    finally:
        await runner.cleanup()

    per_node = {}
    for authority, text in enumerate(texts):
        gauges = _node_gauges(text)
        ledger = ledgers.get(str(authority)) or {}
        records = ledger.get("records") or []
        per_node[str(authority)] = {
            "server_p50_s": round(
                gauges["mysticeti_e2e_finality_p50_seconds"], 4
            ),
            "server_p99_s": round(
                gauges["mysticeti_e2e_finality_p99_seconds"], 4
            ),
            "client_p50_s": round(
                gauges["mysticeti_client_finality_p50_seconds"], 4
            ),
            "client_p99_s": round(
                gauges["mysticeti_client_finality_p99_seconds"], 4
            ),
            "decisions_recorded": ledger.get("recorded", 0),
            "decisions_by_outcome": _outcome_census(records),
            "ledger_digest": ledger.get("ledger_digest"),
            "undecided": ledger.get("undecided", []),
        }
    reachable = [
        rec for rec in per_node.values() if rec["server_p50_s"] > 0
    ]
    server_p50 = _percentile([r["server_p50_s"] for r in reachable], 0.5)
    server_p99 = max((r["server_p99_s"] for r in reachable), default=0.0)
    client_p50 = _percentile(
        [r["client_p50_s"] for r in reachable if r["client_p50_s"] > 0], 0.5
    )
    client_p99 = max((r["client_p99_s"] for r in reachable), default=0.0)
    return {
        "nodes": args.nodes,
        "load_tx_s": args.load,
        "window_utc": [round(started, 1), round(time.time(), 1)],
        "per_node": per_node,
        "server": {"p50_s": round(server_p50, 4), "p99_s": round(server_p99, 4),
                   "samples": len(reachable)},
        "client": {"p50_s": round(client_p50, 4), "p99_s": round(client_p99, 4)},
        "cross_check": {
            "p50_within_tolerance": _within(
                server_p50, client_p50, args.cross_check_tolerance
            ),
            "p99_within_tolerance": _within(
                server_p99, client_p99, args.cross_check_tolerance
            ),
            "tolerance": args.cross_check_tolerance,
        },
        "decisions_recorded_all_nodes": all(
            rec["decisions_recorded"] > 0 for rec in per_node.values()
        ),
    }


def _outcome_census(records) -> dict:
    census: dict = {}
    for record in records:
        key = f"{record.get('rule')}-{record.get('outcome')}"
        census[key] = census.get(key, 0) + 1
    return {k: census[k] for k in sorted(census)}


def _audit_ledgers(harness, adversaries) -> dict:
    """Slot-coverage audit over every live honest node: each round between
    a ledger's first and last decided round must carry exactly one record
    per leader the committer elects there — a skipped slot with no record
    would show up as a hole."""
    holes = []
    censuses = {}
    digests = {}
    for authority in range(harness.n):
        if authority in adversaries:
            continue
        node = harness.nodes[authority]
        if node is None:
            continue
        committer = node.core.committer
        records = committer.ledger.records()
        digests[authority] = committer.ledger.digest()
        censuses[authority] = _outcome_census(records)
        if not records:
            holes.append(f"node {authority}: empty ledger")
            continue
        by_round: dict = {}
        for record in records:
            by_round.setdefault(record["round"], []).append(record)
        for round_ in range(records[0]["round"], records[-1]["round"] + 1):
            expected = len(committer.get_leaders(round_))
            got = len(by_round.get(round_, []))
            if got != expected:
                holes.append(
                    f"node {authority}: round {round_} has {got} record(s), "
                    f"committer elects {expected} leader(s)"
                )
    return {"holes": holes, "censuses": censuses, "digests": digests}


def run_sim_leg(args, wal_dir: str) -> dict:
    """The seeded Byzantine sim twice: coverage + byte-identity."""
    import dataclasses

    from mysticeti_tpu.chaos import run_chaos_sim
    from mysticeti_tpu.scenarios import oracle_verifier_factory, scenario_by_name

    scenario = dataclasses.replace(
        scenario_by_name("byzantine-at-f"), duration_s=args.sim_duration
    )
    adversaries = {spec.node for spec in scenario.adversaries}

    def run_once(tag: str):
        return run_chaos_sim(
            scenario.plan(), scenario.nodes, scenario.duration_s,
            _fresh_dir(os.path.join(wal_dir, tag)),
            parameters=scenario.base_parameters(),
            latency_ranges=scenario.latency_ranges(),
            with_metrics=True,
            verifier_factory=oracle_verifier_factory(scenario.nodes),
        )

    _report_a, harness_a = run_once("a")
    audit = _audit_ledgers(harness_a, adversaries)
    _report_b, harness_b = run_once("b")
    identical = all(
        harness_a.nodes[a] is not None
        and harness_b.nodes[a] is not None
        and harness_a.nodes[a].core.committer.ledger.ledger_bytes()
        == harness_b.nodes[a].core.committer.ledger.ledger_bytes()
        for a in range(scenario.nodes)
        if a not in adversaries
    )
    skips = sum(
        count
        for census in audit["censuses"].values()
        for key, count in census.items()
        if key.endswith("-skip")
    )
    return {
        "scenario": scenario.name,
        "nodes": scenario.nodes,
        "duration_s": scenario.duration_s,
        "every_slot_explained": not audit["holes"],
        "holes": audit["holes"],
        "decision_census": {
            str(a): c for a, c in sorted(audit["censuses"].items())
        },
        "skips_explained": skips,
        "byte_identical": identical,
        "digest": audit["digests"].get(min(audit["digests"], default=0)),
    }


def main() -> int:
    parser = argparse.ArgumentParser(
        prog="finality_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--load", type=int, default=2000,
                        help="closed-loop offered load, tx/s (keep below "
                        "saturation: finality is an SLI, not a stress test)")
    parser.add_argument("--duration", type=float, default=30.0)
    parser.add_argument("--sim-duration", type=float, default=4.0)
    parser.add_argument("--cross-check-tolerance", type=float, default=0.20)
    parser.add_argument("--workdir", default="/tmp/mysticeti-finality")
    parser.add_argument("--out", default="FINALITY.json")
    parser.add_argument("--skip-fleet", action="store_true",
                        help="sim leg only (no process fleet)")
    args = parser.parse_args()

    fleet = None
    if not args.skip_fleet:
        print(f"fleet leg: {args.nodes} nodes at {args.load} tx/s for "
              f"{args.duration}s...", flush=True)
        fleet = asyncio.run(run_fleet_leg(args))
        print(json.dumps({k: fleet[k] for k in
                          ("server", "client", "cross_check")}), flush=True)

    print("sim leg: seeded byzantine-at-f x2 (ledger audit + "
          "byte-identity)...", flush=True)
    sim = run_sim_leg(args, os.path.join(args.workdir, "sim"))
    print(json.dumps({k: sim[k] for k in
                      ("every_slot_explained", "skips_explained",
                       "byte_identical")}), flush=True)

    acceptance = {
        "every_slot_explained": sim["every_slot_explained"],
    }
    if fleet is not None:
        acceptance["client_cross_check"] = (
            fleet["cross_check"]["p50_within_tolerance"]
            and fleet["cross_check"]["p99_within_tolerance"]
        )
        acceptance["decisions_on_every_node"] = (
            fleet["decisions_recorded_all_nodes"]
        )
    artifact = {
        "metric": "finality",
        "nodes": args.nodes,
        "verifier": "cpu",
        "rule": (
            "server (submit→finalized) and client (submit→notification) "
            "p50/p99 agree within the cross-check tolerance; every decided "
            "leader slot carries a ledger record; seeded Byzantine-sim "
            "ledgers byte-identical across same-seed runs"
        ),
        "server": (fleet or {}).get("server", {}),
        "client": (fleet or {}).get("client", {}),
        "fleet": fleet,
        "sim": sim,
        "determinism": {
            "byte_identical": sim["byte_identical"],
            "digest": sim["digest"],
        },
        "acceptance": acceptance,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    ok = all(acceptance.values()) and sim["byte_identical"]
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
