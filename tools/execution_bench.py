#!/usr/bin/env python3
"""EXEC artifact generator: execution-backed finality + state-root agreement.

Two legs, one artifact (``EXEC_rNN.json``, appended to ``BENCH_TREND.json``
under the EXEC family by ``tools/bench_trend.py``):

* **Fleet leg** — a real local fleet (LocalProcessRunner) with the
  execution plane on (``execution: true`` + a gateway listener patched
  into the generated ``parameters.yaml``).  A gateway client per node
  submits the deterministic account/transfer workload (CREATE, two
  nonce-ordered TRANSFERs, one deliberate overdraft per batch — disjoint
  accounts, so batches commute across committed interleavings) while a
  ``want_executed`` subscriber on node 0 records the EXECUTED roots it is
  notified of.  Scrapes ``mysticeti_execution_*`` and the execute-backed
  finality gauges from every node, pulls each node's ``/debug/consensus``
  execution section, and cross-checks per-height state roots across the
  fleet AND against the client-observed notification stream.
* **Sim leg** — the seeded ``execution-byzantine-at-f`` scenario (10
  nodes, f=3 attacking, execution live) run twice with the same seed: the
  verdict must pass (honest state roots agree at every height — a fork
  raises inside the SafetyChecker) and the agreed root-chain digest must
  be byte-identical across the two runs.

Usage:
  python tools/execution_bench.py --out EXEC_r20.json
  python tools/execution_bench.py --skip-fleet --out EXEC_sim.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXEC_GAUGES = (
    "mysticeti_execution_height",
    "mysticeti_execution_accounts",
    "mysticeti_e2e_finality_p50_seconds",
    "mysticeti_e2e_finality_p99_seconds",
)


def _fresh_dir(path: str) -> str:
    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    return path


def _node_series(text) -> dict:
    """Execution gauges + verdict counters + the execute-phase histogram
    (sum/count) from one node's raw /metrics scrape."""
    from mysticeti_tpu.orchestrator.measurement import iter_series

    out = {name: 0.0 for name in EXEC_GAUGES}
    out["txs_by_result"] = {}
    out["execute_phase_sum_s"] = 0.0
    out["execute_phase_count"] = 0.0
    if not text:
        return out
    for name, labels, value in iter_series(text):
        if name in out:
            out[name] = value
        elif name == "mysticeti_execution_txs_total":
            result = labels.get("result", "?")
            out["txs_by_result"][result] = (
                out["txs_by_result"].get(result, 0) + int(value)
            )
        elif name == "mysticeti_e2e_finality_seconds_sum" and (
            labels.get("phase") == "execute"
        ):
            out["execute_phase_sum_s"] = value
        elif name == "mysticeti_e2e_finality_seconds_count" and (
            labels.get("phase") == "execute"
        ):
            out["execute_phase_count"] = value
    return out


def _spend_bundle(account: bytes, node: int):
    """The commuting spend shape from the sim driver
    (mysticeti_tpu/scenarios._exec_driver): two nonce-ordered transfers
    plus a deliberate overdraft — a deterministic typed reject folded
    into the root like any other verdict (the nonce-3 overdraft check is
    the FOLD's job; admission admits ahead-of-state nonces)."""
    from mysticeti_tpu.execution import OP_TRANSFER, ExecTx

    sink = f"sink-{node}".encode()
    return [
        ExecTx(OP_TRANSFER, account, nonce=1, amount=300, dest=sink),
        ExecTx(OP_TRANSFER, account, nonce=2, amount=300, dest=b"treasury"),
        ExecTx(OP_TRANSFER, account, nonce=3, amount=500, dest=sink),
    ]


async def _exec_client(host, port, node, interval_s, stats, stop):
    """Per-node closed-loop gateway submitter.  Each tick CREATEs a fresh
    account and spends the oldest previously-created one; the ingress
    plane's identity lanes + typed sheds are part of the contract — a
    spend arriving before its CREATE committed sheds as
    ``unknown_account`` and is re-offered next tick."""
    from mysticeti_tpu.execution import OP_CREATE, ExecTx
    from mysticeti_tpu.network import (
        GatewaySubmit,
        _read_frame,
        _write_frame,
        decode_message,
        encode_message,
    )

    async def submit(txs):
        _write_frame(
            writer,
            encode_message(
                GatewaySubmit(b"", 0, tuple(tx.to_bytes() for tx in txs))
            ),
        )
        await writer.drain()
        reply = decode_message(await _read_frame(reader))
        stats["submitted"] += len(txs)
        stats["accepted"] += reply.accepted
        stats["shed"] += reply.shed
        if reply.shed and reply.reason:
            reason = reply.reason.decode("utf-8", "replace")
            stats["shed_reasons"][reason] = (
                stats["shed_reasons"].get(reason, 0) + reply.shed
            )
        return reply

    reader, writer = await asyncio.open_connection(host, port)
    batch = 0
    unspent = []
    try:
        while not stop.is_set():
            batch += 1
            account = f"fleet-{node}-{batch}".encode()
            await submit([ExecTx(OP_CREATE, account, amount=1000)])
            unspent.append(account)
            # Spend the oldest created account; requeue on a full shed
            # (its CREATE has not committed yet).
            if len(unspent) > 1:
                target = unspent.pop(0)
                reply = await submit(_spend_bundle(target, node))
                if reply.accepted == 0:
                    unspent.insert(0, target)
            await asyncio.sleep(interval_s)
    finally:
        writer.close()


async def _exec_subscriber(host, port, record, stop):
    """want_executed subscriber: records the synthetic resume reply and the
    per-height EXECUTED roots streamed afterwards."""
    from mysticeti_tpu.network import (
        GatewaySubscribeCommits,
        _read_frame,
        _write_frame,
        decode_message,
        encode_message,
    )

    reader, writer = await asyncio.open_connection(host, port)
    try:
        _write_frame(
            writer,
            encode_message(GatewaySubscribeCommits(0, want_executed=1)),
        )
        await writer.drain()
        first = decode_message(await _read_frame(reader))
        record["resume_reply"] = {
            "height": first.height,
            "keys": len(first.keys),
            "root": first.executed_root.hex(),
        }
        if first.keys:  # not the synthetic reply: a live notification
            record["roots"][str(first.height)] = first.executed_root.hex()
        while not stop.is_set():
            try:
                note = decode_message(
                    await asyncio.wait_for(_read_frame(reader), timeout=1.0)
                )
            except asyncio.TimeoutError:
                continue
            if note.executed_root:
                record["roots"][str(note.height)] = note.executed_root.hex()
    finally:
        writer.close()


def _patch_parameters(path: str, gateway_port_base: int) -> None:
    """Arm the execution plane + gateway in the generated parameters.yaml
    (node processes load it at boot)."""
    from mysticeti_tpu.config import Parameters

    parameters = Parameters.load(path)
    parameters.execution = True
    parameters.ingress.gateway_port_base = gateway_port_base
    parameters.dump(path)


def _cross_check_roots(debug_docs: dict, client_roots: dict) -> dict:
    """Per-height state-root agreement across the fleet's /debug windows
    and the client-observed notification stream."""
    by_height: dict = {}
    for authority, doc in debug_docs.items():
        execution = (doc or {}).get("execution") or {}
        for entry in execution.get("recent_roots", []):
            by_height.setdefault(str(entry["height"]), {})[authority] = entry[
                "root"
            ]
    forks = []
    shared = 0
    for height, roots in sorted(by_height.items(), key=lambda kv: int(kv[0])):
        if len(roots) > 1:
            shared += 1
            if len(set(roots.values())) != 1:
                forks.append({"height": int(height), "roots": roots})
        observed = client_roots.get(height)
        if observed is not None and observed not in roots.values():
            forks.append(
                {"height": int(height), "client": observed, "roots": roots}
            )
    return {
        "shared_heights": shared,
        "client_heights_checked": sum(
            1 for h in client_roots if h in by_height
        ),
        "forks": forks,
        "agree": not forks and shared > 0,
    }


async def run_fleet_leg(args) -> dict:
    from mysticeti_tpu.orchestrator.runner import (
        LocalProcessRunner,
        _http_get_metrics,
    )

    os.environ["INITIAL_DELAY"] = "1"
    workdir = _fresh_dir(os.path.join(args.workdir, "fleet"))
    runner = LocalProcessRunner(workdir, verifier="cpu")
    started = time.time()
    await runner.configure(args.nodes, args.load)
    _patch_parameters(
        os.path.join(workdir, "parameters.yaml"), args.gateway_port_base
    )
    client_stats = {
        "submitted": 0, "accepted": 0, "shed": 0, "shed_reasons": {},
    }
    subscription = {"resume_reply": None, "roots": {}}
    debug_docs = {}
    stop = asyncio.Event()
    tasks = []
    try:
        for authority in range(args.nodes):
            await runner.boot_node(authority)
        await asyncio.sleep(2.0)  # gateways listening
        for authority in range(args.nodes):
            tasks.append(
                asyncio.ensure_future(
                    _exec_client(
                        "127.0.0.1",
                        args.gateway_port_base + authority,
                        authority,
                        args.exec_interval,
                        client_stats,
                        stop,
                    )
                )
            )
        tasks.append(
            asyncio.ensure_future(
                _exec_subscriber(
                    "127.0.0.1", args.gateway_port_base, subscription, stop
                )
            )
        )
        await asyncio.sleep(args.duration)
        stop.set()
        await asyncio.gather(*tasks, return_exceptions=True)
        texts = [await runner.scrape(a) for a in range(args.nodes)]
        for authority in range(args.nodes):
            host, port = runner.parameters.metrics_address(authority)
            doc = await _http_get_metrics(host, port, path="/debug/consensus")
            try:
                debug_docs[str(authority)] = json.loads(doc) if doc else None
            except ValueError:
                debug_docs[str(authority)] = None
    finally:
        stop.set()
        for task in tasks:
            task.cancel()
        await runner.cleanup()

    per_node = {}
    executed_heights = []
    applied_rates = []
    for authority, text in enumerate(texts):
        series = _node_series(text)
        execution = (debug_docs.get(str(authority)) or {}).get(
            "execution"
        ) or {}
        height = int(series["mysticeti_execution_height"])
        executed_heights.append(height)
        applied = series["txs_by_result"].get("applied", 0)
        applied_rates.append(applied / args.duration)
        phase_count = series["execute_phase_count"]
        per_node[str(authority)] = {
            "executed_height": height,
            "accounts": int(series["mysticeti_execution_accounts"]),
            "txs_by_result": series["txs_by_result"],
            "final_root": execution.get("root"),
            "execute_phase_mean_s": round(
                series["execute_phase_sum_s"] / phase_count, 5
            )
            if phase_count
            else None,
            "finality_p50_s": round(
                series["mysticeti_e2e_finality_p50_seconds"], 4
            ),
            "finality_p99_s": round(
                series["mysticeti_e2e_finality_p99_seconds"], 4
            ),
        }
    agreement = _cross_check_roots(debug_docs, subscription["roots"])
    return {
        "nodes": args.nodes,
        "load_tx_s": args.load,
        "window_utc": [round(started, 1), round(time.time(), 1)],
        "client": dict(client_stats),
        "subscriber": {
            "resume_reply": subscription["resume_reply"],
            "executed_notifications": len(subscription["roots"]),
        },
        "per_node": per_node,
        "executed_height_min": min(executed_heights, default=0),
        "executed_height_max": max(executed_heights, default=0),
        "executed_tx_s": round(max(applied_rates, default=0.0), 1),
        "root_agreement": agreement,
        "all_nodes_executed": all(h > 0 for h in executed_heights),
    }


def run_sim_leg(args, wal_dir: str) -> dict:
    import dataclasses

    from mysticeti_tpu.scenarios import run_scenario, scenario_by_name

    scenario = dataclasses.replace(
        scenario_by_name("execution-byzantine-at-f"),
        duration_s=args.sim_duration,
    )
    first = run_scenario(scenario, _fresh_dir(os.path.join(wal_dir, "a")))
    second = run_scenario(scenario, _fresh_dir(os.path.join(wal_dir, "b")))
    execution = first.get("execution", {})
    twin = second.get("execution", {})
    return {
        "scenario": scenario.name,
        "nodes": scenario.nodes,
        "duration_s": scenario.duration_s,
        "passed": bool(first.get("passed")),
        "safety_ok": bool(first.get("safety_ok")),
        "execution_ok": bool(execution.get("execution_ok")),
        "executed_heights": execution.get("executed_heights", {}),
        "chain_length": execution.get("chain_length", 0),
        "final_root": execution.get("final_root"),
        "root_chain_digest": execution.get("root_chain_digest"),
        "byte_identical": (
            bool(execution.get("root_chain_digest"))
            and execution.get("root_chain_digest")
            == twin.get("root_chain_digest")
        ),
    }


def main() -> int:
    parser = argparse.ArgumentParser(
        prog="execution_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--load", type=int, default=1000,
                        help="background benchmark load, tx/s (the exec "
                        "workload rides on top through the gateway)")
    parser.add_argument("--duration", type=float, default=30.0)
    parser.add_argument("--exec-interval", type=float, default=0.25,
                        help="seconds between workload batches per node")
    parser.add_argument("--sim-duration", type=float, default=5.0)
    parser.add_argument("--gateway-port-base", type=int, default=18650)
    parser.add_argument("--workdir", default="/tmp/mysticeti-execution")
    parser.add_argument("--out", default="EXEC.json")
    parser.add_argument("--skip-fleet", action="store_true",
                        help="sim leg only (no process fleet)")
    args = parser.parse_args()

    fleet = None
    if not args.skip_fleet:
        print(f"fleet leg: {args.nodes} nodes, execution plane on, "
              f"{args.duration}s...", flush=True)
        fleet = asyncio.run(run_fleet_leg(args))
        print(json.dumps({
            "executed_height_max": fleet["executed_height_max"],
            "executed_tx_s": fleet["executed_tx_s"],
            "root_agreement": fleet["root_agreement"]["agree"],
        }), flush=True)

    print("sim leg: seeded execution-byzantine-at-f x2 (root-chain "
          "byte-identity)...", flush=True)
    sim = run_sim_leg(args, os.path.join(args.workdir, "sim"))
    print(json.dumps({k: sim[k] for k in
                      ("passed", "execution_ok", "byte_identical")}),
          flush=True)

    acceptance = {
        "sim_passed": sim["passed"],
        "sim_execution_ok": sim["execution_ok"],
        "sim_byte_identical": sim["byte_identical"],
    }
    if fleet is not None:
        acceptance["fleet_roots_agree"] = fleet["root_agreement"]["agree"]
        acceptance["fleet_all_nodes_executed"] = fleet["all_nodes_executed"]
        acceptance["fleet_resume_reply"] = (
            fleet["subscriber"]["resume_reply"] is not None
        )
    artifact = {
        "metric": "execution",
        "nodes": args.nodes,
        "verifier": "cpu",
        "rule": (
            "every node folds the committed sequence to the same per-height "
            "state root (fleet /debug windows + client EXECUTED stream "
            "cross-checked); execute-phase finality measured on the "
            "e2e histogram; seeded execution-byzantine-at-f root chains "
            "byte-identical across same-seed runs"
        ),
        "fleet": fleet,
        "sim": sim,
        "determinism": {
            "byte_identical": sim["byte_identical"],
            "root_chain_digest": sim["root_chain_digest"],
        },
        "acceptance": acceptance,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0 if all(acceptance.values()) else 3


if __name__ == "__main__":
    sys.exit(main())
