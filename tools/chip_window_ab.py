#!/usr/bin/env python3
"""One-command chip-window capture: same-window tpu-vs-cpu fleet A/B.

VERDICT r5 #3 prep: the first artifact where a tpu flavor beats cpu at
fleet level needs a chip window — and chip windows are short, so the run
must be a single command with zero setup decisions left.  This tool runs
the VERIFICATION-BOUND fleet regime (the `CATCHUP_r05.json` configuration:
small blocks to raise the block/signature rate, deep retain window, fast
leader timeout) as back-to-back cpu and tpu-flavor max-load searches in one
weather window, and records:

  * the resolved jax platform ("cpu" = degraded, no chip; "tpu" = cashed
    window) — so the artifact is honest about what it measured;
  * per-probe hostmon weather + a same-window cpu reference probe
    (inherited from tools/maxload_bench.py), so the A/B is self-contained;
  * the headline ratio `tpu_peak / cpu_peak`.

On the degraded (no-chip) backend this doubles as the zero-tax acceptance
artifact: with backend-aware short-circuit routing the tpu flavor must
price at >= 0.9x cpu (ISSUE 6 / VERDICT #4).

Usage:
  python tools/chip_window_ab.py --out MAXLOAD_TAX_r06.json
  python tools/chip_window_ab.py --tpu-flavor tpu-agg --duration 30 \
      --iterations 6 --out CHIPWINDOW_r06.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from maxload_bench import search_one  # noqa: E402 - sibling tool module


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--start-load", type=int, default=400)
    parser.add_argument("--duration", type=float, default=20.0)
    parser.add_argument("--iterations", type=int, default=5)
    parser.add_argument("--max-block-tx", type=int, default=16)
    parser.add_argument("--tpu-flavor", default="tpu",
                        choices=["tpu", "tpu-only", "tpu-agg"])
    parser.add_argument("--workdir", default="/tmp/mysticeti-chipwindow")
    parser.add_argument("--out", default="CHIPWINDOW.json")
    args = parser.parse_args()

    # The verification-bound regime (catchup_bench.py's genesis-time env):
    # small blocks raise the signature rate per committed tx, the retain
    # window keeps sync streams deep, and the short leader timeout keeps
    # stalls from hiding verification cost.
    os.environ["MYSTICETI_MAX_BLOCK_TX"] = str(args.max_block_tx)
    os.environ["MYSTICETI_RETAIN_ROUNDS"] = "100000"
    os.environ["MYSTICETI_LEADER_TIMEOUT"] = "0.25"

    # Prewarm the persistent kernel cache in THIS process and record what
    # platform actually answered — the artifact's chip-window flag.
    print("prewarming kernel cache...", flush=True)
    from mysticeti_tpu import crypto
    from mysticeti_tpu.block_validator import TpuSignatureVerifier

    signers = [
        crypto.Signer.from_seed(i.to_bytes(32, "little"))
        for i in range(args.nodes)
    ]
    backend = TpuSignatureVerifier(
        committee_keys=[s.public_key.bytes for s in signers]
    )
    backend.warmup()
    platform = backend.resolved_backend()
    print(f"resolved jax platform: {platform}", flush=True)

    window_start = time.time()
    runs = []
    for verifier in ("cpu", args.tpu_flavor):
        print(f"max-load search verifier={verifier}...", flush=True)
        run = asyncio.run(
            search_one(verifier, args.nodes, args.start_load, args.duration,
                       args.iterations, args.workdir)
        )
        runs.append(run)
        print(json.dumps(run), flush=True)

    cpu_peak = runs[0]["peak_committed_tx_s"]
    tpu_peak = runs[1]["peak_committed_tx_s"]
    artifact = {
        "metric": "same_window_tpu_vs_cpu_peak_committed_tx_s",
        "resolved_platform": platform,
        "chip_attached": platform != "cpu",
        "regime": {
            "max_block_tx": args.max_block_tx,
            "retain_rounds": 100000,
            "leader_timeout_s": 0.25,
            "note": (
                "verification-bound fleet shape (CATCHUP_r05 regime): "
                "small blocks maximize signatures per committed tx"
            ),
        },
        "window_utc": [round(window_start, 1), round(time.time(), 1)],
        "cpu_peak_committed_tx_s": cpu_peak,
        "tpu_peak_committed_tx_s": tpu_peak,
        "tpu_over_cpu": round(tpu_peak / cpu_peak, 3) if cpu_peak else None,
        "acceptance": (
            "chip window: tpu_over_cpu > 1 proves VERDICT #3; degraded "
            "(chip_attached=false): tpu_over_cpu >= 0.9 proves the "
            "zero-tax data plane (VERDICT #4)"
        ),
        "runs": runs,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
