#!/usr/bin/env python3
"""Perf-trend plane: normalize every bench artifact into one trajectory.

Five rounds of perf artifacts accumulated at the repo root with five
slightly different schemas (``BENCH_r01`` is ``{parsed: {...}}``,
``MAXLOAD_r02`` is flat, ``MAXLOAD_TPU_r03`` nests ``fleet_runs``,
``TENNODE_r05`` has a ``runs`` list, and failed rounds carry
``parsed: null``) — so the perf STORY was only readable by a human diffing
JSON by hand, and nothing could say "this round regressed".  This tool:

* **normalizes** every ``BENCH_*.json`` / ``MAXLOAD_*`` / ``TENNODE_*``
  artifact (tolerating the r1-r5 schema drift and ``parsed: null``
  records) into flat ``{round, source, metric, value, unit}`` records;
* maintains the **append-only** ``BENCH_TREND.json`` index (records are
  deduplicated by (source, metric, seq); re-running over the same
  artifacts is idempotent, new artifacts and live ``bench.py`` appends
  accumulate);
* prints the **terminal regression report** — per metric, the value by
  round with the delta vs the best prior round — and exits **non-zero
  (2) when the newest round of any metric regressed >10%** vs the best
  prior round, so CI and the driver can gate on the trajectory;
* is wired into ``bench.py``: every live run appends its measurement via
  :func:`append_record`, so the trajectory can never be empty again.

Usage:
    python tools/bench_trend.py                     # scan repo root, update index, report
    python tools/bench_trend.py --repo /path --out BENCH_TREND.json
    python tools/bench_trend.py --no-write          # report only
    python tools/bench_trend.py --tolerance 0.2     # custom regression gate
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Optional

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

ARTIFACT_GLOBS = (
    "BENCH_*.json", "MAXLOAD_*.json", "TENNODE_*.json", "OVERLOAD_*.json",
    "SCENARIO_*.json", "PERF_ATTR_*.json", "DETSAN_*.json",
    "FINALITY_*.json", "RECONFIG_*.json", "EXEC_*.json",
)

# >10% below the best prior round fails the gate.
DEFAULT_TOLERANCE = 0.10


def _record(round_, source, metric, value, unit, **extra) -> dict:
    rec = {
        "round": round_,
        "source": source,
        "metric": metric,
        "value": None if value is None else round(float(value), 3),
        "unit": unit,
    }
    rec.update(extra)
    return rec


def normalize(path: str) -> List[dict]:
    """Flatten one artifact into trend records.  Unknown shapes yield an
    unparsed marker record rather than nothing — the trajectory must show
    that a round produced an artifact even when it cannot score it."""
    source = os.path.basename(path)
    match = _ROUND_RE.search(source)
    round_ = int(match.group(1)) if match else None
    # The artifact FAMILY (MAXLOAD vs MAXLOAD_TPU vs TENNODE ...) namespaces
    # the fleet metrics: different families measure different
    # configurations, and comparing e.g. a TPU-fleet peak against a CPU
    # search would make the regression gate fire on configuration
    # differences instead of regressions.
    family = re.sub(r"_r\d+\.json$", "", source)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        return [_record(round_, source, "unparsed", None, "",
                        note=f"unreadable: {exc}")]
    if not isinstance(doc, dict):
        return [_record(round_, source, "unparsed", None, "",
                        note="unrecognized shape")]
    out: List[dict] = []

    # BENCH_rNN: driver wrapper {n, cmd, rc, tail, parsed: {...}|null}.
    if "parsed" in doc:
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed.get("value") is not None:
            out.append(_record(
                round_, source, parsed.get("metric", "bench"),
                parsed["value"], parsed.get("unit", ""),
                vs_baseline=parsed.get("vs_baseline"),
            ))
        else:
            # parsed=null: the round ran and failed — record the failure so
            # the trajectory shows the gap instead of silently skipping it.
            out.append(_record(
                round_, source, "ed25519_verifies_per_sec", None, "sig/s",
                note=f"parsed=null (rc={doc.get('rc')})",
            ))
        return out

    # bench.py direct record (also what append_record receives).
    if doc.get("metric") == "ed25519_verifies_per_sec" and "value" in doc:
        out.append(_record(round_, source, doc["metric"], doc["value"],
                           doc.get("unit", "sig/s"),
                           vs_baseline=doc.get("vs_baseline")))
        return out

    # BENCH_SAMPLES_rNN: all-day samples; score the round by its best and
    # worst sample (the spread IS the story there).
    samples = doc.get("samples_utc")
    if isinstance(samples, list) and samples:
        values = [s.get("value") for s in samples if s.get("value") is not None]
        if values:
            out.append(_record(round_, source, "bench_samples_best",
                               max(values), "sig/s", samples=len(values)))
            out.append(_record(round_, source, "bench_samples_worst",
                               min(values), "sig/s", samples=len(values)))
        return out

    def fleet_run(rec: dict, prefix: str) -> None:
        """One orchestrator fleet-run blob, wherever it is nested."""
        for key, metric in (
            ("max_sustainable_load_tx_s", "max_sustainable_load_tx_s"),
            ("peak_committed_tx_s", "peak_committed_tx_s"),
            ("committed_tx_s", "committed_tx_s"),
        ):
            if rec.get(key) is not None:
                out.append(_record(
                    round_, source, f"{prefix}{metric}", rec[key], "tx/s",
                    verifier=rec.get("verifier"), nodes=rec.get("nodes"),
                ))

    # OVERLOAD: per-multiplier committed-vs-offered rungs + the no-collapse
    # ratios.  Its own family: the rungs measure degradation shape, and a
    # ratio near 1.0 is the win — gating it against MAXLOAD peaks would
    # compare different metrics.
    if doc.get("metric") == "overload_committed_vs_offered":
        for rung in doc.get("rungs") or []:
            mult = rung.get("multiplier")
            if mult is None or rung.get("committed_tx_s") is None:
                continue
            out.append(_record(
                round_, source, f"{family}.committed_tx_s_{mult}x",
                rung["committed_tx_s"], "tx/s",
                offered=rung.get("offered_tx_s"), nodes=doc.get("nodes"),
            ))
        acceptance = doc.get("acceptance") or {}
        for key in ("committed_3x_over_1x", "committed_5x_over_1x",
                    "sim_committed_3x_over_1x"):
            value = acceptance.get(key)
            if value is None:
                value = (doc.get("determinism") or {}).get(key)
            if value is not None:
                out.append(_record(round_, source, f"{family}.{key}", value,
                                   "ratio"))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="overload artifact with no scored rungs")]

    # SCENARIO: the resilience matrix (tools/scenario_matrix.py).  One
    # verdict row per scenario; the SCORED value is pass (1.0) / fail
    # (0.0), so the generic gate fires exactly when a scenario FLIPS from
    # pass to fail (a 100% drop) and never on throughput-ratio noise
    # between passing rounds — the ratio rides along as context.
    if doc.get("metric") == "scenario_matrix":
        for verdict in doc.get("scenarios") or []:
            scenario = (verdict.get("scenario") or {}).get("name")
            if not scenario:
                continue
            out.append(_record(
                round_, source, f"{family}.{scenario}.passed",
                1.0 if verdict.get("passed") else 0.0, "pass",
                ratio=verdict.get("throughput_ratio"),
                min_ratio=(verdict.get("scenario") or {}).get("min_ratio"),
                safety_ok=verdict.get("safety_ok"),
            ))
        determinism = doc.get("determinism") or {}
        if determinism.get("byte_identical") is not None:
            out.append(_record(
                round_, source, f"{family}.determinism_byte_identical",
                1.0 if determinism["byte_identical"] else 0.0, "pass",
                scenario=determinism.get("scenario"),
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="scenario artifact with no verdicts")]

    # RECONFIG: the continuous-churn epoch-reconfiguration matrix
    # (tools/reconfig_matrix.py).  Verdict rows score pass (1.0) / fail
    # (0.0) like the scenario matrix — the generic gate fires exactly when
    # a churn scenario FLIPS from pass to fail; epochs reached and the
    # throughput ratio ride along as context.  The live-testbed epoch
    # cycle (one add-node + one remove-node epoch under load) and the
    # same-seed byte-identity check score the same way.
    if doc.get("metric") == "reconfig":
        for verdict in doc.get("scenarios") or []:
            scenario = (verdict.get("scenario") or {}).get("name")
            if not scenario:
                continue
            out.append(_record(
                round_, source, f"{family}.{scenario}.passed",
                1.0 if verdict.get("passed") else 0.0, "pass",
                ratio=verdict.get("throughput_ratio"),
                max_epoch=verdict.get("max_epoch"),
                min_epoch=verdict.get("min_epoch"),
                safety_ok=verdict.get("safety_ok"),
            ))
        determinism = doc.get("determinism") or {}
        if determinism.get("byte_identical") is not None:
            out.append(_record(
                round_, source, f"{family}.determinism_byte_identical",
                1.0 if determinism["byte_identical"] else 0.0, "pass",
                scenario=determinism.get("scenario"),
            ))
        live = doc.get("live") or {}
        if live.get("passed") is not None:
            out.append(_record(
                round_, source, f"{family}.live_epoch_cycle",
                1.0 if live["passed"] else 0.0, "pass",
                nodes=live.get("nodes"), epochs=live.get("epochs_reached"),
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="reconfig artifact with no verdicts")]

    # DETSAN: the determinism-sanitizer verdict (tools/detsan.py).  Every
    # scored value is pass (1.0) / fail (0.0), so the generic gate fires
    # exactly when a determinism property FLIPS — the clean run-twice sim
    # stops being byte-identical, the bisector stops catching the planted
    # leak, or a historical-fixture detection regresses.
    if doc.get("metric") == "detsan":
        clean = doc.get("clean") or {}
        planted = doc.get("planted") or {}
        if clean.get("identical") is not None:
            out.append(_record(
                round_, source, f"{family}.clean_identical",
                1.0 if clean["identical"] else 0.0, "pass",
                events=clean.get("events_a"), nodes=doc.get("nodes"),
            ))
        if planted.get("identical") is not None:
            detected = (
                not planted["identical"]
                and planted.get("first_divergence") is not None
            )
            out.append(_record(
                round_, source, f"{family}.planted_leak_bisected",
                1.0 if detected else 0.0, "pass",
                first_divergence_index=(
                    (planted.get("first_divergence") or {}).get("index")
                ),
            ))
        for name, detected in sorted((doc.get("fixtures") or {}).items()):
            out.append(_record(
                round_, source, f"{family}.fixture_{name}_detected",
                1.0 if detected else 0.0, "pass",
            ))
        tripwire = doc.get("tripwire") or {}
        if tripwire.get("strict_mode_raised") is not None:
            out.append(_record(
                round_, source, f"{family}.tripwire_strict_raises",
                1.0 if tripwire["strict_mode_raised"] else 0.0, "pass",
                counted_reads=tripwire.get("counted_reads"),
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="detsan artifact with no verdicts")]

    # FINALITY: the submit→finality SLI artifact (tools/finality_bench.py).
    # Latency is lower-is-better, so the SCORED value is its inverse
    # (finalizations per second at the percentile) — the generic
    # higher-is-better gate then fires exactly when p50/p99 finality gets
    # >tolerance SLOWER; the raw seconds ride along as context.  The
    # decision-ledger and server/client cross-check verdicts score as
    # pass (1.0) / fail (0.0) like the scenario matrix.
    if doc.get("metric") == "finality":
        server = doc.get("server") or {}
        for pct in ("p50", "p99"):
            seconds = server.get(f"{pct}_s")
            if seconds and seconds > 0:
                out.append(_record(
                    round_, source, f"{family}.finality_{pct}_inv",
                    1.0 / seconds, "1/s",
                    seconds=round(float(seconds), 6),
                    samples=server.get("samples"), nodes=doc.get("nodes"),
                ))
        acceptance = doc.get("acceptance") or {}
        for key in ("client_cross_check", "every_slot_explained"):
            if acceptance.get(key) is not None:
                out.append(_record(round_, source, f"{family}.{key}",
                                   1.0 if acceptance[key] else 0.0, "pass"))
        determinism = doc.get("determinism") or {}
        if determinism.get("byte_identical") is not None:
            out.append(_record(
                round_, source, f"{family}.ledger_byte_identical",
                1.0 if determinism["byte_identical"] else 0.0, "pass",
                digest=determinism.get("digest"),
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="finality artifact with no scored percentiles")]

    # EXEC: the execution-plane artifact (tools/execution_bench.py).
    # Executed tx/s scores on the generic higher-is-better gate; every
    # agreement/determinism verdict scores pass (1.0) / fail (0.0), so the
    # gate fires exactly when state-root agreement or same-seed
    # reproducibility FLIPS.
    if doc.get("metric") == "execution":
        fleet = doc.get("fleet") or {}
        sim = doc.get("sim") or {}
        if fleet.get("executed_tx_s"):
            out.append(_record(
                round_, source, f"{family}.executed_tx_s",
                fleet["executed_tx_s"], "tx/s",
                nodes=doc.get("nodes"),
                executed_height_max=fleet.get("executed_height_max"),
            ))
        acceptance = doc.get("acceptance") or {}
        for key in ("fleet_roots_agree", "sim_passed", "sim_execution_ok"):
            if acceptance.get(key) is not None:
                out.append(_record(round_, source, f"{family}.{key}",
                                   1.0 if acceptance[key] else 0.0, "pass"))
        determinism = doc.get("determinism") or {}
        if determinism.get("byte_identical") is not None:
            out.append(_record(
                round_, source, f"{family}.root_chain_byte_identical",
                1.0 if determinism["byte_identical"] else 0.0, "pass",
                digest=determinism.get("root_chain_digest"),
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="execution artifact with no verdicts")]

    # PERF_ATTR: the host attribution artifact (tools/perf_attr.py).  One
    # budget row per subsystem, scored as committed leaders per CPU-second
    # (HIGHER is better — the gate's direction), so any subsystem whose
    # per-leader cost creeps >tolerance fires the generic gate; the raw
    # µs/leader rides along as context.  attributed_ratio gates too: an
    # attribution map decaying toward "other" is itself a regression.
    if doc.get("metric") == "perf_attr":
        for sub, rec in sorted((doc.get("subsystems") or {}).items()):
            us = rec.get("us_per_leader") if isinstance(rec, dict) else None
            if not us or us <= 0:
                continue
            out.append(_record(
                round_, source, f"{family}.{sub}.leaders_per_cpu_s",
                1e6 / us, "ldr/cpu-s",
                us_per_leader=round(float(us), 3), cpu_s=rec.get("cpu_s"),
                nodes=doc.get("nodes"),
            ))
        if doc.get("attributed_ratio") is not None:
            out.append(_record(
                round_, source, f"{family}.attributed_ratio",
                doc["attributed_ratio"], "ratio",
            ))
        if out:
            return out
        return [_record(round_, source, "unparsed", None, "",
                        note="perf_attr artifact with no subsystem rows")]

    # MAXLOAD_TAX: same-window A/B.
    if "tpu_over_cpu" in doc:
        for key, unit in (
            ("cpu_peak_committed_tx_s", "tx/s"),
            ("tpu_peak_committed_tx_s", "tx/s"),
            ("tpu_over_cpu", "ratio"),
        ):
            if doc.get(key) is not None:
                out.append(_record(round_, source, key, doc[key], unit))
        return out

    # MAXLOAD_TPU: nested fleet_runs {name: run}.
    if isinstance(doc.get("fleet_runs"), dict):
        for name, rec in sorted(doc["fleet_runs"].items()):
            if isinstance(rec, dict):
                fleet_run(rec, f"{family}.{name}.")
        return out

    # TENNODE_r05-style: runs list — score the best committed rate.
    if isinstance(doc.get("runs"), list) and doc["runs"]:
        best = max(
            (r for r in doc["runs"] if isinstance(r, dict)),
            key=lambda r: r.get("committed_tx_s") or 0.0,
            default=None,
        )
        if best is not None:
            fleet_run(best, f"{family}.")
        return out

    # Flat orchestrator record (MAXLOAD_r02, TENNODE_r02).
    fleet_run(doc, f"{family}.")
    if out:
        return out
    return [_record(round_, source, "unparsed", None, "",
                    note="unrecognized shape")]


# ---------------------------------------------------------------------------
# The append-only index


def load_index(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("records"), list):
            return doc
    except (OSError, ValueError):
        pass
    return {"kind": "mysticeti-bench-trend", "records": []}


def _key(rec: dict) -> tuple:
    return (rec.get("source"), rec.get("metric"), rec.get("seq"))


def merge_index(index: dict, fresh: List[dict]) -> int:
    """Append records not already present (append-only: existing entries are
    never rewritten, so live bench.py appends survive re-scans)."""
    seen = {_key(r) for r in index["records"]}
    added = 0
    for rec in fresh:
        if _key(rec) in seen:
            continue
        index["records"].append(rec)
        seen.add(_key(rec))
        added += 1
    return added


def write_index(index: dict, path: str) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def append_record(record: dict, path: Optional[str] = None) -> None:
    """Live append from bench.py: one measurement lands in the trend index
    the moment it is produced (``seq`` makes repeated live runs distinct
    where artifact records dedup by (source, metric)).  Never raises — the
    trend plane must not be able to break a measurement."""
    try:
        path = path or os.environ.get("BENCH_TREND_PATH", "BENCH_TREND.json")
        index = load_index(path)
        seq = 1 + sum(
            1 for r in index["records"] if r.get("source") == "bench.py(live)"
        )
        rec = _record(
            None, "bench.py(live)", record.get("metric", "bench"),
            record.get("value"), record.get("unit", ""),
            vs_baseline=record.get("vs_baseline"), seq=seq,
        )
        index["records"].append(rec)
        write_index(index, path)
    except Exception:  # noqa: BLE001 - diagnostics only, never fail the bench
        pass


# ---------------------------------------------------------------------------
# The regression report


def trajectory(records: List[dict]) -> Dict[str, List[dict]]:
    """Per metric: the scored records in round order (unscored dropped)."""
    by_metric: Dict[str, List[dict]] = defaultdict(list)
    for rec in records:
        if rec.get("value") is None:
            continue
        by_metric[rec["metric"]].append(rec)
    for recs in by_metric.values():
        recs.sort(key=lambda r: (
            r["round"] if r.get("round") is not None else 1 << 30,
            r.get("seq") or 0,
            r["source"],
        ))
    return dict(by_metric)


def regression_report(records: List[dict], tolerance: float):
    """Returns (lines, regressions): per-metric round-by-round values with
    the delta vs the best PRIOR round; a metric whose newest round sits
    >tolerance below its best prior round is a regression."""
    by_metric = trajectory(records)
    lines: List[str] = []
    regressions: List[str] = []
    rounds_parsed = {
        rec["round"]
        for recs in by_metric.values()
        for rec in recs
        if rec.get("round") is not None
    }
    lines.append(
        f"bench trend: {sum(len(v) for v in by_metric.values())} scored "
        f"record(s), {len(by_metric)} metric(s), "
        f"{len(rounds_parsed)} round(s) parsed"
    )
    for metric in sorted(by_metric):
        recs = by_metric[metric]
        lines.append("")
        lines.append(f"{metric}:")
        best_prior: Optional[float] = None
        for rec in recs:
            value = rec["value"]
            label = (
                f"r{rec['round']:02d}" if rec.get("round") is not None
                else f"live#{rec.get('seq', '?')}"
            )
            delta = ""
            if best_prior is not None and best_prior > 0:
                pct = (value - best_prior) / best_prior * 100
                delta = f"  {pct:+7.1f}% vs best prior"
            lines.append(
                f"  {label:<8}{value:>14,.1f} {rec.get('unit', ''):<6}"
                f"{delta}  [{rec['source']}]"
            )
            best_prior = value if best_prior is None else max(best_prior, value)
        # Gate on the NEWEST ROUND only (history is context, not a
        # verdict).  Live bench.py appends (round=None) are shown above
        # but never gate: a casual laptop run or a documented zero-record
        # must not flip CI red against a real round's number.
        rounds = [r for r in recs if r.get("round") is not None]
        if len(rounds) >= 2:
            latest = rounds[-1]["value"]
            prior_best = max(r["value"] for r in rounds[:-1])
            if prior_best > 0 and latest < prior_best * (1 - tolerance):
                regressions.append(
                    f"{metric}: latest {latest:,.1f} is "
                    f"{(prior_best - latest) / prior_best * 100:.1f}% below "
                    f"best prior {prior_best:,.1f} ({rounds[-1]['source']})"
                )
    if regressions:
        lines.append("")
        lines.append(f"REGRESSIONS (> {tolerance * 100:.0f}% below best prior round):")
        for line in regressions:
            lines.append(f"  {line}")
    return lines, regressions


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_trend", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ), help="directory holding the round artifacts (default: repo root)")
    parser.add_argument("--out", default=None,
                        help="trend index path (default: <repo>/BENCH_TREND.json)")
    parser.add_argument("--no-write", action="store_true",
                        help="report only; do not update the index")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="regression gate: fail when the newest round "
                        "is more than this fraction below the best prior")
    args = parser.parse_args(argv)
    out = args.out or os.path.join(args.repo, "BENCH_TREND.json")

    fresh: List[dict] = []
    for pattern in ARTIFACT_GLOBS:
        for path in sorted(glob.glob(os.path.join(args.repo, pattern))):
            if os.path.abspath(path) == os.path.abspath(out):
                continue
            fresh.extend(normalize(path))
    index = load_index(out)
    added = merge_index(index, fresh)
    if not args.no_write:
        write_index(index, out)
        print(f"{out}: {added} new record(s), "
              f"{len(index['records'])} total", file=sys.stderr)

    lines, regressions = regression_report(index["records"], args.tolerance)
    print("\n".join(lines))
    return 2 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
