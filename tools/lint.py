#!/usr/bin/env python
"""Thin CLI alias for the invariant checker.

    python tools/lint.py                  # gate: exit 1 on new findings
    python tools/lint.py --no-baseline    # show everything
    python tools/lint.py --baseline-regen # refresh analysis/baseline.json

Equivalent to ``python -m mysticeti_tpu.analysis``; see
docs/static-analysis.md for the rule catalog.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
