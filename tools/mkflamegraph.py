#!/usr/bin/env python3
"""Render a folded-stacks file (mysticeti_tpu.profiling / stackcollapse
format) to a self-contained flamegraph SVG.

Usage:
    python tools/mkflamegraph.py node.folded [out.svg]
    python tools/mkflamegraph.py --diff base.folded new.folded [out.svg]

``--diff`` renders an A/B flame diff: frames laid out by the NEW profile,
colored by their share change against the BASE (red grew, blue shrank) —
the before/after view a perf-budget regression investigation starts from.

Equivalent of the reference's ``orchestrator/assets/mkflamegraph.sh`` with
the perf+flamegraph.pl pipeline replaced by the in-repo renderer.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.profiling import render_diff, render_file  # noqa: E402


def main() -> int:
    args = sys.argv[1:]
    if args and args[0] == "--diff":
        if len(args) < 3:
            print(__doc__, file=sys.stderr)
            return 2
        out = render_diff(args[1], args[2], args[3] if len(args) > 3 else None)
        print(out)
        return 0
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    out = render_file(args[0], args[1] if len(args) > 1 else None)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
