#!/usr/bin/env python3
"""Render a folded-stacks file (mysticeti_tpu.profiling / stackcollapse
format) to a self-contained flamegraph SVG.

Usage:
    python tools/mkflamegraph.py node.folded [out.svg]

Equivalent of the reference's ``orchestrator/assets/mkflamegraph.sh`` with
the perf+flamegraph.pl pipeline replaced by the in-repo renderer.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.profiling import render_file  # noqa: E402


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    out = render_file(sys.argv[1], sys.argv[2] if len(sys.argv) > 2 else None)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
