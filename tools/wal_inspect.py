#!/usr/bin/env python3
"""Offline WAL/storage-lifecycle inspector.

Prints, for a node's WAL (segment directory or legacy single file):

* the segment manifest (name, base offset, on-disk size, recorded max round);
* the checkpoint chain (commit height, replay position, validity — a torn or
  corrupt checkpoint is reported, not hidden);
* a per-tag entry census from a full replay (block / payload / own-block /
  state / commit / snapshot), with byte totals;
* torn-tail / unreplayable-state diagnosis.

Exit status: 0 healthy (a torn ACTIVE tail is healthy — recovery truncates
it), non-zero on unreplayable state:

* 2 — a tear inside a SEALED segment (entries after it are unreachable);
* 3 — history below the first live segment was garbage-collected but no
  valid checkpoint covers it (the node cannot boot);
* 4 — manifest missing/corrupt or a listed segment file is gone.

Usage::

    python tools/wal_inspect.py <wal-path> [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mysticeti_tpu.block_store import (  # noqa: E402
    WAL_ENTRY_BLOCK,
    WAL_ENTRY_COMMIT,
    WAL_ENTRY_OWN_BLOCK,
    WAL_ENTRY_PAYLOAD,
    WAL_ENTRY_SNAPSHOT,
    WAL_ENTRY_STATE,
)
from mysticeti_tpu.storage import (  # noqa: E402
    Checkpoint,
    MANIFEST_NAME,
    checkpoint_files,
)
from mysticeti_tpu.wal import HEADER_SIZE, WalReader  # noqa: E402

TAG_NAMES = {
    WAL_ENTRY_BLOCK: "block",
    WAL_ENTRY_PAYLOAD: "payload",
    WAL_ENTRY_OWN_BLOCK: "own-block",
    WAL_ENTRY_STATE: "state",
    WAL_ENTRY_COMMIT: "commit",
    WAL_ENTRY_SNAPSHOT: "snapshot",
}


def _scan_file(path: str, base: int, census: dict) -> int:
    """Replay one segment file; returns bytes consumed (== file size iff the
    segment replays cleanly to its end)."""
    reader = WalReader(path)
    consumed = 0
    try:
        for pos, tag, payload in reader.iter_until():
            entry = HEADER_SIZE + len(payload)
            consumed = pos + entry
            name = TAG_NAMES.get(tag, f"tag-{tag}")
            count, total = census.get(name, (0, 0))
            census[name] = (count + 1, total + entry)
    finally:
        reader.close()
    return consumed


def inspect(path: str) -> dict:
    report: dict = {
        "path": path,
        "segments": [],
        "checkpoints": [],
        "census": {},
        "problems": [],
        "exit_code": 0,
    }
    census: dict = {}

    if os.path.isfile(path):
        report["layout"] = "single-file"
        size = os.path.getsize(path)
        consumed = _scan_file(path, 0, census)
        report["segments"].append(
            {"name": os.path.basename(path), "base": 0, "size": size,
             "replayed": consumed}
        )
        if consumed < size:
            report["torn_tail_bytes"] = size - consumed
    elif os.path.isdir(path):
        report["layout"] = "segmented"
        manifest_path = os.path.join(path, MANIFEST_NAME)
        try:
            with open(manifest_path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            report["problems"].append(f"manifest unreadable: {exc}")
            report["exit_code"] = 4
            report["census"] = {}
            return report
        segments = manifest.get("segments", [])
        for i, entry in enumerate(segments):
            seg_path = os.path.join(path, entry["name"])
            row = {"name": entry["name"], "base": entry["base"],
                   "max_round": entry.get("max_round", 0)}
            if not os.path.exists(seg_path):
                row["missing"] = True
                report["problems"].append(
                    f"segment {entry['name']} listed in manifest but missing"
                )
                report["exit_code"] = 4
                report["segments"].append(row)
                continue
            size = os.path.getsize(seg_path)
            consumed = _scan_file(seg_path, entry["base"], census)
            row["size"] = size
            row["replayed"] = consumed
            report["segments"].append(row)
            if consumed < size:
                if i == len(segments) - 1:
                    report["torn_tail_bytes"] = size - consumed
                else:
                    report["problems"].append(
                        f"tear inside SEALED segment {entry['name']} at local "
                        f"offset {consumed}: {len(segments) - 1 - i} later "
                        "segment(s) unreachable on replay"
                    )
                    report["exit_code"] = max(report["exit_code"], 2)
        first_base = segments[0]["base"] if segments else 0
        valid_ckpt = False
        for ckpt_path in checkpoint_files(path):
            row = {"name": os.path.basename(ckpt_path)}
            try:
                with open(ckpt_path, "rb") as f:
                    ckpt = Checkpoint.from_bytes(f.read())
                row.update(
                    commit_height=ckpt.commit_height,
                    wal_position=ckpt.wal_position,
                    gc_round=ckpt.gc_round,
                    index_entries=len(ckpt.index),
                    committed_refs=len(ckpt.committed_refs),
                    chain_digest=ckpt.chain_digest.hex()[:16],
                    valid=True,
                )
                if ckpt.wal_position >= first_base:
                    valid_ckpt = True
                else:
                    row["stale"] = (
                        "replay position below first live segment"
                    )
            except Exception as exc:  # noqa: BLE001 - any parse failure = corrupt
                row["valid"] = False
                row["error"] = str(exc)
                report["problems"].append(
                    f"checkpoint {row['name']} unusable: {exc}"
                )
            report["checkpoints"].append(row)
        if first_base > 0 and not valid_ckpt:
            report["problems"].append(
                f"history below offset {first_base} was garbage-collected "
                "but no valid checkpoint covers it: UNREPLAYABLE"
            )
            report["exit_code"] = max(report["exit_code"], 3)
    else:
        report["problems"].append("path is neither a file nor a directory")
        report["exit_code"] = 4

    report["census"] = {
        name: {"entries": count, "bytes": total}
        for name, (count, total) in sorted(census.items())
    }
    return report


def render(report: dict) -> str:
    lines = [f"WAL at {report['path']} ({report.get('layout', '?')})"]
    lines.append("  segments:")
    for seg in report["segments"]:
        if seg.get("missing"):
            lines.append(f"    {seg['name']}  MISSING")
            continue
        torn = ""
        if seg.get("replayed", seg.get("size", 0)) < seg.get("size", 0):
            torn = f"  (replays {seg['replayed']}/{seg['size']})"
        lines.append(
            f"    {seg['name']}  base={seg['base']}  size={seg.get('size')}"
            f"  max_round={seg.get('max_round', '-')}{torn}"
        )
    if report["checkpoints"]:
        lines.append("  checkpoints (newest first):")
        for ckpt in report["checkpoints"]:
            if ckpt.get("valid"):
                stale = f"  STALE({ckpt['stale']})" if "stale" in ckpt else ""
                lines.append(
                    f"    {ckpt['name']}  height={ckpt['commit_height']}"
                    f"  replay_from={ckpt['wal_position']}"
                    f"  gc_round={ckpt['gc_round']}"
                    f"  index={ckpt['index_entries']}"
                    f"  chain={ckpt['chain_digest']}{stale}"
                )
            else:
                lines.append(f"    {ckpt['name']}  CORRUPT: {ckpt['error']}")
    elif report.get("layout") == "segmented":
        lines.append("  checkpoints: none")
    lines.append("  entry census:")
    for name, row in report["census"].items():
        lines.append(
            f"    {name:<10} {row['entries']:>8} entries  {row['bytes']:>12} bytes"
        )
    if "torn_tail_bytes" in report:
        lines.append(
            f"  torn active tail: {report['torn_tail_bytes']} bytes "
            "(healthy: recovery truncates it)"
        )
    if report["problems"]:
        lines.append("  PROBLEMS:")
        for problem in report["problems"]:
            lines.append(f"    ! {problem}")
    else:
        lines.append("  state: replayable")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("path", help="WAL directory (segmented) or file")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report")
    args = parser.parse_args(argv)
    report = inspect(args.path)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render(report))
    return report["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
