#!/usr/bin/env python3
"""Headline benchmark: END-TO-END batched Ed25519 verification throughput on
the default JAX device (the real TPU chip under the driver; CPU elsewhere).

End-to-end means raw bytes in, accept/reject bits out: host packing (pure
numpy byte concatenation), transfer, device SHA-512 of R||A||M, mod-L
reduction, point decompression, the double-scalar ladder, and the canonical
compare are ALL inside the timed region.  The measured path is the one a
validator deploys (``ops.ed25519.verify_batch_table``): the signer set is a
known committee, so each signature ships as R||M||s + a key index into a
device-resident key table — not a kernel-only figure, and not a
hypothetical unknown-signer workload either.

Prints exactly ONE JSON line:
  {"metric": "ed25519_verifies_per_sec", "value": N, "unit": "sig/s", "vs_baseline": R}

``vs_baseline`` is measured against the BASELINE.json north-star target of
500k sig-verifies/sec/host (the reference itself publishes no number — its
dalek CPU path verifies serially per block, ~15-25k/s/core).
"""
from __future__ import annotations

import json
import os
import sys
import time

# Persistent compilation cache: mysticeti_tpu.ops.ed25519 sets a per-uid,
# ownership-checked default when JAX_COMPILATION_CACHE_DIR is unset.

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TARGET = 500_000.0  # sig-verifies/sec/host (BASELINE.json north star)


def main() -> None:
    import numpy as np

    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from mysticeti_tpu.ops import ed25519 as E

    batch = int(os.environ.get("BENCH_BATCH", "16384"))
    iters = int(os.environ.get("BENCH_ITERS", "64"))

    # Build a realistic batch: distinct signers over 32-byte block digests
    # (the framework's signed message is always a blake2b-256 digest).
    import random

    rng = random.Random(0)
    n_keys = 16
    keys = [
        Ed25519PrivateKey.from_private_bytes(bytes(rng.randrange(256) for _ in range(32)))
        for _ in range(n_keys)
    ]
    pks, msgs, sigs = [], [], []
    for i in range(batch):
        key = keys[i % n_keys]
        msg = bytes(rng.randrange(256) for _ in range(32))
        pks.append(key.public_key().public_bytes_raw())
        msgs.append(msg)
        sigs.append(key.sign(msg))

    # The deployed node path: the committee's keys live on device, signatures
    # ship with a key index (ops.ed25519.KeyTable / verify_batch_table).
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])

    # Warm-up / compile (outside the timed region, as any long-running
    # validator would be after its first batch).
    ok = E.verify_batch_table(table, pks, msgs, sigs)
    assert bool(np.asarray(ok).all()), "benchmark batch must verify"

    # Steady-state pipelined throughput: every iteration maps pks to indices
    # and packs the raw bytes on the host into ONE device array, then
    # dispatches; results are forced once at the end.  This is how a
    # validator consumes the verifier (batches stream through the async
    # dispatch queue) — each batch's index lookup + packing is inside the
    # timed region, so the number is end-to-end bytes -> bools.
    trials = int(os.environ.get("BENCH_TRIALS", "4"))
    best = 0.0
    for _ in range(trials):
        start = time.perf_counter()
        handles = []
        for _ in range(iters):
            idx = table.indices_for(pks)
            blob = E.pack_blob_indexed(idx, msgs, sigs)
            handles.extend(E.dispatch_indexed_chunks(blob, table))
        # Force every result with one combined device fetch (fetch_handles);
        # per-handle fetches would pay one device round-trip each, which on a
        # remote/tunneled chip measures link latency instead of verification.
        results = E.fetch_handles(handles)
        elapsed = time.perf_counter() - start
        assert results.shape[0] == batch * iters and bool(results.all())
        best = max(best, batch * iters / elapsed)

    value = best
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec",
                "value": round(value, 1),
                "unit": "sig/s",
                "vs_baseline": round(value / BASELINE_TARGET, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
