#!/usr/bin/env python3
"""Headline benchmark: batched Ed25519 verification throughput on the default
JAX device (the real TPU chip under the driver; CPU elsewhere).

Prints exactly ONE JSON line:
  {"metric": "ed25519_verifies_per_sec", "value": N, "unit": "sig/s", "vs_baseline": R}

``vs_baseline`` is measured against the BASELINE.json north-star target of
500k sig-verifies/sec/host (the reference itself publishes no number — its
dalek CPU path verifies serially per block, ~15-25k/s/core).
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/mysticeti-tpu-jax-cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TARGET = 500_000.0  # sig-verifies/sec/host (BASELINE.json north star)


def main() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from mysticeti_tpu.ops import ed25519 as E

    batch = int(os.environ.get("BENCH_BATCH", "2048"))
    iters = int(os.environ.get("BENCH_ITERS", "8"))

    # Build a realistic batch: distinct signers over 32-byte block digests
    # (the framework's signed message is always a blake2b-256 digest).
    import random

    rng = random.Random(0)
    n_keys = 16
    keys = [
        Ed25519PrivateKey.from_private_bytes(bytes(rng.randrange(256) for _ in range(32)))
        for _ in range(n_keys)
    ]
    pks, msgs, sigs = [], [], []
    for i in range(batch):
        key = keys[i % n_keys]
        msg = bytes(rng.randrange(256) for _ in range(32))
        pks.append(key.public_key().public_bytes_raw())
        msgs.append(msg)
        sigs.append(key.sign(msg))

    packed = [jnp.asarray(x) for x in E.pack_batch(pks, msgs, sigs)]

    # Warm-up / compile.
    ok = E.verify_kernel(*packed)
    ok.block_until_ready()
    assert bool(np.asarray(ok).all()), "benchmark batch must verify"

    start = time.perf_counter()
    for _ in range(iters):
        ok = E.verify_kernel(*packed)
    ok.block_until_ready()
    elapsed = time.perf_counter() - start

    value = batch * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec",
                "value": round(value, 1),
                "unit": "sig/s",
                "vs_baseline": round(value / BASELINE_TARGET, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
