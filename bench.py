#!/usr/bin/env python3
"""Headline benchmark: END-TO-END batched Ed25519 verification throughput on
the default JAX device (the real TPU chip under the driver; CPU elsewhere).

End-to-end means raw bytes in, accept/reject bits out: host packing (pure
numpy byte concatenation), transfer, device SHA-512 of R||A||M, mod-L
reduction, point decompression, the double-scalar ladder, and the canonical
compare are ALL inside the timed region.  The measured path is the one a
validator deploys (``ops.ed25519.verify_batch_table``): the signer set is a
known committee, so each signature ships as R||M||s + a key index into a
device-resident key table — not a kernel-only figure, and not a
hypothetical unknown-signer workload either.

Shape of the measurement: BENCH_PROCS worker processes (default 4) feed the
chip concurrently, exactly like a validator fleet sharing a host TPU — each
process has its own PJRT client/connection.  This matters on a tunneled
chip: one TCP stream is bandwidth-limited by the link's delay product
(~10-60 MB/s observed), while the chip itself sustains several hundred
thousand verifies/s; concurrent streams restore the transfer headroom that
co-located hosts have natively.  BENCH_PROCS=1 recovers the single-stream
number.

Prints exactly ONE JSON line:
  {"metric": "ed25519_verifies_per_sec", "value": N, "unit": "sig/s", "vs_baseline": R}

``vs_baseline`` is measured against the BASELINE.json north-star target of
500k sig-verifies/sec/host (the reference itself publishes no number — its
dalek CPU path verifies serially per block, ~15-25k/s/core).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Persistent compilation cache: mysticeti_tpu.ops.ed25519 sets a per-uid,
# ownership-checked default when JAX_COMPILATION_CACHE_DIR is unset.

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TARGET = 500_000.0  # sig-verifies/sec/host (BASELINE.json north star)


def _build_batch(batch: int, seed: int):
    """A realistic batch: a 16-signer committee over 32-byte block digests
    (the framework's signed message is always a blake2b-256 digest)."""
    import random

    # crypto re-exports the ``cryptography`` Ed25519 classes, falling back to
    # the pure-Python RFC 8032 oracle where that package isn't installed —
    # the CPU ladder rung must produce a measurement on such hosts too.
    from mysticeti_tpu.crypto import Ed25519PrivateKey
    from mysticeti_tpu.ops import ed25519 as E

    rng = random.Random(seed)
    n_keys = 16
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(n_keys)
    ]
    pks, msgs, sigs = [], [], []
    for i in range(batch):
        key = keys[i % n_keys]
        msg = bytes(rng.randrange(256) for _ in range(32))
        pks.append(key.public_key().public_bytes_raw())
        msgs.append(msg)
        sigs.append(key.sign(msg))
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
    return table, pks, msgs, sigs


def _run_trial(table, pks, msgs, sigs, iters: int) -> float:
    """One timed trial: ``iters`` full batches, packing + index lookup inside
    the timed region, every dispatch async, ONE combined fetch at the end
    (per-handle fetches on a remote chip would measure link latency)."""
    from mysticeti_tpu.ops import ed25519 as E

    batch = len(sigs)
    start = time.perf_counter()
    handles = []
    for _ in range(iters):
        idx = table.indices_for(pks)
        blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
        handles.extend(E.dispatch_indexed_chunks(blob, table))
    results = E.fetch_handles(handles)
    elapsed = time.perf_counter() - start
    assert results.shape[0] == batch * iters and bool(results.all())
    return elapsed


def _run_trial_mesh(mesh, table, pks, msgs, sigs, iters: int) -> float:
    """Multi-chip trial: the committee-indexed blob sharded over the mesh's
    batch axis (parallel/mesh.py).  Same discipline as ``_run_trial``: every
    dispatch async, ONE combined fetch after the timed region's dispatches —
    blocking per iteration would measure iters x link RTT on a remote slice,
    not throughput."""
    import jax.numpy as jnp

    from mysticeti_tpu.ops import ed25519 as E
    from mysticeti_tpu.parallel.mesh import _cached_indexed_kernel

    kernel = _cached_indexed_kernel(mesh)
    batch = len(sigs)
    start = time.perf_counter()
    handles = []
    for _ in range(iters):
        idx = table.indices_for(pks)
        blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
        for s, count, b in E.iter_buckets(batch):
            handles.append((
                count,
                kernel(
                    jnp.asarray(E._pad_to(blob[s:s + count], b)), table.words
                )[0],
            ))
    results = E.fetch_handles(handles)
    elapsed = time.perf_counter() - start
    assert results.shape[0] == batch * iters and bool(results.all())
    return elapsed


def _worker() -> None:
    """Child-process mode: warm up, then run one timed trial per GO line on
    stdin, reporting {"sigs": N, "elapsed": s} per trial on stdout.

    BENCH_MESH=N (>1) shards every dispatch over an N-device
    ``jax.sharding.Mesh`` — a real multi-chip slice is a flag away; on a
    single-chip host it fails loud rather than silently measuring one chip.
    """
    import numpy as np

    from mysticeti_tpu.ops import ed25519 as E

    batch = int(os.environ["BENCH_BATCH"])
    iters = int(os.environ["BENCH_WORKER_ITERS"])
    seed = int(os.environ["BENCH_SEED"])
    mesh_n = int(os.environ.get("BENCH_MESH", "0"))
    table, pks, msgs, sigs = _build_batch(batch, seed)
    if mesh_n > 1:
        import jax

        from mysticeti_tpu.parallel.mesh import make_mesh

        devices = jax.devices()
        if len(devices) < mesh_n:
            raise RuntimeError(
                f"BENCH_MESH={mesh_n} but only {len(devices)} device(s) "
                "attached"
            )
        mesh = make_mesh(mesh_n, devices=devices[:mesh_n])
        from mysticeti_tpu.parallel.mesh import sharded_verify_batch_indexed

        ok, total = sharded_verify_batch_indexed(
            mesh, table, pks, msgs, sigs
        )  # warm/compile + correctness (psum total checked once)
        assert int(total) == batch and bool(ok.all())
        print("READY", flush=True)
        for line in sys.stdin:
            if line.strip() != "GO":
                continue
            elapsed = _run_trial_mesh(mesh, table, pks, msgs, sigs, iters)
            print(json.dumps({"sigs": batch * iters, "elapsed": elapsed}),
                  flush=True)
        return
    ok = E.verify_batch_table(table, pks, msgs, sigs)  # warm/compile
    assert bool(np.asarray(ok).all()), "benchmark batch must verify"
    print("READY", flush=True)
    for line in sys.stdin:
        if line.strip() != "GO":
            continue
        elapsed = _run_trial(table, pks, msgs, sigs, iters)
        print(json.dumps({"sigs": batch * iters, "elapsed": elapsed}), flush=True)


def _single_process(batch: int, iters: int, trials: int) -> float:
    import numpy as np

    from mysticeti_tpu.ops import ed25519 as E

    table, pks, msgs, sigs = _build_batch(batch, seed=0)
    ok = E.verify_batch_table(table, pks, msgs, sigs)
    assert bool(np.asarray(ok).all()), "benchmark batch must verify"
    best = 0.0
    for _ in range(trials):
        elapsed = _run_trial(table, pks, msgs, sigs, iters)
        best = max(best, batch * iters / elapsed)
    return best


def _multi_process(batch: int, iters: int, trials: int, procs: int,
                   ready_timeout_s: float, stall_timeout_s: float,
                   extra_env: dict = None) -> float:
    """Fleet-shaped measurement: ``procs`` workers, synchronized trials.

    Per trial, every worker runs iters/procs batches concurrently; the
    aggregate rate is total sigs / slowest worker.  Best trial wins (the
    chip is shared with other tenants — see BENCH_SAMPLES_r02.json).
    ``extra_env`` overrides worker environment (the CPU fallback rung pins
    JAX_PLATFORMS=cpu so a wedged accelerator plugin is never touched).
    """
    per_worker_iters = max(1, iters // procs)
    env = dict(os.environ)
    env.update(
        {
            "BENCH_WORKER": "1",
            "BENCH_BATCH": str(batch),
            "BENCH_WORKER_ITERS": str(per_worker_iters),
        }
    )
    if extra_env:
        env.update(extra_env)
    import tempfile

    workers, err_files = [], []
    for w in range(procs):
        wenv = dict(env)
        wenv["BENCH_SEED"] = str(w)
        # Worker stderr goes to a file, not DEVNULL: a deterministic
        # config error (e.g. BENCH_MESH with too few devices) must reach
        # the operator, not vanish while the ladder retries it.
        err = tempfile.TemporaryFile(mode="w+")
        err_files.append(err)
        workers.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=err,
                env=wenv,
                text=True,
            )
        )

    def _worker_stderr_tail(w: int, limit: int = 800) -> str:
        try:
            err_files[w].seek(0)
            return err_files[w].read()[-limit:]
        except OSError:
            return ""
    try:
        # Stall watchdog: a wedged accelerator tunnel (observed after
        # repeated fleet kill cycles — pool session grants exhausted) hangs
        # workers inside PJRT init OR mid-dispatch forever.  Failing loud
        # with a clear message beats hanging the driver's whole bench step.
        # The guard covers every blocking readline: it kills the workers if
        # no line arrives within the phase's stall limit.
        import threading

        stall = {
            "t": time.monotonic(),
            "limit": float(
                os.environ.get("BENCH_READY_TIMEOUT_S", str(ready_timeout_s))
            ),
        }
        stop_guard = threading.Event()
        timed_out = threading.Event()

        def _watchdog():
            while not stop_guard.wait(5.0):
                if time.monotonic() - stall["t"] > stall["limit"]:
                    timed_out.set()
                    for p in workers:
                        p.kill()
                    return

        threading.Thread(target=_watchdog, daemon=True).start()

        def _stalled(phase: str):
            return RuntimeError(
                f"accelerator unreachable: no bench worker progress within "
                f"{stall['limit']:.0f}s during {phase} (wedged tunnel / pool "
                f"session exhaustion?)"
            )

        for w, p in enumerate(workers):
            line = p.stdout.readline().strip()
            stall["t"] = time.monotonic()
            if line != "READY":
                if timed_out.is_set():
                    raise _stalled("warmup")
                raise RuntimeError(
                    f"worker {w} failed to start: {line!r}\n"
                    f"{_worker_stderr_tail(w)}"
                )
        if timed_out.is_set():
            raise _stalled("warmup")
        sys.stderr.write(f"bench: {procs} workers ready\n")
        stall["limit"] = float(
            os.environ.get("BENCH_STALL_TIMEOUT_S", str(stall_timeout_s))
        )
        # Best-of with a time budget: the shared tunnel's transfer weather
        # swings minute to minute (BENCH_SAMPLES_*), so after the minimum
        # trials, keep sampling while the budget lasts — each trial is a
        # full multi-hundred-thousand-signature sustained measurement.
        budget_s = float(os.environ.get("BENCH_MAX_S", "240"))
        max_trials = int(os.environ.get("BENCH_MAX_TRIALS", "10"))
        best = 0.0
        started = time.monotonic()
        trial = 0
        while trial < trials or (
            trial < max_trials and time.monotonic() - started < budget_s
        ):
            trial += 1
            try:
                for p in workers:
                    p.stdin.write("GO\n")
                    p.stdin.flush()
            except (BrokenPipeError, OSError):
                if timed_out.is_set():
                    raise _stalled("a trial") from None
                raise
            sigs_total, slowest = 0, 0.0
            for w, p in enumerate(workers):
                line = p.stdout.readline()
                stall["t"] = time.monotonic()
                if not line.strip():
                    # Distinguish a WEDGE (watchdog fired; the accelerator
                    # session hung) from a worker DEATH (OOM / PJRT crash:
                    # deterministic, must fail loud, not ship trial 1 as a
                    # healthy headline).
                    if timed_out.is_set():
                        if best > 0.0:
                            # Round-4 lesson: one wedged session must not
                            # zero completed measurements.
                            sys.stderr.write(
                                f"bench: worker {w} wedged on trial "
                                f"{trial}; emitting best of {trial - 1} "
                                f"completed trial(s)\n"
                            )
                            return best
                        raise _stalled("a trial")
                    raise RuntimeError(
                        f"bench worker {w} died mid-trial "
                        f"(exit code {p.poll()})\n{_worker_stderr_tail(w)}"
                    )
                rec = json.loads(line)
                sigs_total += rec["sigs"]
                slowest = max(slowest, rec["elapsed"])
            best = max(best, sigs_total / slowest)
        return best
    finally:
        stop_guard.set()
        for p in workers:
            try:
                p.stdin.close()
            except OSError:
                pass
        for p in workers:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                # A hung worker (dropped tunnel mid-dispatch) must not leave
                # the whole fleet unreaped holding the device.
                p.kill()
                p.wait()
        for err in err_files:
            try:
                err.close()
            except OSError:
                pass


def main() -> None:
    if os.environ.get("BENCH_WORKER") == "1":
        _worker()
        return

    batch = int(os.environ.get("BENCH_BATCH", "16384"))
    iters = int(os.environ.get("BENCH_ITERS", "64"))
    trials = int(os.environ.get("BENCH_TRIALS", "4"))
    procs = int(os.environ.get("BENCH_PROCS", "4"))

    if procs <= 0:
        # Debug mode: in-process, no watchdog (a wedge hangs — use >=1).
        value = _single_process(batch, iters, trials)
        _emit(value)
        return

    # Recovery ladder: a wedged accelerator session (pool exhaustion, a
    # worker's PJRT client hanging in init) fails one RUNG, not the whole
    # measurement — respawn with fewer processes and a smaller per-worker
    # footprint before giving up.  Each rung gets progressively shorter
    # stall limits so the ladder fits the driver's patience.  The LAST rung
    # is the guaranteed CPU fallback (VERDICT r5: two consecutive
    # parsed=null rounds must be impossible): JAX_PLATFORMS=cpu pinned in
    # the worker env so a wedged accelerator plugin is never even imported,
    # one process, a small batch, its own timeout — slow, but it always
    # produces a parsed measurement labeled with the backend that made it.
    ladder = [
        {"procs": procs, "batch": batch, "iters": iters, "ready": 600.0,
         "stall": 420.0, "backend": "default", "env": None},
    ]
    if procs > 1:
        ladder.append(
            {"procs": max(1, procs // 2), "batch": batch, "iters": iters,
             "ready": 360.0, "stall": 300.0, "backend": "default",
             "env": None}
        )
    ladder.append(
        {"procs": 1, "batch": min(batch, max(4096, batch // 4)),
         "iters": iters, "ready": 300.0, "stall": 240.0,
         "backend": "default", "env": None}
    )
    ladder.append(
        {"procs": 1, "batch": min(batch, 1024), "iters": min(iters, 4),
         "ready": 420.0, "stall": 300.0, "backend": "cpu",
         "env": {"JAX_PLATFORMS": "cpu", "MYSTICETI_VERIFY_BACKEND": "xla"}}
    )
    budget_s = float(os.environ.get("BENCH_LADDER_BUDGET_S", "1800"))
    started = time.monotonic()
    value, used, last_error = 0.0, None, None
    rung_reports = []
    for rung, spec in enumerate(ladder):
        last = rung == len(ladder) - 1
        if rung > 0 and not last and time.monotonic() - started > budget_s:
            # The budget may skip intermediate rungs, never the CPU
            # fallback: the artifact must always carry a measurement — and
            # the per-rung evidence must record the skip, not silence.
            rung_reports.append({"rung": rung, "backend": spec["backend"],
                                 "ok": False, "skipped": True,
                                 "error": "ladder budget exhausted"})
            sys.stderr.write(
                f"bench: ladder budget exhausted; skipping rung {rung}\n"
            )
            continue
        try:
            value = _multi_process(spec["batch"], spec["iters"], trials,
                                   spec["procs"],
                                   ready_timeout_s=spec["ready"],
                                   stall_timeout_s=spec["stall"],
                                   extra_env=spec["env"])
            used = {"rung": rung, "procs": spec["procs"],
                    "batch": spec["batch"], "backend": spec["backend"]}
            rung_reports.append({"rung": rung, "backend": spec["backend"],
                                 "ok": True, "value": round(value, 1)})
            break
        except (RuntimeError, OSError, ValueError) as exc:
            # ValueError covers json.JSONDecodeError from a worker dying
            # mid-print — that too must fall to the next rung, not exit.
            last_error = exc
            rung_reports.append({"rung": rung, "backend": spec["backend"],
                                 "ok": False, "error": str(exc)[:200]})
            sys.stderr.write(
                f"bench: rung {rung} ({spec['procs']} procs, batch "
                f"{spec['batch']}, backend {spec['backend']}) failed: "
                f"{exc}\n"
            )
    if value <= 0.0:
        # Even a total failure records a parsed (zero) measurement with the
        # per-rung evidence before the nonzero exit — never nothing.
        _emit(0.0, {"backend": "none", "rungs": rung_reports})
        raise last_error or RuntimeError("bench produced no measurement")
    if used is not None and used["rung"] > 0:
        used["rungs"] = rung_reports

    if value < BASELINE_TARGET and os.environ.get("BENCH_ACCOUNTING") != "0":
        # Under target: decompose WHY onto stderr (the driver keeps the
        # output tail).  The e2e rate on a tunneled chip is
        # min(kernel rate, link bandwidth / ~100 B per signature); the
        # probe measures null RTT, host->device bandwidth and kernel-only
        # time so a bandwidth-capped run is distinguishable from a chip or
        # pipeline regression.
        try:
            probe = subprocess.run(
                [sys.executable, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "tools", "bench_probe.py")],
                capture_output=True, text=True, timeout=420,
            )
            if probe.returncode == 0 and probe.stdout.strip():
                acct = json.loads(probe.stdout.strip().splitlines()[-1])
                bw = acct.get("h2d_MBps")
                if bw:
                    acct["wire_ceiling_sig_s_at_100B"] = round(
                        bw * 1e6 / 100.0, 1
                    )
                    acct["measured_fraction_of_wire_ceiling"] = round(
                        value / acct["wire_ceiling_sig_s_at_100B"], 3
                    )
                sys.stderr.write(f"bench accounting: {json.dumps(acct)}\n")
            else:
                sys.stderr.write(
                    f"bench accounting probe failed rc={probe.returncode}\n"
                )
        except Exception as exc:  # accounting must never break the number
            sys.stderr.write(f"bench accounting unavailable: {exc!r}\n")

    _emit(value, used)


def _emit(value: float, used: dict = None) -> None:
    record = {
        "metric": "ed25519_verifies_per_sec",
        "value": round(value, 1),
        "unit": "sig/s",
        "vs_baseline": round(value / BASELINE_TARGET, 4),
    }
    if used:
        # Which ladder rung produced the number: a fallback-rung result
        # (fewer procs / smaller batch) must be distinguishable from the
        # full-config measurement in the recorded artifact.
        record.update(used)
    print(json.dumps(record))
    # Perf-trend plane: every live measurement (zero-records included)
    # lands in the append-only BENCH_TREND.json index so the bench
    # trajectory can never be empty (tools/bench_trend.py; BENCH_TREND=0
    # or an unwritable index silently skips — diagnostics must not break
    # the measurement).
    if os.environ.get("BENCH_TREND") != "0":
        try:
            from tools.bench_trend import append_record

            append_record(record, path=os.environ.get(
                "BENCH_TREND_PATH",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TREND.json"),
            ))
        except Exception:
            pass


if __name__ == "__main__":
    main()
