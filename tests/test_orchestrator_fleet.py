"""Fleet-management periphery: display, ssh manager, testbed lifecycle,
profiling/flamegraph.

Reference tiers covered: display.rs (table/status output), ssh.rs:83-272
(CommandContext composition, retries, parallel fan-out), testbed.rs:21-210
(deploy/start/stop/destroy/status), client/mod.rs:68 (provider seam),
assets/mkflamegraph.sh (profile -> folded -> svg pipeline).  The ssh tests
inject a fake transport at the `_spawn` seam instead of needing a live sshd.
"""
import asyncio
import io
import os
import time

import pytest

from mysticeti_tpu.orchestrator.display import format_table, progress, status
from mysticeti_tpu.orchestrator.ssh import CommandContext, SshError, SshManager
from mysticeti_tpu.orchestrator.testbed import (
    Instance,
    StaticProvider,
    Testbed,
)
from mysticeti_tpu.profiling import SamplingProfiler, flamegraph_svg


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


# ---------------------------------------------------------------------------
# display
# ---------------------------------------------------------------------------


def test_format_table_alignment():
    table = format_table(["id", "host"], [["i-0", "10.0.0.1"], ["i-11", "x"]])
    lines = table.splitlines()
    assert len({len(l) for l in lines}) == 1  # rectangular
    assert "i-11" in table and "10.0.0.1" in table


def test_status_and_progress_plain_streams():
    buf = io.StringIO()
    status("hello", stream=buf)
    progress(3, 10, "scrapes", stream=buf)
    out = buf.getvalue()
    assert "hello" in out and "3/10 scrapes" in out
    assert "\x1b[" not in out  # no ANSI on non-tty


# ---------------------------------------------------------------------------
# ssh
# ---------------------------------------------------------------------------


def test_command_context_compose():
    ctx = CommandContext(path="/opt/repo", env={"TPS": "100"})
    assert ctx.apply("python3 -m mysticeti_tpu run") == (
        "cd /opt/repo && TPS=100 python3 -m mysticeti_tpu run"
    )


def test_command_context_background_pidfile():
    ctx = CommandContext(background="node-3", log_file="/tmp/n3.log")
    cmd = ctx.apply("sleep 60")
    assert "setsid nohup" in cmd
    assert "/tmp/.mysticeti-session-node-3.pid" in cmd
    assert "> /tmp/n3.log" in cmd
    # Spawn is guarded on a live pidfile so SshManager retries cannot
    # double-spawn the node after a dropped connection.
    assert cmd.startswith("if [ -f /tmp/.mysticeti-session-node-3.pid ]")
    assert "kill -0 -- -$(cat /tmp/.mysticeti-session-node-3.pid)" in cmd


class FlakyTransport(SshManager):
    """Fails the first N spawns, then succeeds; records every argv."""

    def __init__(self, hosts, fail_first=0, **kw):
        kw.setdefault("retry_delay_s", 0.0)
        super().__init__(hosts, **kw)
        self.fail_first = fail_first
        self.calls = []

    async def _spawn(self, argv, timeout_s):
        self.calls.append(argv)
        if len(self.calls) <= self.fail_first:
            return 255, b"connection refused"
        return 0, f"ok:{argv[-2]}".encode()


def test_ssh_retries_then_succeeds():
    mgr = FlakyTransport(["h0"], fail_first=2, retries=3)
    out = run(mgr.execute("h0", "true"))
    assert out.startswith("ok:")
    assert len(mgr.calls) == 3


def test_ssh_raises_after_final_retry():
    mgr = FlakyTransport(["h0"], fail_first=99, retries=2)
    with pytest.raises(SshError, match="exit 255"):
        run(mgr.execute("h0", "true"))
    assert len(mgr.calls) == 2


def test_ssh_parallel_fanout_all_hosts():
    hosts = [f"h{i}" for i in range(5)]
    mgr = FlakyTransport(hosts)
    outs = run(mgr.execute_all("uptime"))
    assert len(outs) == 5
    # every host saw the command
    assert {argv[-2] for argv in mgr.calls} == set(hosts)


# ---------------------------------------------------------------------------
# testbed
# ---------------------------------------------------------------------------


def test_static_provider_lifecycle(tmp_path):
    state = str(tmp_path / "testbed.json")
    provider = StaticProvider(["10.0.0.1", "10.0.0.2", "10.0.0.3"], state)
    tb = Testbed(provider)

    created = run(tb.deploy(2, "local"))
    assert [i.host for i in created] == ["10.0.0.1", "10.0.0.2"]

    run(tb.stop())
    assert all(not i.active for i in run(provider.list_instances()))
    run(tb.start())
    assert all(i.active for i in run(provider.list_instances()))

    # state survives re-load (testbed.rs keeps this in cloud tags)
    provider2 = StaticProvider(["10.0.0.1", "10.0.0.2", "10.0.0.3"], state)
    assert [i.host for i in run(provider2.list_instances())] == [
        "10.0.0.1",
        "10.0.0.2",
    ]

    run(tb.destroy())
    assert run(provider.list_instances()) == []


def test_static_provider_ids_never_reused(tmp_path):
    """A terminate+create cycle must not hand a new instance a live
    instance's id (that would silently evict the live host from inventory)."""
    provider = StaticProvider(["h0", "h1", "h2"], str(tmp_path / "s.json"))
    first = run(provider.create_instances(2, "local"))
    run(provider.terminate_instances([first[0].id]))
    replacement = run(provider.create_instances(1, "local"))[0]
    live_ids = {i.id for i in run(provider.list_instances())}
    assert replacement.id not in {first[0].id, first[1].id}
    assert len(live_ids) == 2


def test_static_provider_pool_exhausted(tmp_path):
    provider = StaticProvider(["only-one"], str(tmp_path / "s.json"))
    with pytest.raises(RuntimeError, match="pool exhausted"):
        run(provider.create_instances(2, "local"))


def test_testbed_install_update_over_fake_ssh(tmp_path):
    provider = StaticProvider(["h0", "h1"], str(tmp_path / "s.json"))
    run(provider.create_instances(2, "local"))
    ssh = FlakyTransport(["h0", "h1"])
    tb = Testbed(provider, ssh=ssh, repo_url="https://example.com/repo.git")
    run(tb.install())
    run(tb.update())
    joined = [" ".join(argv) for argv in ssh.calls]
    assert any("git clone" in c or "git -C" in c for c in joined)
    assert {argv[-2] for argv in ssh.calls} == {"h0", "h1"}


# ---------------------------------------------------------------------------
# profiling / flamegraph
# ---------------------------------------------------------------------------


def _busy(deadline):
    x = 0
    while time.perf_counter() < deadline:
        x += sum(range(200))
    return x


def test_sampling_profiler_captures_busy_function():
    prof = SamplingProfiler(hz=200)
    with prof:
        _busy(time.perf_counter() + 0.4)
    folded = prof.folded()
    assert folded, "no samples collected"
    assert any("_busy" in line for line in folded)
    # folded format: "a;b;c N"
    stack, _, count = folded[0].rpartition(" ")
    assert int(count) >= 1 and ";" in stack or ":" in stack


def test_flamegraph_svg_renders(tmp_path):
    folded = ["main;work;inner 60", "main;work;other 30", "main;idle 10"]
    svg = flamegraph_svg(folded)
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert "work" in svg and "inner" in svg
    assert svg.count("<rect") >= 5  # root + 5 frames, minus tiny ones
    # end-to-end file pipeline
    from mysticeti_tpu.profiling import render_file

    src = tmp_path / "x.folded"
    src.write_text("\n".join(folded) + "\n")
    out = render_file(str(src))
    assert out.endswith(".svg") and os.path.exists(out)


def test_ssh_runner_composes_fleet_commands(tmp_path):
    """SshRunner rides SshManager: genesis upload, background boot under a
    pidfile session, kill-session teardown (orchestrator.rs:215-475 shape)."""
    import asyncio

    from mysticeti_tpu.orchestrator.runner import SshRunner

    class RecordingManager(SshManager):
        def __init__(self, hosts):
            super().__init__(hosts, retry_delay_s=0.0)
            self.commands = []  # (host, command)

        async def _spawn(self, argv, timeout_s):
            self.commands.append(argv)
            return 0, b"ok"

    hosts = ["u@h0", "u@h1", "u@h2", "u@h3"]
    mgr = RecordingManager(hosts)
    runner = SshRunner(hosts, remote_repo="/opt/mysticeti", ssh=mgr)

    async def main():
        await runner.configure(4, load_tx_s=200)
        await runner.boot_node(2)
        await runner.kill_node(2)
        await runner.cleanup()

    asyncio.run(main())
    flat = [" ".join(argv) for argv in mgr.commands]
    # upload happened per host (scp) after a mkdir
    assert sum("scp" == argv[0] for argv in mgr.commands) == 4
    assert any("mkdir -p" in c for c in flat)
    # boot: background session with pidfile, cd into the checkout, TPS env
    boot = [c for c in flat if "mysticeti_tpu run" in c and "--authority 2" in c]
    assert boot and "setsid nohup" in boot[0] and "TPS=50" in boot[0]
    assert "mysticeti-node-2.pid" in boot[0]
    assert "cd /opt/mysticeti" in boot[0]
    # teardown kills the session pidfile for every node
    kills = [c for c in flat if ".pid" in c and "kill" in c]
    assert len(kills) >= 5  # node 2 once + cleanup x4


def test_settings_make_ssh_runner_and_testbed_logs(tmp_path):
    """Settings -> SshRunner construction and Testbed.download_logs over the
    fake transport (the `fleet logs` CLI path)."""
    from mysticeti_tpu.orchestrator.settings import Settings

    s = Settings(runner="ssh", hosts=["u@h0", "u@h1"], remote_repo="/opt/m")
    runner = s.make_runner()
    from mysticeti_tpu.orchestrator.runner import SshRunner

    assert isinstance(runner, SshRunner) and runner.remote_repo == "/opt/m"

    provider = StaticProvider(["u@h0", "u@h1"], str(tmp_path / "s.json"))
    run(provider.create_instances(2, "local"))
    ssh = FlakyTransport(["u@h0", "u@h1"])
    tb = Testbed(provider, ssh=ssh)
    dest = str(tmp_path / "logs")
    paths = run(tb.download_logs("/tmp/mysticeti-bench", dest))
    assert len(paths) == 2
    scps = [argv for argv in ssh.calls if argv[0] == "scp"]
    assert len(scps) == 2
    assert any("u@h0:/tmp/mysticeti-bench" in " ".join(a) for a in scps)


def test_monitoring_stack_remote_deploy(tmp_path):
    """monitor.rs:60-105 parity: the stack deploys onto a dedicated
    monitoring instance over the ssh manager — config tree uploaded,
    prometheus + grafana (re)started as background sessions."""
    from mysticeti_tpu.orchestrator.monitor import (
        GRAFANA_PORT,
        MonitoringStack,
    )

    class RecordingSsh(SshManager):
        def __init__(self):
            super().__init__(["monitor@10.0.0.9"], retries=1)
            self.commands = []

        async def _spawn(self, argv, timeout_s):
            self.commands.append(argv)
            return 0, b""

    ssh = RecordingSsh()
    stack = MonitoringStack(str(tmp_path / "mon"))
    url = run(
        stack.deploy_remote(
            ssh, "monitor@10.0.0.9", ["10.0.0.1:1500", "10.0.0.2:1500"]
        )
    )
    assert url == f"http://10.0.0.9:{GRAFANA_PORT}"
    # The generated tree exists locally and was scp'd to the instance.
    assert (tmp_path / "mon" / "prometheus.yaml").exists()
    flat = ["\x00".join(argv) for argv in ssh.commands]
    assert any(a.startswith("scp") and "prometheus.yaml" in a for a in flat)
    joined = " ".join(flat)
    assert "prometheus --config.file=" in joined
    assert "grafana server" in joined
    # Both services run as background sessions (kill_session-compatible).
    assert "mysticeti-prometheus" in joined
    assert "mysticeti-grafana" in joined
    # Teardown kills both sessions.
    before = len(ssh.commands)
    run(stack.stop_remote(ssh, "monitor@10.0.0.9"))
    assert len(ssh.commands) == before + 2
