"""Flamegraph rendering CLI path: folded file -> SVG round-trip through
``profiling.render_file`` and ``tools/mkflamegraph.py``, including the
empty-profile edge case (a node killed before the first flush)."""
import os
import subprocess
import sys

from mysticeti_tpu.profiling import flamegraph_svg, render_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MKFLAMEGRAPH = os.path.join(REPO, "tools", "mkflamegraph.py")

FOLDED = "main:run;core:add_blocks;crypto:verify 42\nmain:run;net:decode 13\n"


def test_render_file_roundtrip_default_output(tmp_path):
    folded = tmp_path / "node.folded"
    folded.write_text(FOLDED)
    out = render_file(str(folded))
    assert out == str(tmp_path / "node.svg")
    svg = open(out).read()
    assert svg.startswith("<svg")
    assert "crypto:verify" in svg and "net:decode" in svg
    # Tooltips carry sample counts + percentages (flamegraph.pl parity).
    assert "42 samples" in svg


def test_render_file_explicit_output_and_empty_profile(tmp_path):
    empty = tmp_path / "empty.folded"
    empty.write_text("")
    out = render_file(str(empty), str(tmp_path / "custom.svg"))
    assert out == str(tmp_path / "custom.svg")
    svg = open(out).read()
    # No division-by-zero; a valid (if trivial) SVG is still produced.
    assert svg.startswith("<svg") and svg.rstrip().endswith("</svg>")


def test_flamegraph_svg_ignores_malformed_lines():
    svg = flamegraph_svg(["not-a-folded-line", "a;b 3", ""])
    assert "a" in svg and "3 samples" in svg


def test_mkflamegraph_cli_roundtrip(tmp_path):
    folded = tmp_path / "node.folded"
    folded.write_text(FOLDED)
    out = tmp_path / "flame.svg"
    proc = subprocess.run(
        [sys.executable, MKFLAMEGRAPH, str(folded), str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == str(out)
    assert open(out).read().startswith("<svg")


def test_mkflamegraph_cli_usage_error():
    proc = subprocess.run(
        [sys.executable, MKFLAMEGRAPH],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "Usage" in proc.stderr or "usage" in proc.stderr
