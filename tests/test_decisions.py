"""Consensus decision ledger + finality SLI plane: trace/record/explain
units (pinned explanations for a direct commit and an indirect skip), ring
flip detection and canonical byte-identity, the phase-split finality
tracker and its client-side mirror, the tag-15/16 soft wire extension,
and the seeded 10-node Byzantine sim acceptance: every decided leader
slot carries an explaining record and same-seed runs produce
byte-identical ledgers."""
import dataclasses
import os
import sys

import pytest

from mysticeti_tpu.config import IngressParameters
from mysticeti_tpu.consensus import AuthorityRound, LeaderStatus
from mysticeti_tpu.decisions import (
    DecisionLedger,
    DecisionTrace,
    explain_record,
    make_record,
)
from mysticeti_tpu.finality import (
    ClientFinalityRecorder,
    FinalityTracker,
    key_sampled,
    percentile,
)
from mysticeti_tpu.ingress import Mempool, ingress_key
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.network import (
    GatewayCommitNotification,
    GatewaySubscribeCommits,
    decode_message,
    encode_message,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

pytestmark = pytest.mark.finality


class _Aggregator:
    """StakeAggregator stand-in: just enough surface for DecisionTrace."""

    def __init__(self, stake, voters):
        self.stake = stake
        self._voters = voters

    def voters(self):
        return list(self._voters)


class _Ref:
    def __init__(self, authority, round_, digest):
        self.authority = authority
        self.round = round_
        self.digest = digest


class _LeaderBlock:
    def __init__(self, authority, round_, digest=b"\xab\xcd\xef\x01" + b"\x00" * 28):
        self.reference = _Ref(authority, round_, digest)

    def author(self):
        return self.reference.authority

    def round(self):
        return self.reference.round


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))


# -- trace + record + explanation units ---------------------------------------


def test_trace_keeps_highest_certificate_tally():
    trace = DecisionTrace()
    trace.note_certificates(_Aggregator(3, [5, 1]))
    trace.note_certificates(_Aggregator(7, [0, 4, 2, 6, 1, 3, 5]))
    trace.note_certificates(_Aggregator(2, [9]))  # lower: ignored
    assert trace.cert_stake == 7
    assert trace.cert_authorities == [0, 1, 2, 3, 4, 5, 6]
    trace.note_blames(_Aggregator(5, [8, 0, 7]))
    assert trace.blame_stake == 5
    assert trace.blame_authorities == [0, 7, 8]


def test_pinned_explanation_direct_commit():
    trace = DecisionTrace()
    trace.note_certificates(_Aggregator(7, [0, 1, 2, 3, 4, 5, 6]))
    status = LeaderStatus.commit(_LeaderBlock(2, 9))
    record = make_record(status, "direct", trace, 2, 1.25)
    assert explain_record(record) == (
        "slot C9 (authority 2, round 9): COMMIT via the direct rule\n"
        "  certificates: 7 stake from authorities [0,1,2,3,4,5,6] "
        "certified the leader block A2R9#abcdef01\n"
        "  decided 2 rounds behind the DAG frontier at t=1.250000"
    )


def test_pinned_explanation_indirect_skip():
    trace = DecisionTrace()
    trace.note_certificates(_Aggregator(3, [0, 5, 8]))
    trace.note_anchor(AuthorityRound(1, 15))
    status = LeaderStatus.skip(AuthorityRound(4, 12))
    record = make_record(status, "indirect", trace, 5, 2.0)
    assert explain_record(record) == (
        "slot E12 (authority 4, round 12): SKIP via the indirect rule\n"
        "  anchor: committed leader B15 has no certified link to any "
        "block of this slot (best certificate tally: 3 stake)\n"
        "  decided 5 rounds behind the DAG frontier at t=2.000000"
    )


def test_explanation_direct_skip_names_blamers_and_flip():
    trace = DecisionTrace()
    trace.note_blames(_Aggregator(7, [1, 2, 3, 4, 5, 6, 7]))
    record = make_record(
        LeaderStatus.skip(AuthorityRound(0, 6)), "direct", trace, 3, 0.5
    )
    record["flipped"] = True
    text = explain_record(record)
    assert "SKIP via the direct rule" in text
    assert "blames: 7 stake from authorities [1,2,3,4,5,6,7]" in text
    assert text.endswith("(flipped from undecided)")


# -- the ledger ---------------------------------------------------------------


def _clock_factory(start=10.0, step=0.25):
    state = {"t": start}

    def clock():
        state["t"] += step
        return state["t"]

    return clock


def test_ledger_records_flips_and_feeds_recorder_and_metrics():
    metrics = Metrics()
    ledger = DecisionLedger(metrics=metrics, clock=_clock_factory())
    ledger.recorder = recorder = _Recorder()

    # Scan 1: slot B4 undecided.
    ledger.note_undecided([AuthorityRound(1, 4)])
    assert ledger.undecided() == ["B4"]
    # Scan 2: B4 decides (a flip), C5 commits fresh, D6 skips.
    flip = ledger.record_decision(
        LeaderStatus.commit(_LeaderBlock(1, 4)), "indirect", None, 2
    )
    fresh = ledger.record_decision(
        LeaderStatus.commit(_LeaderBlock(2, 5)), "direct", None, 2
    )
    skip = ledger.record_decision(
        LeaderStatus.skip(AuthorityRound(3, 6)), "direct", None, 2
    )
    ledger.note_undecided([])
    assert flip["flipped"] and not fresh["flipped"] and not skip["flipped"]
    assert ledger.undecided() == []

    kinds = [kind for kind, _ in recorder.events]
    assert kinds == ["decision-flip", "decision-skip"]
    assert recorder.events[0][1]["slot"] == "B4"
    assert recorder.events[1][1]["slot"] == "D6"

    count = metrics.mysticeti_commit_decision_total
    assert count.labels("indirect", "commit")._value.get() == 1
    assert count.labels("direct", "commit")._value.get() == 1
    assert count.labels("direct", "skip")._value.get() == 1
    assert ledger.state()["recorded"] == 3

    looked = ledger.lookup(2, 5)
    assert looked["rule"] == "direct" and looked["outcome"] == "commit"
    assert ledger.lookup(9, 9) is None


def test_flip_survives_decided_but_unemitted_scans():
    """undecided -> decided-but-above-the-prefix (rescanned) -> emitted:
    the key must still flag flipped when the slot finally records."""
    ledger = DecisionLedger(clock=_clock_factory())
    ledger.note_undecided([AuthorityRound(2, 7)])
    # Next scan: a LOWER slot is undecided, so C7 is decided but unemitted;
    # the frontier snapshot no longer names C7.
    ledger.note_undecided([AuthorityRound(1, 6)])
    record = ledger.record_decision(
        LeaderStatus.skip(AuthorityRound(2, 7)), "direct", None, 4
    )
    assert record["flipped"] is True


def test_ledger_ring_bound_and_canonical_bytes():
    clock = _clock_factory()
    ledger = DecisionLedger(capacity=4, clock=clock)
    for round_ in range(1, 8):
        ledger.record_decision(
            LeaderStatus.skip(AuthorityRound(0, round_)), "direct", None, 2
        )
    state = ledger.state()
    assert state["recorded"] == 7 and state["dropped"] == 3
    records = ledger.records()
    assert len(records) == 4 and records[0]["round"] == 4
    assert ledger.records(last=2)[0]["round"] == 6

    # Same construction, same clock sequence: byte-identical ledgers.
    twin = DecisionLedger(capacity=4, clock=_clock_factory())
    for round_ in range(1, 8):
        twin.record_decision(
            LeaderStatus.skip(AuthorityRound(0, round_)), "direct", None, 2
        )
    assert twin.ledger_bytes() == ledger.ledger_bytes()
    assert twin.digest() == ledger.digest()


# -- finality sampling + phase tracker ----------------------------------------


def test_key_sampling_is_content_deterministic():
    keys = [ingress_key(b"tx-%d" % i) for i in range(400)]
    first = [key_sampled(k, 16) for k in keys]
    assert first == [key_sampled(k, 16) for k in keys]
    assert 0 < sum(first) < len(keys)  # neither none nor all
    assert all(key_sampled(k, 1) for k in keys)
    assert all(key_sampled(k, 0) for k in keys)


def test_percentile_nearest_rank():
    assert percentile([], 0.99) == 0.0
    values = [float(v) for v in range(1, 101)]
    assert percentile(values, 0.50) == 51.0
    assert percentile(values, 0.99) == 100.0


def test_tracker_phase_stamps_and_notify():
    tracker = FinalityTracker(sample_every=1, clock=_clock_factory())
    key = ingress_key(b"tx")
    tracker.on_submit(key, 1.0, 1.1)
    tracker.on_proposal(key, 1.3)
    tracker.on_proposal(key, 9.9)  # second drain of the key: ignored
    tracker.on_commit(key, 1.6, 1.8)
    tracker.on_commit(key, 9.9, 9.9)  # duplicate commit: ignored
    assert tracker.samples() == [pytest.approx(0.8)]  # 1.8 - 1.0
    state = tracker.state()
    assert state["completed"] == 1 and state["pending"] == 1
    tracker.on_notify([key], 2.0)
    assert tracker.state()["pending"] == 0
    assert tracker.percentiles()["p50_s"] == pytest.approx(0.8)


def test_tracker_pending_cap_evicts_oldest():
    tracker = FinalityTracker(
        sample_every=1, pending_cap=16, clock=_clock_factory()
    )
    keys = [ingress_key(b"cap-%d" % i) for i in range(20)]
    for i, key in enumerate(keys):
        tracker.on_submit(key, float(i), float(i))
    assert tracker.state()["pending"] == 16
    assert tracker.state()["expired"] == 4
    # The evicted earliest key no longer completes.
    tracker.on_commit(keys[0], 30.0, 30.0)
    assert tracker.state()["completed"] == 0


def test_client_recorder_keeps_first_submit_stamp():
    clock = _clock_factory(start=0.0, step=1.0)
    recorder = ClientFinalityRecorder(sample_every=1, clock=clock)
    key = ingress_key(b"retry")
    recorder.note_submitted(key)  # t=1
    recorder.note_submitted(key)  # retry at t=2: first stamp kept
    recorder.note_finalized([key, ingress_key(b"unknown")])  # t=3
    assert recorder.samples() == [pytest.approx(2.0)]
    assert recorder.completed == 1
    assert recorder.percentiles()["p50_s"] == pytest.approx(2.0)


def test_mempool_stamps_admission_and_proposal_phases():
    clock = _clock_factory(start=0.0, step=0.5)
    tracker = FinalityTracker(sample_every=1, clock=clock)
    pool = Mempool(IngressParameters(), finality=tracker)
    txs = [b"stamped-%d" % i for i in range(3)]
    accepted, _ = pool.submit("c", txs, t_submit=0.1)
    assert accepted == 3
    drained = pool.drain(10)
    assert sorted(drained) == sorted(txs)
    tracker.on_commit(ingress_key(txs[0]), 5.0, 5.5)
    assert tracker.state()["completed"] == 1
    # submit observed admission for all 3, drain observed proposal for all
    # 3, commit closed one total = 5.5 - 0.1.
    assert tracker.samples() == [pytest.approx(5.4)]


# -- tag 15/16 soft wire extension --------------------------------------------


def test_subscribe_want_details_suffix_roundtrip():
    plain = GatewaySubscribeCommits(12345)
    assert decode_message(encode_message(plain)) == plain
    # Opting out encodes byte-identically to the pre-r17 frame.
    assert encode_message(GatewaySubscribeCommits(12345, 0)) == (
        encode_message(plain)
    )
    detailed = GatewaySubscribeCommits(7, 1)
    decoded = decode_message(encode_message(detailed))
    assert decoded == detailed and decoded.want_details == 1


def test_notification_detail_suffix_roundtrip():
    keys = (b"k" * 16, b"j" * 16)
    plain = GatewayCommitNotification(7, keys)
    assert decode_message(encode_message(plain)) == plain
    assert plain.leader_round == 0 and plain.committed_ts_ns == 0
    detailed = GatewayCommitNotification(7, keys, 42, 1_700_000_000_000_000_000)
    decoded = decode_message(encode_message(detailed))
    assert decoded == detailed
    assert decoded.leader_round == 42
    assert decoded.committed_ts_ns == 1_700_000_000_000_000_000
    # The suffix is omitted at defaults, so old decoders never see it.
    assert len(encode_message(plain)) + 16 == len(encode_message(detailed))


# -- the seeded Byzantine acceptance sim --------------------------------------


def _decision_census(records):
    census = {}
    for record in records:
        key = f"{record['rule']}-{record['outcome']}"
        census[key] = census.get(key, 0) + 1
    return census


@pytest.mark.chaos
def test_byzantine_sim_every_slot_explained_and_ledger_deterministic(tmp_path):
    """10 nodes, f=3 attacking (the PR 12 harness): every decided leader
    slot in every honest ledger carries a record (slot coverage audited
    against the committer's own leader schedule, so a skipped slot with
    no explanation would show as a hole), skips exist and explain
    renders them, and two same-seed runs produce byte-identical
    canonical ledgers."""
    from mysticeti_tpu.chaos import run_chaos_sim
    from mysticeti_tpu.scenarios import (
        oracle_verifier_factory,
        scenario_by_name,
    )

    scenario = dataclasses.replace(
        scenario_by_name("byzantine-at-f"), duration_s=3.5
    )
    adversaries = {spec.node for spec in scenario.adversaries}

    def run_once(tag):
        return run_chaos_sim(
            scenario.plan(), scenario.nodes, scenario.duration_s,
            str(tmp_path / tag),
            parameters=scenario.base_parameters(),
            latency_ranges=scenario.latency_ranges(),
            with_metrics=True,
            verifier_factory=oracle_verifier_factory(scenario.nodes),
        )

    _report_a, harness_a = run_once("a")
    _report_b, harness_b = run_once("b")

    total_skips = 0
    compared = 0
    for authority in range(scenario.nodes):
        if authority in adversaries:
            continue
        node_a = harness_a.nodes[authority]
        node_b = harness_b.nodes[authority]
        if node_a is None or node_b is None:
            continue
        ledger = node_a.core.committer.ledger
        records = ledger.records()
        assert records, f"node {authority}: empty decision ledger"
        # Slot coverage: every round between the first and last decided
        # round carries exactly one record per elected leader.
        by_round = {}
        for record in records:
            by_round.setdefault(record["round"], []).append(record)
        committer = node_a.core.committer
        for round_ in range(records[0]["round"], records[-1]["round"] + 1):
            expected = len(committer.get_leaders(round_))
            got = len(by_round.get(round_, []))
            assert got == expected, (
                f"node {authority}: round {round_} has {got} record(s), "
                f"committer elects {expected} leader(s)"
            )
        census = _decision_census(records)
        total_skips += sum(
            count for key, count in census.items() if key.endswith("-skip")
        )
        # Every record renders as a causal explanation.
        for record in records:
            text = explain_record(record)
            assert record["slot"] in text and record["outcome"].upper() in text
        # Same seed, same ledger bytes.
        assert (
            ledger.ledger_bytes()
            == node_b.core.committer.ledger.ledger_bytes()
        ), f"node {authority}: ledger diverged across same-seed runs"
        compared += 1
    assert compared >= scenario.nodes - len(adversaries) - 1
    # f=3 of 10 attacking MUST produce skipped slots — and each one was
    # covered by the audit above, so every skip has its explanation.
    assert total_skips > 0
