"""mysticeti-lint: per-rule positive/negative fixtures + the repo gate.

Every rule must (a) catch its fixture violation and (b) stay silent on the
compliant twin — a rule that can't tell the two apart enforces nothing.
The final tests run the analyzer over the real package (in-process and via
the ``python -m mysticeti_tpu.analysis`` CLI, the tier-1 CI registration)
and require zero non-baselined findings.
"""
import json
import os
import subprocess
import sys
import textwrap

from mysticeti_tpu.analysis import (
    RULES,
    analyze_paths,
    analyze_source,
    load_baseline,
    new_findings,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mysticeti_tpu")
BASELINE = os.path.join(PKG, "analysis", "baseline.json")


def run(src, path="mysticeti_tpu/example.py", **kw):
    return analyze_source(textwrap.dedent(src), path, **kw)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- rule 1: async-blocking ---------------------------------------------------

def test_async_blocking_positive_sleep():
    findings = run(
        """
        import time

        async def handler():
            time.sleep(0.1)
        """
    )
    assert rules_of(findings) == ["async-blocking"]
    assert "time.sleep" in findings[0].message


def test_async_blocking_positive_direct_dispatch():
    findings = run(
        """
        async def flush(verifier, pks, digests, sigs):
            return verifier.verify_signatures(pks, digests, sigs)
        """
    )
    assert rules_of(findings) == ["async-blocking"]
    assert "verify_signatures" in findings[0].message


def test_async_blocking_negative():
    findings = run(
        """
        import asyncio
        import time

        async def handler(loop, verifier, pks, digests, sigs):
            await asyncio.sleep(0.1)

            def _dispatch():
                # sync nested fn: runs in the executor, not the loop
                return verifier.verify_signatures(pks, digests, sigs)

            return await loop.run_in_executor(None, _dispatch)

        def sync_path():
            time.sleep(0.1)  # blocking is fine outside coroutines
        """
    )
    assert findings == []


# -- rule 2: task-orphan ------------------------------------------------------

def test_task_orphan_positive_shapes():
    findings = run(
        """
        import asyncio

        class Node:
            def start_discarded(self):
                asyncio.ensure_future(self._run())

            def start_attr(self):
                self._task = asyncio.ensure_future(self._run())

            def start_appended(self, loop):
                self._tasks.append(loop.create_task(self._run()))

            def start_lambda(self, loop):
                loop.call_later(1.0, lambda: asyncio.ensure_future(self._run()))
        """
    )
    assert [f.rule for f in findings] == ["task-orphan"] * 4


def test_task_orphan_negative_shapes():
    findings = run(
        """
        import asyncio

        class Node:
            def start_supervised(self):
                self._task = asyncio.ensure_future(self._run())
                self._task.add_done_callback(self._on_done)

            async def awaited(self):
                task = asyncio.ensure_future(self._run())
                return await task

            async def raced(self):
                first = asyncio.ensure_future(self._recv())
                second = asyncio.ensure_future(self._closed.wait())
                done, pending = await asyncio.wait({first, second})

            def handed_to_caller(self):
                return asyncio.ensure_future(self._run())

            def via_helper(self, log):
                self._task = spawn_logged(self._run(), log)
        """
    )
    assert findings == []


# -- rule 3: lock-discipline --------------------------------------------------

def test_lock_discipline_positive_await_under_lock():
    findings = run(
        """
        import threading

        class Collector:
            def __init__(self):
                self._lock = threading.Lock()

            async def flush(self):
                with self._lock:
                    await self._dispatch()
        """
    )
    assert rules_of(findings) == ["lock-discipline"]
    assert "await while holding" in findings[0].message


def test_lock_discipline_positive_guarded_field():
    findings = run(
        """
        import threading

        class Hybrid:
            def __init__(self):
                self._ema_lock = threading.Lock()
                self.cpu_per_sig_s = 0.0  # __init__ is exempt

            def observe(self, sample):
                self.cpu_per_sig_s = 0.8 * self.cpu_per_sig_s + 0.2 * sample
        """
    )
    assert rules_of(findings) == ["lock-discipline"]
    assert "cpu_per_sig_s" in findings[0].message


def test_lock_discipline_negative():
    findings = run(
        """
        import asyncio
        import threading

        class Hybrid:
            def __init__(self):
                self._ema_lock = threading.Lock()
                self._alock = asyncio.Lock()
                self.cpu_per_sig_s = 0.0

            def observe(self, sample):
                with self._ema_lock:
                    self.cpu_per_sig_s = 0.8 * self.cpu_per_sig_s + 0.2 * sample

            async def async_section(self):
                async with self._alock:
                    await self._dispatch()
        """
    )
    assert findings == []


# -- rule 4: jit-purity -------------------------------------------------------

_JIT_FIXTURE = """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np


    @jax.jit
    def kernel(x):
        jax.debug.print("x = {}", x)
        return np.asarray(x) + 1


    @functools.partial(jax.jit, static_argnames=("tile",))
    def tiled(x, tile):
        return jnp.sum(x) + x.item()


    def wrapped_impl(x):
        print(x)
        return x

    wrapped = jax.jit(wrapped_impl)
"""


def test_jit_purity_positive_in_ops():
    findings = run(_JIT_FIXTURE, path="mysticeti_tpu/ops/fake_kernel.py")
    assert [f.rule for f in findings] == ["jit-purity"] * 4
    messages = " ".join(f.message for f in findings)
    assert "jax.debug.print" in messages
    assert ".item()" in messages
    assert "numpy.asarray" in messages
    assert "print()" in messages


def test_jit_purity_negative():
    # Same host-impure code OUTSIDE ops/ and parallel/: other rules own the
    # generic paths; jit purity is scoped to kernel directories.
    assert run(_JIT_FIXTURE, path="mysticeti_tpu/example.py") == []
    # Pure jnp kernels in ops/ are clean.
    clean = run(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return jnp.sum(x * x)
        """,
        path="mysticeti_tpu/ops/fake_kernel.py",
    )
    assert clean == []


# -- rule 5: wall-clock -------------------------------------------------------

def test_wall_clock_positive():
    findings = run(
        """
        import time

        def measure(work):
            started = time.time()
            work()
            return time.time() - started
        """
    )
    assert rules_of(findings) == ["wall-clock"]


def test_wall_clock_negative():
    findings = run(
        """
        import time

        def measure(work):
            started = time.monotonic()
            work()
            return time.monotonic() - started

        def stamp():
            # Timestamping (no interval arithmetic) is the wall clock's job.
            return time.time()
        """
    )
    assert findings == []


# -- rule 6: metrics-labels ---------------------------------------------------

_METRIC_LABELS = {"verified_signatures_total": ("backend", "outcome")}


def test_metrics_labels_positive():
    findings = run(
        """
        class Verifier:
            def count(self, n):
                self.metrics.verified_signatures_total.labels("tpu").inc(n)
        """,
        metric_labels=_METRIC_LABELS,
    )
    assert rules_of(findings) == ["metrics-labels"]
    assert "verified_signatures_total" in findings[0].message


def test_metrics_labels_negative():
    findings = run(
        """
        class Verifier:
            def count(self, n):
                self.metrics.verified_signatures_total.labels("tpu", "accepted").inc(n)
                self.other_series.labels("anything")  # undeclared: skipped
        """,
        metric_labels=_METRIC_LABELS,
    )
    assert findings == []


# -- rule 7: span-names -------------------------------------------------------

_SPAN_STAGES = ("receive", "verify", "commit")


def test_span_names_positive_typo():
    findings = run(
        """
        def trace(tracer, ref):
            tracer.record_span("recieve", ref, 0.0)
            tracer.begin_span("comit", ref)
        """,
        span_stages=_SPAN_STAGES,
    )
    assert rules_of(findings) == ["span-names"]
    assert len(findings) == 2
    assert "recieve" in findings[0].message


def test_span_names_negative():
    findings = run(
        """
        def trace(tracer, ref, stage):
            tracer.record_span("receive", ref, 0.0)
            tracer.begin_span("verify", ref)
            tracer.end_span("commit", ref)
            tracer.end_span(stage, ref)      # computed stage: skipped
            writer.flush("recieve")          # not a span call
        """,
        span_stages=_SPAN_STAGES,
    )
    assert findings == []


def test_span_names_skipped_without_registry():
    findings = run(
        """
        def trace(tracer, ref):
            tracer.record_span("anything-goes", ref, 0.0)
        """
    )
    assert findings == []


def test_span_registry_parsed_from_spans_py():
    """analyze_paths picks the registry up from the real spans.py; it must
    stay a literal tuple so the parse keeps working."""
    import ast

    from mysticeti_tpu.analysis.checker import collect_span_stages
    from mysticeti_tpu.spans import STAGES

    with open(os.path.join(PKG, "spans.py")) as fh:
        parsed = collect_span_stages(ast.parse(fh.read()))
    assert parsed == STAGES


# -- rule 8: metrics-doc ------------------------------------------------------

_METRICS_SRC = """
import ast
from prometheus_client import Counter


class M:
    def __init__(self, r):
        def counter(name, doc, labels=()):
            return Counter(name, doc, labelnames=labels, registry=r)

        self.committed = counter("committed_leaders_total", "x")
        self.health = counter(
            "mysticeti_health_commit_rate", "x", labels=("authority",)
        )
"""


def _doc_findings(doc_text):
    import ast as _ast
    import textwrap as _tw

    from mysticeti_tpu.analysis import check_metrics_doc, collect_metric_names

    names = collect_metric_names(_ast.parse(_tw.dedent(_METRICS_SRC)))
    return check_metrics_doc(
        names, "mysticeti_tpu/metrics.py", _tw.dedent(doc_text),
        "docs/observability.md",
    )


def test_metrics_doc_clean_when_inventory_matches():
    findings = _doc_findings(
        """
        | `committed_leaders_total` | counter | decided leaders |
        | `mysticeti_health_commit_rate` | gauge | commits/s |
        The `mysticeti_health_*` family is sampled by the probe.
        """
    )
    assert findings == []  # wildcard families never count as series


def test_metrics_doc_flags_registered_but_undocumented():
    findings = _doc_findings(
        "| `mysticeti_health_commit_rate` | gauge | commits/s |\n"
    )
    assert [f.rule for f in findings] == ["metrics-doc"]
    assert "committed_leaders_total" in findings[0].message
    assert findings[0].path == "mysticeti_tpu/metrics.py"
    assert findings[0].line > 0  # anchored at the registration line


def test_metrics_doc_flags_documented_but_unregistered():
    findings = _doc_findings(
        """
        | `committed_leaders_total` | counter | decided leaders |
        | `mysticeti_health_commit_rate` | gauge | commits/s |
        | `mysticeti_health_ghost_series` | gauge | renamed away |
        """
    )
    assert [f.rule for f in findings] == ["metrics-doc"]
    assert "mysticeti_health_ghost_series" in findings[0].message
    assert findings[0].path == "docs/observability.md"


def test_metrics_doc_token_match_is_word_bounded():
    # `latency_s` must not ride on `latency_squared_s`-style substrings.
    import ast as _ast

    from mysticeti_tpu.analysis import check_metrics_doc, collect_metric_names

    names = collect_metric_names(
        _ast.parse("self.latency_s = counter('latency_s', 'x')")
    )
    findings = check_metrics_doc(
        names, "m.py", "only `latency_s_total_squared_x` here", "d.md"
    )
    assert len(findings) == 1 and "latency_s" in findings[0].message


def test_metrics_doc_repo_gate_inventory_is_complete():
    """The committed tree's inventory: every registered series documented,
    every documented mysticeti_* series registered (baseline stays empty,
    so this is the live drift gate)."""
    import ast as _ast

    from mysticeti_tpu.analysis import check_metrics_doc, collect_metric_names

    with open(os.path.join(PKG, "metrics.py")) as fh:
        names = collect_metric_names(_ast.parse(fh.read()))
    assert len(names) > 40  # the real registry, not a parse miss
    with open(os.path.join(REPO, "docs", "observability.md")) as fh:
        doc = fh.read()
    findings = check_metrics_doc(
        names, "mysticeti_tpu/metrics.py", doc, "docs/observability.md"
    )
    assert findings == [], "\n".join(f.render() for f in findings)


# -- rule 9: sim-taint --------------------------------------------------------

# The PR 11 regression shape: a real drain-thread's progress census flows
# through a module-wide dict key into another class's admission branch.
_PR11_FIXTURE = """
    class HealthProbe:
        def __init__(self, core):
            self.core = core

        def sample(self):
            signals = {}
            signals["wal_backlog"] = bool(self.core.wal_writer.pending())
            return signals


    class AdmissionController:
        def admit(self, signals):
            if signals.get("wal_backlog"):
                return False
            return True
"""

# The PR 12 regression shape: a wall-clock dispatch measurement folds into a
# field EMA, returns through a helper method, and arms a virtual-time timer.
_PR12_FIXTURE = """
    import time


    class BatchedVerifier:
        def __init__(self, loop):
            self.loop = loop
            self._dispatch_ema_s = 0.001

        def _observe_dispatch(self, started):
            wall = time.monotonic() - started
            self._dispatch_ema_s = 0.9 * self._dispatch_ema_s + 0.1 * wall

        def _effective_delay_s(self):
            return min(0.05, self._dispatch_ema_s * 4.0)

        def _arm_flush(self):
            self.loop.call_later(self._effective_delay_s(), self._flush)

        def _flush(self):
            pass
"""


def test_sim_taint_catches_pr11_wal_backlog_shape():
    findings = run(_PR11_FIXTURE)
    assert "sim-taint" in rules_of(findings)
    messages = " ".join(f.message for f in findings)
    assert "thread-progress" in messages
    assert "branch decision" in messages


def test_sim_taint_catches_pr12_dispatch_ema_shape():
    findings = run(_PR12_FIXTURE)
    assert "sim-taint" in rules_of(findings)
    messages = " ".join(f.message for f in findings)
    assert "wall-clock" in messages
    assert "timer delay" in messages


def test_sim_taint_unseeded_random_into_timer():
    findings = run(
        """
        import asyncio
        import random

        async def retry_pause():
            await asyncio.sleep(random.uniform(0.05, 0.1))
        """
    )
    assert rules_of(findings) == ["sim-taint"]
    assert "unseeded-random" in findings[0].message


def test_sim_taint_negative_gated_and_seeded():
    findings = run(
        """
        import time

        from .runtime import is_simulated, now as runtime_now


        class Calibrator:
            def __init__(self, rng):
                self._rng = rng
                self._cpu_probe = 0.0

            def calibrate(self):
                if not is_simulated():
                    started = time.monotonic()
                    self._cpu_probe = time.monotonic() - started
                if self._cpu_probe > 0.5:
                    return "slow"
                return "fast"

            async def jittered_pause(self, loop):
                # seeded instance RNG: a different dotted head than the
                # module-global random.*
                await __import__("asyncio").sleep(self._rng.uniform(0.01, 0.02))

            def stamp(self):
                return runtime_now()
        """
    )
    assert findings == []


def test_sim_taint_suppression_at_source_silences_all_sinks():
    # One ignore at the nondeterministic READ covers every downstream sink
    # finding (suppression-at-cause, not per-sink).  The unsuppressed twin
    # fires sim-taint (checked above), so an empty sim-taint set here means
    # the single source-line comment silenced them all — and was counted as
    # used (no unused-suppression finding either).
    src = _PR12_FIXTURE.replace(
        "wall = time.monotonic() - started",
        "wall = time.monotonic() - started  # lint: ignore[sim-taint]",
    )
    rules = rules_of(run(src))
    assert "sim-taint" not in rules
    assert "unused-suppression" not in rules


# -- rule 10: await-atomicity -------------------------------------------------

def test_await_atomicity_positive_rmw_spans_await():
    findings = run(
        """
        class Window:
            async def refill(self):
                budget = self.budget
                await self._fetch()
                self.budget = budget + 1
        """
    )
    assert rules_of(findings) == ["await-atomicity"]
    assert "budget" in findings[0].message


def test_await_atomicity_positive_branch_then_write():
    findings = run(
        """
        class Dispatcher:
            async def maybe_flush(self):
                if self._pending_count >= self.batch_size:
                    batch = await self._drain()
                    self._pending_count = 0
                    return batch
        """
    )
    assert rules_of(findings) == ["await-atomicity"]


def test_await_atomicity_negative_lock_held_across_suspension():
    findings = run(
        """
        import asyncio

        class Window:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def refill(self):
                async with self._lock:
                    budget = self.budget
                    await self._fetch()
                    self.budget = budget + 1
        """
    )
    assert findings == []


def test_await_atomicity_negative_augassign_counter_pair():
    # Each += / -= is its own atomic RMW (no read parked across the await).
    findings = run(
        """
        class Gateway:
            async def _handle(self, conn):
                self.connections += 1
                try:
                    await self._serve(conn)
                finally:
                    self.connections -= 1
        """
    )
    assert findings == []


def test_await_atomicity_negative_while_retest_semaphore():
    # The while condition re-evaluates AFTER the body's await: the read the
    # write pairs with is post-suspension, not parked across it.
    findings = run(
        """
        class Pipeline:
            async def _acquire(self):
                while self._inflight >= self.depth:
                    await self._drained.wait()
                self._inflight += 1
        """
    )
    assert findings == []


def test_await_atomicity_single_owner_annotation():
    src = """
        # lint: single-owner[core_task]
        class CoreState:
            async def advance(self):
                round_ = self.round
                await self._persist()
                self.round = round_ + 1
    """
    assert run(src) == []
    # Without the annotation the same shape fires.
    stripped = src.replace("# lint: single-owner[core_task]", "pass")
    assert rules_of(run(stripped)) == ["await-atomicity"]


# -- rules 11+12: lock-order + guard-inference --------------------------------

def test_lock_order_cycle_detected_across_methods():
    import ast as _ast

    from mysticeti_tpu.analysis.checker import _collect_aliases
    from mysticeti_tpu.analysis.lockgraph import (
        collect_module_locks,
        find_lock_cycles,
        lock_order_messages,
    )

    src = textwrap.dedent(
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
    )
    tree = _ast.parse(src)
    module = collect_module_locks(
        tree, _collect_aliases(tree), "mysticeti_tpu/example.py", src
    )
    cycles = find_lock_cycles(module.edges)
    assert cycles, "inverted acquisition order must form a cycle"
    messages = lock_order_messages(cycles)
    assert any("Pair._a" in m and "Pair._b" in m for _, _, m in messages)


def test_lock_order_consistent_nesting_is_clean():
    import ast as _ast

    from mysticeti_tpu.analysis.checker import _collect_aliases
    from mysticeti_tpu.analysis.lockgraph import (
        collect_module_locks,
        find_lock_cycles,
    )

    src = textwrap.dedent(
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """
    )
    tree = _ast.parse(src)
    module = collect_module_locks(
        tree, _collect_aliases(tree), "mysticeti_tpu/example.py", src
    )
    assert find_lock_cycles(module.edges) == []


def test_guard_inference_flags_stray_unguarded_write():
    findings = run(
        """
        import threading

        class Census:
            def __init__(self):
                self._lock = threading.Lock()
                self.tally = 0

            def bump(self):
                with self._lock:
                    self.tally += 1

            def bump2(self):
                with self._lock:
                    self.tally += 2

            def reset(self):
                self.tally = 0
        """
    )
    assert "guard-inference" in rules_of(findings)
    assert any("tally" in f.message for f in findings)


def test_guard_inference_negative_all_writes_guarded():
    findings = run(
        """
        import threading

        class Census:
            def __init__(self):
                self._lock = threading.Lock()
                self.tally = 0

            def bump(self):
                with self._lock:
                    self.tally += 1

            def reset(self):
                with self._lock:
                    self.tally = 0
        """
    )
    assert findings == []


def test_guard_inference_holds_annotation_covers_callee():
    src = """
        import threading

        class Flusher:
            def __init__(self):
                self._lock = threading.Lock()
                self.dirty = 0

            def mark(self):
                with self._lock:
                    self.dirty += 1

            def mark2(self):
                with self._lock:
                    self.dirty += 2

            def _drain(self):  # lint: holds[_lock]
                self.dirty = 0
    """
    assert run(src) == []
    stripped = src.replace("  # lint: holds[_lock]", "")
    assert "guard-inference" in rules_of(run(stripped))


# -- suppressions and baseline ------------------------------------------------

def test_inline_suppression_matches_rule():
    src = """
        import time

        async def handler():
            time.sleep(0.1)  # lint: ignore[async-blocking]
    """
    assert run(src) == []
    # A suppression naming a DIFFERENT rule does not silence the finding —
    # and the mismatched comment is itself flagged as unused.
    wrong = src.replace("async-blocking", "wall-clock")
    assert rules_of(run(wrong)) == ["async-blocking", "unused-suppression"]


def test_unused_suppression_flagged_and_module_directive_exempt():
    findings = run(
        """
        import asyncio

        async def fine():
            await asyncio.sleep(0.1)  # lint: ignore[async-blocking]
        """
    )
    assert rules_of(findings) == ["unused-suppression"]
    assert "async-blocking" in findings[0].message
    # Module-wide directives document a file-level policy; they are exempt
    # from staleness (their whole point is covering future code too).
    assert run(
        """
        # lint: ignore-module[sim-taint]
        import asyncio

        async def fine():
            await asyncio.sleep(0.1)
        """
    ) == []


def test_suppression_text_inside_strings_is_not_a_directive():
    # Only real COMMENT tokens count: a docstring or f-string mentioning the
    # ignore syntax must neither suppress nor count as unused.
    findings = run(
        '''
        def helper():
            """Write `# lint: ignore[async-blocking]` at the call site."""
            return "# lint: ignore[wall-clock]"
        '''
    )
    assert findings == []


def test_baseline_tolerates_exactly_the_recorded_count(tmp_path):
    src = """
        import time

        async def handler():
            time.sleep(0.1)
    """
    found = run(src)
    path = str(tmp_path / "baseline.json")
    write_baseline(path, found)
    baseline = load_baseline(path)
    assert new_findings(found, baseline) == []
    # A second identical violation exceeds the baselined count.
    doubled = run(
        src
        + """
        async def handler2():
            time.sleep(0.2)
    """
    )
    assert len(new_findings(doubled, baseline)) == 1


# -- the repo gate (tier-1) ---------------------------------------------------

def test_package_has_zero_nonbaselined_findings():
    findings = analyze_paths([PKG], root=REPO)
    fresh = new_findings(findings, load_baseline(BASELINE))
    assert fresh == [], "\n".join(f.render() for f in fresh)


def test_cli_gate_exits_zero():
    """The CI registration: `python -m mysticeti_tpu.analysis` must gate at
    zero new findings on the committed tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "mysticeti_tpu.analysis"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_and_rule_listing():
    proc = subprocess.run(
        [sys.executable, "-m", "mysticeti_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0
    assert set(proc.stdout.split()) == set(RULES)
    proc = subprocess.run(
        [sys.executable, "-m", "mysticeti_tpu.analysis", "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0
    assert json.loads(proc.stdout) == []


def test_lint_tool_alias():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0
    assert set(proc.stdout.split()) == set(RULES)


# -- CI/editor integration: sarif, changed, cache, parallel -------------------

def test_cli_sarif_format(tmp_path):
    src = textwrap.dedent(
        """
        import time

        async def handler():
            time.sleep(0.1)
        """
    )
    target = tmp_path / "fixture.py"
    target.write_text(src)
    proc = subprocess.run(
        [
            sys.executable, "-m", "mysticeti_tpu.analysis",
            "--no-baseline", "--no-cache", "--format", "sarif", str(target),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 1  # findings present
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run_ = doc["runs"][0]
    assert run_["tool"]["driver"]["name"] == "mysticeti-lint"
    assert {r["id"] for r in run_["tool"]["driver"]["rules"]} == set(RULES)
    (result,) = run_["results"]
    assert result["ruleId"] == "async-blocking"
    region = result["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] > 1


def test_cli_changed_mode_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "mysticeti_tpu.analysis", "--changed"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_analyze_paths_cache_roundtrip_and_parallel(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    for i in range(6):
        (pkg / f"mod{i}.py").write_text(textwrap.dedent(
            f"""
            import time

            async def handler{i}():
                time.sleep(0.{i + 1})
            """
        ))
    root = str(tmp_path)
    first = analyze_paths([str(pkg)], root=root, jobs=2)
    assert len(first) == 6
    cache_file = tmp_path / ".lint-cache.json"
    assert cache_file.exists()
    # Warm pass: identical results straight from the cache.
    second = analyze_paths([str(pkg)], root=root, jobs=2)
    assert [f.fingerprint() for f in second] == [
        f.fingerprint() for f in first
    ]
    # Editing one file invalidates exactly its entry.
    (pkg / "mod0.py").write_text("x = 1\n")
    third = analyze_paths([str(pkg)], root=root)
    assert len(third) == 5
    # And disabling the cache still produces the same verdict.
    assert len(analyze_paths([str(pkg)], root=root, use_cache=False)) == 5


# -- rule 14: native-fallback -------------------------------------------------


def test_native_fallback_positive_unguarded_call():
    findings = run(
        """
        from .native import native

        def scan(buf):
            return native.wal_scan(buf)
        """
    )
    assert rules_of(findings) == ["native-fallback"]
    assert "wal_scan" in findings[0].message


def test_native_fallback_positive_aliased_import():
    findings = run(
        """
        from mysticeti_tpu.native import native as _native

        def scan(buf):
            return _native.frame_entry(buf)
        """
    )
    assert rules_of(findings) == ["native-fallback"]


def test_native_fallback_negative_is_not_none_gate():
    findings = run(
        """
        from .native import native

        def scan(buf):
            if native is not None:
                return native.wal_scan(buf)
            return pure_scan(buf)
        """
    )
    assert findings == []


def test_native_fallback_negative_else_of_none_gate():
    findings = run(
        """
        from .native import native as _native

        def scan(buf):
            if _native is None:
                return pure_scan(buf)
            else:
                return _native.wal_scan(buf)
        """
    )
    assert findings == []


def test_native_fallback_negative_early_return_promotion():
    findings = run(
        """
        from .native import native

        def scan(buf):
            if native is None:
                return pure_scan(buf)
            return native.wal_scan(buf)
        """
    )
    assert findings == []


def test_native_fallback_negative_hasattr_and_conjunction_gates():
    findings = run(
        """
        from .native import native as _native

        def scan(buf, end):
            if _native is not None and end > 0:
                return _native.wal_scan(buf, end)
            if hasattr(_native, "frame_entry"):
                return _native.frame_entry(buf)
            return pure_scan(buf)
        """
    )
    assert findings == []


def test_native_fallback_positive_wrong_polarity_branch():
    # The call sits in the None branch: exactly the crash the rule exists for.
    findings = run(
        """
        from .native import native

        def scan(buf):
            if native is None:
                return native.wal_scan(buf)
            return pure_scan(buf)
        """
    )
    assert rules_of(findings) == ["native-fallback"]


def test_native_fallback_inline_suppression():
    findings = run(
        """
        from .native import native

        def scan(buf):
            return native.wal_scan(buf)  # lint: ignore[native-fallback]
        """
    )
    assert findings == []
