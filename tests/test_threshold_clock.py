"""Threshold clock tests — mirrors threshold_clock.rs:96-153."""
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.threshold_clock import (
    ThresholdClockAggregator,
    threshold_clock_valid_non_genesis,
)
from mysticeti_tpu.types import BlockReference
from mysticeti_tpu.utils.dag import Dag


def ref(authority, round_):
    return BlockReference(authority, round_, bytes(32))


class TestValidity:
    def test_threshold_clock_valid(self):
        committee = Committee.new_test([1, 1, 1, 1])
        cases = [
            ("A1:[]", False),
            ("A1:[A0, B0]", False),
            ("A1:[A0, B0, C0]", True),
            ("A1:[A0, B0, C0, D0]", True),
            ("A2:[A1, B1, C0, D0]", False),
            ("A2:[A1, B1, C1, D0]", True),
        ]
        for dsl, expected in cases:
            # rounds >1 need the included round-1 blocks drawn first
            prefix = "A1:[A0,B0,C0]; B1:[A0,B0,C0]; C1:[A0,B0,C0]; "
            block = Dag.draw(prefix + dsl).blocks[dsl.split(":")[0].strip()]
            assert (
                threshold_clock_valid_non_genesis(block, committee) is expected
            ), dsl


class TestAggregator:
    def test_reference_sequence(self):
        committee = Committee.new_test([1, 1, 1, 1])
        agg = ThresholdClockAggregator(0)
        agg.add_block(ref(0, 0), committee)
        assert agg.get_round() == 0
        agg.add_block(ref(0, 1), committee)
        assert agg.get_round() == 1
        agg.add_block(ref(1, 0), committee)
        assert agg.get_round() == 1
        agg.add_block(ref(1, 1), committee)
        assert agg.get_round() == 1
        agg.add_block(ref(2, 1), committee)
        assert agg.get_round() == 2
        agg.add_block(ref(3, 1), committee)
        assert agg.get_round() == 2
