"""Fleet health plane: probe/watchdog unit semantics, commit critical-path
attribution from the span stream, cluster aggregation, the deterministic
chaos-sim acceptance path (seeded partition + crash-restart -> SLO alerts
naming the stalled authority and stage, byte-identical health timeline
across same-seed runs), and the trace_report robustness satellites."""
import asyncio
import json
import os
import sys

import pytest

from mysticeti_tpu.health import (
    Alert,
    CriticalPathAnalyzer,
    FleetHealthMonitor,
    HealthProbe,
    SLOThresholds,
    cluster_snapshot,
    cluster_snapshot_from_texts,
    node_health_from_series,
)
from mysticeti_tpu.metrics import Metrics, serve_metrics
from mysticeti_tpu.spans import SpanTracer
from mysticeti_tpu.types import BlockReference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# -- stubs --------------------------------------------------------------------


class _FakeWal:
    def __init__(self):
        self.backlog = False

    def pending(self):
        return self.backlog


class _FakeStore:
    def __init__(self, last_seen):
        self.last_seen = last_seen

    def last_seen_by_authority(self, a):
        return self.last_seen.get(a, 0)


class _FakeCore:
    def __init__(self, authority=0, n=4):
        self.authority = authority
        self.round = 0
        self.wal_writer = _FakeWal()
        self.block_store = _FakeStore({a: 0 for a in range(n)})

    def current_round(self):
        return self.round


class _FakeObserver:
    class _Interp:
        last_height = 0

    def __init__(self):
        self.commit_interpreter = self._Interp()


def _probe(slo=None, n=4, metrics=None):
    clock = {"t": 0.0}
    probe = HealthProbe(
        0, n, metrics=metrics, slo=slo or SLOThresholds(),
        clock=lambda: clock["t"],
    )
    core = _FakeCore(0, n)
    observer = _FakeObserver()
    probe.attach(core=core, net_syncer=None, commit_observer=observer)
    return probe, core, observer, clock


# -- probe / watchdog units ---------------------------------------------------


def test_slo_thresholds_json_round_trip():
    slo = SLOThresholds(
        min_commit_rate=1.5, max_round_stall_s=7.0, max_commit_stall_s=9.0,
        max_authority_lag_rounds=12, max_breaker_open_fraction=0.25,
        min_participation=0.8,
    )
    assert SLOThresholds.from_dict(json.loads(json.dumps(slo.to_dict()))) == slo


def test_probe_rates_and_frontier():
    probe, core, observer, clock = _probe()
    s0 = probe.sample()
    assert s0["round"] == 0 and s0["status"] == "ok"
    # One second later: 4 rounds and 2 commits happened; peer 2 lags.
    clock["t"] = 1.0
    core.round = 4
    observer.commit_interpreter.last_height = 2
    core.block_store.last_seen = {1: 4, 2: 1, 3: 6}
    s1 = probe.sample()
    assert s1["round_advance_rate"] > 0
    assert s1["commit_rate"] > 0
    assert s1["authority_lag_rounds"] == {"1": 0, "2": 3, "3": 0}
    assert s1["frontier_skew_rounds"] == 2  # peer 3 is at round 6, we at 4
    assert s1["round_stall_s"] == 0.0


def test_watchdog_round_stall_fires_once_and_clears():
    probe, core, observer, clock = _probe(
        slo=SLOThresholds(max_round_stall_s=5.0)
    )
    probe.sample()
    clock["t"] = 6.0
    s = probe.sample()
    assert s["status"] == "degraded"
    assert [a.kind for a in probe.alerts] == ["round-stall"]
    assert probe.alerts[0].stage == "receive"
    assert probe.alerts[0].observer == 0
    # Still stalled: NO duplicate alert (transition semantics).
    clock["t"] = 8.0
    probe.sample()
    assert len(probe.alerts) == 1
    # Round advances: the alert clears, a later stall re-fires.
    clock["t"] = 9.0
    core.round = 3
    assert probe.sample()["status"] == "ok"
    clock["t"] = 20.0
    probe.sample()
    assert [a.kind for a in probe.alerts] == ["round-stall", "round-stall"]


def test_watchdog_commit_rate_floor_does_not_collide_with_stall():
    """min_commit_rate uses its own alert kind: sharing commit-stall's
    firing key would let the healthy stall check clear it every tick and
    the rate alert re-fire per sample (per-tick spam)."""
    probe, core, observer, clock = _probe(
        slo=SLOThresholds(
            max_round_stall_s=0.0, max_commit_stall_s=100.0,
            min_commit_rate=5.0,
        )
    )
    probe.sample()
    for t in (1.0, 2.0, 3.0):
        clock["t"] = t
        core.round += 1  # rounds move; commits crawl below the floor
        observer.commit_interpreter.last_height += 1
        probe.sample()
    kinds = [a.kind for a in probe.alerts]
    assert kinds == ["commit-rate"], kinds  # fired exactly once
    assert probe.alerts[0].stage == "commit"


def test_watchdog_authority_lag_names_the_straggler():
    probe, core, observer, clock = _probe(
        slo=SLOThresholds(max_round_stall_s=0.0, max_authority_lag_rounds=5)
    )
    core.round = 10
    core.block_store.last_seen = {1: 10, 2: 2, 3: 9}
    probe.sample()
    assert len(probe.alerts) == 1
    alert = probe.alerts[0]
    assert alert.kind == "authority-lag" and alert.authority == 2
    assert alert.stage == "receive"
    assert "authority 2" in alert.detail


def test_probe_gauges_and_alert_counter():
    metrics = Metrics()
    probe, core, observer, clock = _probe(
        slo=SLOThresholds(max_round_stall_s=1.0), metrics=metrics
    )
    core.round = 7
    core.block_store.last_seen = {1: 7, 2: 5, 3: 7}
    probe.sample()
    clock["t"] = 2.0
    probe.sample()  # round stalled for 2 s -> alert + degraded gauge
    text = metrics.expose().decode()
    assert "mysticeti_health_round_advance_rate" in text
    assert 'mysticeti_health_authority_lag_rounds{authority="2"} 2.0' in text
    assert "mysticeti_health_status 0.0" in text
    assert (
        'mysticeti_health_slo_alerts_total{authority="",kind="round-stall"'
        ',stage="receive"} 1.0' in text
    )


def test_diagnosis_and_health_route():
    probe, core, observer, clock = _probe(
        slo=SLOThresholds(max_round_stall_s=1.0)
    )
    probe.sample()
    doc = probe.diagnosis()
    assert doc["status"] == "ok" and doc["authority"] == 0
    assert doc["signals"]["round"] == 0

    async def scrape(path):
        metrics = Metrics()
        server = await serve_metrics(metrics, "127.0.0.1", 0, health_probe=probe)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
        await writer.drain()
        payload = await reader.read()
        writer.close()
        server.close()
        await server.wait_closed()
        head, _, body = payload.partition(b"\r\n\r\n")
        return head.split(b"\r\n")[0].decode(), body

    status, body = asyncio.run(scrape("/health"))
    assert "200" in status
    assert json.loads(body)["status"] == "ok"
    # Degrade: the route turns 503 — a readiness gate, not just a document.
    clock["t"] = 5.0
    probe.sample()
    status, body = asyncio.run(scrape("/health"))
    assert "503" in status
    assert json.loads(body)["status"] == "degraded"


# -- commit critical-path attribution ----------------------------------------


def _ref(authority, round_, tag):
    return BlockReference(authority, round_, bytes([tag]).ljust(32, b"\x00"))


def test_critical_path_attribution_from_span_stream():
    metrics = Metrics()
    tracer = SpanTracer()
    analyzer = CriticalPathAnalyzer(metrics=metrics, authority=0)
    tracer.add_sink(analyzer.on_span)
    leader = _ref(3, 7, 1)
    # The pipeline chain as the instrumentation records it, receive
    # dominating (authority 3 was slow reaching us).
    tracer.record_span("receive", leader, 0.0, t1=2.0, authority=0)
    tracer.record_span("verify", leader, 2.0, t1=2.1, authority=0)
    tracer.record_span("dag_add", leader, 2.1, t1=2.2, authority=0)
    tracer.record_span("proposal_wait", leader, 2.2, t1=2.5, authority=0)
    tracer.record_span("finalize", leader, 2.5, t1=2.6, authority=0)
    # Another node's track must not pollute this analyzer.
    tracer.record_span("receive", leader, 0.0, t1=9.0, authority=1)
    assert analyzer.leaders_attributed == 0  # no commit span yet
    tracer.record_span("commit", leader, 2.5, t1=2.55, authority=0)
    assert analyzer.leaders_attributed == 1
    top = analyzer.top_blocking()
    assert top[0]["stage"] == "receive" and top[0]["authority"] == 3
    assert top[0]["leaders"] == 1 and top[0]["blocked_s"] == pytest.approx(2.0)
    text = metrics.expose().decode()
    assert 'commit_critical_path_seconds_count{stage="receive"} 1.0' in text
    assert 'commit_critical_path_seconds_count{stage="commit"} 1.0' in text
    # Non-pipeline stages never enter the attribution index.
    tracer.record_span("verify_dispatch", _ref(1, 1, 2), 0.0, t1=1.0, authority=0)
    assert _ref(1, 1, 2) not in analyzer._stages


# -- cluster aggregation ------------------------------------------------------


def _node_text(round_, commit_round, committed, lags, alerts=0):
    lines = [
        f"threshold_clock_round {round_}",
        f"commit_round {commit_round}",
        "mysticeti_health_commit_rate 2.5",
        "mysticeti_health_round_advance_rate 4.0",
        "mysticeti_health_status 1",
    ]
    for a, count in committed.items():
        lines.append(
            f'committed_leaders_total{{authority="{a}",status="committed"}} '
            f"{count}"
        )
    for a, lag in lags.items():
        lines.append(
            f'mysticeti_health_authority_lag_rounds{{authority="{a}"}} {lag}'
        )
    if alerts:
        lines.append(
            'mysticeti_health_slo_alerts_total{kind="round-stall",'
            f'authority="",stage="receive"}} {alerts}'
        )
    return "\n".join(lines) + "\n"


def test_cluster_snapshot_participation_skew_stragglers():
    texts = {
        "0": _node_text(20, 18, {0: 5, 1: 4, 2: 6}, {"1": 0, "2": 1, "3": 9}),
        "1": _node_text(20, 12, {0: 5, 1: 4, 2: 6}, {"0": 0, "2": 2, "3": 11}),
        "2": None,  # unreachable this tick
    }
    snap = cluster_snapshot_from_texts(
        texts, 4, slo=SLOThresholds(min_participation=0.9)
    )
    assert snap["unreachable"] == ["2"]
    assert snap["quorum_participation"] == 0.75  # 3 of 4 authorities committed
    assert snap["commit_skew_rounds"] == 6
    assert snap["straggler_score"]["3"] == 11  # worst view wins
    assert snap["status"] == "degraded"
    assert any(r.startswith("unreachable") for r in snap["degraded_reasons"])
    assert "participation" in snap["degraded_reasons"]


def test_cluster_snapshot_green_path():
    texts = {
        str(i): _node_text(20, 18, {a: 3 for a in range(4)}, {})
        for i in range(4)
    }
    snap = cluster_snapshot_from_texts(texts, 4)
    assert snap["status"] == "ok" and snap["degraded_reasons"] == []
    assert snap["quorum_participation"] == 1.0
    assert snap["commit_rate_by_node"]["0"] == 2.5


def test_node_health_counts_alerts():
    view = node_health_from_series(
        [
            ("mysticeti_health_slo_alerts_total",
             {"kind": "authority-lag", "authority": "2"}, 3.0),
            ("mysticeti_health_slo_alerts_total",
             {"kind": "round-stall", "authority": ""}, 1.0),
        ]
    )
    assert view["slo_alerts"] == {"authority-lag": 3.0, "round-stall": 1.0}
    snap = cluster_snapshot({"0": view}, 4)
    assert snap["slo_alert_totals"] == {"authority-lag": 3.0, "round-stall": 1.0}
    # Cumulative alert history alone must NOT mark the fleet degraded: the
    # node recovered (status gauge is back to ok), so the snapshot is green
    # while the totals preserve the history for the artifact reader.
    assert snap["status"] == "ok" and snap["degraded_reasons"] == []


# -- the deterministic chaos acceptance path ---------------------------------


def _chaos_scenario(tmp_dir):
    from mysticeti_tpu.chaos import (
        CrashFault,
        FaultPlan,
        PartitionFault,
        run_chaos_sim,
    )

    plan = FaultPlan(
        seed=7,
        partitions=[
            PartitionFault(
                start_s=4.0, end_s=14.0, group_a=(2,),
                group_b=(0, 1, 3, 4, 5, 6), symmetric=True,
            )
        ],
        crashes=[CrashFault(node=5, at_s=6.0, downtime_s=6.0)],
    )
    slo = SLOThresholds(
        max_round_stall_s=5.0, max_commit_stall_s=6.0,
        max_authority_lag_rounds=8,
    )
    return run_chaos_sim(plan, 7, 20.0, tmp_dir, slo=slo, with_metrics=True)


def test_chaos_sim_alerts_name_stalled_authority_and_stage(tmp_path):
    """The acceptance scenario: a seeded blackhole partition of authority 2
    plus a crash-restart of authority 5.  The SLO watchdog must NAME both —
    every healthy observer raises authority-lag alerts against 2 (stage
    receive) during the partition and against 5 during its downtime — and
    the health timeline must be byte-identical across two same-seed runs."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    report, harness = _chaos_scenario(str(tmp_path / "a"))

    assert report.health_timeline, "health plane produced no samples"
    lag_alerts = [
        a for a in report.slo_alerts if a["kind"] == "authority-lag"
    ]
    named = {a["authority"] for a in lag_alerts}
    assert 2 in named, report.slo_alerts  # the partitioned authority
    assert 5 in named, report.slo_alerts  # the crashed authority
    assert all(a["stage"] == "receive" for a in lag_alerts)
    # The partitioned node saw its OWN pipeline stall too (round + commit).
    own = {
        (a["kind"], a["observer"])
        for a in report.slo_alerts
        if a["authority"] is None
    }
    assert ("round-stall", 2) in own
    assert ("commit-stall", 2) in own
    # Observers are the healthy nodes; the victim never indicts itself as a
    # peer-lag straggler.
    assert all(
        a["observer"] != a["authority"] for a in lag_alerts
    )
    # Down node recorded as down in the timeline during its outage.
    mid = [
        e for e in report.health_timeline if 7.0 <= e["t"] <= 11.0
    ]
    assert mid and all(e["nodes"]["5"].get("down") for e in mid)
    # Alerts are counted on the per-node metrics too.
    text = harness.metrics[0].expose().decode()
    assert 'mysticeti_health_slo_alerts_total{authority="2"' in text

    # Determinism: same plan, same seed -> byte-identical timeline + alerts.
    report_b, _ = _chaos_scenario(str(tmp_path / "b"))
    assert report.health_timeline_bytes == report_b.health_timeline_bytes
    assert report.slo_alerts == report_b.slo_alerts


def test_fleet_monitor_report_and_down_nodes():
    probes = {}
    clock = {"t": 0.0}
    for a in range(3):
        p = HealthProbe(
            a, 3, slo=SLOThresholds(max_authority_lag_rounds=5),
            clock=lambda: clock["t"],
        )
        core = _FakeCore(a, 3)
        core.round = 10
        core.block_store.last_seen = {b: 10 for b in range(3)}
        p.attach(core=core, commit_observer=_FakeObserver())
        probes[a] = p
    probes[2].detach()  # node 2 is down
    monitor = FleetHealthMonitor(probes.get, 3, interval_s=1.0)
    entry = monitor.tick()
    assert entry["nodes"]["2"] == {"down": True}
    assert "wal_backlog" not in entry["nodes"]["0"]  # volatile key stripped
    report = monitor.fleet_report()
    assert report["down"] == ["2"]
    assert report["status"] == "degraded"


# -- trace_report: critical path + robustness satellites ----------------------


def _write_trace(path, tracer):
    tracer.write(path)
    return path


def test_trace_report_critical_path_mode(tmp_path, capsys):
    from tools.trace_report import main as report_main

    tracer = SpanTracer()
    leader = _ref(3, 7, 1)
    tracer.record_span("receive", leader, 0.0, t1=2.0, authority=0)
    tracer.record_span("verify", leader, 2.0, t1=2.1, authority=0)
    tracer.record_span("dag_add", leader, 2.1, t1=2.2, authority=0)
    tracer.record_span("proposal_wait", leader, 2.2, t1=2.5, authority=0)
    tracer.record_span("commit", leader, 2.5, t1=2.55, authority=0)
    tracer.record_span("finalize", leader, 2.5, t1=2.6, authority=0)
    path = _write_trace(str(tmp_path / "t.json"), tracer)
    assert report_main([path, "--critical-path"]) == 0
    out = capsys.readouterr().out
    assert "1 committed leader observation" in out
    assert "receive" in out and "3" in out  # blocking stage + authority


def test_trace_report_critical_path_no_commits_notes_and_exits_zero(
    tmp_path, capsys
):
    from tools.trace_report import main as report_main

    tracer = SpanTracer()
    tracer.record_span("receive", _ref(1, 1, 1), 0.0, t1=1.0, authority=0)
    path = _write_trace(str(tmp_path / "t.json"), tracer)
    assert report_main([path, "--critical-path"]) == 0
    assert "no committed leaders" in capsys.readouterr().out


def test_trace_report_tolerates_truncated_tail(tmp_path, capsys):
    from tools.trace_report import main as report_main

    tracer = SpanTracer()
    for i in range(5):
        tracer.record_span("commit", _ref(0, i + 1, i + 1), float(i),
                           t1=float(i) + 0.5, authority=0)
    path = str(tmp_path / "t.json")
    tracer.write(path)
    whole = open(path).read()
    # Tear the file mid-event (a SIGKILL landing mid-flush of the .tmp, or
    # a reader racing the writer).
    with open(path, "w") as f:
        f.write(whole[: int(len(whole) * 0.7)])
    assert report_main([path]) == 0
    captured = capsys.readouterr()
    assert "salvaged" in captured.err
    assert "commit" in captured.out


def test_trace_report_empty_trace_exits_zero(tmp_path, capsys):
    from tools.trace_report import main as report_main

    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": []}, f)
    assert report_main([path]) == 0
    assert "no spans" in capsys.readouterr().out


def test_trace_report_missing_file_is_an_error(tmp_path, capsys):
    from tools.trace_report import main as report_main

    assert report_main([str(tmp_path / "absent.json")]) == 2


# -- orderly-shutdown telemetry flush ----------------------------------------


def test_flush_active_writes_span_tail(tmp_path):
    from mysticeti_tpu import spans

    path = str(tmp_path / "tail.json")
    tracer = SpanTracer(flush_path=path, flush_every_s=3600.0)
    spans._active = tracer
    try:
        tracer.record_span("commit", _ref(0, 1, 1), 0.0, t1=1.0, authority=0)
        assert not os.path.exists(path)  # periodic flusher never ran
        spans.flush_active()
        data = json.loads(open(path).read())
        assert any(e.get("ph") == "X" for e in data["traceEvents"])
    finally:
        spans._active = None


def test_metric_reporter_final_sweep_publishes_tail_window():
    from mysticeti_tpu.metrics import MetricReporter

    metrics = Metrics()
    metrics.transaction_committed_latency.observe(0.25)
    reporter = MetricReporter(metrics, interval_s=3600.0)
    reporter.stop(final=True)  # never started: stop must still publish
    text = metrics.expose().decode()
    assert (
        'histogram_pct{name="transaction_committed_latency",pct="50"} 0.25'
        in text
    )
