"""Parity tests for the Pallas verify kernel (interpret mode on CPU).

The Pallas kernel must agree bit-for-bit with the XLA kernel
(``ops/ed25519.verify_impl``) and with the OpenSSL oracle over valid,
corrupted, and structurally-invalid signatures (the same contract the
reference's serial verify upholds, ``mysticeti-core/src/crypto.rs:174-189``).
"""
import random

import numpy as np
import pytest

pytestmark = pytest.mark.kernel

from mysticeti_tpu.crypto import Ed25519PrivateKey

from mysticeti_tpu.ops import ed25519 as E
from mysticeti_tpu.ops import ed25519_pallas as EP


def _batch(n, seed=1, corrupt=True):
    rng = random.Random(seed)
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(8)
    ]
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(n):
        key = keys[i % len(keys)]
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = bytearray(key.sign(msg))
        ok = True
        if corrupt and i % 4 == 1:
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            ok = False
        elif corrupt and i % 4 == 2:
            msg = bytes(rng.randrange(256) for _ in range(32))
            ok = False
        pks.append(key.public_key().public_bytes_raw())
        msgs.append(msg)
        sigs.append(bytes(sig))
        expect.append(ok)
    return pks, msgs, sigs, np.array(expect)


def test_pallas_matches_oracle_and_xla():
    pks, msgs, sigs, expect = _batch(16)
    packed = E.pack_batch(pks, msgs, sigs)
    ref = np.asarray(E.verify_kernel(*[np.asarray(x) for x in packed]))
    got = np.asarray(EP.verify_pallas(*packed, tile=8, interpret=True))
    # The corrupted signature may still occasionally pass host checks; the
    # oracle is the cryptography library's accept/reject per item.
    from mysticeti_tpu import crypto

    oracle = np.array(
        [crypto.PublicKey(p).verify(s, m) for p, m, s in zip(pks, msgs, sigs)]
    )
    np.testing.assert_array_equal(got, oracle)
    np.testing.assert_array_equal(got, ref)


def test_pallas_multi_tile_grid():
    """Two grid tiles: catches block-index mapping errors."""
    pks, msgs, sigs, expect = _batch(16, seed=3, corrupt=False)
    packed = E.pack_batch(pks, msgs, sigs)
    got = np.asarray(EP.verify_pallas(*packed, tile=8, interpret=True))
    assert got.all()


def test_non_canonical_r_rejected_on_host():
    """A signature whose R y-coordinate is encoded as y >= p must be rejected
    in pack_batch (OpenSSL memcmp semantics: a non-canonical encoding can
    never equal the canonical re-encoding).  ADVICE r1 finding."""
    from mysticeti_tpu.ops.ed25519 import P, pack_batch

    pks, msgs, sigs, _ = _batch(8, corrupt=False)
    # Overwrite R with the non-canonical encoding of y = p + 1 (sign bit 0).
    bad_r = int(P + 1).to_bytes(32, "little")
    sigs = list(sigs)
    sigs[3] = bad_r + sigs[3][32:]
    packed = pack_batch(pks, msgs, sigs)
    host_ok = packed[-1]
    assert not host_ok[3]
    assert host_ok[[i for i in range(8) if i != 3]].all()
    got = np.asarray(EP.verify_pallas(*packed, tile=8, interpret=True))
    assert not got[3]


def test_pallas_rejects_bad_tile():
    pks, msgs, sigs, _ = _batch(10, corrupt=False)
    packed = E.pack_batch(pks, msgs, sigs)
    with pytest.raises(ValueError):
        EP.verify_pallas(*packed, tile=8, interpret=True)


def test_pallas_indexed_blob_matches_oracle():
    """The committee-indexed Pallas entry (verify_fused_indexed_blob_pallas)
    in interpret mode: the TPU-only wire format must agree with the
    expected accept/reject pattern and the XLA indexed kernel."""
    pks, msgs, sigs, expect = _batch(16, seed=5)
    table = E.KeyTable(sorted(set(pks)))
    idx = table.indices_for(pks)
    blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
    got = np.asarray(
        EP.verify_fused_indexed_blob_pallas(
            blob, table.words, tile=8, interpret=True
        )
    )
    assert (got == expect).all()
    xla = np.asarray(E.verify_fused_indexed_kernel(blob, table.words))
    assert (got == xla).all()
