"""Deterministic chaos engine: crash-restart with WAL replay, seeded network
faults, and verifier-path graceful degradation (chaos.py).

The acceptance scenario is the 10-node sim with f=3 crash-restarts plus a
timed asymmetric partition: all honest nodes must commit identical leader
prefixes, every restarted node must catch up via WAL replay + sync, and a
same-seed re-run must produce a byte-identical fault schedule AND fault log.
All sims here run on the virtual-time DeterministicLoop — no real I/O, no
real time — and stay tier-1.
"""
import asyncio
import os
import random

import pytest

from mysticeti_tpu.block_validator import (
    BatchedSignatureVerifier,
    HybridSignatureVerifier,
    SignatureVerifier,
)
from mysticeti_tpu.chaos import (
    CrashFault,
    FaultPlan,
    LinkFault,
    PartitionFault,
    SafetyChecker,
    SafetyViolation,
    resolve_schedule,
    run_chaos_sim,
    schedule_bytes,
)
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.network import jittered_backoff
from mysticeti_tpu.types import BlockReference
from mysticeti_tpu.wal import HEADER_SIZE, WalReader


# ---------------------------------------------------------------------------
# Fault plan plumbing


def _full_plan(seed=11):
    return FaultPlan(
        seed=seed,
        link_faults=[
            LinkFault(drop_p=0.02, duplicate_p=0.01, delay_p=0.05,
                      delay_extra_s=(0.05, 0.2)),
        ],
        partitions=[
            PartitionFault(start_s=9.0, end_s=11.5, group_a=(0, 1),
                           group_b=tuple(range(2, 10)), symmetric=False),
        ],
        crashes=[
            CrashFault(node=7, at_s=3.0, downtime_s=3.0),
            CrashFault(node=8, at_s=4.0, downtime_s=3.0),
            CrashFault(node=9, at_s=5.0, downtime_s=3.0, torn_tail_bytes=12),
        ],
    )


def test_fault_plan_json_roundtrip():
    plan = _full_plan()
    again = FaultPlan.from_json(plan.to_json())
    assert again == plan
    assert again.to_json() == plan.to_json()
    # The resolved schedule is a pure function of the plan: byte-identical
    # without running anything, and ordered by (time, kind).
    assert schedule_bytes(again) == schedule_bytes(plan)
    times = [e["t"] for e in resolve_schedule(plan)]
    assert times == sorted(times)


def test_safety_checker_detects_forks_and_gaps():
    class _Commit:
        def __init__(self, height, anchor):
            self.height = height
            self.anchor = anchor

    a1 = BlockReference(0, 3, b"a" * 32)
    a2 = BlockReference(1, 3, b"b" * 32)
    checker = SafetyChecker()
    checker.observe(0, [_Commit(1, a1)])
    checker.observe(1, [_Commit(1, a1)])
    checker.check()
    with pytest.raises(SafetyViolation, match="fork at height 1"):
        checker.observe(2, [_Commit(1, a2)])
        checker.check()
    # A node re-observing the same height after WAL-replay must agree.
    with pytest.raises(SafetyViolation, match="two anchors"):
        checker.observe(0, [_Commit(1, a2)])
    # Gaps in a node's height sequence are a linearizer-order violation.
    checker2 = SafetyChecker()
    checker2.observe(0, [_Commit(1, a1), _Commit(3, a2)])
    with pytest.raises(SafetyViolation, match="gap"):
        checker2.sequence(0)


# ---------------------------------------------------------------------------
# The acceptance scenario


@pytest.mark.chaos
def test_ten_nodes_f3_crash_restart_with_partition(tmp_path):
    """10 nodes, f=3 staggered crash-restarts (overlapping downtime: three
    nodes down at once, exactly quorum left), one torn WAL tail, plus a
    timed asymmetric partition — identical committed prefixes everywhere,
    every restarted node catches up via WAL replay + sync, and a same-seed
    re-run yields a byte-identical fault schedule (and fault log, and
    commits)."""
    plan = _full_plan()
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    report, harness = run_chaos_sim(
        plan, 10, 15.0, str(tmp_path / "a"), with_metrics=True
    )
    replay, _ = run_chaos_sim(
        plan, 10, 15.0, str(tmp_path / "b"), with_metrics=True
    )

    # Byte-identical reproducibility: the resolved schedule trivially, the
    # per-message fault log (every drop/dup/delay draw) and the committed
    # sequences because the whole sim is seeded and single-threaded.
    assert report.schedule_bytes == replay.schedule_bytes
    assert report.fault_log_bytes == replay.fault_log_bytes
    assert report.sequences == replay.sequences

    # Safety: run_chaos_sim already ran checker.check(); assert the prefix
    # property explicitly as well.
    sequences = [report.sequences[a] for a in range(10)]
    longest = max(sequences, key=len)
    for seq in sequences:
        assert seq == longest[: len(seq)]

    # Liveness through the whole scenario (measured ~34 commits in 15 s
    # with this plan; 15 is the 2x-regression tripwire).
    lengths = [len(s) for s in sequences]
    assert all(length >= 15 for length in lengths), lengths

    # Every crashed node committed before its crash, recovered via WAL
    # replay (crash_recovery_total pins the Core recovery path), and caught
    # up well past its at-crash height after restart.
    assert len(report.crash_events) == 3
    for event in report.crash_events:
        node = event["node"]
        assert event["committed_height"] > 0, event
        metrics = harness.metrics[node]
        assert metrics.crash_recovery_total._value.get() == 1.0
        assert (
            harness.checker.committed_height(node)
            >= event["committed_height"] + 5
        ), event

    # Every fault flavor actually fired.
    for kind in ("dropped", "duplicated", "delayed", "blackhole", "crash",
                 "restart", "partition_start", "partition_end"):
        assert report.fault_counts.get(kind, 0) > 0, report.fault_counts


@pytest.mark.chaos
def test_torn_tail_recovery_mid_sim(tmp_path):
    """Crash a node mid-sim and tear its WAL tail: replay must stop cleanly
    at the tear (recovery truncates the torn bytes, leaving a fully
    replayable log) and the restarted node must rejoin and commit."""
    plan = FaultPlan(
        seed=5,
        crashes=[CrashFault(node=2, at_s=4.0, downtime_s=2.0,
                            torn_tail_bytes=10)],
    )
    report, harness = run_chaos_sim(
        plan, 4, 10.0, str(tmp_path), with_metrics=True
    )
    event = report.crash_events[0]
    assert harness.metrics[2].crash_recovery_total._value.get() == 1.0
    # Rejoined and committed past its pre-crash height.
    assert harness.checker.committed_height(2) > event["committed_height"]
    # Recovery truncated the tear before the first post-restart append: the
    # final ACTIVE segment (where the tear landed and appends resumed)
    # replays entry-by-entry to EXACTLY the end of file — no torn bytes left
    # behind, no unreplayable gap.
    from mysticeti_tpu.storage import active_wal_file

    path = active_wal_file(os.path.join(str(tmp_path), "wal-2"))
    reader = WalReader(path)
    end = 0
    for pos, _tag, payload in reader.iter_until():
        end = pos + HEADER_SIZE + len(payload)
    reader.close()
    assert end == os.path.getsize(path)


# ---------------------------------------------------------------------------
# Verifier-path graceful degradation


class ScriptedTpuBackend(SignatureVerifier):
    """Accepts everything until ``dead`` is flipped, then raises the outage
    the remote verifier client propagates after its retry budget."""

    def __init__(self) -> None:
        self.dead = False
        self.calls = 0

    def verify_signatures(self, public_keys, digests, signatures):
        self.calls += 1
        if self.dead:
            raise ConnectionError("verifier service is down")
        return [True] * len(signatures)


class StubCpuBackend(SignatureVerifier):
    def __init__(self) -> None:
        self.calls = 0

    def verify_signatures(self, public_keys, digests, signatures):
        self.calls += 1
        return [True] * len(signatures)


@pytest.mark.chaos
def test_verifier_outage_degrades_to_cpu_with_zero_failed_blocks(tmp_path):
    """Killing the (injected) accelerator backend mid-run flips every node's
    circuit breaker to CPU fallback — asserted via verifier_fallback_total —
    with zero failed blocks and uninterrupted commit progress."""
    backends = {}
    kill_heights = {}

    def factory(authority, committee, metrics):
        tpu, cpu = ScriptedTpuBackend(), StubCpuBackend()
        backends[authority] = (tpu, cpu)
        hybrid = HybridSignatureVerifier(
            tpu=tpu, cpu=cpu, threshold=1, metrics=metrics
        )
        return BatchedSignatureVerifier(committee, hybrid, metrics=metrics)

    async def kill(harness):
        await asyncio.sleep(5.0)
        for authority, (tpu, _cpu) in backends.items():
            tpu.dead = True
            kill_heights[authority] = harness.committed_height(authority)

    report, harness = run_chaos_sim(
        FaultPlan(seed=3), 4, 12.0, str(tmp_path),
        verifier_factory=factory, with_metrics=True, extra_fault=kill,
    )
    for authority in range(4):
        metrics = harness.metrics[authority]
        tpu, cpu = backends[authority]
        # The breaker tripped on the outage...
        assert metrics.verifier_fallback_total._value.get() >= 1.0
        # ...batches kept verifying on the oracle...
        assert cpu.calls > 0
        # ...no block ever failed verification (an outage is not a verdict)...
        assert 'outcome="rejected"' not in metrics.expose().decode()
        # ...and the node kept committing after the kill.
        assert (
            harness.checker.committed_height(authority)
            > kill_heights[authority]
        )
    # Accelerator-routed batches happened before the kill on every node.
    assert all(tpu.calls > 0 for tpu, _ in backends.values())


def test_breaker_opens_falls_back_and_reprobes():
    """Unit: outage trips the breaker (fallback answers the batch), the
    accelerator route stays closed until the probe deadline, and a
    successful probe closes the circuit."""
    clock = {"t": 0.0}
    tpu, cpu = ScriptedTpuBackend(), StubCpuBackend()
    metrics = Metrics()
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=cpu, threshold=1,
                                     metrics=metrics)
    hybrid._breaker_clock = lambda: clock["t"]
    batch = ([b"k" * 32], [b"d" * 32], [b"s" * 64])

    tpu.dead = True
    assert hybrid.verify_signatures(*batch) == [True]  # fallback answered
    assert hybrid.breaker_open
    assert metrics.verifier_fallback_total._value.get() == 1.0
    assert hybrid.backend_label == "hybrid-cpu"

    # While open (before the probe deadline) the accelerator is not touched.
    calls = tpu.calls
    assert hybrid.verify_signatures(*batch) == [True]
    assert tpu.calls == calls

    # Past the deadline one probe goes through; success closes the circuit.
    tpu.dead = False
    clock["t"] = 100.0
    assert hybrid.verify_signatures(*batch) == [True]
    assert tpu.calls == calls + 1
    assert not hybrid.breaker_open
    assert hybrid.backend_label == "hybrid-tpu"


def test_breaker_backoff_doubles_with_bounded_jitter():
    clock = {"t": 0.0}
    tpu, cpu = ScriptedTpuBackend(), StubCpuBackend()
    tpu.dead = True
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=cpu, threshold=1)
    hybrid._breaker_clock = lambda: clock["t"]
    batch = ([b"k" * 32], [b"d" * 32], [b"s" * 64])

    expected = 1.0
    for _ in range(8):
        clock["t"] += 1000.0  # always past the probe deadline: probe + fail
        hybrid.verify_signatures(*batch)
        assert hybrid._breaker_backoff_s == min(
            expected, hybrid.BREAKER_MAX_BACKOFF_S
        )
        # The probe deadline is jittered to [0.5, 1.5)x the backoff so a
        # fleet that lost one shared service never re-probes in lockstep.
        wait = hybrid._breaker_open_until - clock["t"]
        assert 0.5 * hybrid._breaker_backoff_s <= wait
        assert wait < 1.5 * hybrid._breaker_backoff_s
        expected = min(expected * 2.0, hybrid.BREAKER_MAX_BACKOFF_S)


def test_breaker_protocol_error_fails_fast():
    """A VerifierProtocolError (committee mismatch) is a configuration bug,
    not an outage: it propagates and must NOT trip the breaker."""
    from mysticeti_tpu.block_validator import VerifierProtocolError

    class RejectingTpu(SignatureVerifier):
        def verify_signatures(self, public_keys, digests, signatures):
            raise VerifierProtocolError("committee mismatch")

    metrics = Metrics()
    hybrid = HybridSignatureVerifier(
        tpu=RejectingTpu(), cpu=StubCpuBackend(), threshold=1, metrics=metrics
    )
    with pytest.raises(VerifierProtocolError):
        hybrid.verify_signatures([b"k" * 32], [b"d" * 32], [b"s" * 64])
    assert not hybrid.breaker_open
    assert metrics.verifier_fallback_total._value.get() == 0.0


def test_breaker_admits_exactly_one_probe():
    """While a probe is in flight, further dispatches keep falling back even
    after the backoff window re-elapses (a hung service must not collect a
    pile of stuck dispatch threads)."""
    clock = {"t": 0.0}
    tpu = ScriptedTpuBackend()
    tpu.dead = True
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=StubCpuBackend(),
                                     threshold=1)
    hybrid._breaker_clock = lambda: clock["t"]

    def blocks() -> bool:
        # NB: admission is a side effect — the first non-blocked call after
        # the deadline CLAIMS the exclusive probe slot.
        return hybrid._admit_accelerator()[0]

    hybrid.verify_signatures([b"k" * 32], [b"d" * 32], [b"s" * 64])  # trip
    clock["t"] = 1000.0
    assert not blocks()  # the probe slot
    assert blocks()      # exclusive: everyone else blocked
    clock["t"] = 2000.0
    assert blocks()      # still held by the in-flight probe
    hybrid._clear_probe()  # probe path releases on non-outage
    assert not blocks()  # next probe admitted


def test_stale_success_after_trip_does_not_close_breaker():
    """Pipeline depth >= 2: a batch submitted BEFORE the outage can surface
    its success at fetch AFTER a newer failure tripped the circuit (its
    reply was already in the socket buffer).  That stale evidence must not
    re-close the breaker or reset the backoff escalation — the route would
    otherwise flap between dead-backend timeouts all outage long."""
    tpu = ScriptedTpuBackend()
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=StubCpuBackend(),
                                     threshold=1)
    batch = ([b"k" * 32], [b"d" * 32], [b"s" * 64])
    d0 = hybrid.verify_signatures_async(*batch)  # submitted while healthy
    d1 = hybrid.verify_signatures_async(*batch)  # submitted while healthy
    tpu.dead = True
    assert d1.result() == [True]  # outage at fetch: trips, oracle serves it
    assert hybrid.breaker_open
    backoff = hybrid._breaker_backoff_s
    tpu.dead = False  # d0's reply was buffered pre-outage
    assert d0.result() == [True]  # succeeds on the accelerator route
    assert hybrid.breaker_open, "stale success must not close the circuit"
    assert hybrid._breaker_backoff_s == backoff


def test_non_probe_fetch_failure_keeps_probe_exclusivity():
    """While a probe is in flight, a pre-outage straggler failing at fetch
    trips the breaker again but must NOT release the hung probe's exclusive
    slot — 'a hung probe admits no further victims' has to survive
    concurrent non-probe failures."""
    clock = {"t": 0.0}
    tpu = ScriptedTpuBackend()
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=StubCpuBackend(),
                                     threshold=1)
    hybrid._breaker_clock = lambda: clock["t"]
    batch = ([b"k" * 32], [b"d" * 32], [b"s" * 64])
    straggler = hybrid.verify_signatures_async(*batch)  # healthy submit
    tpu.dead = True
    hybrid.verify_signatures(*batch)  # trip
    assert hybrid.breaker_open
    clock["t"] = 1000.0
    assert not hybrid._admit_accelerator()[0]  # probe admitted (now hung)
    assert straggler.result() == [True]  # fails at fetch -> oracle serves it
    clock["t"] = 2000.0
    assert hybrid._admit_accelerator()[0], \
        "a non-probe failure must not readmit dispatches past a live probe"


def test_breaker_counts_degraded_batches_not_trips():
    """verifier_fallback_total counts every batch served by the oracle while
    the accelerator path is down, matching its help text."""
    tpu = ScriptedTpuBackend()
    tpu.dead = True
    metrics = Metrics()
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=StubCpuBackend(),
                                     threshold=1, metrics=metrics)
    hybrid._breaker_clock = lambda: 0.0  # frozen clock: never re-probes
    batch = ([b"k" * 32], [b"d" * 32], [b"s" * 64])
    for _ in range(5):
        hybrid.verify_signatures(*batch)
    assert metrics.verifier_fallback_total._value.get() == 5.0
    assert tpu.calls == 1  # only the tripping dispatch touched the backend


def test_breaker_survives_warmup_outage():
    """An unreachable backend at boot must not kill the warmup thread: the
    hybrid calibrates the oracle, trips the breaker, and serves on CPU."""
    tpu, cpu = ScriptedTpuBackend(), StubCpuBackend()
    tpu.dead = True
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=cpu, metrics=Metrics())

    def failing_warmup():
        raise ConnectionError("service not up yet")

    tpu.warmup = failing_warmup
    hybrid.warmup()  # must not raise
    assert hybrid.breaker_open
    assert hybrid.cpu_per_sig_s > 0.0  # oracle still calibrated


def test_own_block_reproposal_wins_dissemination_index(tmp_path):
    """After a torn-tail restart, the round we actually RE-PROPOSE must win
    the own-block dissemination index over a peer-delivered copy of the
    lost pre-crash block — our later blocks build on the re-proposal."""
    from mysticeti_tpu.block_store import BlockStore
    from mysticeti_tpu.wal import walf

    writer, reader = walf(str(tmp_path / "wal"))
    store = BlockStore(0, 4, reader)
    lost = BlockReference(0, 5, b"a" * 32)       # pre-crash block, via peer
    reproposed = BlockReference(0, 5, b"b" * 32)  # post-restart proposal
    store._add_own_index(lost)
    store._add_own_index(reproposed, proposed=True)
    assert store._own_blocks[5] == reproposed.digest
    store._add_own_index(lost)  # a late duplicate never demotes the proposal
    assert store._own_blocks[5] == reproposed.digest
    writer.close()
    reader.close()


# ---------------------------------------------------------------------------
# Remote verifier client: bounded retries + backoff


def test_remote_client_bounded_retries_when_service_absent(tmp_path):
    from mysticeti_tpu.verifier_service import RemoteSignatureVerifier

    metrics = Metrics()
    client = RemoteSignatureVerifier(
        socket_path=str(tmp_path / "absent.sock"),
        committee_keys=[b"\x01" * 32],
        metrics=metrics,
        max_attempts=3,
    )
    client.RETRY_BASE_BACKOFF_S = 0.001  # keep the test fast
    with pytest.raises(OSError):
        client.verify_signatures([b"\x01" * 32], [bytes(32)], [bytes(64)])
    # Every failed attempt tore down (or failed to build) a connection.
    assert metrics.verifier_reconnect_total._value.get() == 3.0


def test_breaker_catches_exhausted_remote_retries(tmp_path):
    """Integration: Hybrid(tpu=RemoteSignatureVerifier) against a dead
    service — the client's retry budget exhausts, the breaker catches the
    propagated OSError, and the batch is answered by the oracle."""
    from mysticeti_tpu.verifier_service import RemoteSignatureVerifier

    metrics = Metrics()
    remote = RemoteSignatureVerifier(
        socket_path=str(tmp_path / "dead.sock"),
        committee_keys=[b"\x01" * 32],
        metrics=metrics,
        max_attempts=2,
    )
    remote.RETRY_BASE_BACKOFF_S = 0.001
    hybrid = HybridSignatureVerifier(
        tpu=remote, cpu=StubCpuBackend(), threshold=1, metrics=metrics
    )
    out = hybrid.verify_signatures([b"\x01" * 32], [bytes(32)], [bytes(64)])
    assert out == [True]
    assert hybrid.breaker_open
    assert metrics.verifier_fallback_total._value.get() == 1.0
    assert metrics.verifier_reconnect_total._value.get() == 2.0


def test_remote_client_retry_rides_out_service_restart(tmp_path):
    """A service restart mid-burst is a retry, not an outage: the client's
    bounded backoff bridges the listener gap without surfacing an error."""
    from concurrent.futures import ThreadPoolExecutor

    from mysticeti_tpu import crypto
    from mysticeti_tpu.verifier_service import (
        RemoteSignatureVerifier,
        VerifierServer,
    )

    signer = crypto.Signer.from_seed(b"\x07" * 32)
    keys = [signer.public_key.bytes]
    digest = crypto.blake2b_256(b"retry")
    metrics = Metrics()

    async def main():
        loop = asyncio.get_running_loop()
        pool = ThreadPoolExecutor(max_workers=1)
        client = RemoteSignatureVerifier(
            socket_path=str(tmp_path / "verifier.sock"),
            committee_keys=keys,
            metrics=metrics,
        )

        def call():
            return client.verify_signatures(
                keys, [digest], [signer.sign(digest)]
            )

        server1 = VerifierServer(client.socket_path, committee_keys=keys,
                                 backend=StubCpuBackend())
        await server1.start()
        assert await loop.run_in_executor(pool, call) == [True]
        await server1.stop()
        # Socket gone: the call below must retry through the gap while the
        # replacement server comes up.
        future = loop.run_in_executor(pool, call)
        await asyncio.sleep(0.05)
        server2 = VerifierServer(client.socket_path, committee_keys=keys,
                                 backend=StubCpuBackend())
        await server2.start()
        try:
            assert await future == [True]
        finally:
            await server2.stop()
            pool.shutdown(wait=False)

    asyncio.run(main())
    assert metrics.verifier_reconnect_total._value.get() >= 1.0


# ---------------------------------------------------------------------------
# Dial backoff jitter (network.py satellite)


def test_jittered_backoff_is_seeded_and_bounded():
    a = [jittered_backoff(1.0, random.Random(5)) for _ in range(1)]
    b = [jittered_backoff(1.0, random.Random(5)) for _ in range(1)]
    assert a == b  # seeded: reproducible
    rng = random.Random(9)
    draws = [jittered_backoff(2.0, rng) for _ in range(64)]
    assert all(1.0 <= d < 3.0 for d in draws)  # [0.5, 1.5) x delay
    assert len(set(draws)) > 32  # actually jittered, not constant


# ---------------------------------------------------------------------------
# CLI


def test_chaos_cli_replays_plan_from_json(tmp_path, capsys):
    from mysticeti_tpu.cli import main

    plan = FaultPlan(
        seed=2,
        link_faults=[LinkFault(drop_p=0.05)],
        crashes=[CrashFault(node=1, at_s=2.0, downtime_s=1.5)],
    )
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(plan.to_json())
    rc = main([
        "chaos", "--plan", str(plan_path), "--nodes", "4",
        "--duration", "6", "--working-directory", str(tmp_path / "wals"),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fault schedule digest:" in out
    assert "safety: OK" in out
    assert "crash=1" in out

    rc = main(["chaos", "--plan", str(plan_path), "--dump-schedule"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "'kind': 'crash'" in out and "'kind': 'restart'" in out
