"""Commit-rule gold suite, single leader, no pipeline — the 9 canonical cases of
``consensus/tests/base_committer_tests.rs``."""
import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder

from helpers import DagBlockWriter, build_dag, build_dag_layer

WAVE = DEFAULT_WAVE_LENGTH


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def make_committer(committee, writer, **kwargs):
    b = UniversalCommitterBuilder(committee, writer.block_store)
    b.with_wave_length(kwargs.get("wave_length", WAVE))
    b.with_number_of_leaders(kwargs.get("number_of_leaders", 1))
    b.with_pipeline(kwargs.get("pipeline", False))
    return b.build()


def test_direct_commit(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 5)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == committee.elect_leader(WAVE, 0)


def test_idempotence(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    all_refs = build_dag(committee, writer, None, 5)
    committer = make_committer(committee, writer)
    committed = committer.try_commit(AuthorityRound(0, 0))
    assert len(committed) == 1
    build_dag(committee, writer, all_refs, 8)
    last = committed[-1]
    sequence = committer.try_commit(AuthorityRound(last.authority, last.round))
    assert len(sequence) == 1
    assert sequence[0].round == 6


def test_multiple_direct_commit(committee, tmp_path):
    last_committed = AuthorityRound(0, 0)
    for n in range(1, 11):
        enough_blocks = WAVE * (n + 1) - 1
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{n}")
        build_dag(committee, writer, None, enough_blocks)
        committer = make_committer(committee, writer)
        sequence = committer.try_commit(last_committed)
        assert len(sequence) == 1
        leader_round = n * WAVE
        assert sequence[0].kind == LeaderStatus.COMMIT
        assert sequence[0].block.author() == committee.elect_leader(leader_round, 0)
        last = sequence[-1]
        last_committed = AuthorityRound(last.authority, last.round)


def test_direct_commit_late_call(committee, tmp_path):
    n = 10
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, WAVE * (n + 1) - 1)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == n
    for i, status in enumerate(sequence):
        leader_round = (i + 1) * WAVE
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(leader_round, 0)


def test_no_genesis_commit(committee, tmp_path):
    first_commit_round = 2 * WAVE - 1
    for r in range(first_commit_round):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{r}")
        build_dag(committee, writer, None, r)
        committer = make_committer(committee, writer)
        assert committer.try_commit(AuthorityRound(0, 0)) == []


def test_no_leader(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    # Wave 0 completes, then build to the decision round of leader 1 without the leader.
    references = build_dag(committee, writer, None, WAVE - 1)
    leader_round_1 = WAVE
    leader_1 = committee.elect_leader(leader_round_1, 0)
    connections = [
        (a, references) for a in committee.authority_indexes() if a != leader_1
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, 2 * WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
    assert sequence[0].round == leader_round_1


def test_direct_skip(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != committee.elect_leader(leader_round_1, 0)
    ]
    build_dag(committee, writer, references_without_leader_1, 2 * WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == committee.elect_leader(leader_round_1, 0)
    assert sequence[0].round == leader_round_1


def test_indirect_commit(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]
    quorum = committee.quorum_threshold()
    validity = committee.validity_threshold()
    authorities = list(committee.authority_indexes())

    # Only 2f+1 validators vote for leader 1.
    refs_with_votes = build_dag_layer(
        [(a, references_1) for a in authorities[:quorum]], writer
    )
    refs_without_votes = build_dag_layer(
        [(a, references_without_leader_1) for a in authorities[quorum:]], writer
    )

    # Only f+1 validators certify leader 1.
    references_3 = []
    references_3.extend(
        build_dag_layer(
            [(a, refs_with_votes) for a in authorities[:validity]], writer
        )
    )
    mixed = (refs_without_votes + refs_with_votes)[:quorum]
    references_3.extend(
        build_dag_layer(
            [(a, mixed) for a in authorities[validity:]], writer
        )
    )

    # Build to the decision round of the 2nd leader; it indirect-commits leader 1.
    build_dag(committee, writer, references_3, 3 * WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 2
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == leader_1


def test_indirect_skip(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_2 = 2 * WAVE
    references_2 = build_dag(committee, writer, None, leader_round_2)
    leader_2 = committee.elect_leader(leader_round_2, 0)
    references_without_leader_2 = [
        r for r in references_2 if r.authority != leader_2
    ]
    validity = committee.validity_threshold()
    authorities = list(committee.authority_indexes())

    # Only f+1 validators connect to leader 2.
    references = []
    references.extend(
        build_dag_layer(
            [(a, references_2) for a in authorities[:validity]], writer
        )
    )
    references.extend(
        build_dag_layer(
            [(a, references_without_leader_2) for a in authorities[validity:]], writer
        )
    )
    build_dag(committee, writer, references, 4 * WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 3

    leader_1 = committee.elect_leader(WAVE, 0)
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == leader_1
    assert sequence[1].kind == LeaderStatus.SKIP
    assert sequence[1].authority == leader_2
    assert sequence[1].round == leader_round_2
    leader_3 = committee.elect_leader(3 * WAVE, 0)
    assert sequence[2].kind == LeaderStatus.COMMIT
    assert sequence[2].block.author() == leader_3


def test_undecided(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != committee.elect_leader(leader_round_1, 0)
    ]
    authorities = list(committee.authority_indexes())
    quorum = committee.quorum_threshold()

    # Exactly one vote for leader 1; 2f more blocks that miss it.
    connections = [(authorities[0], references_1)] + [
        (a, references_without_leader_1) for a in authorities[1:quorum]
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, 2 * WAVE - 1)

    committer = make_committer(committee, writer)
    assert committer.try_commit(AuthorityRound(0, 0)) == []
