"""Whole-stack deterministic simulation tests — parity with the reference's
``--features simulator`` tier (net_sync.rs:583-781): full NetworkSyncers over the
in-memory latency network on the virtual-time loop.  No real I/O, no real time;
reproducible by seed."""
import asyncio
import os

import pytest

from mysticeti_tpu.block_handler import TestBlockHandler
from mysticeti_tpu.block_store import BlockStore
from mysticeti_tpu.commit_observer import TestCommitObserver
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.core import Core, CoreOptions
from mysticeti_tpu.net_sync import NetworkSyncer
from mysticeti_tpu.runtime.simulated import run_simulation
from mysticeti_tpu.simulated_network import SimulatedNetwork
from mysticeti_tpu.wal import walf


class _SimNodeNetwork:
    """Adapter giving NetworkSyncer the TcpNetwork surface over the sim."""

    def __init__(self, queue):
        self.connections = queue

    async def stop(self):
        pass


def build_node(committee, signers, authority, tmp_dir, sim_net, parameters):
    wal_writer, wal_reader = walf(os.path.join(tmp_dir, f"wal-{authority}"))
    recovered, observer_recovered = BlockStore.open(
        authority, wal_reader, wal_writer, committee
    )
    handler = TestBlockHandler(
        last_transaction=authority * 1_000_000,
        committee=committee,
        authority=authority,
    )
    core = Core(
        block_handler=handler,
        authority=authority,
        committee=committee,
        parameters=parameters,
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signers[authority],
    )
    observer = TestCommitObserver(
        core.block_store, committee, recovered_state=observer_recovered
    )
    return NetworkSyncer(
        core,
        observer,
        _SimNodeNetwork(sim_net.node_connections[authority]),
        parameters=parameters,
    )


async def _run_nodes(n, tmp_dir, virtual_seconds, fault=None, leaders=1,
                     committee=None, parameters=None, health_out=None):
    if committee is None:
        committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    if parameters is None:
        parameters = Parameters(leader_timeout_s=1.0, number_of_leaders=leaders)
    sim_net = SimulatedNetwork(n)
    nodes = [
        build_node(committee, signers, a, tmp_dir, sim_net, parameters)
        for a in range(n)
    ]
    monitor = None
    if health_out is not None:
        # Fleet health plane riding the sim (health_out: a mutable dict
        # receiving {"monitor": FleetHealthMonitor}): one probe per node,
        # centrally sampled on the virtual clock, with the SLO watchdog
        # armed — the run asserts its own diagnosis.
        from mysticeti_tpu.health import FleetHealthMonitor, HealthProbe

        slo = health_out.pop("slo")
        probes = {
            a: HealthProbe(a, n, slo=slo).attach(
                core=node.core,
                net_syncer=node,
                commit_observer=node.syncer.commit_observer,
            )
            for a, node in enumerate(nodes)
        }
        monitor = FleetHealthMonitor(probes.get, n, interval_s=1.0)
        health_out["monitor"] = monitor
    for node in nodes:
        await node.start()
    await sim_net.connect_all()
    if monitor is not None:
        monitor.start()
    if fault is not None:
        await fault(sim_net, nodes)
    await asyncio.sleep(virtual_seconds)
    if monitor is not None:
        monitor.stop()
        monitor.tick()  # final sample for the participation verdict
    for node in nodes:
        await node.stop()
    sim_net.close()
    return nodes


def _committed(node):
    return list(node.syncer.commit_observer.committed_leaders)


def _assert_prefix_consistent(sequences):
    """All commit sequences must be prefixes of the longest (safety)."""
    longest = max(sequences, key=len)
    for seq in sequences:
        assert seq == longest[: len(seq)], f"fork: {seq} vs {longest}"


def test_four_nodes_commit(tmp_path):
    nodes = run_simulation(_run_nodes(4, str(tmp_path), 30.0), seed=3)
    sequences = [_committed(n) for n in nodes]
    # Rate-scaled threshold: the healthy configuration commits ~12 leaders
    # per virtual second (measured 363 in 30 s); 150 catches any 2x liveness
    # regression while leaving headroom for seed variation.
    assert all(len(s) >= 150 for s in sequences), [len(s) for s in sequences]
    _assert_prefix_consistent(sequences)
    # No-fault equal-progress: nodes may only differ by a small tail.
    lengths = sorted(len(s) for s in sequences)
    assert lengths[-1] - lengths[0] <= 5, lengths


def test_ten_nodes_commit(tmp_path):
    nodes = run_simulation(_run_nodes(10, str(tmp_path), 25.0), seed=5)
    sequences = [_committed(n) for n in nodes]
    # Measured 280 in 25 s; 120 = 2x-regression tripwire.
    assert all(len(s) >= 120 for s in sequences), [len(s) for s in sequences]
    _assert_prefix_consistent(sequences)
    lengths = sorted(len(s) for s in sequences)
    assert lengths[-1] - lengths[0] <= 5, lengths


def test_determinism_same_seed(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    a = run_simulation(_run_nodes(4, str(tmp_path / "a"), 15.0), seed=7)
    b = run_simulation(_run_nodes(4, str(tmp_path / "b"), 15.0), seed=7)
    assert [_committed(n) for n in a] == [_committed(n) for n in b]


def test_one_node_down(tmp_path):
    """3/4 nodes alive is a quorum: progress must continue (net_sync.rs:602 tier)."""

    async def fault(sim_net, nodes):
        await nodes[3].stop()
        sim_net.isolate(3)

    nodes = run_simulation(
        _run_nodes(4, str(tmp_path), 40.0, fault=fault), seed=11
    )
    sequences = [_committed(n) for n in nodes[:3]]
    # 3/4 quorum with one silent leader slot: slower than full strength but
    # must stay within the same order of magnitude (measured healthy ~12/s).
    assert all(len(s) >= 40 for s in sequences), [len(s) for s in sequences]
    _assert_prefix_consistent(sequences)


def test_partition_heals(tmp_path):
    """Minority partition stalls the cut node; healing lets sync catch it up
    (test_network_partition, net_sync.rs:753-780)."""

    async def fault(sim_net, nodes):
        async def schedule():
            sim_net.partition([0], [1, 2, 3])
            await asyncio.sleep(10.0)
            await sim_net.heal()

        asyncio.ensure_future(schedule())

    nodes = run_simulation(
        _run_nodes(4, str(tmp_path), 60.0, fault=fault), seed=13
    )
    sequences = [_committed(n) for n in nodes]
    # The majority made progress...
    assert all(len(s) >= 100 for s in sequences[1:]), [len(s) for s in sequences]
    # ...and the healed node caught up with a consistent (possibly shorter) prefix.
    _assert_prefix_consistent(sequences)
    assert len(sequences[0]) >= 1, "partitioned node never caught up"


def test_fifty_nodes_commit(tmp_path):
    """BASELINE #4/#5-scale committee on the deterministic simulator:
    50 authorities with UNEVEN stakes and stake-weighted leader election
    exercise AuthoritySet, the weighted-sampling elector, and the committers
    at a tier no hardware is needed for (reference sim tier:
    net_sync.rs:583-781 stops at 10)."""
    from mysticeti_tpu.committee import (
        Authority,
        Committee as C,
        STAKE_WEIGHTED,
    )

    n = 50
    signers = C.benchmark_signers(n)
    committee = C(
        [Authority(1 + (i % 3), s.public_key) for i, s in enumerate(signers)],
        leader_election=STAKE_WEIGHTED,
    )
    nodes = run_simulation(
        _run_nodes(n, str(tmp_path), 6.0, committee=committee), seed=29
    )
    sequences = [_committed(node) for node in nodes]
    # Commit-prefix consistency (safety) across all 50 validators...
    _assert_prefix_consistent(sequences)
    # ...with liveness: every node commits leaders, and progress is shared.
    # (6 virtual seconds: the r3 version ran 10 at ~6 min wall; the decode
    # memo + burst delivery + threshold scaling keep this in the default
    # tier at ~2 min.)
    assert all(len(s) >= 12 for s in sequences), sorted(len(s) for s in sequences)[:5]
    lengths = sorted(len(s) for s in sequences)
    assert lengths[-1] - lengths[0] <= 8, (lengths[0], lengths[-1])
    # Stake-weighted election actually rotated leaders across the committee.
    leaders = {ref.authority for seq in sequences for ref in seq}
    assert len(leaders) >= 10, sorted(leaders)


def _hundred_nodes_scenario(tmp_path):
    """BASELINE #5-scale committee (100 authorities) through the WHOLE stack
    on the deterministic simulator: uneven stakes, stake-weighted election,
    full net_sync/verify/commit path per node.  The reference's sim tier
    stops at 10 (net_sync.rs:583-781)."""
    from mysticeti_tpu.committee import (
        Authority,
        Committee as C,
        STAKE_WEIGHTED,
    )

    from mysticeti_tpu.health import SLOThresholds

    n = 100
    signers = C.benchmark_signers(n)
    committee = C(
        [Authority(1 + (i % 3), s.public_key) for i, s in enumerate(signers)],
        leader_election=STAKE_WEIGHTED,
    )
    # Health plane armed (VERDICT weak #7): beyond committing, the run must
    # assert its own diagnosis — no SLO alert fires and every authority
    # stays above the participation floor.  Thresholds sized for a healthy
    # 5-virtual-second run: rounds advance well under 4 s apart, commits
    # flow from the first waves, and no authority's frontier should trail
    # by anything close to 10 rounds.
    health = {
        "slo": SLOThresholds(
            max_round_stall_s=4.0,
            max_commit_stall_s=4.0,
            max_authority_lag_rounds=10,
        )
    }
    nodes = run_simulation(
        _run_nodes(n, str(tmp_path), 5.0, committee=committee,
                   health_out=health),
        seed=31,
    )
    sequences = [_committed(node) for node in nodes]
    _assert_prefix_consistent(sequences)
    assert all(len(s) >= 6 for s in sequences), sorted(len(s) for s in sequences)[:5]
    lengths = sorted(len(s) for s in sequences)
    assert lengths[-1] - lengths[0] <= 8, (lengths[0], lengths[-1])
    leaders = {ref.authority for seq in sequences for ref in seq}
    assert len(leaders) >= 15, sorted(leaders)
    # The run's own health report is green: no watchdog alert, full
    # participation, every frontier within the lag floor.
    report = health["monitor"].fleet_report()
    assert report["status"] == "ok", report
    assert report["alerts"] == [], report["alerts"][:5]
    assert report["participation"] == 1.0, report
    assert report["samples"] >= 4, report


@pytest.mark.skipif(
    not os.environ.get("MYSTICETI_BIG_SIMS"),
    reason="100-authority whole-stack sim: several minutes wall; run with "
    "MYSTICETI_BIG_SIMS=1 (the driver artifact HUNDRED_r04.json pins it)",
)
def test_hundred_nodes_commit(tmp_path):
    """Driver-artifact entry point (HUNDRED_r0N.json pins MYSTICETI_BIG_SIMS
    so the scenario also runs standalone in the fast tier on demand)."""
    _hundred_nodes_scenario(tmp_path)


@pytest.mark.slow
def test_hundred_nodes_commit_slow_tier(tmp_path):
    """VERDICT r5 weak #7: the env-gated variant above silently does not run
    in routine CI, so nothing asserted the 100-authority sim stays green
    between rounds.  This wrapper puts the same scenario in the slow/kernel
    tier unconditionally — rot shows up as a tier-2 failure, not as a
    surprise when the next driver artifact is due."""
    if os.environ.get("MYSTICETI_BIG_SIMS"):
        pytest.skip("already exercised via test_hundred_nodes_commit")
    _hundred_nodes_scenario(tmp_path)


def test_helper_streams_serve_partitioned_authority(tmp_path):
    """Others-blocks helper streams (synchronizer.rs:169-205, dormant in the
    reference; live behind SynchronizerParameters.disseminate_others_blocks):
    with the 0<->3 link severed, node 3 asks its surviving peers to RELAY
    authority 0's blocks — a helper that is not the block author serves the
    stream, and node 3 keeps pace with the fleet."""
    from mysticeti_tpu.config import SynchronizerParameters

    parameters = Parameters(
        leader_timeout_s=1.0,
        synchronizer=SynchronizerParameters(disseminate_others_blocks=True),
    )
    relayed = {}

    async def fault(sim_net, nodes):
        sim_net.partition([0], [3])

        async def probe():
            # Sample relay counters near the end of the run, while the
            # connections (and their disseminators) are still alive.
            await asyncio.sleep(25.0)
            for helper in (1, 2):
                d = nodes[helper]._disseminators.get(3)
                if d is not None:
                    relayed[helper] = d.helper_blocks_sent

        asyncio.ensure_future(probe())

    nodes = run_simulation(
        _run_nodes(4, str(tmp_path), 30.0, fault=fault,
                   parameters=parameters),
        seed=17,
    )
    sequences = [_committed(n) for n in nodes]
    _assert_prefix_consistent(sequences)
    # The relay actually carried authority-0 blocks to node 3 (the helper is
    # by construction not the author: only nodes 1 and 2 can serve it).
    assert sum(relayed.values()) > 0, relayed
    # And the cut node kept pace via the push relay — same tight tail the
    # healthy 4-node run holds, not the fetcher's sample-interval crawl.
    lengths = sorted(len(s) for s in sequences)
    assert lengths[0] >= 100, lengths
    assert lengths[-1] - lengths[0] <= 10, lengths


def test_subscribe_others_message_roundtrip():
    """Wire round-trip of the new soft-extension tag (wire-format §7)."""
    from mysticeti_tpu.network import (
        SubscribeOthersFrom,
        decode_message,
        encode_message,
    )

    msg = SubscribeOthersFrom(authority=7, round=12345)
    assert decode_message(encode_message(msg)) == msg


def test_multi_leader_whole_stack(tmp_path):
    """The multi-leader configuration live end-to-end (not just in the
    committer gold suite): number_of_leaders=2 over the simulated network
    must commit at least as fast as single-leader and stay fork-free with
    equal progress (universal_committer.rs:151-176 wiring through Core)."""
    nodes = run_simulation(
        _run_nodes(4, str(tmp_path), 30.0, leaders=2), seed=23
    )
    sequences = [_committed(n) for n in nodes]
    # Two leader slots per round: the committed-leader rate must not regress
    # vs the single-leader threshold used in test_four_nodes_commit.
    assert all(len(s) >= 150 for s in sequences), [len(s) for s in sequences]
    _assert_prefix_consistent(sequences)
    lengths = sorted(len(s) for s in sequences)
    assert lengths[-1] - lengths[0] <= 5, lengths
