"""Keyed-tile verification path: per-key precomputed combs + tile grouping.

The keyed kernel must be bit-identical to the CPU oracle and the generic
fused path — it is a pure strength reduction (zero doublings, no on-device A
decompression), not a semantics change.  Runs under the Pallas interpreter
on the CPU test mesh.
"""
import random

import numpy as np
import pytest
from mysticeti_tpu.crypto import Ed25519PrivateKey

from mysticeti_tpu.ops import ed25519 as E

pytestmark = [pytest.mark.kernel,
              pytest.mark.filterwarnings("ignore::DeprecationWarning")]


@pytest.fixture(scope="module")
def keyring():
    rng = random.Random(7)
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(4)
    ]
    return rng, keys


def _batch(rng, keys, n, tamper_every=None):
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = bytes(rng.randrange(256) for _ in range(32))
        s = k.sign(m)
        good = True
        if tamper_every and i % tamper_every == 0:
            s = bytes([s[0] ^ 1]) + s[1:]
            good = False
        pks.append(k.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(s)
        expect.append(good)
    return pks, msgs, sigs, np.array(expect)


def test_group_blob_for_tiles_properties():
    rng = np.random.default_rng(3)
    n, num_keys, tile, bucket = 50, 5, 4, 128
    idx = rng.integers(0, num_keys, size=n)
    blob = rng.integers(1, 2**31, size=(n, 26), dtype=np.int64).astype(np.uint32)
    blob[:, 24] = idx
    blob[:, 25] = 1
    blob[::11, 25] = 0  # some rejected lanes
    g = E.group_blob_for_tiles(blob, num_keys, tile, bucket)
    assert g is not None
    grouped, tile_keys, positions = g
    assert grouped.shape == (bucket, 26) and len(tile_keys) == bucket // tile
    # positions is injective and the grouped rows hold the original data
    assert len(set(positions.tolist())) == n
    assert (grouped[positions] == blob).all()
    # every tile contains rows of ONE key (among live lanes)
    for t in range(bucket // tile):
        rows = grouped[t * tile : (t + 1) * tile]
        live = rows[rows[:, 25] != 0]
        if len(live):
            assert (live[:, 24] == tile_keys[t]).all()
    # overflow: 5 keys x 1 tile minimum > 1-tile bucket
    assert E.group_blob_for_tiles(blob, num_keys, tile, tile) is None


def test_keyed_kernel_matches_oracle(keyring):
    from mysticeti_tpu.ops import ed25519_pallas as PK

    rng, keys = keyring
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
    n, tile, bucket = 24, 8, 64
    pks, msgs, sigs, expect = _batch(rng, keys, n, tamper_every=5)
    idx = table.indices_for(pks)
    blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
    acomb, valid = table.neg_combs()
    assert valid.all()
    g = E.group_blob_for_tiles(blob, len(table), tile, bucket)
    assert g is not None
    grouped, tile_keys, positions = g
    out = np.asarray(
        PK.verify_keyed_blob(
            grouped, table.words, acomb, tile_keys,
            E._pad_to(positions, bucket), tile=tile, interpret=True,
        )
    )[:n]
    assert (out == expect).all()
    # parity with the CPU oracle
    from mysticeti_tpu.crypto import InvalidSignature

    for i in range(n):
        try:
            keys[i % len(keys)].public_key().verify(sigs[i], msgs[i])
            oracle = True
        except InvalidSignature:
            oracle = False
        assert out[i] == oracle


def test_keyed_flat_variant_matches_blob(keyring):
    """verify_keyed_flat (96 B/sig wire variant: key index reconstructed
    from tile_keys, ok as a packed bitmask, grouped-order output) agrees
    with verify_keyed_blob on the same grouped batch.  Kept as the option
    for byte-dominated links; the deployed dispatch uses the 26-column
    upload (measured faster on this tunnel — see ops/ed25519.py)."""
    from mysticeti_tpu.ops import ed25519_pallas as PK

    rng, keys = keyring
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
    n, tile, bucket = 24, 8, 64
    pks, msgs, sigs, expect = _batch(rng, keys, n, tamper_every=5)
    idx = table.indices_for(pks)
    blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
    acomb, valid = table.neg_combs()
    g = E.group_blob_for_tiles(blob, len(table), tile, bucket)
    grouped, tile_keys, positions = g
    okmask = np.packbits(
        grouped[:, 25].astype(bool), bitorder="little"
    ).view(np.uint32)
    flat = np.concatenate([grouped[:, :24].reshape(-1), okmask])
    out_flat = np.asarray(
        PK.verify_keyed_flat(
            flat, table.words, acomb, tile_keys, tile=tile, interpret=True
        )
    )
    out_blob = np.asarray(
        PK.verify_keyed_blob(
            grouped, table.words, acomb, tile_keys, None,
            tile=tile, interpret=True,
        )
    )
    assert (out_flat == out_blob).all()
    assert (out_flat[positions] == expect).all()


def test_keyed_dispatch_end_to_end_forced_pallas(keyring, monkeypatch):
    """verify_batch_table with the backend forced to pallas(interpret) takes
    the keyed dispatch path and still matches expectations, including
    unknown-key stragglers."""
    rng, keys = keyring
    monkeypatch.setenv("MYSTICETI_VERIFY_BACKEND", "pallas")
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys[:-1]])
    pks, msgs, sigs, expect = _batch(rng, keys, 40, tamper_every=7)
    out = E.verify_batch_table(table, pks, msgs, sigs)
    assert (out == expect).all()


def test_keyed_rejects_invalid_committee_key(keyring):
    """An off-curve key table entry force-rejects its lanes (the generic
    kernel rejects them via decompression failure — outputs must agree)."""
    from mysticeti_tpu.ops import ed25519_pallas as PK

    rng, keys = keyring
    bad_pk = bytes([0xFF] * 31 + [0x7F])  # y >= p: non-canonical encoding
    assert E._decode_point(bad_pk) is None
    table = E.KeyTable([keys[0].public_key().public_bytes_raw(), bad_pk])
    acomb, valid = table.neg_combs()
    assert valid.tolist() == [True, False]
    n, tile, bucket = 8, 8, 32
    pks, msgs, sigs, expect = _batch(rng, keys[:1], n)
    # route half the lanes to the invalid key
    idx = np.array([0, 1] * (n // 2))
    blob = E.pack_blob_indexed(idx, msgs, sigs, num_keys=len(table))
    blob[:, 25] &= valid[np.clip(blob[:, 24].astype(np.int64), 0, 1)]
    g = E.group_blob_for_tiles(blob, len(table), tile, bucket)
    grouped, tile_keys, positions = g
    out = np.asarray(
        PK.verify_keyed_blob(
            grouped, table.words, acomb, tile_keys,
            E._pad_to(positions, bucket), tile=tile, interpret=True,
        )
    )[:n]
    assert (out == (idx == 0) & expect).all()


def test_neg_combs_first_window_is_negated_key(keyring):
    """Spot-check the comb contents: entry (w=0, v=1) must be the Niels form
    of -A itself."""
    _, keys = keyring
    pk = keys[0].public_key().public_bytes_raw()
    table = E.KeyTable([pk])
    acomb, valid = table.neg_combs()
    assert valid.all()
    x, y = E._decode_point(pk)
    import mysticeti_tpu.ops.field as F

    arr = np.asarray(acomb)
    assert (arr[0, 0, 0, :, 1] == F.int_to_limbs((y + x) % E.P)).all()
    assert (arr[0, 0, 1, :, 1] == F.int_to_limbs((y - x) % E.P)).all()
    assert (
        arr[0, 0, 2, :, 1]
        == F.int_to_limbs((E.P - E._D2 * x % E.P * y % E.P) % E.P)
    ).all()
