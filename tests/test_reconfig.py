"""Epoch reconfiguration plane (reconfig.py): dynamic committee membership
anchored on the committed leader sequence.

Unit coverage of the pure machinery (change codec, validity/idempotence,
committee digest, epoch chain, the fold, snapshot chain adoption) plus the
durability soft-tails (checkpoint + snapshot manifest) and wire tag 17; then
deterministic churn sims: two live transitions with byte-identical same-seed
replay, a cross-boundary snapshot rejoin, and a crash-restart that must
re-derive its epoch from WAL replay alone.
"""
import asyncio

import pytest

from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters, StorageParameters
from mysticeti_tpu.network import EpochInfo, decode_message, encode_message
from mysticeti_tpu.reconfig import (
    CHANGE_ADD,
    CHANGE_REMOVE,
    CHANGE_REWEIGHT,
    RECONFIG_MAGIC,
    CommitteeChange,
    EpochChain,
    EpochRecord,
    ReconfigState,
    apply_change,
    change_is_valid,
    committee_digest,
    parse_reconfig_tx,
)
from mysticeti_tpu.serde import SerdeError
from mysticeti_tpu.storage import Checkpoint, SnapshotManifest
from mysticeti_tpu.types import Share, StatementBlock

pytestmark = pytest.mark.reconfig


# ---------------------------------------------------------------------------
# Change transaction codec


def test_change_codec_roundtrip():
    for change in (
        CommitteeChange(CHANGE_ADD, 3, 2),
        CommitteeChange(CHANGE_REMOVE, 1),
        CommitteeChange(CHANGE_REWEIGHT, 0, 7),
    ):
        raw = change.to_bytes()
        assert raw.startswith(RECONFIG_MAGIC)
        assert CommitteeChange.from_bytes(raw) == change
        assert parse_reconfig_tx(raw) == change


def test_change_constructor_rejects_nonsense():
    with pytest.raises(ValueError):
        CommitteeChange(9, 0, 1)  # unknown kind
    with pytest.raises(ValueError):
        CommitteeChange(CHANGE_ADD, 0, 0)  # add at stake 0
    with pytest.raises(ValueError):
        CommitteeChange(CHANGE_REWEIGHT, 0, 0)  # reweight to 0 is a REMOVE


def test_parse_ignores_ordinary_and_garbled_payloads():
    # Ordinary payloads (benchmark counters, random bytes) are not changes.
    assert parse_reconfig_tx(b"\x01\x00\x00\x00\x00\x00\x00\x00") is None
    assert parse_reconfig_tx(b"") is None
    # Magic but truncated/garbled body: deterministically "not a change",
    # never an error (honest nodes must not fork on whether to raise).
    assert parse_reconfig_tx(RECONFIG_MAGIC + b"\x00") is None
    assert parse_reconfig_tx(RECONFIG_MAGIC + b"\xff" * 17) is None
    # Trailing junk after a well-formed change is garbled too.
    good = CommitteeChange(CHANGE_ADD, 1, 1).to_bytes()
    assert parse_reconfig_tx(good + b"x") is None


# ---------------------------------------------------------------------------
# Validity + idempotence of the fold


def _committee(stakes):
    return Committee.new_for_benchmarks(len(stakes), stakes=list(stakes))


def test_change_validity_rules():
    committee = _committee((1, 1, 1, 0))
    # ADD activates a zero-stake registered member only.
    assert change_is_valid(committee, CommitteeChange(CHANGE_ADD, 3, 1))
    assert not change_is_valid(committee, CommitteeChange(CHANGE_ADD, 0, 1))
    # REMOVE deactivates an active member only.
    assert change_is_valid(committee, CommitteeChange(CHANGE_REMOVE, 0))
    assert not change_is_valid(committee, CommitteeChange(CHANGE_REMOVE, 3))
    # REWEIGHT must actually change an active stake.
    assert change_is_valid(committee, CommitteeChange(CHANGE_REWEIGHT, 1, 5))
    assert not change_is_valid(committee, CommitteeChange(CHANGE_REWEIGHT, 1, 1))
    assert not change_is_valid(committee, CommitteeChange(CHANGE_REWEIGHT, 3, 5))
    # Unknown index: never valid.
    assert not change_is_valid(committee, CommitteeChange(CHANGE_ADD, 9, 1))


def test_apply_change_is_idempotent_and_preserves_last_active():
    committee = _committee((1, 1, 1, 0))
    add = CommitteeChange(CHANGE_ADD, 3, 2)
    next_c = apply_change(committee, add)
    assert next_c is not None
    assert next_c.epoch == 1
    assert next_c.get_stake(3) == 2
    # Duplicate submission: validity is judged against the NEW committee,
    # so the second application is a deterministic no-op.
    assert apply_change(next_c, add) is None
    # Never deactivate the last active member.
    lonely = _committee((1, 0, 0, 0))
    assert apply_change(lonely, CommitteeChange(CHANGE_REMOVE, 0)) is None


def test_committee_digest_is_canonical():
    a = _committee((1, 1, 1, 0))
    b = _committee((1, 1, 1, 0))
    assert committee_digest(a) == committee_digest(b)
    # Stakes, epoch, and membership all feed the digest.
    assert committee_digest(a) != committee_digest(_committee((1, 1, 1, 1)))
    assert committee_digest(a) != committee_digest(a.with_stakes([1, 1, 1, 0], 1))


# ---------------------------------------------------------------------------
# Epoch chain


def _record(epoch, height, stakes):
    committee = _committee(stakes).with_stakes(list(stakes), epoch)
    return EpochRecord(
        epoch=epoch,
        boundary_height=height,
        boundary_round=height * 3,
        digest=committee_digest(committee),
        stakes=tuple(stakes),
    )


def test_epoch_chain_roundtrip_and_contiguity():
    chain = EpochChain([_record(1, 5, (1, 2, 1, 0)), _record(2, 9, (1, 2, 1, 1))])
    again = EpochChain.from_bytes(chain.to_bytes())
    assert again.records == chain.records
    assert again.epoch == 2
    assert again.last_height == 9
    # Empty bytes decode as the genesis chain (epoch 0).
    empty = EpochChain.from_bytes(b"")
    assert empty.epoch == 0 and empty.last_height == 0
    # Non-contiguous epochs and regressing heights are rejected.
    with pytest.raises(SerdeError, match="not contiguous"):
        EpochChain([_record(2, 5, (1, 2, 1, 0))])
    with pytest.raises(SerdeError, match="must not decrease"):
        EpochChain([_record(1, 5, (1, 2, 1, 0)), _record(2, 3, (1, 2, 1, 1))])


def test_epoch_chain_derive_committee_checks_digest():
    genesis = _committee((1, 1, 1, 0))
    chain = EpochChain([_record(1, 5, (1, 2, 1, 0))])
    derived = chain.derive_committee(genesis)
    assert derived.epoch == 1 and derived.get_stake(1) == 2
    # A stake vector sized for a different registry is rejected.
    with pytest.raises(SerdeError, match="registry"):
        EpochChain([_record(1, 5, (1, 2, 1))]).derive_committee(genesis)
    # A record whose digest does not describe this genesis is rejected.
    bogus = EpochRecord(1, 5, 15, b"\x13" * 32, (1, 2, 1, 0))
    with pytest.raises(SerdeError, match="digest mismatch"):
        EpochChain([bogus]).derive_committee(genesis)


# ---------------------------------------------------------------------------
# ReconfigState: the fold over committed sub-dags


def _change_block(authority, round_, change, extra_payloads=()):
    statements = [Share(p) for p in extra_payloads]
    statements.append(Share(change.to_bytes()))
    return StatementBlock.build(authority, round_, (), statements)


def test_observe_commit_folds_and_skips_replayed_heights():
    state = ReconfigState(_committee((1, 1, 1, 0)))
    add = CommitteeChange(CHANGE_ADD, 3, 1)
    blocks = [_change_block(0, 4, add, extra_payloads=[b"ordinary-tx"])]
    transition = state.observe_commit(7, 4, blocks)
    assert transition is not None
    assert transition.committee.epoch == 1
    assert state.epoch == 1
    assert state.committee.is_active(3)
    assert state.chain.records[-1].boundary_height == 7
    # Crash replay re-delivers the same commit: folded heights are skipped
    # wholesale, so the replay is a no-op.
    assert state.observe_commit(7, 4, blocks) is None
    assert state.epoch == 1
    # A later duplicate of the same change is invalid at fold time: no-op.
    assert state.observe_commit(8, 5, [_change_block(1, 5, add)]) is None
    # Two valid changes in one sub-dag fold as two epochs at one boundary.
    both = [
        _change_block(0, 6, CommitteeChange(CHANGE_REWEIGHT, 0, 3)),
        _change_block(1, 6, CommitteeChange(CHANGE_REMOVE, 2)),
    ]
    transition = state.observe_commit(9, 6, both)
    assert transition is not None
    assert [rec.epoch for rec in transition.records] == [2, 3]
    assert state.epoch == 3
    assert not state.committee.is_active(2)


def test_committee_for_epoch_is_epoch_matched():
    state = ReconfigState(_committee((1, 1, 1, 0)))
    state.observe_commit(3, 2, [_change_block(0, 2, CommitteeChange(CHANGE_REWEIGHT, 1, 4))])
    assert state.committee_for_epoch(0).epoch == 0
    assert state.committee_for_epoch(0).get_stake(1) == 1
    assert state.committee_for_epoch(1).get_stake(1) == 4
    # Epochs this chain never derived — including invented future ones —
    # return None (the caller falls back to the CURRENT committee's rules,
    # never lenient ones).
    assert state.committee_for_epoch(7) is None


def test_adopt_chain_extends_prefix_or_rejects():
    genesis = _committee((1, 1, 1, 0))
    server = ReconfigState(genesis)
    server.observe_commit(5, 3, [_change_block(0, 3, CommitteeChange(CHANGE_ADD, 3, 1))])
    server.observe_commit(9, 6, [_change_block(1, 6, CommitteeChange(CHANGE_REWEIGHT, 0, 2))])
    raw = server.chain.to_bytes()

    # A fresh rejoiner adopts the whole chain and lands on the current epoch.
    joiner = ReconfigState(genesis)
    transition = joiner.adopt_chain(raw)
    assert transition is not None
    assert joiner.epoch == 2
    assert committee_digest(joiner.committee) == committee_digest(server.committee)
    assert [rec.epoch for rec in transition.records] == [1, 2]
    # Shorter-or-equal chains are ignored (we are already at or past them).
    assert joiner.adopt_chain(raw) is None
    assert joiner.adopt_chain(b"") is None
    # A longer chain that does not extend ours is a divergence, not an
    # adoption (the shorter-chain gate cannot mask a conflicting prefix).
    other = ReconfigState(genesis)
    other.observe_commit(4, 2, [_change_block(2, 2, CommitteeChange(CHANGE_REMOVE, 2))])
    assert other.epoch == 1
    with pytest.raises(SerdeError, match="does not extend"):
        other.adopt_chain(raw)


# ---------------------------------------------------------------------------
# Durability soft-tails + wire tag 17


def _manifest(epoch_chain=b""):
    return SnapshotManifest(
        commit_height=42,
        last_committed_leader=None,
        gc_round=7,
        chain_digest=b"\x21" * 32,
        committed_refs=[],
        epoch_chain=epoch_chain,
    )


def test_snapshot_manifest_epoch_chain_soft_tail():
    chain = EpochChain([_record(1, 5, (1, 2, 1, 0))]).to_bytes()
    again = SnapshotManifest.from_bytes(_manifest(chain).to_bytes())
    assert again.epoch_chain == chain
    assert EpochChain.from_bytes(again.epoch_chain).epoch == 1
    # Frozen-committee manifests omit the tail entirely: byte-identical to
    # a build that predates the field, and they decode as "epoch 0".
    legacy = _manifest().to_bytes()
    assert not legacy.endswith(chain)
    assert SnapshotManifest.from_bytes(legacy).epoch_chain == b""
    assert len(legacy) < len(_manifest(chain).to_bytes())


def test_checkpoint_epoch_chain_soft_tail(tmp_path):
    chain = EpochChain([_record(1, 5, (1, 2, 1, 0))]).to_bytes()

    def checkpoint(epoch_chain):
        return Checkpoint(
            wal_position=128,
            commit_height=17,
            gc_round=3,
            last_committed_leader=None,
            chain_digest=b"\x05" * 32,
            committed_state=None,
            handler_state=None,
            last_own_block=None,
            pending=[],
            committed_refs=[],
            index=[],
            epoch_chain=epoch_chain,
        )

    again = Checkpoint.from_bytes(checkpoint(chain).to_bytes())
    assert again.epoch_chain == chain
    assert again.commit_height == 17
    # Empty chain: the tail is omitted (pre-reconfig byte identity) and
    # decodes back as empty.
    legacy = checkpoint(b"").to_bytes()
    assert Checkpoint.from_bytes(legacy).epoch_chain == b""
    assert len(legacy) < len(checkpoint(chain).to_bytes())


def test_epoch_info_wire_roundtrip():
    msg = EpochInfo(3, b"\x11" * 32)
    again = decode_message(encode_message(msg))
    assert isinstance(again, EpochInfo)
    assert again.epoch == 3
    assert again.digest == b"\x11" * 32


# ---------------------------------------------------------------------------
# Churn sims (DeterministicLoop virtual time; tier 1)


def _sim_parameters(snapshot_catchup=False, threshold=10):
    storage = (
        StorageParameters(
            segment_bytes=16 * 1024,
            checkpoint_interval=5,
            gc_depth=30,
            snapshot_catchup=True,
            catchup_threshold_commits=threshold,
        )
        if snapshot_catchup
        else StorageParameters()
    )
    return Parameters(
        leader_timeout_s=0.3,
        reconfig=True,
        leader_liveness_horizon_rounds=4,
        storage=storage,
    )


def _driver(events):
    """events: [(at_s, coroutine-factory(harness))] in virtual time."""

    async def run(harness):
        now = 0.0
        for at_s, action in events:
            if at_s > now:
                await asyncio.sleep(at_s - now)
                now = at_s
            await action(harness)

    return run


def _submit(change):
    async def action(harness):
        harness.submit_change(0, change)

    return action


@pytest.mark.chaos
def test_churn_two_transitions_byte_identical_same_seed(tmp_path):
    """Reweight + remove under load: every live node reaches epoch 2, the
    retired authority stops cleanly, boundary (height, digest) agree
    fleet-wide (the checker's cross-epoch audit), and a same-seed replay is
    byte-identical through both transitions."""
    n = 6
    plan = FaultPlan(seed=18)
    events = [
        (2.0, _submit(CommitteeChange(CHANGE_REWEIGHT, 1, 3))),
        (5.0, _submit(CommitteeChange(CHANGE_REMOVE, 4))),
        (7.0, lambda harness: harness.retire(4)),
    ]
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    report, harness = run_chaos_sim(
        plan, n, 10.0, str(tmp_path / "a"),
        parameters=_sim_parameters(),
        committee=Committee.new_for_benchmarks(n),
        extra_fault=_driver(events),
    )
    replay, _ = run_chaos_sim(
        plan, n, 10.0, str(tmp_path / "b"),
        parameters=_sim_parameters(),
        committee=Committee.new_for_benchmarks(n),
        extra_fault=_driver(events),
    )

    live = [a for a in range(n) if a != 4]
    assert all(report.epochs.get(a) == 2 for a in live), report.epochs
    # The departing authority saw at least the boundary that removed it.
    assert report.epochs.get(4, 0) >= 1
    assert set(report.epoch_boundaries) == {1, 2}
    # Everyone kept committing after the second boundary.
    assert all(len(report.sequences[a]) > 0 for a in live)

    # Byte-identical same-seed replay, across both epoch boundaries.
    assert report.sequences == replay.sequences
    assert report.epochs == replay.epochs
    assert report.epoch_boundaries == replay.epoch_boundaries
    assert report.fault_log_bytes == replay.fault_log_bytes


@pytest.mark.chaos
def test_snapshot_rejoin_across_epoch_boundary(tmp_path):
    """A registered-at-zero-stake authority sleeps through its own ADD
    boundary, then boots from an empty WAL: it must adopt a snapshot whose
    epoch chain carries the boundary, land on the current committee, and
    commit a sequence that is exactly a window of the fleet's."""
    n = 6
    plan = FaultPlan(seed=7)
    events = [
        (3.0, _submit(CommitteeChange(CHANGE_ADD, 5, 1))),
        (7.0, lambda harness: harness.join(5)),
    ]
    report, harness = run_chaos_sim(
        plan, n, 14.0, str(tmp_path),
        parameters=_sim_parameters(snapshot_catchup=True),
        committee=Committee.new_for_benchmarks(n, stakes=[1, 1, 1, 1, 1, 0]),
        extra_fault=_driver(events),
        absent={5},
    )

    assert all(report.epochs.get(a) == 1 for a in range(n)), report.epochs
    joiner = report.sequences[5]
    assert joiner, "joiner never committed"
    height = harness.checker.committed_height(5)
    # Prefix consistency across the adopted baseline: the joiner's observed
    # window is exactly the fleet's sequence ending at its height.
    reference_node = max(range(5), key=harness.checker.committed_height)
    assert harness.checker.committed_height(reference_node) >= height
    reference = report.sequences[reference_node]
    assert joiner == reference[height - len(joiner):height]
    # Far behind at join time, the window is a strict suffix: the commits
    # below the adopted baseline were never replayed block-by-block.
    assert len(joiner) < height


@pytest.mark.chaos
def test_crash_restart_rederives_epoch_from_wal(tmp_path):
    """Crash a node after a boundary commit lands in its WAL but (with the
    checkpoint cadence at 5 commits) possibly before any checkpoint covers
    it: the restart must re-derive the same epoch from replayed commits and
    rejoin the post-boundary committee."""
    n = 6
    plan = FaultPlan(seed=23, crashes=[CrashFault(node=3, at_s=4.0, downtime_s=3.0)])
    events = [(2.0, _submit(CommitteeChange(CHANGE_REWEIGHT, 1, 3)))]
    report, harness = run_chaos_sim(
        plan, n, 11.0, str(tmp_path),
        parameters=_sim_parameters(),
        committee=Committee.new_for_benchmarks(n),
        extra_fault=_driver(events),
    )

    # Every node — including the restarted one — derived the boundary, and
    # the checker's cross-epoch audit (inside run_chaos_sim) saw identical
    # (height, digest) for it everywhere.
    assert all(report.epochs.get(a) == 1 for a in range(n)), report.epochs
    assert set(report.epoch_boundaries) == {1}
    # The restarted node kept committing after recovery.
    crashed_seq = report.sequences[3]
    longest = max(report.sequences.values(), key=len)
    assert crashed_seq == longest[: len(crashed_seq)]
    assert len(crashed_seq) > 0
