"""Determinism sanitizer: recorder, bisector, tripwire, and run-twice sims.

The dynamic twin of the ``sim-taint`` lint (tests/test_static_analysis.py):
these tests prove the runtime side catches what execution shows — a clean
seeded sim is event-identical across runs, a planted wall-clock leak is
bisected to its first diverging event, and the strict-mode tripwire turns
an un-gated wall-clock read into a stack trace.
"""
import asyncio
import time

import pytest

from mysticeti_tpu import detsan
from mysticeti_tpu.detsan import (
    DetsanRecorder,
    Tripwire,
    WallClockLeak,
    find_divergence,
    run_twice,
)
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.runtime.simulated import run_simulation


class _FakeLoop:
    """Just enough loop surface for DetsanRecorder.record()."""

    def __init__(self):
        self.now = 0.0
        self._ready = []
        self._scheduled = []

    def time(self):
        return self.now


def _tick(recorder, loop, label, vtime):
    def callback():
        pass

    callback.__qualname__ = label
    loop.now = vtime
    recorder.record(loop, callback)


# -- the bisector over synthetic traces ---------------------------------------

def test_find_divergence_identical_traces():
    a, b = DetsanRecorder(), DetsanRecorder()
    loop_a, loop_b = _FakeLoop(), _FakeLoop()
    for i in range(32):
        _tick(a, loop_a, f"cb{i % 5}", i * 0.01)
        _tick(b, loop_b, f"cb{i % 5}", i * 0.01)
    report = find_divergence(a, b)
    assert report.identical
    assert report.events_a == report.events_b == 32
    assert report.first_divergence is None


def test_find_divergence_bisects_first_diverging_event():
    a, b = DetsanRecorder(), DetsanRecorder()
    loop_a, loop_b = _FakeLoop(), _FakeLoop()
    diverge_at = 21
    for i in range(64):
        _tick(a, loop_a, f"cb{i}", i * 0.01)
        if i == diverge_at:
            _tick(b, loop_b, "rogue_timer", i * 0.01 + 0.003)
        else:
            _tick(b, loop_b, f"cb{i}", i * 0.01)
    report = find_divergence(a, b)
    assert not report.identical
    assert report.first_divergence is not None
    assert report.first_divergence["index"] == diverge_at
    assert report.first_divergence["label_a"] == f"cb{diverge_at}"
    assert report.first_divergence["label_b"] == "rogue_timer"
    # Chained digests keep every later event diverged too; the bisector
    # must still name the FIRST one.
    assert a.events[diverge_at + 1].chain != b.events[diverge_at + 1].chain


def test_trace_cap_bounds_storage_but_not_counting():
    recorder = DetsanRecorder(cap=4)
    loop = _FakeLoop()
    for i in range(10):
        _tick(recorder, loop, "cb", i * 0.01)
    assert len(recorder.events) == 4
    assert recorder.count == 10


def test_divergence_past_cap_reports_boundary_not_wrong_event():
    a, b = DetsanRecorder(cap=4), DetsanRecorder(cap=4)
    loop_a, loop_b = _FakeLoop(), _FakeLoop()
    for i in range(8):
        _tick(a, loop_a, f"cb{i}", i * 0.01)
        # identical through the stored prefix; diverges at event 6 (> cap)
        _tick(b, loop_b, f"cb{i}" if i < 6 else "rogue", i * 0.01)
    report = find_divergence(a, b)
    assert not report.identical
    assert report.first_divergence is None
    assert "beyond" in report.note


# -- run-twice over real simulations ------------------------------------------

def _clean_main():
    async def main():
        queue = asyncio.Queue()

        async def producer():
            for i in range(16):
                await asyncio.sleep(0.01)
                await queue.put(i)

        task = asyncio.ensure_future(producer())
        total = 0
        for _ in range(16):
            total += await queue.get()
        await task
        return total

    return main()


def test_run_twice_clean_sim_is_identical():
    report = run_twice(_clean_main, seed=7)
    assert report.identical, report.to_dict()
    assert report.events_a > 0


def test_run_twice_bisects_wall_clock_leak():
    def leaky_main():
        async def main():
            for _ in range(8):
                jitter = (time.perf_counter_ns() % 997) / 1e5
                await asyncio.sleep(0.01 + jitter)

        return main()

    report = run_twice(leaky_main, seed=7)
    assert not report.identical
    assert report.first_divergence is not None
    assert report.first_divergence["index"] >= 0
    assert report.first_divergence["vtime_a"] != report.first_divergence["vtime_b"]


def test_recorder_labels_are_address_free():
    recorder = DetsanRecorder()
    run_simulation(_clean_main(), seed=3, detsan=recorder)
    assert recorder.count > 0
    for event in recorder.events:
        assert "0x" not in event.label, event.label


def test_chaos_sim_run_twice_identical():
    """The acceptance shape: a seeded multi-node chaos sim diffed event-by-
    event (tools/detsan.py runs the 10-node version; 4 nodes keeps tier-1
    fast)."""
    import tempfile

    from mysticeti_tpu.chaos import FaultPlan, run_chaos_sim

    def once():
        recorder = DetsanRecorder()
        with tempfile.TemporaryDirectory() as wal_dir:
            run_chaos_sim(
                FaultPlan(seed=11), 4, 1.0, wal_dir, detsan=recorder
            )
        return recorder

    report = find_divergence(once(), once())
    assert report.identical, report.to_dict()
    assert report.events_a > 100


# -- the wall-clock tripwire ---------------------------------------------------

def _package_probe():
    """A clock reader whose frame claims package provenance (real leaks were
    all fixed, so the tripwire is exercised against a synthetic module)."""
    namespace = {"__name__": "mysticeti_tpu._detsan_test_probe", "time": time}
    exec("def read_clock():\n    return time.monotonic()\n", namespace)
    return namespace["read_clock"]


def test_tripwire_counts_reads_and_ticks_metric():
    read_clock = _package_probe()
    metrics = Metrics()

    async def main():
        return read_clock()

    tripwire = Tripwire(metrics=metrics, strict=False)
    with tripwire:
        run_simulation(main())
    assert tripwire.total_reads == 1
    (site,) = tripwire.reads
    assert site.startswith("mysticeti_tpu._detsan_test_probe:")
    value = metrics.mysticeti_detsan_wallclock_reads_total.labels(
        site=site
    )._value.get()
    assert value == 1.0


def test_tripwire_strict_mode_raises_at_site():
    read_clock = _package_probe()

    async def main():
        return read_clock()

    with pytest.raises(WallClockLeak, match="_detsan_test_probe"):
        with Tripwire(strict=True):
            run_simulation(main())


def test_tripwire_env_knob_enables_strict(monkeypatch):
    monkeypatch.setenv(detsan.STRICT_ENV, "1")
    assert Tripwire().strict
    monkeypatch.delenv(detsan.STRICT_ENV)
    assert not Tripwire().strict


def test_tripwire_ignores_reads_outside_simulation():
    read_clock = _package_probe()
    with Tripwire(strict=True) as tripwire:
        read_clock()  # no running DeterministicLoop: passes through
    assert tripwire.total_reads == 0


def test_tripwire_ignores_third_party_frames():
    async def main():
        # caller module is tests.* / test_detsan, not mysticeti_tpu.*
        return time.monotonic()

    with Tripwire(strict=True) as tripwire:
        run_simulation(main())
    assert tripwire.total_reads == 0


def test_tripwire_uninstall_restores_time_module():
    originals = {name: getattr(time, name) for name in ("monotonic", "time")}
    with Tripwire():
        assert time.monotonic is not originals["monotonic"]
    for name, fn in originals.items():
        assert getattr(time, name) is fn
