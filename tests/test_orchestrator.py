"""Orchestrator harness tests: scrape parsing, search state machine, fault
schedule, and a short end-to-end local multiprocess benchmark
(measurement.rs:362-468 / benchmark.rs:310-391 tiers)."""
import asyncio
import os

import pytest

from mysticeti_tpu.orchestrator import (
    BenchmarkParameters,
    CrashRecoverySchedule,
    FaultsType,
    LoadType,
    Measurement,
    MeasurementsCollection,
    ParametersGenerator,
)

SCRAPE = """
# HELP latency_s end-to-end tx latency
latency_s_bucket{workload="shared",le="0.1"} 5.0
latency_s_bucket{workload="shared",le="0.5"} 85.0
latency_s_bucket{workload="shared",le="+Inf"} 100.0
latency_s_sum{workload="shared"} 31.5
latency_s_count{workload="shared"} 100
latency_squared_s_total{workload="shared"} 13.5
latency_s_sum{workload="owned"} 1.0
latency_s_count{workload="owned"} 3
benchmark_duration_total 50.0
"""


def test_measurement_from_prometheus():
    m = Measurement.from_prometheus(SCRAPE, "shared")
    assert m.count == 100
    assert m.sum_s == 31.5
    assert m.squared_sum_s == 13.5
    assert m.benchmark_duration_s == 50.0
    assert m.tps() == pytest.approx(2.0)
    assert m.avg_latency_s() == pytest.approx(0.315)
    assert m.stdev_latency_s() == pytest.approx((13.5 / 100 - 0.315**2) ** 0.5)
    assert m.buckets["0.5"] == 85.0


def test_measurements_collection_aggregation(tmp_path):
    c = MeasurementsCollection({"nodes": 2})
    for node in ("0", "1"):
        c.add(node, Measurement.from_prometheus(SCRAPE, "shared"))
    assert c.aggregate_tps() == pytest.approx(2.0)  # max per-node view: 100 tx / 50 s
    assert c.aggregate_average_latency_s() == pytest.approx(0.315)
    path = str(tmp_path / "m.json")
    c.save(path)
    loaded = MeasurementsCollection.load(path)
    assert loaded.aggregate_tps() == pytest.approx(2.0)
    assert "tps" in loaded.display_summary()


def test_host_sampler_and_summary(tmp_path):
    """node_exporter-equivalent host series: the sampler observes this
    process, the collection aggregates and round-trips it through save/load."""
    import os
    import time

    from mysticeti_tpu.orchestrator.hostmon import (
        HostSampler,
        parse_remote_sample,
    )

    sampler = HostSampler()
    pids = {"node-0": os.getpid()}
    first = sampler.sample(pids)
    assert first["per_process"]["node-0"]["cpu_pct"] is None  # no interval yet
    assert first["per_process"]["node-0"]["rss_mb"] > 0
    time.sleep(0.05)
    second = sampler.sample(pids)
    assert second["per_process"]["node-0"]["cpu_pct"] is not None
    assert second["mem_available_mb"] > 0

    c = MeasurementsCollection({"nodes": 1})
    c.add("0", Measurement.from_prometheus(SCRAPE, "shared"))
    c.add_host_sample(first)
    c.add_host_sample(second)
    summary = c.host_summary()
    assert summary["samples"] == 2
    assert "cpu_pct_avg" in summary
    assert summary["per_process_cpu_pct_avg"]["node-0"] >= 0
    path = str(tmp_path / "m.json")
    c.save(path)
    loaded = MeasurementsCollection.load(path)
    assert loaded.host_summary()["samples"] == 2
    assert "host cpu" in loaded.display_summary()

    # Dead pid: sampled gracefully (skipped), no crash.
    gone = sampler.sample({"node-1": 2**22 + 12345})
    assert "node-1" not in gone["per_process"]

    # Remote (ssh) sample parsing.
    parsed = parse_remote_sample(
        "0.42 0.30 0.20 1/123 4567\n"
        "MemTotal:       16384000 kB\n"
        "MemAvailable:    8192000 kB\n"
    )
    assert parsed["load_1m"] == pytest.approx(0.42)
    assert parsed["mem_available_mb"] == pytest.approx(8000.0)
    assert parse_remote_sample("garbage") is None

    # SshRunner nests per-host samples under "hosts"; the summary flattens.
    remote = MeasurementsCollection({"nodes": 2})
    remote.add_host_sample(
        {"timestamp_s": 1.0, "hosts": {"host-0": parsed, "host-1": parsed}}
    )
    rs = remote.host_summary()
    assert rs["samples"] == 1
    assert rs["load_1m_max"] == pytest.approx(0.42)


def _collection(load, tps, latency):
    c = MeasurementsCollection()
    m = Measurement(
        benchmark_duration_s=10.0,
        count=int(tps * 10),
        sum_s=latency * tps * 10,
        squared_sum_s=latency * latency * tps * 10,
    )
    c.add("0", m)
    return c


def test_search_generator_doubles_then_bisects():
    gen = ParametersGenerator(4, LoadType.search(100, max_iterations=10), duration_s=1)
    p1 = gen.next_parameters()
    assert p1.load == 100
    gen.register_result(p1, _collection(100, tps=100, latency=0.2))  # sustained
    p2 = gen.next_parameters()
    assert p2.load == 200  # doubled
    gen.register_result(p2, _collection(200, tps=200, latency=0.2))
    p3 = gen.next_parameters()
    assert p3.load == 400
    # breaking point: tps collapses below 2/3 of offered
    gen.register_result(p3, _collection(400, tps=100, latency=0.3))
    p4 = gen.next_parameters()
    assert p4.load == 300  # bisect between 200 and 400
    gen.register_result(p4, _collection(300, tps=295, latency=0.25))
    assert gen.max_sustainable_load() == 300


def test_out_of_capacity_latency_spike():
    gen = ParametersGenerator(4, LoadType.search(100), duration_s=1)
    p = gen.next_parameters()
    gen.register_result(p, _collection(100, tps=100, latency=0.1))
    p2 = gen.next_parameters()
    spiked = _collection(200, tps=200, latency=0.9)  # > 5x previous latency
    assert gen.out_of_capacity(p2, spiked)


def test_fixed_generator():
    gen = ParametersGenerator(4, LoadType.fixed([50, 100]), duration_s=1)
    loads = []
    while (p := gen.next_parameters()) is not None:
        loads.append(p.load)
        gen.register_result(p, _collection(p.load, p.load, 0.1))
    assert loads == [50, 100]


def test_crash_recovery_schedule():
    sched = CrashRecoverySchedule(FaultsType.crash_recovery(3), committee_size=10)
    killed, booted = set(), []
    for _ in range(6):
        k, b = sched.update()
        killed.update(k)
        booted.extend(b)
    assert killed, "some nodes must die"
    assert all(n >= 7 for n in killed), "only the fault budget tail dies"
    assert booted, "crash-recovery must also boot nodes back"


def test_permanent_schedule():
    sched = CrashRecoverySchedule(FaultsType.permanent(2), committee_size=4)
    k, b = sched.update()
    assert k == [2, 3] and b == []
    assert sched.update() == ([], [])


def test_local_benchmark_end_to_end(tmp_path):
    """Short real benchmark: 3 local validator subprocesses, one scrape cycle."""
    from mysticeti_tpu.orchestrator.orchestrator import Orchestrator
    from mysticeti_tpu.orchestrator.runner import LocalProcessRunner

    async def main():
        runner = LocalProcessRunner(str(tmp_path / "fleet"), tps_per_node=20)
        gen = ParametersGenerator(3, LoadType.fixed([60]), duration_s=14.0)
        orch = Orchestrator(
            runner,
            gen,
            results_dir=str(tmp_path / "results"),
            scrape_interval_s=7.0,
        )
        collections = await orch.run_benchmarks()
        return collections

    # One retry: subprocess validators at a fixed 14s budget are sensitive to
    # machine load (e.g. concurrent XLA compiles in a full-suite run).
    for attempt in range(2):
        collections = asyncio.run(main())
        assert len(collections) == 1
        c = collections[0]
        if c.scrapers and c.aggregate_tps() > 0:
            break
    assert c.scrapers, "no scrapes succeeded"
    assert c.benchmark_duration() > 0
    assert c.aggregate_tps() > 0, c.display_summary()
    assert os.path.exists(str(tmp_path / "results" / "measurements-0.json"))
    # The run ships with its own diagnosis (health plane): the cluster
    # snapshot timeline rode the scrape loop, persisted, and summarizes.
    assert c.health_samples, "no fleet health snapshots recorded"
    snap = c.health_samples[-1]
    assert set(snap["reachable"]) <= {"0", "1", "2"}
    assert "quorum_participation" in snap and "straggler_score" in snap
    from mysticeti_tpu.orchestrator.measurement import MeasurementsCollection

    reloaded = MeasurementsCollection.load(
        str(tmp_path / "results" / "measurements-0.json")
    )
    assert reloaded.health_samples == c.health_samples
    assert reloaded.health_summary() is not None
    assert "fleet health" in c.display_summary()


def test_benchmark_duration_starts_at_first_commit(tmp_path, monkeypatch):
    """tps = count / benchmark_duration must not be diluted by pre-load
    warmup: the duration counter opens at the FIRST committed benchmark tx
    (reference scrapes duration from the load client, protocol/mod.rs:57-67)."""
    import struct

    from mysticeti_tpu.commit_observer import TestCommitObserver
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.metrics import Metrics
    from mysticeti_tpu.block_store import BlockStore
    from mysticeti_tpu.wal import walf

    committee = Committee.new_test([1] * 4)
    _w, reader = walf(str(tmp_path / "wal"))
    store = BlockStore(0, len(committee), reader)
    metrics = Metrics()
    observer = TestCommitObserver(store, committee, metrics=metrics)

    t = [1000.0]
    monkeypatch.setattr("mysticeti_tpu.commit_observer.time.monotonic", lambda: t[0])

    # 300 s of warmup pass with no commits: duration must stay 0.
    t[0] += 300.0
    tx = struct.pack("<d", 0.0) + b"\0" * 24
    observer._update_metrics_batch(tx[:8], now=0.0)
    assert metrics.benchmark_duration._value.get() == 0.0

    # 20 s into the loaded phase the counter reflects loaded time only.
    t[0] += 20.0
    observer._update_metrics_batch(tx[:8], now=0.0)
    assert metrics.benchmark_duration._value.get() == 20.0


def test_observe_latency_batch_matches_per_sample():
    """The vectorized histogram path must expose byte-identical series to
    per-sample observe() — the orchestrator's scraper parses this text."""
    import numpy as np

    from mysticeti_tpu.metrics import Metrics

    samples = [0.0, 0.05, 0.1, 0.100001, 0.9, 1.0, 2.0, 42.0, 89.9, 90.0, 1e4]
    batched, plain = Metrics(), Metrics()
    batched.observe_latency_batch("shared", np.array(samples))
    for v in samples:
        plain.latency_s.labels("shared").observe(v)
        plain.latency_squared_s.labels("shared").inc(v**2)

    def series(m, needle):
        return sorted(
            line
            for line in m.expose().decode().splitlines()
            if line.startswith(needle) and "_created" not in line
        )

    assert series(batched, "latency_s") == series(plain, "latency_s")
    assert series(batched, "latency_squared_s") == series(
        plain, "latency_squared_s"
    )
