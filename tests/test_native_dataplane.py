"""Native data-plane parity corpus (r19).

Every batched native function added for the node hot path — block_digests,
encode_blocks_frame, split_frames, parse_blocks_spans, and the
from_bytes_many wrapper — is A/B'd here against its pure-Python twin:
byte-identical outputs on the tags 1-17 golden corpus and on randomized
blocks, AND byte-identical error shapes on torn/short frames (a malformed
frame must be indistinguishable across paths — operators grep these
messages).

The suite is meaningful in BOTH modes and the tier-1 gate runs it twice:
  - extension loaded: direct native-vs-reference A/B, plus production paths
    forced onto the fallback in-process for three-way agreement;
  - MYSTICETI_NO_NATIVE=1: the native-only tests skip; the production-path
    assertions then pin the pure fallback against the same pinned corpus
    hex and reference walks, closing the cross-mode loop.
"""
import asyncio
import hashlib
import random

import pytest

from test_mesh_data_plane import GOLDEN_CORPUS

import mysticeti_tpu.native as native_pkg
import mysticeti_tpu.network as network_mod
import mysticeti_tpu.types as types_mod
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.native import active_functions, native
from mysticeti_tpu.network import (
    MAX_FRAME,
    Blocks,
    EpochInfo,
    GatewayCommitNotification,
    GatewaySubmit,
    GatewaySubmitReply,
    GatewaySubscribeCommits,
    RequestBlocksResponse,
    TimestampedBlocks,
    _FrameReceiver,
    decode_message,
    encode_message,
)
from mysticeti_tpu.serde import Reader, SerdeError, Writer
from mysticeti_tpu.types import Share, StatementBlock

needs_native = pytest.mark.skipif(
    native is None, reason="native extension unavailable"
)

SIGNERS = Committee.benchmark_signers(4)
GENESIS = [StatementBlock.new_genesis(i).reference for i in range(4)]

# Golden corpus for the gateway/epoch tags (13-17); tags 1-12 are imported
# from test_mesh_data_plane.GOLDEN_CORPUS.  Same contract: the hex is the
# wire format — a mismatch is a protocol break, not a test to update.
GATEWAY_CORPUS = [
    (
        GatewaySubmit(b"lane-a", 1, (b"tx-one", b"tx-two-bytes")),
        "0d060000006c616e652d6101020000000600000074782d6f6e650c000000"
        "74782d74776f2d6279746573",
    ),
    (GatewaySubmit(b"", 0, ()), "0d000000000000000000"),
    (
        GatewaySubmitReply(2, 3, 1, 250, b"mempool full"),
        "0e020300000001000000fa000000000000000c0000006d656d706f6f6c2066756c6c",
    ),
    (GatewaySubscribeCommits(7), "0f0700000000000000"),
    (GatewaySubscribeCommits(7, want_details=1), "0f070000000000000001"),
    (
        GatewayCommitNotification(9, (bytes(range(16)), bytes(range(16, 32)))),
        "1009000000000000000200000010000000000102030405060708090a0b0c0d0e0f"
        "10000000101112131415161718191a1b1c1d1e1f",
    ),
    (
        GatewayCommitNotification(
            9, (bytes(range(16)),), leader_round=4, committed_ts_ns=123456789
        ),
        "1009000000000000000100000010000000000102030405060708090a0b0c0d0e0f"
        "040000000000000015cd5b0700000000",
    ),
    (
        EpochInfo(2, bytes(range(32))),
        "110200000000000000200000000001020304050607"
        "08090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
    ),
]

FULL_CORPUS = list(GOLDEN_CORPUS) + GATEWAY_CORPUS

# (tag, stamped, mono, wall, parts) for every Blocks-shaped message kind —
# the shapes encode_blocks_frame/parse_blocks_spans cover.
BLOCKS_SHAPES = [
    (2, False, 0, 0, (b"block-one", b"block-two-bytes")),
    (2, False, 0, 0, ()),
    (2, False, 0, 0, (b"",)),
    (4, False, 0, 0, (b"resp", b"", b"x" * 300)),
    (12, True, 111, 222, (b"stamped-block",)),
    (12, True, 2**64 - 1, 0, (b"", b"a")),
]


def _shape_message(tag, stamped, mono, wall, parts):
    if tag == 2:
        return Blocks(tuple(parts))
    if tag == 4:
        return RequestBlocksResponse(tuple(parts))
    return TimestampedBlocks(tuple(parts), sent_monotonic_ns=mono,
                             sent_wall_ns=wall)


# -- pure-Python references, independent of any production gating --


def _py_encode_blocks(tag, stamped, mono, wall, parts):
    w = Writer()
    w.u8(tag)
    if stamped:
        w.u64(mono).u64(wall)
    w.u32(len(parts))
    for p in parts:
        w.bytes(p)
    return w.finish()


def _py_parse_spans(payload):
    """Reader-based walk of a blocks-shaped payload into (off, len) spans.

    Raises SerdeError with the exact Reader wording on torn frames — the
    message contract parse_blocks_spans must reproduce byte-for-byte."""
    r = Reader(payload)
    tag = r.u8()
    mono = wall = 0
    if tag == 12:
        mono, wall = r.u64(), r.u64()
    spans = []
    for _ in range(r.u32()):
        n = r.u32()
        off = r.pos
        r._take(n)
        spans.append((off, n))
    r.expect_done()
    return tag, mono, wall, spans


def _py_split_frames(buf, start, have, max_frame):
    spans = []
    while have - start >= 4:
        length = int.from_bytes(buf[start : start + 4], "little")
        if length > max_frame:
            return spans, start, length
        end = start + 4 + length
        if end > have:
            break
        spans.append((start + 4, length))
        start = end
    return spans, start, 0


def _ref_digests(part):
    full = hashlib.blake2b(part, digest_size=32).digest()
    signed = hashlib.blake2b(part[:-64] if len(part) >= 64 else b"",
                             digest_size=32).digest()
    return full, signed


def _random_parts(rng, n):
    return tuple(
        rng.randbytes(rng.choice([0, 1, 7, 63, 64, 65, 200, 1000]))
        for _ in range(n)
    )


def _build_block(rng, author=0, round_=5):
    payloads = [rng.randbytes(rng.randrange(0, 120))
                for _ in range(rng.randrange(0, 6))]
    return StatementBlock.build(
        author, round_, GENESIS, [Share(p) for p in payloads],
        signer=SIGNERS[author],
    )


# -- corpus: tags 13-17 pinned, full 1-17 coverage --


def test_gateway_corpus_byte_identical():
    seen = set()
    for message, hexpect in GATEWAY_CORPUS:
        frame = encode_message(message)
        assert frame.hex() == hexpect, type(message).__name__
        assert decode_message(frame) == message
        assert decode_message(memoryview(bytearray(frame))) == message
        seen.add(frame[0])
    assert seen == set(range(13, 18))


def test_full_corpus_covers_every_wire_tag():
    tags = {encode_message(m)[0] for m, _ in FULL_CORPUS}
    assert tags == set(range(1, 18))


# -- native primitive A/B (skip under MYSTICETI_NO_NATIVE=1) --


@needs_native
def test_encode_blocks_frame_parity():
    rng = random.Random(0x19)
    cases = list(BLOCKS_SHAPES)
    for _ in range(25):
        tag, stamped = rng.choice([(2, False), (4, False), (12, True)])
        mono = rng.randrange(2**64) if stamped else 0
        wall = rng.randrange(2**64) if stamped else 0
        cases.append((tag, stamped, mono, wall,
                      _random_parts(rng, rng.randrange(0, 8))))
    for tag, stamped, mono, wall, parts in cases:
        expect = _py_encode_blocks(tag, stamped, mono, wall, parts)
        got = native.encode_blocks_frame(tag, stamped, mono, wall, parts)
        assert got == expect
        # The production encoder lands on the same bytes for the message type.
        assert encode_message(_shape_message(tag, stamped, mono, wall,
                                             parts)) == expect
        # memoryview parts are accepted (synchronizer fan-out reuses views).
        views = tuple(memoryview(p) for p in parts)
        assert native.encode_blocks_frame(tag, stamped, mono, wall,
                                          views) == expect


@needs_native
def test_block_digests_parity():
    rng = random.Random(0xD1)
    sizes = [0, 1, 63, 64, 65, 127, 128, 129, 255, 256, 257, 1024]
    parts = [bytes(rng.randrange(256) for _ in range(n)) for n in sizes]
    parts += [rng.randbytes(rng.randrange(0, 5000)) for _ in range(20)]
    got = native.block_digests(parts)
    assert got == [_ref_digests(p) for p in parts]
    # Batched == per-item, and memoryview inputs land on the same digests.
    for p in parts[:8]:
        assert native.block_digests([p]) == [_ref_digests(p)]
        assert native.block_digests([memoryview(p)]) == [_ref_digests(p)]
    assert native.block_digests([]) == []


@needs_native
def test_parse_blocks_spans_parity():
    rng = random.Random(0x5B)
    payloads = [_py_encode_blocks(*shape) for shape in BLOCKS_SHAPES]
    payloads += [
        _py_encode_blocks(tag, tag == 12, rng.randrange(2**64),
                          rng.randrange(2**64),
                          _random_parts(rng, rng.randrange(0, 6)))
        for tag in (2, 4, 12) for _ in range(8)
    ]
    for payload in payloads:
        expect = _py_parse_spans(payload)
        tag, mono, wall, spans = native.parse_blocks_spans(payload)
        assert (tag, mono, wall) == expect[:3]
        assert list(spans) == list(expect[3])
        # Spans reconstruct the exact block bytes.
        reparsed = [payload[off : off + ln] for off, ln in spans]
        r = Reader(payload)
        r.u8()
        if tag == 12:
            r.u64(), r.u64()
        assert reparsed == [bytes(r.bytes()) for _ in range(r.u32())]


@needs_native
def test_parse_blocks_spans_torn_frames_error_shape():
    """Every prefix of every blocks-shaped payload fails with the EXACT
    Reader wording — truncated input / trailing garbage are operator-visible
    strings and must not depend on which path parsed the frame."""
    for shape in BLOCKS_SHAPES:
        payload = _py_encode_blocks(*shape)
        for cut in range(len(payload)):
            torn = payload[:cut]
            with pytest.raises(SerdeError) as py_exc:
                _py_parse_spans(torn)
            if cut == 0:
                # An empty payload is not blocks-shaped; the production
                # decoder never routes it to the native parser.
                continue
            with pytest.raises(ValueError) as nat_exc:
                native.parse_blocks_spans(torn)
            assert str(nat_exc.value) == str(py_exc.value), cut
        trailing = payload + b"\x00"
        with pytest.raises(ValueError, match="trailing garbage: 1 bytes"):
            native.parse_blocks_spans(trailing)
        with pytest.raises(SerdeError, match="trailing garbage: 1 bytes"):
            _py_parse_spans(trailing)


@needs_native
def test_parse_blocks_spans_rejects_non_blocks_tags():
    for tag in (1, 3, 5, 6, 13, 17, 200):
        with pytest.raises(ValueError,
                           match=f"not a blocks-shaped frame: tag {tag}"):
            native.parse_blocks_spans(bytes([tag]) + b"\x00" * 8)


@needs_native
def test_split_frames_parity():
    payloads = [encode_message(m) for m, _ in FULL_CORPUS]
    stream = b"".join(
        len(p).to_bytes(4, "little") + p for p in payloads
    )
    buf = bytearray(stream) + bytearray(8)  # slack past `have`, never read
    for cut in range(len(stream) + 1):
        expect = _py_split_frames(buf, 0, cut, MAX_FRAME)
        spans, start, oversized = native.split_frames(buf, 0, cut, MAX_FRAME)
        assert (list(spans), start, oversized) == \
            (list(expect[0]), expect[1], expect[2]), cut
    # Nonzero start offsets (compacted assembly buffer mid-stream).
    for start in (1, 5, len(payloads[0]) + 4):
        shifted = bytearray(b"\xee" * start) + bytearray(stream)
        expect = _py_split_frames(shifted, start, len(shifted), MAX_FRAME)
        spans, new_start, oversized = native.split_frames(
            shifted, start, len(shifted), MAX_FRAME
        )
        assert (list(spans), new_start, oversized) == \
            (list(expect[0]), expect[1], expect[2])
    # Oversized frame: both report the claimed length and stop at it.
    huge = (MAX_FRAME + 1).to_bytes(4, "little")
    pre = len(payloads[0]).to_bytes(4, "little") + payloads[0]
    evil = bytearray(pre + huge)
    expect = _py_split_frames(evil, 0, len(evil), MAX_FRAME)
    spans, start, oversized = native.split_frames(evil, 0, len(evil),
                                                  MAX_FRAME)
    assert oversized == MAX_FRAME + 1 == expect[2]
    assert (list(spans), start) == (list(expect[0]), expect[1])


# -- production-path parity (run in BOTH modes) --


def test_decode_message_torn_frames_error_shape():
    """decode_message on a torn blocks-shaped payload raises SerdeError with
    the Reader wording regardless of which parser is active — asserted
    against the reference walk here, in both tier-1 modes."""
    for shape in BLOCKS_SHAPES:
        payload = _py_encode_blocks(*shape)
        for cut in range(1, len(payload)):
            with pytest.raises(SerdeError) as ref_exc:
                _py_parse_spans(payload[:cut])
            with pytest.raises(SerdeError) as got_exc:
                decode_message(payload[:cut])
            assert str(got_exc.value) == str(ref_exc.value), (shape, cut)
        with pytest.raises(SerdeError, match="trailing garbage: 1 bytes"):
            decode_message(payload + b"\x00")


@needs_native
def test_decode_message_native_matches_forced_fallback(monkeypatch):
    """Three-way: native decode == pure decode == corpus message, including
    the zero-copy memoryview mode, for every corpus entry."""
    assert network_mod._native_parse_spans is not None
    for message, _ in FULL_CORPUS:
        frame = encode_message(message)
        native_msg = decode_message(frame)
        native_view_msg = decode_message(memoryview(bytearray(frame)))
        with monkeypatch.context() as m:
            m.setattr(network_mod, "_native_parse_spans", None)
            pure_msg = decode_message(frame)
        assert native_msg == pure_msg == message
        assert native_view_msg == message


@needs_native
def test_encode_message_native_matches_forced_fallback(monkeypatch):
    assert network_mod._native_encode_frame is not None
    for message, hexpect in FULL_CORPUS:
        with monkeypatch.context() as m:
            m.setattr(network_mod, "_native_encode_frame", None)
            pure = encode_message(message)
        assert encode_message(message) == pure
        assert pure.hex() == hexpect


class _StubTransport:
    def __init__(self):
        self.closed = False
        self.paused = False

    def close(self):
        self.closed = True

    def pause_reading(self):
        self.paused = True

    def resume_reading(self):
        self.paused = False


def _drain_receiver(stream, chunks):
    """Feed ``stream`` into a _FrameReceiver in the given chunk sizes and
    return (receiver, [frame bytes...])."""
    recv = _FrameReceiver(object(), _StubTransport())
    pos = 0
    for size in chunks:
        chunk = stream[pos : pos + size]
        pos += len(chunk)
        while chunk:
            view = recv.get_buffer(len(chunk))
            n = min(len(view), len(chunk))
            view[:n] = chunk[:n]
            recv.buffer_updated(n)
            chunk = chunk[n:]
    return recv, [bytes(f) for f in recv._frames]


def test_frame_receiver_parity_over_chunkings():
    """The assembly-buffer parse (native batch split or pure loop — whichever
    this mode runs) yields exactly the reference frame list for arbitrary
    chunk boundaries, with the torn tail left pending."""
    payloads = [encode_message(m) for m, _ in FULL_CORPUS]
    stream = b"".join(len(p).to_bytes(4, "little") + p for p in payloads)
    rng = random.Random(0xF2)
    chunkings = [[len(stream)], [1] * len(stream)]
    for _ in range(6):
        chunks, left = [], len(stream)
        while left:
            n = min(left, rng.randrange(1, 40))
            chunks.append(n)
            left -= n
        chunkings.append(chunks)
    for chunks in chunkings:
        recv, frames = _drain_receiver(stream, chunks)
        assert frames == payloads
        assert recv._start == recv._have  # nothing left unparsed
    # Torn tail: everything before the cut parses, the remainder pends.
    cut = len(stream) - 3
    recv, frames = _drain_receiver(stream[:cut], [cut])
    assert frames == payloads[:-1]
    assert recv._have - recv._start == len(payloads[-1]) + 4 - 3


def test_frame_receiver_oversized_frame_closes():
    evil = (MAX_FRAME + 1).to_bytes(4, "little") + b"boom"
    recv, frames = _drain_receiver(evil, [len(evil)])
    assert frames == []
    assert isinstance(recv._exc, SerdeError)
    assert str(recv._exc) == f"frame of {MAX_FRAME + 1} bytes exceeds MAX_FRAME"
    assert recv._transport.closed


@needs_native
def test_frame_receiver_native_matches_forced_fallback(monkeypatch):
    assert network_mod._native_split_frames is not None
    payloads = [encode_message(m) for m, _ in FULL_CORPUS]
    stream = b"".join(len(p).to_bytes(4, "little") + p for p in payloads)
    chunks = [7] * (len(stream) // 7) + [len(stream) % 7]
    _, native_frames = _drain_receiver(stream, chunks)
    with monkeypatch.context() as m:
        m.setattr(network_mod, "_native_split_frames", None)
        _, pure_frames = _drain_receiver(stream, chunks)
    assert native_frames == pure_frames == payloads


# -- from_bytes_many: batched decode+digest vs per-raw fallback --


def _assert_blocks_equal(a, b):
    assert a.reference == b.reference
    assert a.includes == b.includes
    assert a.statements == b.statements
    assert a.signature == b.signature
    assert a.to_bytes() == b.to_bytes()
    assert a.signed_digest() == b.signed_digest()
    assert a.shared_transaction_stamps() == b.shared_transaction_stamps()


def _mixed_raws(rng):
    raws = []
    for i in range(8):
        raws.append(_build_block(rng, author=i % 4, round_=3 + i).to_bytes())
    good = raws[0]
    raws.insert(2, good[: len(good) // 2])  # truncated
    raws.insert(5, good + b"\x00\x01")  # trailing garbage
    flipped = bytearray(good)
    flipped[3] ^= 0xFF
    raws.insert(7, bytes(flipped))  # may decode or not; paths must agree
    raws.append(b"")
    return raws


def test_from_bytes_many_matches_per_raw_decode():
    rng = random.Random(0xB10C)
    raws = _mixed_raws(rng)
    batched = StatementBlock.from_bytes_many(raws)
    assert len(batched) == len(raws)
    for raw, got in zip(raws, batched):
        try:
            expect = StatementBlock.from_bytes(raw)
        except SerdeError:
            expect = None
        if expect is None:
            assert got is None
        else:
            _assert_blocks_equal(got, expect)


@needs_native
def test_from_bytes_many_native_matches_forced_fallback(monkeypatch):
    assert types_mod._native_block_digests is not None
    rng = random.Random(0xAB)
    raws = _mixed_raws(rng)
    # memoryview inputs ride the receive path; both sides must accept them.
    raws = [memoryview(r) if i % 3 == 0 else r for i, r in enumerate(raws)]
    native_out = StatementBlock.from_bytes_many(raws)
    with monkeypatch.context() as m:
        m.setattr(types_mod, "_native_block_digests", None)
        m.setattr(types_mod, "_native_decode", None)
        pure_out = StatementBlock.from_bytes_many(raws)
    assert len(native_out) == len(pure_out)
    for a, b in zip(native_out, pure_out):
        if a is None or b is None:
            assert a is None and b is None
        else:
            _assert_blocks_equal(a, b)
            # The batched path precomputes what verify would re-derive.
            assert a._signed_digest is not None


def test_signed_digest_cached_on_build_and_decode():
    from mysticeti_tpu import crypto

    block = _build_block(random.Random(7))
    raw = block.to_bytes()
    assert block.signed_digest() == crypto.blake2b_256(raw[:-64])
    assert block._signed_digest is not None  # build caches, never recomputes
    decoded = StatementBlock.from_bytes(raw)
    assert decoded.signed_digest() == block.signed_digest()


# -- offload executor (tentpole d) --


def test_offload_inactive_without_native_or_under_sim(monkeypatch):
    from mysticeti_tpu.core_task import DataPlaneOffload

    off = DataPlaneOffload()
    monkeypatch.setattr("mysticeti_tpu.runtime.is_simulated", lambda: True)
    assert off.active() is False  # sim: inline path, byte-identical replay
    assert off.should_offload(10**9) is False

    off2 = DataPlaneOffload()
    monkeypatch.setattr("mysticeti_tpu.runtime.is_simulated", lambda: False)
    assert off2.active() is (native is not None)
    if native is not None:
        assert off2.should_offload(DataPlaneOffload.MIN_BATCH_BYTES)
        assert not off2.should_offload(DataPlaneOffload.MIN_BATCH_BYTES - 1)
    off.stop()
    off2.stop()


def test_offload_runs_on_worker_and_records_stage(monkeypatch):
    import threading

    from mysticeti_tpu.core_task import DataPlaneOffload
    from mysticeti_tpu.metrics import Metrics

    metrics = Metrics()
    off = DataPlaneOffload(metrics=metrics)

    async def go():
        seen = {}

        def work(x):
            seen["thread"] = threading.current_thread().name
            return x * 2

        return await off.run("decode", work, 21), seen

    result, seen = asyncio.run(go())
    off.stop()
    assert result == 42
    assert seen["thread"].startswith("dataplane-offload")
    hist = metrics.dataplane_offload_seconds.labels("decode")
    assert sum(b.get() for b in hist._buckets) >= 1


# -- build-failure marker (satellite: no retry storm) --


def test_build_failure_marker_roundtrip(tmp_path, monkeypatch):
    marker = tmp_path / "_native.buildfail"
    monkeypatch.setattr(native_pkg, "_FAIL_MARKER", str(marker))
    assert native_pkg._read_marker() == ""
    native_pkg._write_marker("abc123")
    assert native_pkg._read_marker() == "abc123"
    native_pkg._clear_marker()
    assert native_pkg._read_marker() == ""
    native_pkg._clear_marker()  # idempotent on a missing marker


def test_build_writes_marker_when_toolchain_missing(tmp_path, monkeypatch):
    marker = tmp_path / "_native.buildfail"
    monkeypatch.setattr(native_pkg, "_FAIL_MARKER", str(marker))
    monkeypatch.setattr(native_pkg.shutil, "which", lambda _name: None)
    assert native_pkg._build("deadbeef") is False
    assert native_pkg._read_marker() == "deadbeef"


def test_load_skips_rebuild_when_marker_matches(tmp_path, monkeypatch):
    """A source whose build already failed must NOT re-invoke g++ on the
    next boot; editing the source (new fingerprint) re-arms the build."""
    src = tmp_path / "mysticeti_native.cpp"
    src.write_text("int main() { return 1; }\n")
    marker = tmp_path / "_native.buildfail"
    monkeypatch.setattr(native_pkg, "_SRC", str(src))
    monkeypatch.setattr(native_pkg, "_SO", str(tmp_path / "_native.so"))
    monkeypatch.setattr(native_pkg, "_FAIL_MARKER", str(marker))
    monkeypatch.delenv("MYSTICETI_NO_NATIVE", raising=False)

    calls = []

    def fake_build(fingerprint=""):
        calls.append(fingerprint)
        native_pkg._write_marker(fingerprint)
        return False

    monkeypatch.setattr(native_pkg, "_build", fake_build)
    assert native_pkg._load() is None
    assert calls == [native_pkg._src_fingerprint()]
    # Second boot, same source: marker short-circuits, no g++ retry storm.
    assert native_pkg._load() is None
    assert len(calls) == 1
    # Source edited: fingerprint changes, the build is retried once more.
    src.write_text("int main() { return 2; }\n")
    assert native_pkg._load() is None
    assert len(calls) == 2


def test_no_native_env_disables_load(monkeypatch):
    monkeypatch.setenv("MYSTICETI_NO_NATIVE", "1")
    assert native_pkg._load() is None


# -- active_functions info series --


def test_active_functions_inventory():
    fns = active_functions()
    assert isinstance(fns, tuple)
    assert list(fns) == sorted(fns)
    if native is None:
        assert fns == ()
    else:
        assert {"block_digests", "encode_blocks_frame", "split_frames",
                "parse_blocks_spans", "decode_block", "frame_entry",
                "wal_scan"} <= set(fns)


def test_native_active_metric_and_health_field():
    from mysticeti_tpu.metrics import Metrics

    metrics = Metrics()
    gauge = metrics.mysticeti_native_active
    assert gauge.labels("any")._value.get() == (1 if native is not None else 0)
    for fn in active_functions():
        assert gauge.labels(fn)._value.get() == 1
