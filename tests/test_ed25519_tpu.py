"""JAX Ed25519 verifier parity suite — the crypto-parity tier the reference never
needed (SURVEY §4): RFC 8032 vectors + randomized accept/reject agreement with the
``cryptography`` (OpenSSL) oracle, plus field-arithmetic unit checks."""
import random

import numpy as np
import pytest

pytestmark = pytest.mark.kernel

import jax.numpy as jnp

from mysticeti_tpu.crypto import Ed25519PrivateKey, InvalidSignature

from mysticeti_tpu.ops import ed25519 as E
from mysticeti_tpu.ops import field as F

P = F.P


# ---------------------------------------------------------------------------
# Field arithmetic
# ---------------------------------------------------------------------------


def _limbs(x):
    return jnp.asarray(F.int_to_limbs(x % P))


def test_field_roundtrip_and_ops():
    import jax

    rng = random.Random(7)
    cases = [rng.randrange(P) for _ in range(20)] + [0, 1, P - 1, P - 19, 2**255 - 20]

    @jax.jit
    def all_ops(A, B):
        vc = jax.vmap(F.canonical)
        return (
            vc(jax.vmap(F.mul)(A, B)),
            vc(jax.vmap(F.add)(A, B)),
            vc(jax.vmap(F.sub)(A, B)),
        )

    a_vals = cases
    b_vals = list(reversed(cases))
    A = jnp.stack([_limbs(a) for a in a_vals])
    B = jnp.stack([_limbs(b) for b in b_vals])
    got_mul, got_add, got_sub = all_ops(A, B)
    for i, (a, b) in enumerate(zip(a_vals, b_vals)):
        assert F.limbs_to_int(got_mul[i]) == a * b % P
        assert F.limbs_to_int(got_add[i]) == (a + b) % P
        assert F.limbs_to_int(got_sub[i]) == (a - b) % P


def test_field_mul_carry_saturation_regression():
    """Regression: a product whose carry ripple lands a full 2^13 on limb 19 —
    the dropped-carry bug found via a real signature (all-zero-seed key,
    msg=0x3f*32).  The reduction must fold that bit, not drop it."""
    e = [3118, 7793, 4844, 2951, 244, 530, 1793, 2089, 4981, 369,
         7492, 2771, 7811, 8145, 3290, 7683, 2110, 4276, 4727, 297]
    f = [6206, 1368, 1220, 7754, 597, 386, 3963, 7916, 5491, 5782,
         3507, 4421, 4725, 3696, 3677, 6152, 1606, 7840, 8029, 388]
    A = jnp.asarray(np.array(e, np.int32))
    B = jnp.asarray(np.array(f, np.int32))
    got = F.limbs_to_int(F.canonical(F.mul(A, B)))
    want = F.limbs_to_int(A) * F.limbs_to_int(B) % P
    assert got == want


def test_field_invert_and_sqrt_exponent():
    import jax

    rng = random.Random(8)
    a = rng.randrange(1, P)
    A = _limbs(a)

    @jax.jit
    def both(A):
        return F.canonical(F.invert(A)), F.canonical(F.pow22523(A))

    inv, sqrt_e = both(A)
    assert F.limbs_to_int(inv) == pow(a, P - 2, P)
    assert F.limbs_to_int(sqrt_e) == pow(a, (P - 5) // 8, P)


def test_field_partial_form_chain():
    """Long chains of ops keep the partial-form invariant (no int32 overflow)."""
    import jax

    rng = random.Random(9)
    a = rng.randrange(P)

    @jax.jit
    def chain(A):
        def body(_, st):
            A, max_limb = st
            A = F.mul(A, F.sub(A, F.add(A, A)))
            return A, jnp.maximum(max_limb, jnp.max(A))

        return jax.lax.fori_loop(0, 60, body, (A, jnp.int32(0)))

    A, max_limb = chain(_limbs(a))
    x = a
    for _ in range(60):
        x = (x * ((x - 2 * x) % P)) % P
    assert int(max_limb) <= (1 << 13) + 64
    assert F.limbs_to_int(F.canonical(A)) == x


# ---------------------------------------------------------------------------
# RFC 8032 vectors
# ---------------------------------------------------------------------------

RFC8032_VECTORS = [
    # (public key, message, signature) hex
    (
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249015"
        "55fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


def test_rfc8032_vectors():
    pks = [bytes.fromhex(pk) for pk, _, _ in RFC8032_VECTORS]
    msgs = [bytes.fromhex(m) for _, m, _ in RFC8032_VECTORS]
    sigs = [bytes.fromhex(s) for _, _, s in RFC8032_VECTORS]
    assert E.verify_batch(pks, msgs, sigs).all()


def test_rfc8032_corrupted():
    pks = [bytes.fromhex(pk) for pk, _, _ in RFC8032_VECTORS]
    msgs = [bytes.fromhex(m) for _, m, _ in RFC8032_VECTORS]
    sigs = [bytearray(bytes.fromhex(s)) for _, _, s in RFC8032_VECTORS]
    sigs[0][3] ^= 0x40  # corrupt R
    sigs[1][40] ^= 0x01  # corrupt S
    msgs[2] = msgs[2] + b"x"  # corrupt message
    res = E.verify_batch(pks, msgs, [bytes(s) for s in sigs])
    assert not res.any()


# ---------------------------------------------------------------------------
# Randomized parity with the OpenSSL oracle
# ---------------------------------------------------------------------------


def _oracle_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    from mysticeti_tpu.crypto import Ed25519PublicKey

    try:
        Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
        return True
    except Exception:
        return False


def test_randomized_oracle_parity():
    """Accept/reject must agree bit-for-bit with OpenSSL over a mixed batch of
    valid, corrupted, wrong-key, and garbage signatures (BASELINE config #2)."""
    rng = random.Random(1234)
    pks, msgs, sigs = [], [], []
    for i in range(48):
        key = Ed25519PrivateKey.from_private_bytes(bytes(rng.randrange(256) for _ in range(32)))
        pk = key.public_key().public_bytes_raw()
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        mode = i % 6
        if mode == 1:
            sig = bytearray(sig)
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(sig)
        elif mode == 2:
            msg = bytes(rng.randrange(256) for _ in range(32))  # different message
        elif mode == 3:
            other = Ed25519PrivateKey.generate()
            pk = other.public_key().public_bytes_raw()  # wrong key
        elif mode == 4:
            sig = bytes(rng.randrange(256) for _ in range(64))  # garbage sig
        elif mode == 5:
            pk = bytes(rng.randrange(256) for _ in range(32))  # garbage key
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)

    ours = E.verify_batch(pks, msgs, sigs)
    oracle = np.array([_oracle_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)])
    assert (ours == oracle).all(), (
        f"mismatch at {np.nonzero(ours != oracle)[0]}: ours={ours} oracle={oracle}"
    )


def test_noncanonical_s_rejected():
    key = Ed25519PrivateKey.generate()
    pk = key.public_key().public_bytes_raw()
    msg = b"m" * 32
    sig = bytearray(key.sign(msg))
    s = int.from_bytes(sig[32:], "little") + E.L  # s' = s + L: same equation mod L
    if s < 2**256:
        sig[32:] = s.to_bytes(32, "little")
        res = E.verify_batch([pk], [msg], [bytes(sig)])
        assert not res[0], "malleable s must be rejected"


def test_block_signature_integration():
    """The verifier accepts real block signatures produced by the framework's
    Signer over the signed digest (crypto.rs:199-223 layering)."""
    from mysticeti_tpu import crypto
    from mysticeti_tpu.types import StatementBlock

    signer = crypto.Signer.from_seed(b"tpu-integration-test-seed-000000")
    blocks = [
        StatementBlock.build(0, r, [], (), signer=signer) for r in range(1, 9)
    ]
    pks = [signer.public_key.bytes] * len(blocks)
    msgs = [b.signed_digest() for b in blocks]
    sigs = [b.signature for b in blocks]
    assert E.verify_batch(pks, msgs, sigs).all()
    # And rejects a signature transplanted between blocks.
    sigs[0] = blocks[1].signature
    assert not E.verify_batch(pks, msgs, sigs)[0]
