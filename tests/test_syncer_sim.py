"""Syncer-tier randomized deterministic simulation (syncer.rs:183-318 parity).

The middle testing tier between core-level manual exchange and the whole-stack
network simulation: Syncer objects driven directly as simulator states, with
seeded random block-delivery latencies and per-round leader timeouts.  Shakes
out signal/timeout interleavings cheaply across many seeds.
"""
import asyncio
import os

import pytest

from mysticeti_tpu.commit_observer import TestCommitObserver
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.net_sync import AsyncSignals
from mysticeti_tpu.runtime.simulated import run_simulation
from mysticeti_tpu.syncer import Syncer
from mysticeti_tpu.types import AuthoritySet

from helpers import open_core

WAVE = 3
LEADER_TIMEOUT_S = 1.0


async def _run_syncers(n, tmp_dir, virtual_seconds):
    """N syncers; every new own block is delivered to every peer after a
    seeded random 100-1800 ms latency (the reference's latency range)."""
    loop = asyncio.get_event_loop()
    rng = loop.rng
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    all_authorities = AuthoritySet()
    for a in range(n):
        all_authorities.insert(a)

    syncers = []
    for a in range(n):
        core = open_core(committee, a, tmp_dir, signers[a])
        observer = TestCommitObserver(core.block_store, committee)
        syncers.append(Syncer(core, WAVE, AsyncSignals(), observer))

    cursors = [[0] * n for _ in range(n)]  # cursors[src][dst]: delivered round

    def relay_from(src: int) -> None:
        """Ship src's new blocks (all authorities' blocks it stores) to peers."""
        for dst in range(n):
            if dst == src:
                continue
            blocks = syncers[src].core.block_store.get_own_blocks(
                cursors[src][dst], 100
            )
            if not blocks:
                continue
            cursors[src][dst] = max(b.round() for b in blocks)
            delay = 0.1 + rng.random() * 1.7
            loop.call_later(delay, deliver, dst, [b.to_bytes() for b in blocks])

    def deliver(dst: int, raw_blocks) -> None:
        from mysticeti_tpu.types import StatementBlock

        blocks = [StatementBlock.from_bytes(r) for r in raw_blocks]
        syncers[dst].add_blocks(blocks, all_authorities.copy())
        relay_from(dst)

    async def leader_timeout(idx: int) -> None:
        syncer = syncers[idx]
        while True:
            waiter = syncer.signals.round_notify.subscribe()
            round_at_start = syncer.signals.current_round
            try:
                await asyncio.wait_for(waiter.wait(), timeout=LEADER_TIMEOUT_S)
            except asyncio.TimeoutError:
                syncer.force_new_block(round_at_start + 1, all_authorities.copy())
                relay_from(idx)

    async def pump(idx: int) -> None:
        # Periodically relay: covers blocks created by commits/proposals that
        # did not pass through deliver().
        while True:
            await asyncio.sleep(0.25)
            relay_from(idx)

    for idx, s in enumerate(syncers):
        s.force_new_block(1, all_authorities.copy())
        relay_from(idx)
    tasks = [asyncio.ensure_future(leader_timeout(i)) for i in range(n)] + [
        asyncio.ensure_future(pump(i)) for i in range(n)
    ]
    await asyncio.sleep(virtual_seconds)
    for t in tasks:
        t.cancel()
    return syncers


@pytest.mark.parametrize("seed", range(10))
def test_randomized_syncers_commit_consistently(tmp_path, seed):
    d = tmp_path / f"s{seed}"
    d.mkdir()
    syncers = run_simulation(_run_syncers(4, str(d), 30.0), seed=seed)
    sequences = [list(s.commit_observer.committed_leaders) for s in syncers]
    # Progress: ~30 virtual seconds of 1 s leader timeouts must commit well
    # beyond a trickle on every node (catches 2x liveness regressions).
    assert all(len(seq) >= 8 for seq in sequences), [len(s) for s in sequences]
    # Safety: all sequences are prefixes of the longest.
    longest = max(sequences, key=len)
    for seq in sequences:
        assert seq == longest[: len(seq)], f"fork: {seq} vs {longest}"
