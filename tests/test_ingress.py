"""Overload-resilient ingress plane: mempool caps/dedup/fairness units, AIMD
admission semantics, gateway wire roundtrips + live submit/commit stream, the
soft-cap/dedup counter satellites, and the seeded deterministic overload sim
(10-node, 3x offered load: committed tx/s inside the stated band of the 1x
run, shed log byte-identical across same-seed runs, no client lane starved,
dedup under duplicate flood)."""
import asyncio
import os
import struct
import sys

import pytest

from mysticeti_tpu.config import IngressParameters
from mysticeti_tpu.ingress import (
    SHED_ADMISSION,
    SHED_DUPLICATE,
    SHED_LANE_CAP,
    SHED_MEMPOOL_BYTES,
    SHED_MEMPOOL_TXS,
    AdmissionController,
    IngressGateway,
    IngressPlane,
    Mempool,
    OverloadScenario,
    ingress_key,
    run_overload_sim,
)
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.network import (
    GATEWAY_ACK,
    GATEWAY_QUEUED,
    GATEWAY_SHED,
    GatewayCommitNotification,
    GatewaySubmit,
    GatewaySubmitReply,
    GatewaySubscribeCommits,
    decode_message,
    encode_message,
)
from mysticeti_tpu.serde import SerdeError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

pytestmark = pytest.mark.ingress


def _txs(n, size=32, tag=0):
    return [
        struct.pack("<QQ", tag, i) + b"\x00" * (size - 16) for i in range(n)
    ]


# -- mempool units ------------------------------------------------------------


def test_mempool_count_cap_sheds_typed():
    pool = Mempool(IngressParameters(mempool_max_transactions=5))
    accepted, sheds = pool.submit("a", _txs(8))
    assert accepted == 5
    assert sheds == {SHED_MEMPOOL_TXS: 3}
    assert pool.pending() == 5


def test_mempool_byte_cap_sheds_typed():
    pool = Mempool(IngressParameters(mempool_max_bytes=100))
    accepted, sheds = pool.submit("a", _txs(5, size=40))
    assert accepted == 2  # third would cross 100 bytes
    assert sheds == {SHED_MEMPOOL_BYTES: 3}
    assert pool.pending_bytes() == 80


def test_mempool_lane_cap_and_dedup_flood():
    pool = Mempool(
        IngressParameters(lane_max_transactions=4, dedup_window=1000)
    )
    batch = _txs(4)
    accepted, sheds = pool.submit("a", batch)
    assert accepted == 4 and not sheds
    # Duplicate flood: identical bytes must shed as duplicate, not requeue.
    accepted, sheds = pool.submit("a", batch)
    assert accepted == 0
    assert sheds == {SHED_DUPLICATE: 4}
    # Fresh txs beyond the lane cap shed as lane_cap.
    accepted, sheds = pool.submit("a", _txs(2, tag=9))
    assert accepted == 0
    assert sheds == {SHED_LANE_CAP: 2}
    # Draining frees the lane; previously-shed duplicates stay duplicates
    # (the dedup window outlives the queue residency).
    assert len(pool.drain(10)) == 4
    accepted, sheds = pool.submit("a", batch)
    assert accepted == 0 and sheds == {SHED_DUPLICATE: 4}


def test_mempool_lane_table_evicts_empty_lanes():
    """MAX_LANES must not be a LIFETIME cap: gateway connections mint one
    lane each, so after the table fills, a new client must evict the oldest
    drained-empty lane instead of being shed forever (permanent ingress DoS
    after 1024 cumulative connections otherwise)."""
    import mysticeti_tpu.ingress as ingress_mod

    pool = Mempool(IngressParameters())
    old_cap = ingress_mod.MAX_LANES
    ingress_mod.MAX_LANES = 4
    try:
        for i in range(4):
            accepted, sheds = pool.submit(f"conn-{i}", _txs(2, tag=i))
            assert accepted == 2 and not sheds
        # Table full, every lane non-empty: genuine pressure, typed shed.
        accepted, sheds = pool.submit("conn-4", _txs(2, tag=99))
        assert accepted == 0 and sheds == {SHED_LANE_CAP: 2}
        # Drain empties the lanes; the next new client evicts one and gets
        # admitted — a churn of short-lived connections never wedges ingress.
        pool.drain(100)
        accepted, sheds = pool.submit("conn-5", _txs(2, tag=100))
        assert accepted == 2 and not sheds
        assert len(pool._lanes) <= 4
    finally:
        ingress_mod.MAX_LANES = old_cap


def test_mempool_wrr_no_lane_starved():
    pool = Mempool(IngressParameters())
    pool.submit("whale", _txs(1000, tag=1))
    pool.submit("small-1", _txs(10, tag=2))
    pool.submit("small-2", _txs(10, tag=3))
    drained = pool.drain(100)
    assert len(drained) == 100
    stats = pool.lane_stats()
    # One WRR cycle serves every non-empty lane before any second turn: the
    # whale cannot starve the small lanes regardless of queue depth.
    assert stats["small-1"]["drained"] > 0
    assert stats["small-2"]["drained"] > 0
    assert stats["whale"]["drained"] > 0


def test_mempool_priority_lane_weight():
    pool = Mempool(IngressParameters(priority_weight=4))
    pool.submit("bulk", _txs(400, tag=1))
    pool.submit("urgent", _txs(400, tag=2), priority=True)
    pool.drain(320)
    stats = pool.lane_stats()
    # Priority lanes take priority_weight chunks per WRR turn.
    assert stats["urgent/priority"]["drained"] >= 3 * stats["bulk"]["drained"]


# -- admission controller -----------------------------------------------------


def _controller(**over):
    defaults = dict(
        admission_initial_tx_s=1000.0,
        admission_min_tx_s=100.0,
        admission_additive_tx_s=50.0,
        admission_decrease_factor=0.5,
        high_watermark=0.8,
        low_watermark=0.4,
    )
    defaults.update(over)
    clock = {"t": 0.0}
    ctl = AdmissionController(
        IngressParameters(**defaults), clock=lambda: clock["t"]
    )
    return ctl, clock


def test_admission_token_bucket_sheds_tail_with_retry_hint():
    ctl, clock = _controller()
    admitted, retry = ctl.admit(400)  # burst window = 0.5s * 1000/s
    assert admitted == 400 and retry == 0
    admitted, retry = ctl.admit(400)
    assert admitted == 100  # bucket drained to 100 tokens
    assert retry >= 25  # the deficit-derived hint, floored
    clock["t"] = 1.0
    admitted, _ = ctl.admit(400)
    assert admitted == 400  # refilled at the rate


def test_admission_aimd_cut_floor_and_recovery():
    ctl, _clock = _controller()
    assert ctl.tick({"mempool_occupancy": 0.9}) == ["mempool"]
    assert ctl.rate == 500.0 and ctl.shed_mode
    for _ in range(10):
        ctl.tick({"mempool_occupancy": 0.9})
    assert ctl.rate == 100.0  # the floor holds
    assert ctl.tick({"mempool_occupancy": 0.1}) == []
    assert ctl.rate == 150.0 and not ctl.shed_mode  # additive recovery
    # Hysteresis: between the watermarks the rate holds and mode is sticky.
    before = ctl.rate
    ctl.tick({"mempool_occupancy": 0.6})
    assert ctl.rate == before


def test_admission_core_queue_and_wal_signals():
    ctl, _clock = _controller()
    reasons = ctl.tick(
        {
            "mempool_occupancy": 0.5,
            "core_queue_depth": 30,
            "core_queue_capacity": 32,
            "wal_backlog": True,
        }
    )
    assert reasons == ["core-queue", "wal"]
    # A WAL backlog with a DRAINED mempool is normal at load — not congestion.
    ctl2, _ = _controller()
    assert ctl2.tick({"mempool_occupancy": 0.1, "wal_backlog": True}) == []


# -- plane accounting ---------------------------------------------------------


def test_plane_every_rejection_counted_and_logged():
    metrics = Metrics()
    plane = IngressPlane(
        IngressParameters(
            mempool_max_transactions=10,
            admission=False,
        ),
        metrics=metrics,
        clock=lambda: 1.5,
    )
    result = plane.submit("c1", _txs(16))
    assert result.status == GATEWAY_SHED
    assert result.accepted == 10 and result.shed == 6
    assert result.reason == SHED_MEMPOOL_TXS
    assert result.retry_after_ms >= 25
    # The metric family, the reason ledger, and the structured log agree.
    assert plane.shed_total() == 6
    assert plane.shed_by_reason == {SHED_MEMPOOL_TXS: 6}
    assert (
        metrics.mysticeti_ingress_shed_total.labels(SHED_MEMPOOL_TXS)
        ._value.get()
        == 6
    )
    assert metrics.mysticeti_ingress_admitted_total._value.get() == 10
    (entry,) = plane.shed_log
    assert entry == {
        "t": 1.5,
        "client": "c1",
        "reason": SHED_MEMPOOL_TXS,
        "n": 6,
        "retry_after_ms": entry["retry_after_ms"],
    }
    # Same seed-free inputs -> byte-identical canonical log.
    assert plane.shed_log_bytes() == plane.shed_log_bytes()


def test_plane_status_ack_queued_shed():
    plane = IngressPlane(
        IngressParameters(
            mempool_max_transactions=10,
            queued_watermark=0.5,
            admission=False,
        )
    )
    assert plane.submit("c", _txs(2)).status == GATEWAY_ACK
    assert plane.submit("c", _txs(4, tag=1)).status == GATEWAY_QUEUED
    assert plane.submit("c", _txs(8, tag=2)).status == GATEWAY_SHED


def test_plane_shed_mode_transition_recorded():
    class _Rec:
        def __init__(self):
            self.events = []

        def record(self, kind, **fields):
            self.events.append((kind, fields))

    rec = _Rec()
    plane = IngressPlane(
        IngressParameters(mempool_max_transactions=10, admission=True),
        recorder=rec,
    )
    plane.submit("c", _txs(10))
    plane.tick()  # occupancy 1.0 >= high watermark -> shed mode on
    plane.drain(10)
    plane.tick()  # drained -> recovery, shed mode off
    kinds = [(k, f["on"]) for k, f in rec.events if k == "shed-mode"]
    assert kinds == [("shed-mode", True), ("shed-mode", False)]


def test_health_probe_embeds_ingress_state():
    from mysticeti_tpu.health import HealthProbe

    plane = IngressPlane(IngressParameters())
    plane.submit("c", _txs(3))

    class _FakeWal:
        def pending(self):
            return False

    class _FakeStore:
        def last_seen_by_authority(self, a):
            return 0

    class _FakeCore:
        wal_writer = _FakeWal()
        block_store = _FakeStore()

        def current_round(self):
            return 0

    probe = HealthProbe(0, 4, clock=lambda: 0.0)
    probe.attach(core=_FakeCore(), ingress=plane)
    snapshot = probe.sample()
    assert snapshot["ingress"]["mempool_transactions"] == 3
    assert "admitted_rate_tx_s" in snapshot["ingress"]
    assert snapshot["ingress"] == plane.health_state()


# -- soft-cap / dedup counter satellites -------------------------------------


def test_legacy_soft_cap_truncation_counts(monkeypatch):
    from mysticeti_tpu import block_handler as bh
    from mysticeti_tpu.committee import Committee

    monkeypatch.setattr(bh, "SOFT_MAX_PROPOSED_PER_BLOCK", 10)
    metrics = Metrics()
    handler = bh.BenchmarkFastPathBlockHandler(
        Committee.new_test([1] * 4), 0, metrics=metrics
    )
    handler.submit(_txs(25))
    received = handler._receive_with_limit()
    assert len(received) == 10
    # The re-queued remainder is visible, not silent (PR 10 lesson).
    assert (
        metrics.mysticeti_ingress_shed_total.labels("soft_cap_deferred")
        ._value.get()
        == 15
    )
    # Nothing was lost: the remainder drains on later proposals — and
    # re-truncating the already-counted remainder must NOT count it again
    # (each transaction's deferral lands on the series exactly once).
    handler.pending_transactions = 0
    assert len(handler._receive_with_limit()) == 10
    handler.pending_transactions = 0
    assert len(handler._receive_with_limit()) == 5
    assert (
        metrics.mysticeti_ingress_shed_total.labels("soft_cap_deferred")
        ._value.get()
        == 15
    )


def test_aggregator_dedup_counters(tmp_path):
    from mysticeti_tpu.block_handler import _LoggingAggregator
    from mysticeti_tpu.log import TransactionLog

    metrics = Metrics()
    agg = _LoggingAggregator(
        TransactionLog.start(str(tmp_path / "certified.txt")), metrics=metrics
    )
    agg.duplicate_transaction("locator", 1)
    agg.duplicate_transaction("locator", 2)
    agg.unknown_transaction("locator", 3)
    dedup = metrics.mysticeti_transaction_dedup_total
    assert dedup.labels("duplicate")._value.get() == 2
    assert dedup.labels("unknown")._value.get() == 1


# -- gateway wire -------------------------------------------------------------


def test_gateway_wire_roundtrip():
    for msg in (
        GatewaySubmit(b"lane-a", 1, (b"tx-1", b"tx-2" * 100)),
        GatewaySubmit(b"", 0, ()),
        GatewaySubmitReply(GATEWAY_SHED, 3, 2, 250, b"admission"),
        GatewaySubmitReply(GATEWAY_ACK, 5, 0, 0, b""),
        GatewaySubscribeCommits(0),
        GatewaySubscribeCommits(12345),
        GatewayCommitNotification(7, (b"k" * 16, b"j" * 16)),
    ):
        assert decode_message(encode_message(msg)) == msg


def test_gateway_tags_version_skew_resets():
    # The soft-extension contract (docs/wire-format.md §7): an endpoint that
    # predates a tag rejects the frame (SerdeError -> connection reset), so
    # gateway tags are safe to add exactly like tags 8-12 were.  A tag from
    # the FUTURE must behave the same on us.
    with pytest.raises(SerdeError):
        decode_message(bytes([17]) + b"\x00" * 8)
    # Truncated gateway frames reject rather than misparse.
    with pytest.raises(SerdeError):
        decode_message(encode_message(GatewaySubmit(b"c", 0, (b"tx",)))[:-2])


def test_gateway_live_submit_and_commit_stream():
    from mysticeti_tpu.network import _read_frame, _write_frame

    async def main():
        plane = IngressPlane(
            IngressParameters(mempool_max_transactions=8, admission=False)
        )
        gateway = await IngressGateway(plane, "127.0.0.1", 0).start()
        port = gateway._server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            # SUBMIT -> ACK.
            _write_frame(
                writer,
                encode_message(GatewaySubmit(b"lane", 0, tuple(_txs(3)))),
            )
            await writer.drain()
            reply = decode_message(await _read_frame(reader))
            assert isinstance(reply, GatewaySubmitReply)
            assert reply.status == GATEWAY_ACK and reply.accepted == 3
            assert plane.mempool.lane_stats()["lane"]["pending"] == 3
            # SUBMIT past the cap -> typed SHED with retry hint.
            _write_frame(
                writer,
                encode_message(
                    GatewaySubmit(b"lane", 0, tuple(_txs(8, tag=1)))
                ),
            )
            await writer.drain()
            reply = decode_message(await _read_frame(reader))
            assert reply.status == GATEWAY_SHED
            assert reply.accepted == 5 and reply.shed == 3
            assert reply.reason == SHED_MEMPOOL_TXS.encode()
            assert reply.retry_after_ms > 0
            # Commit stream: subscribe, then feed the committed sequence.
            _write_frame(
                writer, encode_message(GatewaySubscribeCommits(0))
            )
            await writer.drain()
            await asyncio.sleep(0.05)  # subscription registered

            class _Commit:
                def __init__(self, height, blocks):
                    self.height = height
                    self.blocks = blocks

            class _Block:
                def __init__(self, statements):
                    self.statements = statements

            from mysticeti_tpu.types import Share

            tx = _txs(1, tag=2)[0]
            plane.note_committed([_Commit(4, [_Block([Share(tx)])])])
            note = decode_message(await _read_frame(reader))
            assert isinstance(note, GatewayCommitNotification)
            assert note.height == 4
            assert note.keys == (ingress_key(tx),)
            # Re-subscribe REPLACES the filter (wire-format §5b): the old
            # sink is removed, and heights at or below the new from_height
            # are suppressed while later ones flow.
            _write_frame(
                writer, encode_message(GatewaySubscribeCommits(10))
            )
            await writer.drain()
            await asyncio.sleep(0.05)
            assert len(plane._commit_sinks) == 1
            tx2, tx3 = _txs(2, tag=3)
            plane.note_committed([_Commit(10, [_Block([Share(tx2)])])])
            plane.note_committed([_Commit(11, [_Block([Share(tx3)])])])
            note = decode_message(await _read_frame(reader))
            assert note.height == 11
            assert note.keys == (ingress_key(tx3),)
        finally:
            writer.close()
            await gateway.stop()

    asyncio.run(main())


# -- closed-loop generator ----------------------------------------------------


def test_closed_loop_generator_honors_retry_after():
    from mysticeti_tpu.runtime.simulated import run_simulation
    from mysticeti_tpu.transactions_generator import TransactionGenerator

    class _SheddingPlane:
        """Sheds everything with a 500 ms retry hint for the first second,
        then accepts everything."""

        def __init__(self):
            self.calls = []

        def submit(self, batch):
            from mysticeti_tpu.ingress import SubmitResult
            from mysticeti_tpu.network import GATEWAY_ACK, GATEWAY_SHED

            t = asyncio.get_event_loop().time()
            self.calls.append((round(t, 3), len(batch)))
            if t < 1.0:
                return SubmitResult(GATEWAY_SHED, 0, len(batch), 500, "admission")
            return SubmitResult(GATEWAY_ACK, len(batch), 0)

    plane = _SheddingPlane()
    gen = TransactionGenerator(
        submit=plane.submit, seed=3, tps=100, transaction_size=32,
        closed_loop=True,
    )

    async def main():
        gen.start()
        await asyncio.sleep(3.0)
        gen.stop()

    run_simulation(main(), seed=3)
    assert gen.shed_observed > 0
    assert gen.retries > 0  # the shed tail was re-offered after the hint
    assert gen.accepted > 0
    # During the shed window the client backed off: submission gaps of at
    # least the 500 ms retry hint exist (an open-loop client ticks at 100 ms).
    shed_window = [t for t, _ in plane.calls if t < 1.0]
    gaps = [b - a for a, b in zip(shed_window, shed_window[1:])]
    assert gaps and min(gaps) >= 0.45


def test_overload_schedule_multiplier():
    from mysticeti_tpu.transactions_generator import (
        TransactionGenerator,
        parse_overload_schedule,
    )

    schedule = parse_overload_schedule("0:1, 30:3, 60:5")
    assert schedule == [(0.0, 1.0), (30.0, 3.0), (60.0, 5.0)]
    gen = TransactionGenerator(
        submit=lambda b: None, seed=0, tps=100, transaction_size=32,
        overload_schedule=schedule,
    )
    assert gen.multiplier(0.0) == 1.0
    assert gen.multiplier(29.9) == 1.0
    assert gen.multiplier(30.0) == 3.0
    assert gen.multiplier(61.0) == 5.0


def test_ingress_parameters_yaml_roundtrip(tmp_path):
    from mysticeti_tpu.config import Parameters

    p = Parameters()
    p.ingress.mempool_max_transactions = 777
    p.ingress.gateway_port_base = 9000
    path = str(tmp_path / "parameters.yaml")
    p.dump(path)
    loaded = Parameters.load(path)
    assert loaded.ingress.mempool_max_transactions == 777
    assert loaded.ingress.gateway_port_base == 9000
    # A pre-r11 file without the block loads with defaults.
    with open(path) as f:
        text = f.read()
    import yaml

    raw = yaml.safe_load(text)
    raw.pop("ingress")
    with open(path, "w") as f:
        yaml.safe_dump(raw, f)
    assert Parameters.load(path).ingress.enabled


# -- the seeded deterministic overload sim (acceptance) -----------------------


def _scenario(mult, seed=11, **over):
    defaults = dict(
        seed=seed,
        nodes=10,
        duration_s=10.0,
        base_tps=300,
        max_per_proposal=30,
        mempool_max_transactions=600,
        multiplier_schedule=[(0.0, mult)],
        clients_per_node=3,
        duplicate_flood=True,
    )
    defaults.update(over)
    return OverloadScenario(**defaults)


@pytest.mark.slow
def test_overload_sim_ten_nodes_graceful_degradation():
    """The full acceptance scenario at 10 nodes (slow tier twin of the
    8-node tier-1 run below — same assertions, bigger committee)."""
    _assert_overload(nodes=10)


def test_overload_sim_graceful_degradation_tier1():
    _assert_overload(nodes=10, duration_s=8.0)


def _assert_overload(**over):
    r1 = run_overload_sim(_scenario(1.0, **over))
    r3 = run_overload_sim(_scenario(3.0, **over))
    r3b = run_overload_sim(_scenario(3.0, **over))

    # Graceful degradation: committed throughput at 3x offered stays within
    # the stated band of the 1x run (>= 80%) — no collapse past saturation.
    assert r3.committed_tx >= 0.8 * r1.committed_tx, (
        r1.committed_tx,
        r3.committed_tx,
    )
    # Overload actually happened and every rejection is accounted for.
    assert r3.shed_by_reason, "3x offered load must shed"
    assert r3.shed_mode_entered
    assert r3.offered_tx > r3.admitted_tx
    assert sum(r3.shed_by_reason.values()) + r3.admitted_tx == r3.offered_tx
    # Dedup under duplicate flood.
    assert r3.shed_by_reason.get(SHED_DUPLICATE, 0) > 0
    # Fairness: no client lane starved on the overloaded node.
    drained = {
        lane: s["drained"]
        for lane, s in r3.lane_stats.items()
        if lane.startswith("client-")
    }
    assert len(drained) == 3
    assert min(drained.values()) > 0
    assert min(drained.values()) >= 0.5 * max(drained.values()), drained
    # Seeded determinism: the shed schedule is byte-identical across
    # same-seed runs, and so is everything downstream of it.
    assert r3.shed_log_bytes == r3b.shed_log_bytes
    assert r3.shed_schedule_digest == r3b.shed_schedule_digest
    assert r3.committed_tx == r3b.committed_tx
    assert r3.commit_heights == r3b.commit_heights
    # Commit safety survived overload on every node (prefix consistency is
    # audited inside run_overload_sim by the chaos SafetyChecker).
    assert all(h > 0 for h in r3.commit_heights.values())
