"""Parity tests for the fused raw-bytes Ed25519 verification path.

The fused path (pack_bytes -> device SHA-512 + mod-L + parse + ladder) must
agree bit-for-bit with the CPU oracle (cryptography/OpenSSL) — the same
accept/reject contract the consensus layer depends on (BASELINE config #2).
"""
import random

import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.kernel

from mysticeti_tpu.crypto import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
    InvalidSignature,
)

from mysticeti_tpu.ops import ed25519 as E
from mysticeti_tpu.ops import scalar as S


def _keypair(rng):
    key = Ed25519PrivateKey.from_private_bytes(
        bytes(rng.randrange(256) for _ in range(32))
    )
    return key, key.public_key().public_bytes_raw()


def _oracle(pk: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
        return True
    except (InvalidSignature, ValueError):
        return False


def _fused(pks, msgs, sigs) -> np.ndarray:
    words, s_words, host_ok = E.pack_bytes(pks, msgs, sigs)
    return np.asarray(
        E.verify_fused_kernel(
            jnp.asarray(words), jnp.asarray(s_words), jnp.asarray(host_ok)
        )
    )


def test_fused_accepts_valid_and_rejects_corrupted():
    rng = random.Random(10)
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(24):
        key, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        if i % 4 == 1:  # corrupt signature R
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        elif i % 4 == 2:  # corrupt message
            msg = bytes([msg[0] ^ 1]) + msg[1:]
        elif i % 4 == 3:  # wrong key
            _, pk = _keypair(rng)
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
        expect.append(_oracle(pk, msg, sig))
    got = _fused(pks, msgs, sigs)
    assert list(got) == expect
    assert any(expect) and not all(expect)


def test_fused_rejects_noncanonical_s():
    """s' = s + L is congruent mod L but non-canonical: RFC 8032 / OpenSSL
    reject it, and so must the kernel (malleability defense)."""
    rng = random.Random(11)
    key, pk = _keypair(rng)
    msg = bytes(32)
    sig = key.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    forged = sig[:32] + (s + S.L).to_bytes(32, "little")
    assert not _oracle(pk, msg, forged)
    got = _fused([pk, pk], [msg, msg], [sig, forged])
    assert list(got) == [True, False]


def test_fused_rejects_noncanonical_a_and_r():
    """Point encodings with y >= p must be rejected (A via the explicit
    canonicity check, R via the exact raw-limb compare)."""
    rng = random.Random(12)
    key, pk = _keypair(rng)
    msg = bytes(range(32))
    sig = key.sign(msg)
    # Non-canonical A: p + small y (valid curve ys: p+1 has x solution? just
    # require reject regardless — the oracle rejects any y >= p encoding).
    bad_a = (S.P + 3).to_bytes(32, "little")
    # Non-canonical R likewise.
    bad_r_sig = (S.P + 3).to_bytes(32, "little") + sig[32:]
    expect = [_oracle(pk, msg, sig), _oracle(bad_a, msg, sig), _oracle(pk, msg, bad_r_sig)]
    got = _fused([pk, bad_a, pk], [msg] * 3, [sig, sig, bad_r_sig])
    assert list(got) == expect == [True, False, False]


def test_fused_matches_host_path():
    """Fused device packing must agree with the host pack_batch path on the
    same inputs (valid + invalid mix)."""
    rng = random.Random(13)
    pks, msgs, sigs = [], [], []
    for i in range(16):
        key, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        if i % 3 == 2:
            sig = sig[:32] + bytes([sig[32] ^ 255]) + sig[33:]
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
    fused = _fused(pks, msgs, sigs)
    host = np.asarray(E.verify_kernel(*[jnp.asarray(x) for x in E.pack_batch(pks, msgs, sigs)]))
    assert list(fused) == list(host)


def test_verify_batch_end_to_end_padding_and_malformed():
    """verify_batch: odd sizes (bucket padding), malformed lengths masked."""
    rng = random.Random(14)
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(37):
        key, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        ok = True
        if i == 5:
            sig = sig[:40]  # malformed length
            ok = False
        elif i == 11:
            pk = pk[:10]
            ok = False
        elif i == 20:
            sig = bytes(64)
            ok = False
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
        expect.append(ok)
    got = E.verify_batch(pks, msgs, sigs)
    assert list(got) == expect
    assert E.verify_batch([], [], []).shape == (0,)


def test_verify_batch_nonfused_fallback():
    """Non-32-byte messages take the host-hash path and still verify."""
    rng = random.Random(15)
    pks, msgs, sigs = [], [], []
    for i in range(5):
        key, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(7 + i * 13))
        pks.append(pk)
        msgs.append(msg)
        sigs.append(key.sign(msg))
    got = E.verify_batch(pks, msgs, sigs)
    assert list(got) == [True] * 5


def test_fused_pallas_interpret_parity():
    """The Pallas fused wrapper agrees with the XLA fused kernel (interpret
    mode on CPU, tiny tile)."""
    from mysticeti_tpu.ops import ed25519_pallas as PK

    rng = random.Random(16)
    pks, msgs, sigs = [], [], []
    for i in range(8):
        key, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        if i % 2:
            msg = bytes([msg[0] ^ 1]) + msg[1:]
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
    words, s_words, host_ok = E.pack_bytes(pks, msgs, sigs)
    got = np.asarray(
        PK.verify_fused_pallas(
            jnp.asarray(words),
            jnp.asarray(s_words),
            jnp.asarray(host_ok),
            tile=8,
            interpret=True,
        )
    )
    want = np.asarray(
        E.verify_fused_kernel(
            jnp.asarray(words), jnp.asarray(s_words), jnp.asarray(host_ok)
        )
    )
    assert list(got) == list(want)
    assert any(want) and not all(want)
