"""Linearizer tests: sub-dag expansion, dedup across commits, height, recovery
(linearizer.rs:91-166 parity)."""
import pytest

from mysticeti_tpu.block_store import CommitData
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound
from mysticeti_tpu.consensus.linearizer import CommittedSubDag, Linearizer
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder
from mysticeti_tpu.state import CommitObserverRecoveredState

from helpers import DagBlockWriter, build_dag


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def test_collect_sub_dag_dedup(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 9)
    committer = (
        UniversalCommitterBuilder(committee, writer.block_store).build()
    )
    sequence = committer.try_commit(AuthorityRound(0, 0))
    leaders = [s.block for s in sequence if s.block is not None]
    assert len(leaders) >= 2

    linearizer = Linearizer(writer.block_store)
    sub_dags = linearizer.handle_commit(leaders)
    assert [sd.height for sd in sub_dags] == list(range(1, len(sub_dags) + 1))
    # No block appears in two sub-dags.
    seen = set()
    for sd in sub_dags:
        for block in sd.blocks:
            assert block.reference not in seen
            seen.add(block.reference)
        # Sorted by round.
        rounds = [b.round() for b in sd.blocks]
        assert rounds == sorted(rounds)
        assert sd.anchor in {b.reference for b in sd.blocks}
    # First sub-dag contains the leader's full causal history (incl. genesis).
    assert any(b.round() == 0 for b in sub_dags[0].blocks)


def test_commit_data_roundtrip(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 5)
    committer = UniversalCommitterBuilder(committee, writer.block_store).build()
    leaders = [
        s.block
        for s in committer.try_commit(AuthorityRound(0, 0))
        if s.block is not None
    ]
    linearizer = Linearizer(writer.block_store)
    [sub_dag] = linearizer.handle_commit(leaders)
    cd = CommitData(
        leader=sub_dag.anchor,
        sub_dag=[b.reference for b in sub_dag.blocks],
        height=sub_dag.height,
    )
    rebuilt = CommittedSubDag.new_from_commit_data(cd, writer.block_store)
    assert rebuilt.anchor == sub_dag.anchor
    assert rebuilt.height == sub_dag.height
    assert {b.reference for b in rebuilt.blocks} == {
        b.reference for b in sub_dag.blocks
    }


def test_recover_state(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 9)
    committer = UniversalCommitterBuilder(committee, writer.block_store).build()
    leaders = [
        s.block
        for s in committer.try_commit(AuthorityRound(0, 0))
        if s.block is not None
    ]
    first = Linearizer(writer.block_store)
    sub_dags = first.handle_commit(leaders[:1])

    recovered = CommitObserverRecoveredState(
        sub_dags=[
            CommitData(sd.anchor, [b.reference for b in sd.blocks], sd.height)
            for sd in sub_dags
        ]
    )
    second = Linearizer(writer.block_store)
    second.recover_state(recovered)
    assert second.last_height == sub_dags[-1].height
    # Continuing after recovery produces the same result as the uninterrupted run.
    rest_first = first.handle_commit(leaders[1:])
    rest_second = second.handle_commit(leaders[1:])
    assert [
        (sd.height, {b.reference for b in sd.blocks}) for sd in rest_first
    ] == [(sd.height, {b.reference for b in sd.blocks}) for sd in rest_second]
