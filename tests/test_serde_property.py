"""Property tests for the canonical wire format (hypothesis).

The reference trusts bincode for this; our own format needs its invariants
pinned: primitive round-trips, whole-block round-trips over arbitrary
payloads, and total rejection of truncation/trailing garbage (a malformed
frame must raise SerdeError, never mis-decode — consensus reads untrusted
bytes off the network, types.rs:315-347 path).
"""
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.serde import Reader, SerdeError, Writer
from mysticeti_tpu.types import Share, StatementBlock, VerificationError

SIGNERS = Committee.benchmark_signers(4)
GENESIS = [StatementBlock.new_genesis(i).reference for i in range(4)]


@given(
    st.lists(
        st.one_of(
            st.tuples(st.just("u8"), st.integers(0, 255)),
            st.tuples(st.just("u32"), st.integers(0, 2**32 - 1)),
            st.tuples(st.just("u64"), st.integers(0, 2**64 - 1)),
            st.tuples(st.just("bytes"), st.binary(max_size=64)),
        ),
        max_size=20,
    )
)
@settings(max_examples=200, deadline=None)
def test_primitive_roundtrip(fields):
    w = Writer()
    for kind, value in fields:
        getattr(w, kind)(value)
    r = Reader(w.finish())
    for kind, value in fields:
        assert getattr(r, kind)() == value
    r.expect_done()


@given(
    round_=st.integers(1, 2**32 - 1),
    author=st.integers(0, 3),
    payloads=st.lists(st.binary(min_size=0, max_size=200), max_size=8),
)
@settings(max_examples=50, deadline=None)
def test_block_roundtrip_arbitrary_payloads(round_, author, payloads):
    block = StatementBlock.build(
        author,
        round_,
        GENESIS,
        [Share(p) for p in payloads],
        signer=SIGNERS[author],
    )
    decoded = StatementBlock.from_bytes(block.to_bytes())
    assert decoded.reference == block.reference
    assert [s.transaction for s in decoded.statements] == payloads
    assert decoded.to_bytes() == block.to_bytes()  # canonical: re-encode identical


@given(
    round_=st.integers(1, 2**32 - 1),
    author=st.integers(0, 3),
    payloads=st.lists(st.binary(min_size=0, max_size=200), max_size=8),
)
@settings(max_examples=50, deadline=None)
def test_memoryview_decode_roundtrip(round_, author, payloads):
    """Zero-copy receive mode: decoding from a memoryview over a mutable
    buffer yields the same block as decoding bytes, the wire frame views
    are content-equal to the bytes path, and nothing decoded retains the
    caller's buffer — clobbering it after decode changes nothing."""
    from mysticeti_tpu.network import Blocks, decode_message, encode_message
    from mysticeti_tpu.serde import Reader

    block = StatementBlock.build(
        author, round_, GENESIS, [Share(p) for p in payloads],
        signer=SIGNERS[author],
    )
    frame = encode_message(Blocks((block.to_bytes(),)))
    buf = bytearray(frame)
    msg_view = decode_message(memoryview(buf))
    assert msg_view == decode_message(frame)
    decoded = StatementBlock.from_bytes(msg_view.blocks[0])
    del msg_view  # release the frame views before reuse
    buf[:] = b"\x00" * len(buf)  # simulate the receive buffer recycling
    assert decoded.reference == block.reference
    assert decoded.to_bytes() == block.to_bytes()
    assert [s.transaction for s in decoded.statements] == payloads
    # Reader memoryview mode: length-prefixed fields come back as views of
    # the input; fixed-width fields (digests) always materialize.
    w_probe = Reader(memoryview(bytearray(b"\x03\x00\x00\x00abc")))
    out = w_probe.bytes()
    assert type(out) is memoryview and bytes(out) == b"abc"
    assert type(Reader(b"\x03\x00\x00\x00abc").bytes()) is bytes


@given(data=st.data())
@settings(max_examples=100, deadline=None)
def test_corrupted_frames_never_misdecode(data):
    block = StatementBlock.build(
        0, 5, GENESIS, [Share(b"payload")], signer=SIGNERS[0]
    )
    raw = block.to_bytes()
    mode = data.draw(st.sampled_from(["truncate", "trailing", "flip"]))
    if mode == "truncate":
        cut = data.draw(st.integers(0, len(raw) - 1))
        mutated = raw[:cut]
    elif mode == "trailing":
        extra = data.draw(st.binary(min_size=1, max_size=16))
        mutated = raw + extra
    else:
        pos = data.draw(st.integers(0, len(raw) - 1))
        bit = 1 << data.draw(st.integers(0, 7))
        mutated = raw[:pos] + bytes([raw[pos] ^ bit]) + raw[pos + 1 :]
    try:
        decoded = StatementBlock.from_bytes(mutated)
    except (SerdeError, VerificationError, ValueError, OverflowError):
        return  # rejected loudly: correct
    # A bit flip can land in the payload/signature and still parse — but then
    # the signature check must fail and the digest must differ from the
    # original (no silent acceptance of a different frame as the same block).
    committee = Committee.new_test([1] * 4)
    if mutated != raw:
        with pytest.raises(VerificationError):
            decoded.verify(committee)


@pytest.mark.parametrize("use_native", [True, False])
@given(
    entries=st.lists(
        st.tuples(st.integers(1, 200), st.binary(max_size=300)), max_size=12
    ),
    cut_fraction=st.floats(0.0, 1.0),
)
@settings(max_examples=100, deadline=None)
def test_wal_replay_prefix_under_truncation(
    tmp_path_factory, use_native, entries, cut_fraction
):
    """Crash-recovery contract (wal.rs:270-293): after truncating the file at
    ANY byte, replay yields exactly the entries wholly before the cut —
    everything durable is recovered, the torn tail is dropped, nothing
    mis-frames."""
    import mysticeti_tpu.wal as wal_mod
    from mysticeti_tpu.wal import HEADER_SIZE, walf

    saved_native = wal_mod._native
    if not use_native:
        wal_mod._native = None  # pure-Python fallback path
    elif saved_native is None:
        pytest.skip("native extension unavailable")

    tmp = tmp_path_factory.mktemp("walprop")
    path = str(tmp / "wal")
    writer, reader = walf(path)
    offsets = []
    for tag, payload in entries:
        pos = writer.write(tag, payload)
        offsets.append((pos, tag, payload))
    writer.sync()
    size = writer.position()
    writer.close()

    cut = int(size * cut_fraction)
    with open(path, "r+b") as f:
        f.truncate(cut)

    try:
        replayed = list(reader.iter_until())
    finally:
        wal_mod._native = saved_native
    # Truncation can only damage the tail: every entry that fits wholly
    # before the cut MUST be recovered verbatim, and nothing after it may
    # mis-frame into a phantom entry.
    expect = [
        (pos, tag, payload)
        for pos, tag, payload in offsets
        if pos + HEADER_SIZE + len(payload) <= cut
    ]
    assert replayed == expect


@given(data=st.data())
@settings(max_examples=120, deadline=None)
def test_native_and_python_decoders_agree(data):
    """Differential: the C++ decode_block and the interpreter fast path must
    accept exactly the same frames and produce identical components — on
    well-formed blocks with every statement kind, and on mutated bytes."""
    import mysticeti_tpu.types as types_mod
    from mysticeti_tpu.types import (
        BlockReference,
        TransactionLocator,
        TransactionLocatorRange,
        Vote,
        VoteRange,
    )

    if types_mod._native_decode is None:
        pytest.skip("native extension unavailable")

    def rand_locator(d):
        ref = BlockReference(
            d.draw(st.integers(0, 3)), d.draw(st.integers(1, 50)),
            d.draw(st.binary(min_size=32, max_size=32)),
        )
        return TransactionLocator(ref, d.draw(st.integers(0, 1000)))

    statements = []
    for _ in range(data.draw(st.integers(0, 6))):
        kind = data.draw(st.sampled_from(["share", "vote", "reject", "range"]))
        if kind == "share":
            statements.append(Share(data.draw(st.binary(max_size=120))))
        elif kind == "vote":
            statements.append(Vote(rand_locator(data), True, None))
        elif kind == "reject":
            conflict = (
                rand_locator(data)
                if data.draw(st.booleans())
                else None
            )
            statements.append(Vote(rand_locator(data), False, conflict))
        else:
            ref = rand_locator(data).block
            s = data.draw(st.integers(0, 500))
            statements.append(
                VoteRange(TransactionLocatorRange(
                    ref, s, s + data.draw(st.integers(0, 500))
                ))
            )
    block = StatementBlock.build(
        data.draw(st.integers(0, 3)), data.draw(st.integers(1, 1000)),
        GENESIS, statements, signer=SIGNERS[0],
    )
    raw = bytearray(block.to_bytes())
    if data.draw(st.booleans()):  # half the cases: mutate
        mode = data.draw(st.sampled_from(["truncate", "trailing", "flip"]))
        if mode == "truncate":
            raw = raw[: data.draw(st.integers(0, len(raw) - 1))]
        elif mode == "trailing":
            raw += data.draw(st.binary(min_size=1, max_size=8))
        else:
            pos = data.draw(st.integers(0, len(raw) - 1))
            raw[pos] ^= 1 << data.draw(st.integers(0, 7))
    raw = bytes(raw)

    def decode(force_python):
        saved = types_mod._native_decode
        if force_python:
            types_mod._native_decode = None
        try:
            return ("ok", StatementBlock.from_bytes(raw))
        except (SerdeError, ValueError, OverflowError) as exc:
            return ("err", type(exc).__name__)
        finally:
            types_mod._native_decode = saved

    native = decode(force_python=False)
    python = decode(force_python=True)
    assert native[0] == python[0], (native, python)
    if native[0] == "ok":
        a, b = native[1], python[1]
        assert a.reference == b.reference
        assert a.includes == b.includes
        assert a.statements == b.statements
        assert (a.meta_creation_time_ns, a.epoch_marker, a.epoch,
                a.signature) == (
            b.meta_creation_time_ns, b.epoch_marker, b.epoch, b.signature)


def test_forged_huge_counts_rejected_without_allocation():
    """A 24-byte frame claiming 2^32-1 includes/statements must be rejected
    by bounds checks BEFORE any proportional allocation (native decoder DoS
    guard), and identically by both decoders."""
    import struct as _struct

    import mysticeti_tpu.types as types_mod

    for which in ("includes", "statements"):
        if which == "includes":
            frame = _struct.pack("<QQI", 0, 1, 0xFFFFFFFF) + b"\0" * 4
        else:
            frame = _struct.pack("<QQI", 0, 1, 0) + _struct.pack(
                "<I", 0xFFFFFFFF
            )
        for force_python in (False, True):
            saved = types_mod._native_decode
            if force_python:
                types_mod._native_decode = None
            try:
                with pytest.raises((SerdeError, ValueError)):
                    StatementBlock.from_bytes(frame)
            finally:
                types_mod._native_decode = saved


@given(data=st.data())
@settings(max_examples=60, deadline=None)
def test_decoder_share_runs_match_python_walk(data):
    """The native decoder's precomputed share spans must equal the
    statement-walk result (committee.shared_ranges fast path)."""
    import mysticeti_tpu.types as types_mod
    from mysticeti_tpu.committee import shared_ranges
    from mysticeti_tpu.types import (
        BlockReference,
        TransactionLocator,
        Vote,
    )

    if types_mod._native_decode is None:
        pytest.skip("native extension unavailable")

    statements = []
    for _ in range(data.draw(st.integers(0, 12))):
        if data.draw(st.booleans()):
            statements.append(Share(data.draw(st.binary(max_size=40))))
        else:
            ref = BlockReference(0, 1, bytes(32))
            statements.append(
                Vote(TransactionLocator(ref, data.draw(st.integers(0, 9))))
            )
    built = StatementBlock.build(0, 7, GENESIS, statements, signer=SIGNERS[0])
    decoded = StatementBlock.from_bytes(built.to_bytes())
    # The fast path must actually be in play (walk-vs-walk would be vacuous).
    assert decoded._share_runs is not None
    assert built._share_runs is None
    assert shared_ranges(decoded) == shared_ranges(built)


@given(payloads=st.lists(st.binary(min_size=0, max_size=24), max_size=10))
@settings(max_examples=60, deadline=None)
def test_decoder_stamps_match_python_walk(payloads):
    """Decoder-precomputed stamp bytes == the statement-walk result,
    including the zero-stamp rule for sub-8-byte payloads."""
    import mysticeti_tpu.types as types_mod

    if types_mod._native_decode is None:
        pytest.skip("native extension unavailable")
    built = StatementBlock.build(
        0, 3, GENESIS, [Share(p) for p in payloads], signer=SIGNERS[0]
    )
    decoded = StatementBlock.from_bytes(built.to_bytes())
    assert decoded._stamps is not None and built._stamps is None
    assert decoded.shared_transaction_stamps() == \
        built.shared_transaction_stamps()
    expected = b"".join(
        p[:8] if len(p) >= 8 else b"\x00" * 8 for p in payloads
    )
    assert decoded.shared_transaction_stamps() == expected
