"""Property tests for the canonical wire format (hypothesis).

The reference trusts bincode for this; our own format needs its invariants
pinned: primitive round-trips, whole-block round-trips over arbitrary
payloads, and total rejection of truncation/trailing garbage (a malformed
frame must raise SerdeError, never mis-decode — consensus reads untrusted
bytes off the network, types.rs:315-347 path).
"""
from hypothesis import given, settings
from hypothesis import strategies as st

import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.serde import Reader, SerdeError, Writer
from mysticeti_tpu.types import Share, StatementBlock, VerificationError

SIGNERS = Committee.benchmark_signers(4)
GENESIS = [StatementBlock.new_genesis(i).reference for i in range(4)]


@given(
    st.lists(
        st.one_of(
            st.tuples(st.just("u8"), st.integers(0, 255)),
            st.tuples(st.just("u32"), st.integers(0, 2**32 - 1)),
            st.tuples(st.just("u64"), st.integers(0, 2**64 - 1)),
            st.tuples(st.just("bytes"), st.binary(max_size=64)),
        ),
        max_size=20,
    )
)
@settings(max_examples=200, deadline=None)
def test_primitive_roundtrip(fields):
    w = Writer()
    for kind, value in fields:
        getattr(w, kind)(value)
    r = Reader(w.finish())
    for kind, value in fields:
        assert getattr(r, kind)() == value
    r.expect_done()


@given(
    round_=st.integers(1, 2**32 - 1),
    author=st.integers(0, 3),
    payloads=st.lists(st.binary(min_size=0, max_size=200), max_size=8),
)
@settings(max_examples=50, deadline=None)
def test_block_roundtrip_arbitrary_payloads(round_, author, payloads):
    block = StatementBlock.build(
        author,
        round_,
        GENESIS,
        [Share(p) for p in payloads],
        signer=SIGNERS[author],
    )
    decoded = StatementBlock.from_bytes(block.to_bytes())
    assert decoded.reference == block.reference
    assert [s.transaction for s in decoded.statements] == payloads
    assert decoded.to_bytes() == block.to_bytes()  # canonical: re-encode identical


@given(data=st.data())
@settings(max_examples=100, deadline=None)
def test_corrupted_frames_never_misdecode(data):
    block = StatementBlock.build(
        0, 5, GENESIS, [Share(b"payload")], signer=SIGNERS[0]
    )
    raw = block.to_bytes()
    mode = data.draw(st.sampled_from(["truncate", "trailing", "flip"]))
    if mode == "truncate":
        cut = data.draw(st.integers(0, len(raw) - 1))
        mutated = raw[:cut]
    elif mode == "trailing":
        extra = data.draw(st.binary(min_size=1, max_size=16))
        mutated = raw + extra
    else:
        pos = data.draw(st.integers(0, len(raw) - 1))
        bit = 1 << data.draw(st.integers(0, 7))
        mutated = raw[:pos] + bytes([raw[pos] ^ bit]) + raw[pos + 1 :]
    try:
        decoded = StatementBlock.from_bytes(mutated)
    except (SerdeError, VerificationError, ValueError, OverflowError):
        return  # rejected loudly: correct
    # A bit flip can land in the payload/signature and still parse — but then
    # the signature check must fail and the digest must differ from the
    # original (no silent acceptance of a different frame as the same block).
    committee = Committee.new_test([1] * 4)
    if mutated != raw:
        with pytest.raises(VerificationError):
            decoded.verify(committee)


@pytest.mark.parametrize("use_native", [True, False])
@given(
    entries=st.lists(
        st.tuples(st.integers(1, 200), st.binary(max_size=300)), max_size=12
    ),
    cut_fraction=st.floats(0.0, 1.0),
)
@settings(max_examples=100, deadline=None)
def test_wal_replay_prefix_under_truncation(
    tmp_path_factory, use_native, entries, cut_fraction
):
    """Crash-recovery contract (wal.rs:270-293): after truncating the file at
    ANY byte, replay yields exactly the entries wholly before the cut —
    everything durable is recovered, the torn tail is dropped, nothing
    mis-frames."""
    import mysticeti_tpu.wal as wal_mod
    from mysticeti_tpu.wal import HEADER_SIZE, walf

    saved_native = wal_mod._native
    if not use_native:
        wal_mod._native = None  # pure-Python fallback path
    elif saved_native is None:
        pytest.skip("native extension unavailable")

    tmp = tmp_path_factory.mktemp("walprop")
    path = str(tmp / "wal")
    writer, reader = walf(path)
    offsets = []
    for tag, payload in entries:
        pos = writer.write(tag, payload)
        offsets.append((pos, tag, payload))
    writer.sync()
    size = writer.position()
    writer.close()

    cut = int(size * cut_fraction)
    with open(path, "r+b") as f:
        f.truncate(cut)

    try:
        replayed = list(reader.iter_until())
    finally:
        wal_mod._native = saved_native
    # Truncation can only damage the tail: every entry that fits wholly
    # before the cut MUST be recovered verbatim, and nothing after it may
    # mis-frame into a phantom entry.
    expect = [
        (pos, tag, payload)
        for pos, tag, payload in offsets
        if pos + HEADER_SIZE + len(payload) <= cut
    ]
    assert replayed == expect
