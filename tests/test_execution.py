"""Deterministic execution plane: typed-transaction serde, the account state
machine's apply/nonce/overdraft semantics, chained state roots, checkpoint /
snapshot durability tails, execute-phase finality, the tag-15/16 EXECUTED
wire suffixes, ingress identity lanes + typed pre-consensus sheds, and the
seeded multi-node sims (adversary mix never diverges honest roots,
crash-restart re-derives the same chain, a snapshot rejoiner lands on the
fleet root, same-seed runs are byte-identical).  A planted wall-clock
nondeterminism is caught statically (sim-taint) and dynamically (detsan)."""
import asyncio
import dataclasses
import struct
import time

import pytest

from mysticeti_tpu.config import (
    IngressParameters,
    Parameters,
    StorageParameters,
)
from mysticeti_tpu.execution import (
    APPLIED,
    EXEC_MAGIC,
    GENESIS_ROOT,
    OP_CREATE,
    OP_MINT,
    OP_TRANSFER,
    REJECT_BAD_NONCE,
    REJECT_EXISTS,
    REJECT_OVERDRAFT,
    REJECT_UNKNOWN,
    ExecTx,
    ExecutionState,
    parse_exec_tx,
)
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.types import Share

pytestmark = pytest.mark.execution


def _block(*txs):
    class _Block:
        def __init__(self, statements):
            self.statements = statements

    return _Block([Share(tx.to_bytes()) for tx in txs])


def _fold(state, height, *txs):
    return state.observe_commit(height, [_block(*txs)])


# -- transaction serde --------------------------------------------------------


def test_exec_tx_roundtrip_all_ops():
    for tx in (
        ExecTx(OP_CREATE, b"alice", amount=1000),
        ExecTx(OP_MINT, b"alice", nonce=3, amount=7),
        ExecTx(OP_TRANSFER, b"alice", nonce=4, amount=40, dest=b"bob"),
    ):
        data = tx.to_bytes()
        assert data.startswith(EXEC_MAGIC)
        assert ExecTx.from_bytes(data) == tx
        assert parse_exec_tx(data) == tx


def test_parse_ordinary_payloads_are_opaque():
    # The benchmark workload (8/16-byte LE counters) and arbitrary client
    # bytes must never parse as execution transactions.
    for payload in (
        struct.pack("<QQ", 0, 7) + b"\x00" * 496,
        b"",
        b"\x00" * 8,
        b"ordinary transaction bytes",
    ):
        assert parse_exec_tx(payload) is None


def test_parse_garbled_magic_is_opaque_not_an_error():
    # Magic + junk: honest nodes must agree to IGNORE it, not fork on
    # whether decoding raises.
    assert parse_exec_tx(EXEC_MAGIC) is None
    assert parse_exec_tx(EXEC_MAGIC + b"\xff\xff\xff") is None
    # Trailing garbage after a valid encoding is garbled too.
    valid = ExecTx(OP_MINT, b"a", nonce=1, amount=2).to_bytes()
    assert parse_exec_tx(valid + b"\x00") is None


def test_exec_tx_validation():
    with pytest.raises(ValueError):
        ExecTx(9, b"a")  # unknown op
    with pytest.raises(ValueError):
        ExecTx(OP_CREATE, b"")  # empty account key
    with pytest.raises(ValueError):
        ExecTx(OP_CREATE, b"a" * 65)  # oversize account key
    with pytest.raises(ValueError):
        ExecTx(OP_MINT, b"a", dest=b"b")  # dest on a non-transfer
    with pytest.raises(ValueError):
        ExecTx(OP_TRANSFER, b"a", dest=b"")  # transfer without dest


# -- apply semantics ----------------------------------------------------------


def test_create_mint_transfer_lifecycle():
    st = ExecutionState()
    assert st.root == GENESIS_ROOT and st.last_height == 0
    result = _fold(
        st,
        1,
        ExecTx(OP_CREATE, b"alice", amount=100),
        ExecTx(OP_MINT, b"alice", nonce=1, amount=50),
        ExecTx(OP_TRANSFER, b"alice", nonce=2, amount=40, dest=b"bob"),
    )
    assert result.applied == 3 and result.rejected == 0
    assert st.probe(b"alice") == (110, 3)
    # Transfer auto-creates the destination at nonce 0.
    assert st.probe(b"bob") == (40, 0)
    assert st.last_height == 1 and st.root != GENESIS_ROOT


def test_typed_rejects_consume_nothing_but_count():
    st = ExecutionState()
    _fold(st, 1, ExecTx(OP_CREATE, b"alice", amount=10))
    result = _fold(
        st,
        2,
        ExecTx(OP_CREATE, b"alice", amount=5),  # exists
        ExecTx(OP_MINT, b"ghost", nonce=0, amount=5),  # unknown
        ExecTx(OP_MINT, b"alice", nonce=9, amount=5),  # wrong nonce
        ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=99, dest=b"b"),
    )
    assert result.applied == 0 and result.rejected == 4
    assert dict(result.verdicts) == {
        REJECT_EXISTS: 1,
        REJECT_UNKNOWN: 1,
        REJECT_BAD_NONCE: 1,
        REJECT_OVERDRAFT: 1,
    }
    # Rejected transactions move no balances and consume no nonces...
    assert st.probe(b"alice") == (10, 1)
    assert st.probe(b"ghost") is None
    # ...but the rejects are part of the deterministic fold: the root
    # still advanced (height is digested even with zero deltas).
    assert st.root != GENESIS_ROOT and st.last_height == 2


def test_self_transfer_consumes_nonce_only():
    st = ExecutionState()
    _fold(st, 1, ExecTx(OP_CREATE, b"a", amount=10))
    result = _fold(
        st, 2, ExecTx(OP_TRANSFER, b"a", nonce=1, amount=5, dest=b"a")
    )
    assert result.applied == 1
    assert st.probe(b"a") == (10, 2)


def test_create_with_nonzero_nonce_rejected():
    st = ExecutionState()
    result = _fold(st, 1, ExecTx(OP_CREATE, b"a", nonce=3, amount=10))
    assert dict(result.verdicts) == {REJECT_BAD_NONCE: 1}
    assert st.probe(b"a") is None


# -- root chaining ------------------------------------------------------------


def test_root_chain_is_deterministic_across_replicas():
    txs = [
        ExecTx(OP_CREATE, b"a", amount=100),
        ExecTx(OP_TRANSFER, b"a", nonce=1, amount=30, dest=b"b"),
        ExecTx(OP_MINT, b"a", nonce=2, amount=5),
    ]
    a, b = ExecutionState(), ExecutionState()
    for st in (a, b):
        _fold(st, 1, txs[0])
        _fold(st, 2, *txs[1:])
    assert a.root == b.root
    assert a.root_at(1) == b.root_at(1)
    assert a.to_bytes() == b.to_bytes()


def test_root_chain_depends_on_order_and_predecessor():
    t1 = ExecTx(OP_CREATE, b"a", amount=100)
    t2 = ExecTx(OP_CREATE, b"b", amount=100)
    a, b = ExecutionState(), ExecutionState()
    _fold(a, 1, t1)
    _fold(a, 2, t2)
    _fold(b, 1, t2)
    _fold(b, 2, t1)
    # Same final account table, different committed order: the CHAIN
    # differs at height 1 (the root is a history commitment, not a state
    # snapshot)...
    assert a.root_at(1) != b.root_at(1)
    # ...and stays different at height 2 through the prev-root chaining
    # even though the tables now agree.
    assert {k: v for k, v in a._exec_accounts.items()} == {
        k: v for k, v in b._exec_accounts.items()
    }
    assert a.root != b.root


def test_observe_commit_skips_replayed_heights():
    st = ExecutionState()
    _fold(st, 1, ExecTx(OP_CREATE, b"a", amount=10))
    root = st.root
    # Crash replay re-delivers committed heights: the fold must be
    # idempotent (None return, nothing moves).
    assert _fold(st, 1, ExecTx(OP_MINT, b"a", nonce=1, amount=99)) is None
    assert st.root == root and st.probe(b"a") == (10, 1)


# -- durability ---------------------------------------------------------------


def test_to_bytes_recover_roundtrip_is_byte_exact():
    st = ExecutionState()
    _fold(st, 1, ExecTx(OP_CREATE, b"alice", amount=100))
    _fold(
        st, 2, ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=30, dest=b"bob")
    )
    data = st.to_bytes()
    twin = ExecutionState()
    twin.recover(data)
    assert twin.last_height == 2 and twin.root == st.root
    assert twin.probe(b"alice") == (70, 2) and twin.probe(b"bob") == (30, 0)
    assert twin.applied_total == st.applied_total
    assert twin.to_bytes() == data
    # Recovery resumes the chain exactly: the next fold lands on the same
    # root on both.
    nxt = ExecTx(OP_MINT, b"alice", nonce=2, amount=1)
    _fold(st, 3, nxt)
    _fold(twin, 3, nxt)
    assert twin.root == st.root


def test_adopt_only_moves_forward():
    ahead, behind = ExecutionState(), ExecutionState()
    _fold(ahead, 1, ExecTx(OP_CREATE, b"a", amount=10))
    _fold(ahead, 2, ExecTx(OP_MINT, b"a", nonce=1, amount=5))
    _fold(behind, 1, ExecTx(OP_CREATE, b"a", amount=10))
    # A remote at or behind our height carries nothing: ignored.
    assert not ahead.adopt(behind.to_bytes())
    assert not ahead.adopt(ahead.to_bytes())
    assert not ahead.adopt(b"")
    # A remote ahead is adopted wholesale.
    assert behind.adopt(ahead.to_bytes())
    assert behind.last_height == 2 and behind.root == ahead.root


def test_checkpoint_and_manifest_exec_state_soft_tail():
    """With the plane off, checkpoint/manifest bytes are UNCHANGED from
    pre-r20 (soft-tail contract, docs/wire-format.md §7); with it on, the
    state rides both and round-trips exactly."""
    from mysticeti_tpu.storage import SnapshotManifest

    base = dict(
        commit_height=7,
        last_committed_leader=None,
        gc_round=3,
        chain_digest=b"\x11" * 32,
    )
    plain = SnapshotManifest(**base).to_bytes()
    # Empty exec_state (and epoch_chain) encode NOTHING: byte-identical.
    assert SnapshotManifest(**base, exec_state=b"").to_bytes() == plain

    st = ExecutionState()
    _fold(st, 7, ExecTx(OP_CREATE, b"a", amount=10))
    carrying = SnapshotManifest(**base, exec_state=st.to_bytes())
    decoded = SnapshotManifest.from_bytes(carrying.to_bytes())
    assert decoded.exec_state == st.to_bytes()
    assert decoded.epoch_chain == b""
    # A pre-r20 decoder reading the plain frame sees no tail; a new
    # decoder reading the plain frame sees empty state.
    assert SnapshotManifest.from_bytes(plain).exec_state == b""


# -- admission (pre-consensus) ------------------------------------------------


def test_admission_verdict_sheds_only_currently_doomed():
    st = ExecutionState()
    _fold(st, 1, ExecTx(OP_CREATE, b"alice", amount=100))
    # Already doomed against current state: typed sheds.
    assert st.admission_verdict(ExecTx(OP_CREATE, b"alice")) == REJECT_EXISTS
    assert (
        st.admission_verdict(ExecTx(OP_MINT, b"ghost", nonce=0))
        == REJECT_UNKNOWN
    )
    assert (
        st.admission_verdict(ExecTx(OP_MINT, b"alice", nonce=0, amount=1))
        == REJECT_BAD_NONCE  # stale: account nonce is already 1
    )
    assert (
        st.admission_verdict(
            ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=500, dest=b"b")
        )
        == REJECT_OVERDRAFT
    )
    # Curable by in-flight traffic: admitted, the fold decides.
    assert st.admission_verdict(ExecTx(OP_CREATE, b"bob", amount=5)) is None
    assert (
        st.admission_verdict(ExecTx(OP_MINT, b"alice", nonce=4, amount=1))
        is None  # nonce ahead: earlier txs may be in flight
    )
    assert (
        st.admission_verdict(
            ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=100, dest=b"b")
        )
        is None
    )


def test_metrics_verdict_labels_and_gauges():
    metrics = Metrics()
    st = ExecutionState(metrics=metrics)
    _fold(
        st,
        1,
        ExecTx(OP_CREATE, b"a", amount=10),
        ExecTx(OP_MINT, b"a", nonce=9, amount=1),
    )
    counter = metrics.mysticeti_execution_txs_total
    assert counter.labels(APPLIED)._value.get() == 1
    assert counter.labels(REJECT_BAD_NONCE)._value.get() == 1
    assert metrics.mysticeti_execution_height._value.get() == 1
    assert metrics.mysticeti_execution_accounts._value.get() == 1


# -- execute-phase finality ---------------------------------------------------


def test_finality_total_closes_at_execute_when_expected():
    from mysticeti_tpu.finality import FinalityTracker

    metrics = Metrics()
    tracker = FinalityTracker(metrics=metrics, sample_every=1)
    tracker.execute_expected = True
    key = b"k" * 16
    tracker.on_submit(key, 1.0, 1.1)
    tracker.on_commit(key, 2.0, 2.2)
    # Committed but not executed: the headline total is still open.
    assert tracker.completed == 0 and tracker.samples() == []
    tracker.on_execute([key], 2.5)
    assert tracker.completed == 1
    assert tracker.samples() == [pytest.approx(1.5)]
    # The execute phase is finalize -> execute; notify measures from the
    # EXECUTE stamp, not finalize.
    tracker.on_notify([key], 2.6)
    hist = metrics.mysticeti_e2e_finality_seconds
    assert hist.labels("execute")._sum.get() == pytest.approx(0.3)
    assert hist.labels("notify")._sum.get() == pytest.approx(0.1)
    assert hist.labels("total")._sum.get() == pytest.approx(1.5)


def test_finality_total_closes_at_commit_without_execution():
    from mysticeti_tpu.finality import FinalityTracker

    tracker = FinalityTracker(sample_every=1)
    key = b"k" * 16
    tracker.on_submit(key, 1.0, 1.1)
    tracker.on_commit(key, 2.0, 2.2)
    assert tracker.completed == 1
    assert tracker.samples() == [pytest.approx(1.2)]


# -- wire suffixes (tags 15/16) -----------------------------------------------


def test_subscribe_and_notification_suffix_tiers_roundtrip():
    from mysticeti_tpu.network import (
        GatewayCommitNotification,
        GatewaySubscribeCommits,
        decode_message,
        encode_message,
    )

    root = b"\xab" * 32
    for msg in (
        # Tag 15: want_executed (tier 2) forces the want_details byte.
        GatewaySubscribeCommits(5),
        GatewaySubscribeCommits(5, want_details=1),
        GatewaySubscribeCommits(5, want_details=0, want_executed=1),
        GatewaySubscribeCommits(5, want_details=1, want_executed=1),
        # Tag 16: executed_root (tier 2) forces the detail pair.
        GatewayCommitNotification(4, (b"k" * 16,)),
        GatewayCommitNotification(4, (b"k" * 16,), 9, 123456789),
        GatewayCommitNotification(4, (b"k" * 16,), 0, 0, root),
        GatewayCommitNotification(4, (b"k" * 16,), 9, 123456789, root),
        # The synthetic resume reply: height, no keys, root only.
        GatewayCommitNotification(17, (), executed_root=root),
    ):
        assert decode_message(encode_message(msg)) == msg
    # Suffix-free frames are byte-identical to pre-r20: strictly shorter
    # than any suffixed variant (a pre-r20 peer never sees new bytes
    # unless it ASKED, wire-format §5b).
    plain = encode_message(GatewaySubscribeCommits(5))
    assert len(plain) < len(
        encode_message(GatewaySubscribeCommits(5, want_executed=1))
    )
    note = encode_message(GatewayCommitNotification(4, (b"k" * 16,)))
    assert len(note) < len(
        encode_message(
            GatewayCommitNotification(4, (b"k" * 16,), executed_root=root)
        )
    )


# -- ingress: identity lanes, typed sheds, deferred notification --------------


class _FakeCore:
    """Just enough core surface for IngressPlane.attach(core=...)."""

    def __init__(self, execution):
        self.execution = execution
        self.execution_listeners = []

    def fold(self, height, blocks):
        result = self.execution.observe_commit(height, blocks)
        if result is not None:
            for listener in self.execution_listeners:
                listener(result)
        return result


def _plane_with_execution(**params):
    from mysticeti_tpu.ingress import IngressPlane

    state = ExecutionState()
    _fold(state, 1, ExecTx(OP_CREATE, b"alice", amount=100))
    core = _FakeCore(state)
    plane = IngressPlane(
        IngressParameters(admission=False, **params)
    ).attach(core=core)
    return plane, core, state


def test_ingress_sheds_doomed_exec_txs_before_consensus():
    from mysticeti_tpu.ingress import (
        SHED_ACCOUNT_EXISTS,
        SHED_BAD_NONCE,
        SHED_INSUFFICIENT_BALANCE,
        SHED_UNKNOWN_ACCOUNT,
    )

    plane, _, _ = _plane_with_execution()
    result = plane.submit(
        "client",
        [
            ExecTx(OP_CREATE, b"alice").to_bytes(),
            ExecTx(OP_MINT, b"alice", nonce=0, amount=1).to_bytes(),
            ExecTx(OP_MINT, b"ghost", nonce=0).to_bytes(),
            ExecTx(
                OP_TRANSFER, b"alice", nonce=1, amount=999, dest=b"b"
            ).to_bytes(),
            ExecTx(
                OP_TRANSFER, b"alice", nonce=1, amount=10, dest=b"b"
            ).to_bytes(),
        ],
    )
    assert result.accepted == 1 and result.shed == 4
    assert plane.shed_by_reason == {
        SHED_ACCOUNT_EXISTS: 1,
        SHED_BAD_NONCE: 1,
        SHED_UNKNOWN_ACCOUNT: 1,
        SHED_INSUFFICIENT_BALANCE: 1,
    }
    # Nothing doomed reached the mempool: consensus never pays for it.
    assert plane.mempool.pending() == 1


def test_ingress_identity_lanes_key_on_spending_account():
    plane, _, _ = _plane_with_execution()
    valid = ExecTx(
        OP_TRANSFER, b"alice", nonce=1, amount=10, dest=b"b"
    ).to_bytes()
    ordinary = struct.pack("<QQ", 7, 1) + b"\x00" * 16
    result = plane.submit("conn-1", [valid, ordinary])
    assert result.accepted == 2
    stats = plane.mempool.lane_stats()
    # The execution tx laned by ACCOUNT (sender identity), regardless of
    # which connection carried it; the ordinary tx kept the caller's lane.
    account_lane = f"acct:{b'alice'.hex()}"
    assert stats[account_lane]["pending"] == 1
    assert stats["conn-1"]["pending"] == 1
    # A second connection spending the SAME account shares its lane (and
    # its fairness cap): identity-backed, not connection-backed.
    valid2 = ExecTx(
        OP_TRANSFER, b"alice", nonce=2, amount=10, dest=b"c"
    ).to_bytes()
    assert plane.submit("conn-2", [valid2]).accepted == 1
    assert plane.mempool.lane_stats()[account_lane]["pending"] == 2


def test_ingress_defers_notification_until_executed():
    from mysticeti_tpu.ingress import ingress_key

    plane, core, state = _plane_with_execution()
    seen = []
    plane.add_commit_sink(lambda h, keys, info: seen.append((h, keys, info)))
    tx = ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=10, dest=b"b")

    class _Commit:
        def __init__(self, height, blocks):
            self.height = height
            self.blocks = blocks

    block = _block(tx)
    # The syncer notifies BEFORE the core folds (handle_commit runs ahead
    # of handle_committed_subdag): the notification must buffer.
    plane.note_committed([_Commit(2, [block])])
    assert seen == [] and len(plane._pending_exec) == 1
    # The fold flushes it — same loop pass — with the root attached.
    core.fold(2, [block])
    ((height, keys, info),) = seen
    assert height == 2
    assert keys == [ingress_key(tx.to_bytes())]
    assert info["executed_height"] == 2
    assert info["executed_root"] == state.root
    assert plane.executed_height == 2 and plane.executed_root == state.root
    # /health embeds the executed frontier.
    health = plane.health_state()
    assert health["execution"] == {
        "executed_height": 2,
        "executed_root": state.root.hex(),
    }


def test_gateway_subscribe_executed_resume_reply_and_stream():
    """Satellite 1: the resume gap.  A subscriber with ``want_executed``
    gets an immediate synthetic notification pinning the node's current
    executed height/root (no keys), then live notifications carrying the
    EXECUTED suffix — so a resuming client knows exactly where execution
    stands without racing the stream."""
    from mysticeti_tpu.ingress import IngressGateway, ingress_key
    from mysticeti_tpu.network import (
        GatewayCommitNotification,
        GatewaySubscribeCommits,
        _read_frame,
        _write_frame,
        decode_message,
        encode_message,
    )

    async def main():
        plane, core, state = _plane_with_execution()
        resume_root = state.root
        gateway = await IngressGateway(plane, "127.0.0.1", 0).start()
        port = gateway._server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            _write_frame(
                writer,
                encode_message(GatewaySubscribeCommits(0, want_executed=1)),
            )
            await writer.drain()
            note = decode_message(await _read_frame(reader))
            assert isinstance(note, GatewayCommitNotification)
            assert note.height == 1 and note.keys == ()
            assert note.executed_root == resume_root

            class _Commit:
                def __init__(self, height, blocks):
                    self.height = height
                    self.blocks = blocks

            tx = ExecTx(OP_TRANSFER, b"alice", nonce=1, amount=10, dest=b"b")
            block = _block(tx)
            plane.note_committed([_Commit(2, [block])])
            core.fold(2, [block])
            note = decode_message(await _read_frame(reader))
            assert note.height == 2
            assert note.keys == (ingress_key(tx.to_bytes()),)
            assert note.executed_root == state.root
        finally:
            writer.close()
            await gateway.stop()

    asyncio.run(main())


# -- seeded multi-node sims ---------------------------------------------------


def _exec_scenario(**overrides):
    from mysticeti_tpu.scenarios import scenario_by_name

    return dataclasses.replace(
        scenario_by_name("execution-byzantine-at-f"), **overrides
    )


@pytest.mark.chaos
def test_execution_under_byzantine_at_f_roots_agree(tmp_path):
    """The 10-node acceptance sim: equivocate + withhold + invalid_sig at
    f=3 with the execution workload live.  Every honest node folds real
    state and the SafetyChecker's per-height state-root audit holds — a
    fork would have raised, failing the verdict's safety gate."""
    from mysticeti_tpu.scenarios import run_scenario

    verdict = run_scenario(_exec_scenario(duration_s=5.0), str(tmp_path))
    assert verdict["safety_ok"], verdict.get("safety_error")
    assert verdict["passed"], verdict
    execution = verdict["execution"]
    assert execution["execution_ok"]
    assert execution["chain_length"] > 0
    heights = execution["executed_heights"]
    assert len(heights) == 7  # the honest, non-crashed cohort
    assert all(h > 0 for h in heights.values())
    # Every honest node executed up to (near) the shared frontier.
    assert max(heights.values()) - min(heights.values()) <= 2


@pytest.mark.chaos
def test_execution_sim_byte_identical_across_same_seed_runs(tmp_path):
    """Same seed, same scenario: the agreed state-root CHAIN (height ->
    root, every honest node) reproduces byte-for-byte, pinned by the
    verdict's chain digest — execution is a pure function of the seed."""
    from mysticeti_tpu.chaos import run_chaos_sim
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.scenarios import (
        _exec_driver,
        oracle_verifier_factory,
    )

    scenario = _exec_scenario(
        nodes=5, duration_s=3.5, adversaries=(), min_ratio=0.0
    )

    def once(tag):
        # Honest plan: the oracle verifier needs per-index keys, which the
        # adversary path would have defaulted for us (run_chaos_sim).
        report, harness = run_chaos_sim(
            scenario.plan(),
            scenario.nodes,
            scenario.duration_s,
            str(tmp_path / tag),
            parameters=scenario.base_parameters(),
            latency_ranges=scenario.latency_ranges(),
            committee=Committee.new_for_benchmarks(scenario.nodes),
            with_metrics=True,
            verifier_factory=oracle_verifier_factory(scenario.nodes),
            extra_fault=_exec_driver(scenario),
        )
        return report, harness

    first, harness_a = once("a")
    second, harness_b = once("b")
    assert first.state_root_chain  # real state was folded
    assert first.state_root_chain == second.state_root_chain
    assert first.executed == second.executed
    assert first.sequences == second.sequences
    # The harness-level account tables agree node-for-node too.
    for a in range(scenario.nodes):
        exec_a = harness_a.nodes[a].core.execution
        exec_b = harness_b.nodes[a].core.execution
        assert exec_a.to_bytes() == exec_b.to_bytes()


@pytest.mark.chaos
def test_execution_crash_restart_rederives_identical_roots(tmp_path):
    """A node crashing mid-chain recovers its execution state from the
    checkpoint + WAL tail and re-derives the SAME roots: the checker's
    self-conflict arm (note_state_root on rebuild) would raise if the
    recovered chain disagreed with what the node reported pre-crash."""
    from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim
    from mysticeti_tpu.scenarios import Scenario, _exec_driver

    scenario = Scenario(
        name="exec-crash",
        description="crash-restart execution recovery",
        nodes=4,
        duration_s=14.0,
        seed=23,
        execution=True,
    )
    plan = FaultPlan(
        seed=23, crashes=[CrashFault(node=1, at_s=5.0, downtime_s=2.0)]
    )
    params = Parameters(
        leader_timeout_s=1.0,
        execution=True,
        storage=StorageParameters(
            segment_bytes=16 * 1024, checkpoint_interval=5, gc_depth=20
        ),
    )
    report, harness = run_chaos_sim(
        plan,
        4,
        scenario.duration_s,
        str(tmp_path),
        parameters=params,
        with_metrics=True,
        extra_fault=_exec_driver(scenario),
    )
    crashed_at = report.crash_events[0]["committed_height"]
    assert harness.metrics[1].crash_recovery_total._value.get() == 1.0
    # The restarted node kept executing past its crash point...
    restarted = harness.nodes[1].core.execution
    assert restarted.last_height > crashed_at
    # ...and at every height the checker saw from BOTH node 1 and node 0,
    # the roots agree (the per-height audit in check() already enforced
    # this across the whole fleet without raising).
    shared = 0
    for height in report.state_root_chain:
        mine = harness.checker.state_root_at(1, height)
        theirs = harness.checker.state_root_at(0, height)
        if mine is not None and theirs is not None:
            assert mine == theirs
            shared += 1
    assert shared > 0


@pytest.mark.chaos
@pytest.mark.storage
def test_execution_snapshot_rejoiner_lands_on_fleet_root(tmp_path):
    """A rejoiner whose history was GC'd fleet-wide adopts the execution
    state off the snapshot manifest and lands on the fleet's exact root —
    then keeps folding live commits onto the same chain."""
    from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim
    from mysticeti_tpu.scenarios import Scenario, _exec_driver

    scenario = Scenario(
        name="exec-snapshot",
        description="snapshot rejoiner execution adoption",
        nodes=4,
        duration_s=45.0,
        seed=13,
        execution=True,
    )
    params = Parameters(
        leader_timeout_s=1.0,
        execution=True,
        storage=StorageParameters(
            segment_bytes=16 * 1024,
            checkpoint_interval=5,
            gc_depth=20,
            snapshot_catchup=True,
            catchup_threshold_commits=50,
        ),
    )
    plan = FaultPlan(
        seed=13, crashes=[CrashFault(node=3, at_s=3.0, downtime_s=30.0)]
    )
    report, harness = run_chaos_sim(
        plan,
        4,
        scenario.duration_s,
        str(tmp_path),
        parameters=params,
        with_metrics=True,
        extra_fault=_exec_driver(scenario),
    )
    node3 = harness.nodes[3]
    assert node3.core.storage.snapshots_adopted == 1
    rejoined = node3.core.execution
    crashed_at = report.crash_events[0]["committed_height"]
    # It genuinely skipped history (adopted a baseline past the crash)
    # and kept executing on the fleet chain.
    assert rejoined.last_height > crashed_at
    for height in report.state_root_chain:
        mine = harness.checker.state_root_at(3, height)
        theirs = harness.checker.state_root_at(0, height)
        if mine is not None and theirs is not None:
            assert mine == theirs
    # The rejoiner's post-adoption roots entered the agreed chain.
    assert harness.checker.executed_height(3) > crashed_at


# -- planted nondeterminism: the lint and the sanitizer must bite -------------


def test_sim_taint_catches_wallclock_flow_into_state_root():
    """The exact regression the plane must never grow: a wall-clock read
    folded into the state-root digest.  The sim-taint lint flags the flow
    statically — before any sim has to diverge."""
    import textwrap

    from mysticeti_tpu.analysis import analyze_source

    findings = analyze_source(
        textwrap.dedent(
            """
            import hashlib
            import struct
            import time


            class LeakyExecution:
                def __init__(self):
                    self.root = b"\\x00" * 32

                def observe_commit(self, height, deltas):
                    stamp = time.monotonic()
                    material = self.root + struct.pack("<d", stamp)
                    self.root = hashlib.blake2b(
                        material, digest_size=32
                    ).digest()
                    return self.root
            """
        ),
        "mysticeti_tpu/example.py",
    )
    assert "sim-taint" in sorted({f.rule for f in findings})
    messages = " ".join(f.message for f in findings)
    assert "wall-clock" in messages
    assert "canonical digest" in messages


def test_detsan_run_twice_catches_wallclock_leak_in_exec_fold():
    """The dynamic twin: an execution fold whose pacing leaks wall clock
    diverges between same-seed runs and the detsan bisector names the
    first diverging event; the deterministic twin is event-identical."""
    from mysticeti_tpu.detsan import run_twice

    def fold_main(leaky):
        async def main():
            state = ExecutionState()
            _fold(state, 1, ExecTx(OP_CREATE, b"a", amount=1000))
            for height in range(2, 10):
                if leaky:
                    jitter = (time.perf_counter_ns() % 997) / 1e5
                else:
                    jitter = 0.0
                await asyncio.sleep(0.01 + jitter)
                _fold(
                    state,
                    height,
                    ExecTx(
                        OP_TRANSFER,
                        b"a",
                        nonce=height - 1,
                        amount=1,
                        dest=b"sink",
                    ),
                )
            return state.root

        return main()

    clean = run_twice(lambda: fold_main(False), seed=7)
    assert clean.identical, clean.to_dict()
    leaky = run_twice(lambda: fold_main(True), seed=7)
    assert not leaky.identical
    assert leaky.first_divergence is not None
