"""Whole-node smoke tests over real localhost TCP (validator.rs:355-596 tier).

These run on the REAL asyncio loop (not the simulator): they exercise actual
sockets, frames, reconnects and the wal-sync thread.
"""
import asyncio
import os
import socket

import pytest

from mysticeti_tpu.cli import benchmark_genesis
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Identifier, Parameters, PrivateConfig
from mysticeti_tpu.validator import Validator


def _free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _setup(tmp_path, n):
    ports = _free_ports(2 * n)
    identifiers = [
        Identifier("127.0.0.1", ports[2 * i], ports[2 * i + 1]) for i in range(n)
    ]
    parameters = Parameters(identifiers=identifiers, leader_timeout_s=0.5)
    signers = Committee.benchmark_signers(n)
    from mysticeti_tpu.committee import Authority

    committee = Committee([Authority(1, s.public_key) for s in signers])
    privates = [
        PrivateConfig.new_in_dir(i, str(tmp_path / f"v{i}")) for i in range(n)
    ]
    return committee, parameters, signers, privates


async def _start_all(committee, parameters, signers, privates, n, verifier="accept"):
    return [
        await Validator.start_benchmarking(
            i,
            committee,
            parameters,
            privates[i],
            signer=signers[i],
            tps=20,
            serve_metrics_endpoint=(i == 0),
            verifier=verifier,
        )
        for i in range(n)
    ]


async def _wait_commits(validators, minimum, timeout_s):
    async def poll():
        while True:
            if all(len(v.committed_leaders()) >= minimum for v in validators):
                return
            await asyncio.sleep(0.2)

    await asyncio.wait_for(poll(), timeout=timeout_s)


def test_make_verifier_kinds(monkeypatch):
    """Every --verifier choice constructs (regression: the hybrid wiring
    once referenced an unimported class and only failed at node boot)."""
    from mysticeti_tpu import block_validator as bv
    from mysticeti_tpu.validator import _make_verifier

    # Warmup threads would trace/compile the kernel; wiring is what's tested.
    monkeypatch.setattr(bv.HybridSignatureVerifier, "warmup", lambda self: None)
    monkeypatch.setattr(bv.TpuSignatureVerifier, "warmup", lambda self: None)
    committee = Committee.new_for_benchmarks(4)

    v = _make_verifier("tpu", committee)
    assert isinstance(v, bv.BatchedSignatureVerifier)
    assert isinstance(v.verifier, bv.HybridSignatureVerifier)
    assert isinstance(v.verifier.tpu, bv.TpuSignatureVerifier)

    v = _make_verifier("tpu-only", committee)
    assert isinstance(v.verifier, bv.TpuSignatureVerifier)

    v = _make_verifier("cpu", committee)
    assert isinstance(v.verifier, bv.CpuSignatureVerifier)

    assert isinstance(
        _make_verifier("accept", committee), bv.AcceptAllBlockVerifier
    )
    with pytest.raises(ValueError):
        _make_verifier("gpu", committee)


def test_validator_commit(tmp_path):
    """4 validators over localhost TCP commit leaders (validator_commit)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_sync_late_boot(tmp_path):
    """A late-booting node catches up through subscribe/sync (validator_sync)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 3)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
            late = await Validator.start_benchmarking(
                3, committee, parameters, privates[3], signer=signers[3],
                tps=20, serve_metrics_endpoint=False,
            )
            validators.append(late)
            await _wait_commits([late], minimum=1, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_crash_faults(tmp_path):
    """3 of 4 validators keep committing (validator_crash_faults)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 3)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=90)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_metrics_endpoint(tmp_path):
    """The /metrics endpoint serves the benchmark-defining series."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=1, timeout_s=60)
            host, port = parameters.metrics_address(0)
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await asyncio.wait_for(reader.read(-1), timeout=10)
            writer.close()
            assert b"committed_leaders_total" in data
            assert b"benchmark_duration" in data
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_benchmark_genesis_roundtrip(tmp_path):
    wd = str(tmp_path / "genesis")
    benchmark_genesis(["10.0.0.1", "10.0.0.2", "10.0.0.3"], wd)
    committee = Committee.load(os.path.join(wd, "committee.yaml"))
    parameters = Parameters.load(os.path.join(wd, "parameters.yaml"))
    assert len(committee) == 3
    assert len(parameters.identifiers) == 3
    assert parameters.identifiers[1].hostname == "10.0.0.2"
    assert os.path.exists(os.path.join(wd, "validator-0", "seed"))


def test_validator_shutdown_and_start(tmp_path):
    """Stop the whole committee, restart on the SAME ports with the SAME
    WALs, and commits must resume past the pre-restart point — catches port
    reuse and WAL-reopen-under-assembly bugs (validator_shutdown_and_start,
    validator.rs:~500-596)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()
        before = min(len(v.committed_leaders()) for v in validators)
        assert before >= 2

        # Ports linger in TIME_WAIT; the server binds with SO_REUSEADDR, but
        # give the loop a beat to tear the old sockets down.
        await asyncio.sleep(0.5)

        restarted = await _start_all(committee, parameters, signers, privates, 4)
        try:
            # Recovery replays the WAL: committed history is intact and the
            # committee makes NEW progress beyond it.
            await _wait_commits(restarted, minimum=before + 2, timeout_s=60)
        finally:
            for v in restarted:
                await v.stop()

    asyncio.run(main())


def test_production_validators_commit_submitted_txs(tmp_path):
    """Production tier (validator.rs:165-212): SimpleBlockHandler ingestion,
    SimpleCommitObserver -> CommitConsumer delivery, submit-ack callbacks.
    The benchmarking smoke tests cover the fast-path node; this covers the
    application-facing assembly that nothing else drives."""
    n = 4
    committee, parameters, signers, privates = _setup(tmp_path, n)

    async def main():
        started = [
            await Validator.start_production(
                i,
                committee,
                parameters,
                privates[i],
                signer=signers[i],
                verifier="cpu",
            )
            for i in range(n)
        ]
        validators = [v for v, _, _ in started]
        handlers = [h for _, h, _ in started]
        consumers = [c for _, _, c in started]
        try:
            acked = []
            payloads = [f"tx-{i}".encode() for i in range(20)]
            for i, p in enumerate(payloads):
                handlers[i % n].submit(p, done=lambda p=p: acked.append(p))

            async def collect(consumer, want, timeout_s=60.0):
                got = set()
                loop = asyncio.get_event_loop()
                deadline = loop.time() + timeout_s
                while len(got) < len(want):
                    remaining = deadline - loop.time()
                    assert remaining > 0, f"only {len(got)}/{len(want)} delivered"
                    sub_dag = await asyncio.wait_for(
                        consumer.queue.get(), timeout=remaining
                    )
                    for block in sub_dag.blocks:
                        for _, tx in block.shared_transactions():
                            if tx in want:
                                got.add(tx)
                return got

            want = set(payloads)
            got = await collect(consumers[0], want)
            assert got == want
            # every node's consumer sees the same transactions
            got1 = await collect(consumers[1], want)
            assert got1 == want
            # submit-acks fired once each tx was drained into a proposal
            assert set(acked) == want
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_production_replay_above_last_sent_height(tmp_path):
    """SimpleCommitObserver recovery (commit_observer.rs:232-260): a restarted
    node re-sends exactly the committed sub-dags above the consumer's
    last_sent_height."""
    n = 4
    committee, parameters, signers, privates = _setup(tmp_path, n)

    async def main():
        from mysticeti_tpu.validator import CommitConsumer

        started = [
            await Validator.start_production(
                i, committee, parameters, privates[i],
                signer=signers[i], verifier="accept",
            )
            for i in range(n)
        ]
        validators = [v for v, _, _ in started]
        consumers = [c for _, _, c in started]
        try:
            # run until some sub-dags are committed and delivered
            heights = []
            while len(heights) < 5:
                sub_dag = await asyncio.wait_for(consumers[0].queue.get(), 30.0)
                heights.append(sub_dag.height)
        finally:
            for v in validators:
                await v.stop()

        assert heights == sorted(heights)
        # Restart node 0 with a consumer that has seen up to heights[1]:
        # recovery must re-send heights above it, in order, before new ones.
        resumed = CommitConsumer(last_sent_height=heights[1])
        v0, _, consumer = await Validator.start_production(
            0, committee, parameters, privates[0],
            signer=signers[0], commit_consumer=resumed, verifier="accept",
        )
        try:
            replayed = []
            while len(replayed) < len(heights) - 2:
                sub_dag = await asyncio.wait_for(consumer.queue.get(), 30.0)
                replayed.append(sub_dag.height)
            assert replayed[: len(heights) - 2] == heights[2:]
        finally:
            await v0.stop()

    asyncio.run(main())
