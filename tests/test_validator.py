"""Whole-node smoke tests over real localhost TCP (validator.rs:355-596 tier).

These run on the REAL asyncio loop (not the simulator): they exercise actual
sockets, frames, reconnects and the wal-sync thread.
"""
import asyncio
import os
import socket

import pytest

from mysticeti_tpu.cli import benchmark_genesis
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Identifier, Parameters, PrivateConfig
from mysticeti_tpu.validator import Validator


def _free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _setup(tmp_path, n):
    ports = _free_ports(2 * n)
    identifiers = [
        Identifier("127.0.0.1", ports[2 * i], ports[2 * i + 1]) for i in range(n)
    ]
    parameters = Parameters(identifiers=identifiers, leader_timeout_s=0.5)
    signers = Committee.benchmark_signers(n)
    from mysticeti_tpu.committee import Authority

    committee = Committee([Authority(1, s.public_key) for s in signers])
    privates = [
        PrivateConfig.new_in_dir(i, str(tmp_path / f"v{i}")) for i in range(n)
    ]
    return committee, parameters, signers, privates


async def _start_all(committee, parameters, signers, privates, n, verifier="accept"):
    return [
        await Validator.start_benchmarking(
            i,
            committee,
            parameters,
            privates[i],
            signer=signers[i],
            tps=20,
            serve_metrics_endpoint=(i == 0),
            verifier=verifier,
        )
        for i in range(n)
    ]


async def _wait_commits(validators, minimum, timeout_s):
    async def poll():
        while True:
            if all(len(v.committed_leaders()) >= minimum for v in validators):
                return
            await asyncio.sleep(0.2)

    await asyncio.wait_for(poll(), timeout=timeout_s)


def test_validator_commit(tmp_path):
    """4 validators over localhost TCP commit leaders (validator_commit)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_sync_late_boot(tmp_path):
    """A late-booting node catches up through subscribe/sync (validator_sync)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 3)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
            late = await Validator.start_benchmarking(
                3, committee, parameters, privates[3], signer=signers[3],
                tps=20, serve_metrics_endpoint=False,
            )
            validators.append(late)
            await _wait_commits([late], minimum=1, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_crash_faults(tmp_path):
    """3 of 4 validators keep committing (validator_crash_faults)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 3)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=90)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_validator_metrics_endpoint(tmp_path):
    """The /metrics endpoint serves the benchmark-defining series."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=1, timeout_s=60)
            host, port = parameters.metrics_address(0)
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await asyncio.wait_for(reader.read(-1), timeout=10)
            writer.close()
            assert b"committed_leaders_total" in data
            assert b"benchmark_duration" in data
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_benchmark_genesis_roundtrip(tmp_path):
    wd = str(tmp_path / "genesis")
    benchmark_genesis(["10.0.0.1", "10.0.0.2", "10.0.0.3"], wd)
    committee = Committee.load(os.path.join(wd, "committee.yaml"))
    parameters = Parameters.load(os.path.join(wd, "parameters.yaml"))
    assert len(committee) == 3
    assert len(parameters.identifiers) == 3
    assert parameters.identifiers[1].hostname == "10.0.0.2"
    assert os.path.exists(os.path.join(wd, "validator-0", "seed"))


def test_validator_shutdown_and_start(tmp_path):
    """Stop the whole committee, restart on the SAME ports with the SAME
    WALs, and commits must resume past the pre-restart point — catches port
    reuse and WAL-reopen-under-assembly bugs (validator_shutdown_and_start,
    validator.rs:~500-596)."""

    async def main():
        committee, parameters, signers, privates = _setup(tmp_path, 4)
        validators = await _start_all(committee, parameters, signers, privates, 4)
        try:
            await _wait_commits(validators, minimum=2, timeout_s=60)
        finally:
            for v in validators:
                await v.stop()
        before = min(len(v.committed_leaders()) for v in validators)
        assert before >= 2

        # Ports linger in TIME_WAIT; the server binds with SO_REUSEADDR, but
        # give the loop a beat to tear the old sockets down.
        await asyncio.sleep(0.5)

        restarted = await _start_all(committee, parameters, signers, privates, 4)
        try:
            # Recovery replays the WAL: committed history is intact and the
            # committee makes NEW progress beyond it.
            await _wait_commits(restarted, minimum=before + 2, timeout_s=60)
        finally:
            for v in restarted:
                await v.stop()

    asyncio.run(main())
