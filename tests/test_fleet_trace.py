"""Fleet causal trace plane: wire timestamp extension (tag 12), cross-node
journey merge + skew estimation (tools/fleet_trace.py), the always-on flight
recorder with its dump triggers, and the shared truncated-trace extraction.

Acceptance pins (ISSUE 9): a seeded 10-node sim produces a byte-identical
merged fleet trace AND byte-identical flight-recorder dumps across same-seed
runs; the skew estimator recovers injected per-node clock offsets within
tolerance; a chaos safety failure writes recorder dumps for every live node;
the timestamp extension is version-skew safe in both directions.
"""
import asyncio
import json
import os
import sys

import pytest

from mysticeti_tpu import spans
from mysticeti_tpu.block_handler import TestBlockHandler
from mysticeti_tpu.block_store import BlockStore
from mysticeti_tpu.commit_observer import TestCommitObserver
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.core import Core, CoreOptions
from mysticeti_tpu.flight_recorder import FlightRecorder
from mysticeti_tpu.metrics import Metrics, serve_metrics
from mysticeti_tpu.net_sync import NetworkSyncer
from mysticeti_tpu.network import (
    Blocks,
    SerdeError,
    TimestampedBlocks,
    decode_message,
    encode_message,
)
from mysticeti_tpu.runtime.simulated import run_simulation
from mysticeti_tpu.simulated_network import SimulatedNetwork
from mysticeti_tpu.spans import PIPELINE_STAGES, format_ref
from mysticeti_tpu.wal import walf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

pytestmark = pytest.mark.tracing


# -- wire format: tag 12, version-skew safe both directions -------------------


def test_timestamped_blocks_roundtrip():
    msg = TimestampedBlocks(
        (b"abc", b"defg"), sent_monotonic_ns=123456789, sent_wall_ns=987654321
    )
    decoded = decode_message(encode_message(msg))
    assert isinstance(decoded, TimestampedBlocks)
    assert isinstance(decoded, Blocks)  # every receive path handles it
    assert decoded.blocks == (b"abc", b"defg")
    assert decoded.sent_monotonic_ns == 123456789
    assert decoded.sent_wall_ns == 987654321


def test_plain_blocks_unchanged_on_wire():
    """New receiver <- old sender: tag 2 frames decode exactly as before."""
    decoded = decode_message(encode_message(Blocks((b"xy",))))
    assert type(decoded) is Blocks
    assert decoded.blocks == (b"xy",)


def test_old_receiver_resets_on_unknown_tag():
    """Old receiver <- new sender: a pre-r9 decoder has no tag 12 branch, so
    the frame MUST reject (connection reset, per wire-format §7) — same
    contract every unknown tag already obeys."""
    frame = bytearray(encode_message(
        TimestampedBlocks((b"z",), sent_monotonic_ns=1, sent_wall_ns=2)
    ))
    assert frame[0] == 12
    # Emulate the pre-r9 tag table: any tag beyond it rejects.
    frame[0] = 200
    with pytest.raises(SerdeError):
        decode_message(bytes(frame))


def test_wall_jump_detection():
    """The monotonic stamp's purpose: consecutive frames whose wall delta
    disagrees with the monotonic delta mean the sender's wall clock STEPPED
    — the receiver drops that frame's transit sample."""
    from mysticeti_tpu.net_sync import WALL_JUMP_TOLERANCE_US
    from mysticeti_tpu.network import wall_jump_us

    t0 = (1_000_000_000, 5_000_000_000)
    # Both clocks advanced 1s: consistent, well inside tolerance.
    steady = (t0[0] + 1_000_000_000, t0[1] + 1_000_000_000)
    assert wall_jump_us(t0, steady) == 0
    # 30ms of NTP slew over the gap: tolerated.
    slew = (t0[0] + 1_000_000_000, t0[1] + 1_030_000_000)
    assert wall_jump_us(t0, slew) <= WALL_JUMP_TOLERANCE_US
    # A 2s wall STEP (backwards or forwards) while monotonic moved 1s.
    jumped = (t0[0] + 1_000_000_000, t0[1] + 3_000_000_000)
    assert wall_jump_us(t0, jumped) > WALL_JUMP_TOLERANCE_US
    jumped_back = (t0[0] + 1_000_000_000, t0[1] - 1_000_000_000)
    assert wall_jump_us(t0, jumped_back) > WALL_JUMP_TOLERANCE_US


def test_disseminator_stamps_only_when_knob_on():
    from mysticeti_tpu.config import SynchronizerParameters
    from mysticeti_tpu.synchronizer import BlockDisseminator

    def make(knob):
        return BlockDisseminator(
            connection=None, block_store=None, block_ready=None,
            parameters=SynchronizerParameters(timestamp_frames=knob),
        )

    off = make(False)._blocks_message((b"b",))
    assert type(off) is Blocks
    on = make(True)._blocks_message((b"b",))
    assert isinstance(on, TimestampedBlocks)
    assert on.sent_wall_ns > 0 and on.sent_monotonic_ns > 0


# -- the deterministic 10-node sim: merge + dump byte-identity ---------------


class _SimNodeNetwork:
    def __init__(self, queue):
        self.connections = queue

    async def stop(self):
        pass


def _build_node(committee, signers, authority, tmp_dir, sim_net, parameters,
                recorder=None):
    wal_writer, wal_reader = walf(os.path.join(tmp_dir, f"wal-{authority}"))
    recovered, observer_recovered = BlockStore.open(
        authority, wal_reader, wal_writer, committee
    )
    handler = TestBlockHandler(
        last_transaction=authority * 1_000_000,
        committee=committee,
        authority=authority,
    )
    core = Core(
        block_handler=handler,
        authority=authority,
        committee=committee,
        parameters=parameters,
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signers[authority],
    )
    observer = TestCommitObserver(
        core.block_store, committee, recovered_state=observer_recovered
    )
    if recorder is not None:
        observer.recorder = recorder
    return NetworkSyncer(
        core,
        observer,
        _SimNodeNetwork(sim_net.node_connections[authority]),
        parameters=parameters,
        recorder=recorder,
    )


async def _run_traced_fleet(n, tmp_dir, virtual_seconds, recorders):
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = Parameters(leader_timeout_s=1.0)
    parameters.synchronizer.timestamp_frames = True
    sim_net = SimulatedNetwork(n)
    nodes = [
        _build_node(committee, signers, a, tmp_dir, sim_net, parameters,
                    recorder=recorders[a])
        for a in range(n)
    ]
    for node in nodes:
        await node.start()
    await sim_net.connect_all()
    await asyncio.sleep(virtual_seconds)
    for node in nodes:
        await node.stop()
    sim_net.close()
    # Recorder dumps must be taken ON the virtual loop (a production dump
    # runs where the incident is; and is_simulated() gates the wall stamp).
    dumps = [recorders[a].snapshot_bytes() for a in range(n)]
    return nodes, dumps


def _traced_fleet_run(tmp_dir, seed, n=10):
    recorders = [FlightRecorder(authority=a) for a in range(n)]
    tracer = spans.start_from_env()
    assert tracer is not None
    try:
        nodes, dumps = run_simulation(
            _run_traced_fleet(n, tmp_dir, 8.0, recorders), seed=seed
        )
    finally:
        spans.stop_from_env()
    committed = [
        list(node.syncer.commit_observer.committed_leaders) for node in nodes
    ]
    path = os.environ["MYSTICETI_TRACE"].replace("%p", str(os.getpid()))
    with open(path, "rb") as f:
        return f.read(), committed, dumps, path


def test_ten_node_merge_and_dumps_byte_identical(tmp_path, monkeypatch):
    """(a) of the acceptance tests: same seed => byte-identical raw trace,
    byte-identical MERGED fleet trace, and byte-identical flight-recorder
    dumps on every node; plus the journeys are genuinely stitched (author's
    propose + per-peer transit + every pipeline stage)."""
    from tools.fleet_trace import merge

    monkeypatch.setenv("MYSTICETI_TRACE", str(tmp_path / "trace-%p.json"))
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    raw_a, committed, dumps_a, path = _traced_fleet_run(
        str(tmp_path / "a"), seed=23
    )
    trace_a = tmp_path / "run-a.json"
    trace_a.write_bytes(raw_a)
    raw_b, _, dumps_b, _ = _traced_fleet_run(str(tmp_path / "b"), seed=23)

    assert raw_a == raw_b
    assert dumps_a == dumps_b
    # The dumps hold real event streams (commits + connection churn).
    doc = json.loads(dumps_a[0])
    kinds = {e["kind"] for e in doc["events"]}
    assert "commit" in kinds and "peer-connect" in kinds
    assert doc["dropped"] == 0 and doc["recorded"] == len(doc["events"])
    assert "generated_unix" not in doc  # sim dumps carry no wall clock

    merged_a = merge([str(trace_a)])
    merged_b = merge([path])
    canon = lambda d: json.dumps(d, sort_keys=True).encode()  # noqa: E731
    assert canon({**merged_a, "inputs": []}) == canon({**merged_b, "inputs": []})

    # Stitching: every committed mid-sequence leader's journey names its
    # author, carries per-peer transit, and crosses every pipeline stage.
    sequences = [seq for seq in committed if seq]
    assert sequences and all(len(s) >= 20 for s in sequences)
    leader = sequences[0][len(sequences[0]) // 2]
    label = format_ref(leader)
    journey = next(j for j in merged_a["journeys"] if j["block"] == label)
    assert journey["author"] == leader.authority
    assert journey["fully_stitched"] and journey["propose_anchored"]
    assert journey["transit_ms"], journey
    seen_stages = set()
    for node_stages in journey["nodes"].values():
        seen_stages.update(node_stages)
    assert set(PIPELINE_STAGES) <= seen_stages
    assert merged_a["fully_stitched"] >= 20
    assert merged_a["transit_observations"] > 0
    # The skew table is embedded and names every authority.
    assert set(merged_a["skew"]["offsets_us"]) == {str(a) for a in range(10)}


# -- skew estimator: injected offsets recovered ------------------------------


def _synthetic_trace(path, authority, peers, offsets_us, base_delay_us=50_000):
    """One node's trace containing only transit spans: raw transit from
    peer p = base delay + jitter + (own offset - p's offset); one
    zero-jitter frame per link pins the minimum."""
    events = [
        {"args": {"name": f"A{authority}"}, "name": "thread_name",
         "ph": "M", "pid": 1, "tid": authority},
    ]
    jitters = (0, 1_500, 3_000, 700)
    for p in peers:
        for i, jitter in enumerate(jitters):
            raw = base_delay_us + jitter + (
                offsets_us[authority] - offsets_us[p]
            )
            events.append(
                {
                    "args": {
                        "block": f"A{p}R{i + 1}#aabbccdd",
                        "src": p,
                        "raw_us": raw,
                    },
                    "cat": "pipeline",
                    "dur": max(0, raw),
                    "name": "transit",
                    "ph": "X",
                    "pid": 1,
                    "tid": authority,
                    "ts": 1_000_000 * (i + 1),
                }
            )
    doc = {
        "displayTimeUnit": "ms",
        "otherData": {"clock_runtime_us": 0, "clock_wall_us": 0},
        "traceEvents": events,
    }
    with open(path, "w") as f:
        json.dump(doc, f)


def test_skew_estimator_recovers_injected_offsets(tmp_path):
    """(b): per-node clock offsets injected into synthetic transit data are
    recovered within tolerance by min-transit alignment."""
    from tools.fleet_trace import merge

    offsets_us = {0: 0, 1: 40_000, 2: -25_000}  # +40ms, -25ms vs node 0
    paths = []
    for a in range(3):
        peers = [p for p in range(3) if p != a]
        path = str(tmp_path / f"trace-{a}.json")
        _synthetic_trace(path, a, peers, offsets_us)
        paths.append(path)
    doc = merge(paths)
    skew = doc["skew"]["offsets_us"]
    for a, injected in offsets_us.items():
        assert abs(skew[str(a)] - injected) <= 2_000, (skew, injected)
    # Corrected link latency lands back on the true delay floor (50ms).
    for link, row in doc["skew"]["links"].items():
        assert abs(row["latency_min_ms"] - 50.0) <= 2.0, (link, row)


# -- shared stage extraction: truncated multi-node trace ---------------------


def _pipeline_trace_doc():
    events = []
    for a in (0, 1):
        events.append({"args": {"name": f"A{a}"}, "name": "thread_name",
                       "ph": "M", "pid": 1, "tid": a})
    for label, author in (("A0R5#11112222", 0), ("A1R6#33334444", 1)):
        events.append({"args": {"block": label}, "cat": "pipeline", "dur": 10,
                       "name": "propose", "ph": "X", "pid": 1, "tid": author,
                       "ts": 1000})
        for a in (0, 1):
            for i, stage in enumerate(PIPELINE_STAGES):
                if a == author and stage in ("receive", "verify", "dag_add"):
                    continue
                events.append({
                    "args": {"block": label}, "cat": "pipeline",
                    "dur": 100 * (i + 1), "name": stage, "ph": "X",
                    "pid": 1, "tid": a, "ts": 2000 + 1000 * i,
                })
    return {"displayTimeUnit": "ms",
            "otherData": {"clock_runtime_us": 0, "clock_wall_us": 0},
            "traceEvents": events}


def test_truncated_trace_same_boundaries_in_both_tools(tmp_path):
    """Regression (satellite 3): trace_report --critical-path and the fleet
    merge share ONE salvage + stage-extraction path, so a multi-node trace
    truncated mid-flush yields the SAME committed leaders and the same
    per-leader stage sets in both tools."""
    from tools.fleet_trace import merge
    from tools.trace_report import attribute_critical_paths, load_events, load_spans

    full = json.dumps(_pipeline_trace_doc())
    # Tear inside the last event object: both tools must salvage the same
    # complete prefix.
    torn = full[: full.rfind('{"args"') + 40]
    path = tmp_path / "torn.json"
    path.write_text(torn)

    events, note = load_events(str(path))
    assert "truncated" in note
    report_chains = {
        (rec["leader"], rec["track"][1]): set(rec["stages"])
        for rec in attribute_critical_paths(load_spans(events))
    }
    merged = merge([str(path)])
    merge_chains = {}
    for j in merged["journeys"]:
        for a, stages in j["nodes"].items():
            merge_chains[(j["block"], int(a))] = {
                s for s in stages if s in PIPELINE_STAGES
            }
    assert set(report_chains) == set(merge_chains)
    for key, stages in report_chains.items():
        assert merge_chains[key] == stages, key
    # And the torn tail really cost something vs the intact file.
    intact = tmp_path / "full.json"
    intact.write_text(full)
    assert len(merge([str(intact)])["journeys"]) >= len(merged["journeys"])


# -- flight recorder unit + dump triggers ------------------------------------


def test_flight_recorder_ring_bounds_and_dump(tmp_path):
    rec = FlightRecorder(authority=3, capacity=4)
    for i in range(7):
        rec.record("evt", i=i)
    assert rec.recorded == 7 and rec.dropped == 3
    events = rec.events()
    assert len(events) == 4 and events[-1]["i"] == 6
    assert rec.events(last=2)[0]["i"] == 5

    path = str(tmp_path / "fr.json")
    written = rec.dump("shutdown", path=path)
    assert written == path and not os.path.exists(path + ".tmp")
    doc = json.loads(open(path).read())
    assert doc["authority"] == 3 and len(doc["events"]) == 4
    assert rec.dumps[0]["trigger"] == "shutdown"
    assert rec.dumps[0]["file"] == "fr.json"  # basenames only (determinism)


def test_flight_recorder_alert_dump_is_debounced(tmp_path):
    path = str(tmp_path / "fr.json")
    rec = FlightRecorder(authority=0, dump_path=path, alert_debounce_s=1e9)
    rec.on_alert("round-stall", None, "receive", 12.0, "stalled")
    assert os.path.exists(path + ".alert")
    os.unlink(path + ".alert")
    rec.on_alert("round-stall", None, "receive", 13.0, "still stalled")
    assert not os.path.exists(path + ".alert")  # inside the debounce window
    # Both alerts are in the ring regardless.
    assert sum(1 for e in rec.events() if e["kind"] == "slo-alert") == 2


def test_debug_flight_recorder_route(tmp_path):
    async def scenario():
        metrics = Metrics()
        rec = FlightRecorder(authority=7, metrics=metrics)
        rec.record("probe", detail="hello")
        server = await serve_metrics(
            metrics, "127.0.0.1", 0, flight_recorder=rec
        )
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /debug/flight-recorder HTTP/1.1\r\nHost: t\r\n\r\n")
        await writer.drain()
        payload = await reader.read()
        writer.close()
        server.close()
        await server.wait_closed()
        return payload

    payload = asyncio.run(scenario())
    head, body = payload.split(b"\r\n\r\n", 1)
    assert b"200 OK" in head and b"application/json" in head
    doc = json.loads(body)
    assert doc["authority"] == 7
    assert doc["events"][0]["kind"] == "probe"


# -- chaos integration: safety failure dumps every live node -----------------


def test_chaos_safety_failure_dumps_every_live_node(tmp_path):
    """(c): the moment the SafetyChecker fails, run_chaos_sim writes a valid
    flight-recorder dump for every live node before re-raising."""
    from mysticeti_tpu.chaos import FaultPlan, SafetyViolation, run_chaos_sim
    from mysticeti_tpu.types import BlockReference

    async def poison(harness):
        await asyncio.sleep(2.0)
        fake = BlockReference(9, 99, b"\xff" * 32)
        # A forged anchor at height 1 for authority 0: global prefix
        # consistency must fail at the end-of-run audit.
        harness.checker._anchors.setdefault(0, {})[1] = fake

    with pytest.raises(SafetyViolation):
        run_chaos_sim(
            FaultPlan(seed=5), 4, 6.0, str(tmp_path), extra_fault=poison
        )
    for a in range(4):
        path = tmp_path / f"flight-recorder-{a}.json"
        assert path.exists(), f"no dump for live node {a}"
        doc = json.loads(path.read_text())
        assert doc["authority"] == a
        assert any(e["kind"] == "commit" for e in doc["events"])


def test_chaos_recorder_dumps_byte_identical_same_seed(tmp_path):
    from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim

    plan = FaultPlan(
        seed=11, crashes=[CrashFault(node=2, at_s=3.0, downtime_s=1.5)]
    )
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    report_a, _ = run_chaos_sim(plan, 4, 8.0, str(tmp_path / "a"))
    report_b, _ = run_chaos_sim(plan, 4, 8.0, str(tmp_path / "b"))
    assert report_a.recorder_dumps == report_b.recorder_dumps
    assert set(report_a.recorder_dumps) == {0, 1, 2, 3}
    crashed = json.loads(report_a.recorder_dumps[2])
    kinds = [e["kind"] for e in crashed["events"]]
    assert "crash" in kinds and "restart" in kinds


# -- fleetmon: flight-recorder embed + --dump-on-red -------------------------


def test_fleetmon_dump_on_red(tmp_path):
    import argparse

    from tools.fleetmon import run as fleetmon_run

    async def scenario():
        metrics = Metrics()
        rec = FlightRecorder(authority=0, metrics=metrics)
        rec.record("commit", height=4)
        server = await serve_metrics(
            metrics, "127.0.0.1", 0, flight_recorder=rec
        )
        port = server.sockets[0].getsockname()[1]
        args = argparse.Namespace(
            targets=[f"127.0.0.1:{port}", "127.0.0.1:1"],  # node 1 is dead
            fleet_dir=None, interval=0.1, duration=0.0, once=True,
            out=str(tmp_path / "fleetmon.json"), min_participation=0.0,
            max_ticks=10, no_dashboard=True, dump_on_red=True,
            max_loop_lag=0.0,
        )
        rc = await fleetmon_run(args)
        server.close()
        await server.wait_closed()
        return rc

    rc = asyncio.run(scenario())
    assert rc == 3  # the dead node fails the readiness gate
    artifact = json.loads((tmp_path / "fleetmon.json").read_text())
    # Flight-recorder summary embedded: last events for the live node,
    # None for the unreachable one.
    summary = artifact["flight_recorder"]
    assert summary["0"]["last_events"][-1]["kind"] == "commit"
    assert summary["1"] is None
    # --dump-on-red wrote the live node's full ring next to the artifact.
    dump = tmp_path / "fleetmon.json.flight-0.json"
    assert dump.exists()
    assert artifact["flight_recorder_dumps"] == [dump.name]
    doc = json.loads(dump.read_text())
    assert doc["events"][0]["kind"] == "commit"
