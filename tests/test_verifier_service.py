"""Shared per-host verifier service: wire protocol, warmup gate, fleet wiring.

The round-4 fleet artifacts showed the cost of one JAX runtime per validator
process (serial warmups, N accelerator connections); verifier_service.py
moves the runtime into one host-level process.  These tests drive the unix-
socket protocol end-to-end with an injected backend (no accelerator needed)
plus the real TpuSignatureVerifier on the CPU-jax test platform.
"""
import asyncio
import os
import threading

import pytest

from mysticeti_tpu import crypto
from mysticeti_tpu.block_validator import CpuSignatureVerifier, SignatureVerifier
from mysticeti_tpu.verifier_service import (
    RemoteSignatureVerifier,
    VerifierServer,
)


class CountingBackend(SignatureVerifier):
    """CPU oracle + call accounting, to observe dispatch/warmup behavior."""

    def __init__(self) -> None:
        self.inner = CpuSignatureVerifier()
        self.warmups = 0
        self.calls = 0

    def warmup(self) -> None:
        self.warmups += 1

    def verify_signatures(self, public_keys, digests, signatures):
        self.calls += 1
        return self.inner.verify_signatures(public_keys, digests, signatures)


def _sigs(n, signers):
    pks, digests, sigs = [], [], []
    for i in range(n):
        signer = signers[i % len(signers)]
        digest = crypto.blake2b_256(b"payload-%d" % i)
        pks.append(signer.public_key.bytes)
        digests.append(digest)
        sigs.append(signer.sign(digest))
    return pks, digests, sigs


@pytest.fixture()
def signers():
    return [crypto.Signer.from_seed(i.to_bytes(32, "little")) for i in range(4)]


async def _with_server(tmp_path, committee_keys, backend, fn):
    server = VerifierServer(
        str(tmp_path / "verifier.sock"),
        committee_keys=committee_keys,
        backend=backend,
    )
    await server.start()
    try:
        return await fn(server)
    finally:
        await server.stop()


def test_roundtrip_indexed_and_raw(tmp_path, signers):
    keys = [s.public_key.bytes for s in signers]
    backend = CountingBackend()

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        await asyncio.to_thread(client.warmup)
        base = backend.calls  # warmup + server-side calibration dispatches
        # The service measured its own dispatch costs and shared them.
        assert client.dispatch_calibration() is not None
        pks, digests, sigs = _sigs(8, signers)
        # Corrupt one signature: result order must be preserved.
        sigs[3] = bytes(64)
        ok = await asyncio.to_thread(
            client.verify_signatures, pks, digests, sigs
        )
        assert ok == [True, True, True, False, True, True, True, True]
        # A pk OUTSIDE the committee routes through the RAW frame.
        stranger = crypto.Signer.from_seed(b"\x99" * 32)
        digest = crypto.blake2b_256(b"raw")
        ok = await asyncio.to_thread(
            client.verify_signatures,
            [stranger.public_key.bytes],
            [digest],
            [stranger.sign(digest)],
        )
        assert ok == [True]
        assert backend.calls == base + 2

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


def test_hello_is_the_warmup_gate_and_runs_once(tmp_path, signers):
    keys = [s.public_key.bytes for s in signers]
    backend = CountingBackend()

    async def scenario(server):
        clients = [
            RemoteSignatureVerifier(
                socket_path=server.socket_path, committee_keys=keys
            )
            for _ in range(3)
        ]
        # Concurrent warmups (a booting fleet): exactly one backend warmup.
        await asyncio.gather(
            *(asyncio.to_thread(c.warmup) for c in clients)
        )
        assert backend.warmups == 1

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


def test_committee_mismatch_rejected(tmp_path, signers):
    keys = [s.public_key.bytes for s in signers]

    async def scenario(server):
        other = crypto.Signer.from_seed(b"\x42" * 32)
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path,
            committee_keys=[other.public_key.bytes],
        )
        with pytest.raises(ConnectionError, match="committee mismatch"):
            await asyncio.to_thread(client.warmup)

    asyncio.run(_with_server(tmp_path, keys, CountingBackend(), scenario))


def test_client_reconnects_after_service_restart(tmp_path, signers):
    """The service restarting between fleets severs every cached client
    connection; the next call must transparently reconnect.  A dedicated
    1-thread executor pins the client to ONE os thread so its thread-local
    connection is actually reused across the restart."""
    from concurrent.futures import ThreadPoolExecutor

    keys = [s.public_key.bytes for s in signers]

    async def main():
        loop = asyncio.get_running_loop()
        pool = ThreadPoolExecutor(max_workers=1)
        client = RemoteSignatureVerifier(
            socket_path=str(tmp_path / "verifier.sock"), committee_keys=keys
        )
        pks, digests, sigs = _sigs(2, signers)

        def call():
            return client.verify_signatures(pks, digests, sigs)

        server1 = VerifierServer(
            client.socket_path, committee_keys=keys, backend=CountingBackend()
        )
        await server1.start()
        assert await loop.run_in_executor(pool, call) == [True, True]
        await server1.stop()
        server2 = VerifierServer(
            client.socket_path, committee_keys=keys, backend=CountingBackend()
        )
        await server2.start()
        try:
            assert await loop.run_in_executor(pool, call) == [True, True]
        finally:
            await server2.stop()
            pool.shutdown(wait=False)

    asyncio.run(main())


def test_concurrent_clients_share_one_backend(tmp_path, signers):
    keys = [s.public_key.bytes for s in signers]
    backend = CountingBackend()

    async def scenario(server):
        async def one_validator(seed):
            client = RemoteSignatureVerifier(
                socket_path=server.socket_path, committee_keys=keys
            )
            pks, digests, sigs = _sigs(16, signers)
            return await asyncio.to_thread(
                client.verify_signatures, pks, digests, sigs
            )

        results = await asyncio.gather(*(one_validator(i) for i in range(4)))
        assert all(all(r) for r in results)
        # 4 verify dispatches on top of warmup + server-side calibration.
        assert backend.calls == 2 + 4

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


def test_service_telemetry_queue_and_inflight_gauges(tmp_path, signers):
    """With metrics wired, the service tracks queue depth and per-connection
    in-flight requests (back to zero once answered) plus dispatch shape and
    padding series."""
    from mysticeti_tpu.metrics import Metrics

    keys = [s.public_key.bytes for s in signers]
    backend = CountingBackend()
    metrics = Metrics()

    async def scenario():
        server = VerifierServer(
            str(tmp_path / "verifier.sock"),
            committee_keys=keys,
            backend=backend,
            metrics=metrics,
        )
        await server.start()
        try:
            client = RemoteSignatureVerifier(
                socket_path=server.socket_path, committee_keys=keys
            )
            pks, digests, sigs = _sigs(8, signers)
            oks = await asyncio.to_thread(
                client.verify_signatures, pks, digests, sigs
            )
            assert all(oks)
        finally:
            await server.stop()

    asyncio.run(scenario())
    assert metrics.verifier_service_queue_depth._value.get() == 0
    scrape = metrics.expose().decode()
    # The per-connection in-flight child is REMOVED once the connection
    # closes: reconnecting fleets must not grow dead labeled series forever.
    assert 'verifier_service_inflight{connection="c0"}' not in scrape
    # One 8-signature dispatch was observed, with zero padding on the
    # CPU-backed test backend.
    assert "verify_dispatch_batch_size_count 1.0" in scrape
    assert 'verify_padding_wasted_total{backend="service"} 0.0' in scrape


def test_make_verifier_uses_service_when_env_set(tmp_path, signers, monkeypatch):
    """validator.py:_make_verifier routes tpu kinds through the service —
    and the validator side never builds its own JAX backend."""
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.validator import _make_verifier

    committee = Committee.new_for_benchmarks(4)
    keys = [committee.get_public_key(a).bytes for a in range(4)]
    backend = CountingBackend()

    async def scenario(server):
        monkeypatch.setenv("MYSTICETI_VERIFIER_SOCKET", server.socket_path)
        verifier = _make_verifier("tpu", committee)
        # ready is set by a warmup thread whose HELLO needs THIS event loop
        # (the server runs on it) — wait off-loop.
        assert await asyncio.to_thread(verifier.ready.wait, 30)
        hybrid = verifier.verifier
        assert isinstance(hybrid.tpu, RemoteSignatureVerifier)
        # Hybrid calibration probed the service once (1-sig dispatch).
        assert backend.calls >= 1
        only = _make_verifier("tpu-only", committee)
        assert await asyncio.to_thread(only.ready.wait, 30)
        assert isinstance(only.verifier, RemoteSignatureVerifier)

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


@pytest.mark.slow
def test_service_with_real_jax_backend(tmp_path, signers):
    """Whole stack against the real TpuSignatureVerifier (CPU-jax platform):
    HELLO triggers the actual trace/compile; verifies stay correct."""
    keys = [s.public_key.bytes for s in signers]

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        await asyncio.to_thread(client.warmup)
        pks, digests, sigs = _sigs(8, signers)
        sigs[5] = bytes(64)
        ok = await asyncio.to_thread(
            client.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 5 + [False] + [True] * 2

    asyncio.run(_with_server(tmp_path, keys, None, scenario))


def test_empty_hello_does_not_poison_the_committee(tmp_path, signers):
    """ADVICE r5: a first HELLO with ZERO keys (a RAW-only client) must not
    be adopted as the service committee — later clients presenting the real
    committee used to get a permanent 'committee mismatch' ERR."""
    keys = [s.public_key.bytes for s in signers]

    async def scenario(server):
        keyless = RemoteSignatureVerifier(socket_path=server.socket_path)
        await asyncio.to_thread(keyless.warmup)  # HELLO with 0 keys
        # RAW verifies work for the keyless client...
        stranger = crypto.Signer.from_seed(b"\x77" * 32)
        digest = crypto.blake2b_256(b"raw-only")
        ok = await asyncio.to_thread(
            keyless.verify_signatures,
            [stranger.public_key.bytes], [digest], [stranger.sign(digest)],
        )
        assert ok == [True]
        # ...and the first REAL committee still establishes the key set.
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        await asyncio.to_thread(client.warmup)
        pks, digests, sigs = _sigs(4, signers)
        ok = await asyncio.to_thread(
            client.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 4

    asyncio.run(_with_server(tmp_path, None, CountingBackend(), scenario))


def test_server_pipelines_requests_on_one_connection(tmp_path, signers):
    """The service reads/decodes request N+1 while N computes: two
    back-to-back requests on ONE connection against a slow backend complete
    in ~one compute time, not two (the stop-and-wait shape), and replies
    come back in request order."""
    import struct
    import time as _time

    from mysticeti_tpu.verifier_service import T_RAW, _frame

    # Wide enough that scheduler noise on a loaded 2-core CI box stays
    # small against the overlap margin (serial = 2*delay, gate = 1.8*delay).
    delay = 0.3
    keys = [s.public_key.bytes for s in signers]

    class SlowBackend(CountingBackend):
        def verify_signatures(self, public_keys, digests, signatures):
            _time.sleep(delay)
            return super().verify_signatures(public_keys, digests, signatures)

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        pks, digests, sigs = _sigs(2, signers)
        body = b"".join(
            pk + d + s for pk, d, s in zip(pks, digests, sigs)
        )

        def pipelined():
            conn = client._connect()
            try:
                for req_id in (1, 2):
                    conn.sendall(
                        _frame(T_RAW, struct.pack("<II", req_id, 2) + body)
                    )
                started = _time.monotonic()
                out = []
                for expect in (1, 2):
                    type_, payload = client._read_frame(conn)
                    (echoed,) = struct.unpack_from("<I", payload)
                    out.append((type_, echoed, list(payload[4:])))
                return _time.monotonic() - started, out
            finally:
                conn.close()

        elapsed, replies = await asyncio.to_thread(pipelined)
        assert [r[1] for r in replies] == [1, 2]  # in request order
        assert all(r[2] == [1, 1] for r in replies)
        # Overlapped: well under 2 x the per-request compute.
        assert elapsed < 2 * delay * 0.9, elapsed

    asyncio.run(_with_server(tmp_path, keys, SlowBackend(), scenario))


def test_client_async_dispatch_overlaps_and_survives_restart(tmp_path, signers):
    """verify_signatures_async sends now and reads at result(): two
    in-flight requests overlap through the service, and a service restart
    between submit and fetch re-runs the batch through the sync retry path
    instead of losing it."""
    import time as _time

    # Wide enough that scheduler noise on a loaded 2-core CI box stays
    # small against the overlap margin (serial = 2*delay, gate = 1.8*delay).
    delay = 0.3
    keys = [s.public_key.bytes for s in signers]

    class SlowBackend(CountingBackend):
        def verify_signatures(self, public_keys, digests, signatures):
            _time.sleep(delay)
            return super().verify_signatures(public_keys, digests, signatures)

    async def main():
        server = VerifierServer(
            str(tmp_path / "verifier.sock"), committee_keys=keys,
            backend=SlowBackend(),
        )
        await server.start()
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        pks, digests, sigs = _sigs(4, signers)

        def overlapped():
            # Pay warmup + server-side calibration + pool connects OUTSIDE
            # the timed region (the pure-Python oracle's 256-sig calibration
            # costs ~1 s), then time two overlapped in-flight requests.
            w1 = client.verify_signatures_async(pks, digests, sigs)
            w2 = client.verify_signatures_async(pks, digests, sigs)
            w1.result(), w2.result()  # two pooled conns now warm
            started = _time.monotonic()
            h1 = client.verify_signatures_async(pks, digests, sigs)
            h2 = client.verify_signatures_async(pks, digests, sigs)
            out = (h1.result(), h2.result())
            return _time.monotonic() - started, out

        try:
            elapsed, (r1, r2) = await asyncio.to_thread(overlapped)
            assert r1 == [True] * 4 and r2 == [True] * 4
            assert elapsed < 2 * delay * 0.9, elapsed
            # Submit, then kill and restart the service before fetching.
            handle = await asyncio.to_thread(
                client.verify_signatures_async, pks, digests, sigs
            )
        finally:
            await server.stop()
        server2 = VerifierServer(
            str(tmp_path / "verifier.sock"), committee_keys=keys,
            backend=CountingBackend(),
        )
        await server2.start()
        try:
            assert await asyncio.to_thread(handle.result) == [True] * 4
        finally:
            await server2.stop()

    asyncio.run(main())


def test_cpu_advertising_service_short_circuits(tmp_path, signers):
    """Acceptance (a): against a service advertising a CPU-only backend, the
    hybrid verifier pins routing to the in-process oracle — batches complete
    with ZERO socket frames and verify_shortcircuit_total flips."""
    from mysticeti_tpu.block_validator import HybridSignatureVerifier
    from mysticeti_tpu.metrics import Metrics

    keys = [s.public_key.bytes for s in signers]
    backend = CountingBackend()
    metrics = Metrics()

    async def scenario(server):
        remote = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        hybrid = HybridSignatureVerifier(tpu=remote, metrics=metrics)
        # Frozen clock: the re-HELLO upgrade probe's deadline never passes,
        # so the steady state under test is PURE short-circuit.
        hybrid._breaker_clock = lambda: 0.0
        await asyncio.to_thread(hybrid.warmup)
        assert remote.advertised_backend == "cpu"
        assert hybrid.pinned_backend == "cpu"
        assert hybrid.threshold() == hybrid.NEVER
        base_calls = backend.calls

        def socket_is_lava(*_a, **_k):
            raise AssertionError("pinned batch touched the service socket")

        remote.verify_signatures = socket_is_lava
        remote.verify_signatures_async = socket_is_lava
        # Well above DEFAULT_THRESHOLD: unpinned, this WOULD offload.
        pks, digests, sigs = _sigs(40, signers)
        sigs[5] = bytes(64)
        expected = [True] * 5 + [False] + [True] * 34

        def run_batch():
            ok = hybrid.verify_signatures(pks, digests, sigs)
            return ok, hybrid.backend_label

        for _ in range(2):
            ok, label = await asyncio.to_thread(run_batch)
            assert ok == expected
            assert label == "hybrid-cpu"
        assert backend.calls == base_calls  # zero frames reached the service
        flips = metrics.verify_shortcircuit_total.labels(
            "backend-cpu"
        )._value.get()
        assert flips == 2

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


def test_backend_upgrade_reopens_offload_without_restart(tmp_path, signers):
    """Acceptance (b): when the service re-advertises an accelerator
    backend (chip window opened / service restarted on real hardware), the
    pinned hybrid's re-HELLO probe unpins routing and offload resumes — no
    validator restart."""
    from mysticeti_tpu.block_validator import HybridSignatureVerifier

    keys = [s.public_key.bytes for s in signers]

    class SwitchableBackend(CountingBackend):
        platform = "cpu"

        def resolved_backend(self):
            return self.platform

    backend = SwitchableBackend()

    async def scenario(server):
        remote = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        clock = {"t": 0.0}
        hybrid = HybridSignatureVerifier(tpu=remote, threshold=1)
        hybrid._breaker_clock = lambda: clock["t"]
        await asyncio.to_thread(hybrid.warmup)
        assert hybrid.pinned_backend == "cpu"
        base = backend.calls
        pks, digests, sigs = _sigs(4, signers)
        # Pinned: the batch stays on the oracle (no service dispatch).
        ok = await asyncio.to_thread(
            hybrid.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 4 and backend.calls == base
        # The chip arrives: service now resolves to an accelerator.
        backend.platform = "tpu"
        clock["t"] = 60.0  # past any jittered probe deadline
        # This batch carries the re-HELLO probe (still verified on the
        # oracle — the probe is a HELLO frame, never a verify).
        ok = await asyncio.to_thread(
            hybrid.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 4 and backend.calls == base
        assert remote.advertised_backend == "tpu"
        assert hybrid.pinned_backend is None
        # Offload is open again: the next batch rides the socket.
        ok = await asyncio.to_thread(
            hybrid.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 4
        assert backend.calls == base + 1

    asyncio.run(_with_server(tmp_path, keys, backend, scenario))


class AuditBuf(bytearray):
    """bytearray that counts slice-assignments — each one is exactly one
    copy of payload bytes into the wire buffer (struct.pack_into goes
    through the C buffer API, so only digest/sig/key copies count)."""

    def __init__(self, *args):
        super().__init__(*args)
        self.writes = 0

    def __setitem__(self, key, value):
        self.writes += 1
        super().__setitem__(key, value)


class ScriptedSock:
    """In-memory service endpoint: records what sendall receives (object
    identity included) and answers each VERIFY/RAW with an all-valid
    RESULT via recv_into — so the copy/reuse audit is deterministic and
    socket-free."""

    def __init__(self):
        self.sent = []
        self.recv_targets = []
        self._rx = bytearray()

    def sendall(self, data):
        assert isinstance(data, memoryview), type(data)
        self.sent.append((data.obj, len(data)))
        import struct as _s

        length, type_, req_id, n = _s.unpack_from("<IBII", data)
        payload = _s.pack("<I", req_id) + b"\x01" * n
        self._rx += _s.pack("<IB", len(payload), 129) + payload  # T_RESULT

    def recv_into(self, view):
        assert isinstance(view, memoryview)
        self.recv_targets.append(view.obj)
        n = min(len(view), len(self._rx))
        view[:n] = self._rx[:n]
        del self._rx[:n]
        return n

    def close(self):
        pass


def test_pack_path_copies_once_and_reuses_buffer(signers):
    """Acceptance (c): the pack path performs exactly ONE copy of each
    digest/signature (and key, on the RAW path) per direction, sends
    straight from the per-connection buffer (object identity on the
    socket), and reuses that buffer across >= 10 dispatches with zero
    reallocation."""
    keys = [s.public_key.bytes for s in signers]
    client = RemoteSignatureVerifier(
        socket_path="/nonexistent.sock", committee_keys=keys
    )
    sock = ScriptedSock()
    client._tls.conn = sock  # bypass connect/HELLO: unit-level wire audit
    client._tls.req_id = 0
    pack = client._wire("pack")
    audit = AuditBuf(len(pack.buf))
    pack.buf = audit

    pks, digests, sigs = _sigs(8, signers)
    for i in range(12):
        before = audit.writes
        assert client.verify_signatures(pks, digests, sigs) == [True] * 8
        # T_VERIFY: key rides as a packed index — 2 slice-copies per
        # record (digest, sig), nothing else touches payload bytes.
        assert audit.writes - before == 2 * len(sigs)
    sent_objs = {id(obj) for obj, _ in sock.sent}
    assert sent_objs == {id(audit)}, "send did not come straight from the buffer"
    assert pack.buf is audit and pack.grows == 0, "buffer was reallocated"
    # Receive direction: every reply landed in the same recv buffer via
    # recv_into (one kernel->buffer copy; no per-chunk concatenation).
    recv_objs = {id(obj) for obj in sock.recv_targets}
    assert recv_objs == {id(client._wire("recv").buf)}

    # RAW path (a pk outside the committee): 3 copies per record.
    stranger = crypto.Signer.from_seed(b"\x55" * 32)
    digest = crypto.blake2b_256(b"raw-audit")
    before = audit.writes
    ok = client.verify_signatures(
        [stranger.public_key.bytes] * 4, [digest] * 4,
        [stranger.sign(digest)] * 4,
    )
    assert ok == [True] * 4
    assert audit.writes - before == 3 * 4


def test_hello_ok_version_skew_old_client(tmp_path, signers):
    """An old-protocol client (pre-r6: parses HELLO_OK only when exactly
    16 bytes) still interoperates with the new server — it loses the
    calibration (falls back to its own probe) but VERIFY/RESULT work."""
    import struct

    from mysticeti_tpu.verifier_service import (
        T_HELLO,
        T_HELLO_OK,
        T_RESULT,
        T_VERIFY,
        _frame,
    )

    keys = [s.public_key.bytes for s in signers]

    async def scenario(server):
        pks, digests, sigs = _sigs(3, signers)
        body = b"".join(
            struct.pack("<H", keys.index(pk)) + d + s
            for pk, d, s in zip(pks, digests, sigs)
        )

        def old_client():
            import socket as _socket

            conn = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            conn.settimeout(30)
            conn.connect(server.socket_path)

            def read_frame():  # the pre-r6 client's recv-loop parse
                header = b""
                while len(header) < 5:
                    header += conn.recv(5 - len(header))
                length, type_ = struct.unpack("<IB", header)
                payload = b""
                while len(payload) < length:
                    payload += conn.recv(length - len(payload))
                return type_, payload

            try:
                hello = struct.pack("<H", len(keys)) + b"".join(keys)
                conn.sendall(_frame(T_HELLO, hello))
                t1, reply = read_frame()
                # Old parse rule: calibration iff len == 16.
                calibration = (
                    struct.unpack("<dd", reply) if len(reply) == 16 else None
                )
                conn.sendall(
                    _frame(T_VERIFY, struct.pack("<II", 5, 3) + body)
                )
                t2, payload = read_frame()
                return t1, len(reply), calibration, t2, list(payload[4:])
            finally:
                conn.close()

        t1, reply_len, calibration, t2, oks = await asyncio.to_thread(
            old_client
        )
        assert t1 == T_HELLO_OK and t2 == T_RESULT
        assert reply_len > 16  # the new backend suffix is present...
        assert calibration is None  # ...and the old client ignores it
        assert oks == [1, 1, 1]

    asyncio.run(_with_server(tmp_path, keys, CountingBackend(), scenario))


def test_hello_ok_version_skew_old_server(tmp_path, signers, monkeypatch):
    """A new client against an old server (16-byte HELLO_OK, no backend
    suffix): calibration still seeds, the backend stays UNKNOWN, and the
    hybrid therefore never pins (conservative default)."""
    from mysticeti_tpu.verifier_service import VerifierServer as VS

    keys = [s.public_key.bytes for s in signers]
    # An empty backend suffix makes the payload exactly 16 bytes — byte-
    # identical to the pre-r6 server's HELLO_OK.
    monkeypatch.setattr(VS, "_resolved_backend", lambda self: "")

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        await asyncio.to_thread(client.warmup)
        assert client.dispatch_calibration() is not None
        assert client.advertised_backend is None
        pks, digests, sigs = _sigs(4, signers)
        ok = await asyncio.to_thread(
            client.verify_signatures, pks, digests, sigs
        )
        assert ok == [True] * 4

    asyncio.run(_with_server(tmp_path, keys, CountingBackend(), scenario))


def test_foreign_uid_peer_refused(tmp_path, signers, monkeypatch):
    """VERDICT r5 #5: a connection from another local user is severed at
    accept (SO_PEERCRED) before any frame is processed."""
    import mysticeti_tpu.verifier_service as vs

    keys = [s.public_key.bytes for s in signers]
    monkeypatch.setattr(vs, "_peer_uid", lambda sock: os.getuid() + 1)

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        with pytest.raises((ConnectionError, OSError)):
            await asyncio.to_thread(client.warmup)

    asyncio.run(_with_server(tmp_path, keys, CountingBackend(), scenario))


def test_socket_dir_hardening(tmp_path, signers, monkeypatch):
    """The service refuses to bind into a directory another uid owns, and
    tightens an owned-but-loose parent to 0700 (+ the socket to 0600)."""
    import stat as stat_mod

    keys = [s.public_key.bytes for s in signers]

    async def refused():
        server = VerifierServer(
            str(tmp_path / "unowned" / "verifier.sock"), committee_keys=keys,
            backend=CountingBackend(),
        )
        (tmp_path / "unowned").mkdir()
        monkeypatch.setattr(os, "getuid", lambda: 0x5EED)
        with pytest.raises(PermissionError, match="owned by uid"):
            await server.start()

    asyncio.run(refused())
    monkeypatch.undo()

    async def tightened():
        sock_dir = tmp_path / "loose"
        sock_dir.mkdir()
        os.chmod(sock_dir, 0o755)
        server = VerifierServer(
            str(sock_dir / "verifier.sock"), committee_keys=keys,
            backend=CountingBackend(),
        )
        await server.start()
        try:
            mode = stat_mod.S_IMODE(os.stat(sock_dir).st_mode)
            assert mode == 0o700, oct(mode)
            smode = stat_mod.S_IMODE(os.stat(server.socket_path).st_mode)
            assert smode == 0o600, oct(smode)
        finally:
            await server.stop()

    asyncio.run(tightened())


def test_pipelined_hello_then_verify_waits_for_committee(tmp_path, signers):
    """A client that pipelines HELLO + VERIFY without waiting for HELLO_OK
    must still get correct verdicts: the verify may not EXECUTE before the
    HELLO that establishes the committee finishes (it would see no keys and
    report every slot invalid)."""
    import struct

    from mysticeti_tpu.verifier_service import (
        T_HELLO,
        T_HELLO_OK,
        T_RESULT,
        T_VERIFY,
        _frame,
    )

    keys = [s.public_key.bytes for s in signers]

    async def scenario(server):
        client = RemoteSignatureVerifier(
            socket_path=server.socket_path, committee_keys=keys
        )
        pks, digests, sigs = _sigs(3, signers)
        body = b"".join(
            struct.pack("<H", keys.index(pk)) + d + s
            for pk, d, s in zip(pks, digests, sigs)
        )

        def pipelined():
            import socket as _socket

            conn = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            conn.settimeout(30)
            conn.connect(server.socket_path)
            try:
                hello = struct.pack("<H", len(keys)) + b"".join(keys)
                # HELLO and VERIFY in ONE write: no wait for HELLO_OK.
                conn.sendall(
                    _frame(T_HELLO, hello)
                    + _frame(T_VERIFY, struct.pack("<II", 9, 3) + body)
                )
                t1, _ = client._read_frame(conn)
                t2, payload = client._read_frame(conn)
                return t1, t2, list(payload[4:])
            finally:
                conn.close()

        t1, t2, oks = await asyncio.to_thread(pipelined)
        assert t1 == T_HELLO_OK and t2 == T_RESULT
        assert oks == [1, 1, 1], oks  # NOT all-zeros

    asyncio.run(_with_server(tmp_path, None, CountingBackend(), scenario))
