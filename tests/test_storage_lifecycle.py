"""Storage lifecycle plane (storage.py): segmented WAL + manifest atomicity,
commit-anchored checkpoints with fallback, DAG garbage collection, and
snapshot catch-up — unit coverage plus deterministic sims on the
virtual-time loop (crash-during-roll, crash-during-checkpoint, torn
manifest, bounded disk, O(recent) bootstrap)."""
import json
import os

import pytest

from mysticeti_tpu.chaos import CrashFault, FaultPlan, run_chaos_sim
from mysticeti_tpu.config import Parameters, StorageParameters
from mysticeti_tpu.storage import (
    MANIFEST_NAME,
    SegmentedWalWriter,
    active_wal_file,
    checkpoint_files,
    open_store,
    open_wal,
)
from mysticeti_tpu.wal import HEADER_SIZE, WalError, walf

pytestmark = pytest.mark.storage


def _params(**storage_kwargs):
    defaults = dict(segment_bytes=16 * 1024, checkpoint_interval=5, gc_depth=20)
    defaults.update(storage_kwargs)
    return Parameters(
        leader_timeout_s=1.0, storage=StorageParameters(**defaults)
    )


# ---------------------------------------------------------------------------
# Segmented WAL units


def test_roll_read_iter_and_reopen(tmp_path):
    params = StorageParameters(segment_bytes=2048)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    positions = [w.writev(1, (bytes([i % 250]) * 100,)) for i in range(50)]
    assert w.segment_count() > 1  # it actually rolled
    # Positions stay a single contiguous u64 space across segments.
    for i, p in enumerate(positions):
        tag, payload = r.read(p)
        assert (tag, bytes(payload)) == (1, bytes([i % 250]) * 100)
    assert [e[0] for e in r.iter_until()] == positions
    # Replay-from-position (the checkpoint seam) crosses segment boundaries.
    assert [e[0] for e in r.iter_from(positions[30])] == positions[30:]
    w.close()
    r.close()

    w2, r2 = open_wal(path, params)
    assert [e[0] for e in r2.iter_until()] == positions
    assert w2.write(2, b"post-reopen") == positions[-1] + HEADER_SIZE + 100
    w2.close()
    r2.close()


def test_entries_never_straddle_segments(tmp_path):
    params = StorageParameters(segment_bytes=1024)
    w, r = open_wal(str(tmp_path / "wal"), params)
    for i in range(20):
        w.write(1, b"x" * 300)
    w.flush()
    for name, base, size, _mr in w.segments_snapshot():
        # Every segment starts at an entry boundary: a standalone reader on
        # the bare file replays it fully.
        reader_positions = []
        from mysticeti_tpu.wal import WalReader

        reader = WalReader(os.path.join(str(tmp_path / "wal"), name))
        consumed = 0
        for pos, _tag, payload in reader.iter_until():
            consumed = pos + HEADER_SIZE + len(payload)
        reader.close()
        assert consumed == size, name
    w.close()
    r.close()


def test_single_file_migration(tmp_path):
    path = str(tmp_path / "wal")
    w, r = walf(path)
    p = w.write(7, b"legacy-entry")
    w.sync()
    w.close()
    r.close()
    assert os.path.isfile(path)
    w2, r2 = open_wal(path, StorageParameters(segment_bytes=4096))
    assert os.path.isdir(path)  # migrated in place
    assert r2.read(p) == (7, b"legacy-entry")
    w2.close()
    r2.close()


def test_torn_active_tail_truncated_on_reopen(tmp_path):
    params = StorageParameters(segment_bytes=4096)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    good = w.write(1, b"good")
    w.write(2, b"to-be-torn" * 10)
    w.sync()
    w.close()
    r.close()
    active = active_wal_file(path)
    with open(active, "r+b") as f:
        f.truncate(os.path.getsize(active) - 8)

    w2, r2 = open_wal(path, params)
    replayed = list(r2.iter_from(0, w2.position()))
    assert [(t, bytes(d)) for _, t, d in replayed] == [(1, b"good")]
    # The recovery contract: truncate at the tear, then appends resume there.
    w2.truncate_to(good + HEADER_SIZE + 4)
    p3 = w2.write(3, b"after")
    assert p3 == good + HEADER_SIZE + 4
    assert [t for _, t, _ in r2.iter_until()] == [1, 3]
    w2.close()
    r2.close()


def test_tear_in_sealed_segment_drops_later_segments(tmp_path):
    params = StorageParameters(segment_bytes=1024)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    for i in range(12):
        w.write(1, bytes([i]) * 300)
    w.sync()
    segments = w.segments_snapshot()
    assert len(segments) >= 3
    w.close()
    r.close()
    # Tear INSIDE the second segment (sealed): everything after it is
    # unreachable on replay and must be dropped.
    victim = os.path.join(path, segments[1][0])
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 5)

    w2, r2 = open_wal(path, params)
    entries = list(r2.iter_from(0, w2.position()))
    end = entries[-1][0] + HEADER_SIZE + len(entries[-1][2])
    assert end < w2.position()  # replay stops at the tear
    w2.truncate_to(end)
    # The torn segment became the active one; later segments are gone.
    assert w2.position() == end
    assert w2.segments_snapshot()[-1][0] == segments[1][0]
    assert not os.path.exists(os.path.join(path, segments[2][0]))
    p = w2.write(9, b"resumed")
    assert p == end
    assert [t for _, t, _ in r2.iter_from(p)] == [9]
    w2.close()
    r2.close()


def test_crash_during_roll_orphan_segment_recovered(tmp_path):
    params = StorageParameters(segment_bytes=2048)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    positions = [w.write(1, b"z" * 150) for _ in range(10)]
    names = [s[0] for s in w.segments_snapshot()]
    w.close()
    r.close()
    # Crash window: the next segment file was created but the manifest
    # rewrite never happened.
    orphan = os.path.join(path, f"wal.{len(names):06d}")
    open(orphan, "wb").close()
    w2, r2 = open_wal(path, params)
    assert not os.path.exists(orphan) or os.path.getsize(orphan) == 0
    assert [e[0] for e in r2.iter_until()] == positions
    w2.write(1, b"continues")
    w2.close()
    r2.close()


def test_torn_manifest_tmp_is_ignored(tmp_path):
    params = StorageParameters(segment_bytes=2048)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    positions = [w.write(1, b"m" * 100) for _ in range(5)]
    w.close()
    r.close()
    # A crash mid-manifest-rewrite leaves a half-written tmp; the rename
    # never happened so the real manifest is intact.
    with open(os.path.join(path, MANIFEST_NAME + ".tmp"), "w") as f:
        f.write('{"version": 1, "segments": [{"nam')
    w2, r2 = open_wal(path, params)
    assert [e[0] for e in r2.iter_until()] == positions
    assert not os.path.exists(os.path.join(path, MANIFEST_NAME + ".tmp"))
    w2.close()
    r2.close()


def test_corrupt_manifest_is_loud(tmp_path):
    params = StorageParameters(segment_bytes=2048)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    w.write(1, b"x")
    w.close()
    r.close()
    with open(os.path.join(path, MANIFEST_NAME), "w") as f:
        f.write("{broken json")
    with pytest.raises(WalError, match="manifest"):
        open_wal(path, params)


def test_wal_size_bytes_counts_live_segments_only(tmp_path):
    params = StorageParameters(segment_bytes=1024)
    w, r = open_wal(str(tmp_path / "wal"), params)
    for i in range(1, 13):
        p = w.write(1, bytes([i]) * 300)
        w.note_round(i, p)
    w.flush()
    total = w.position()
    assert w.size_bytes() == total
    reclaimed, removed = w.retire_below(6, keep_from_position=total)
    assert removed > 0 and reclaimed > 0
    # The gauge source now reports live bytes, not lifetime bytes written.
    assert w.size_bytes() == total - reclaimed
    assert w.position() == total  # logical append position is untouched
    w.close()
    r.close()


def test_retire_below_is_prefix_only(tmp_path):
    """A sealed segment still holding live rounds STOPS garbage collection:
    deleting a later low-round segment past it would punch a hole in the
    base space, which recovery would misread as a mid-log tear."""
    params = StorageParameters(segment_bytes=1024)
    w, r = open_wal(str(tmp_path / "wal"), params)
    positions = []
    for i in range(12):
        positions.append(w.write(1, bytes([i]) * 300))
    w.flush()
    segs = w.segments_snapshot()
    assert len(segs) >= 4
    # First segment keeps a LIVE round; the second holds only retired ones.
    w.note_round(100, positions[0])
    w.note_round(1, segs[1][1])
    reclaimed, removed = w.retire_below(50, keep_from_position=w.position())
    assert (reclaimed, removed) == (0, 0)  # blocked by the live prefix
    # Bases stay contiguous, so a reopen sees no phantom tear.
    snapshot = w.segments_snapshot()
    for prev, cur in zip(snapshot, snapshot[1:]):
        assert cur[1] == prev[1] + prev[2]
    w.close()
    r.close()
    w2, r2 = open_wal(str(tmp_path / "wal"), params)
    assert [e[0] for e in r2.iter_until()] == positions
    w2.close()
    r2.close()


def test_gc_crash_between_manifest_and_unlink_recovers(tmp_path):
    """Crash-safety order of segment GC: the manifest drops the victims
    BEFORE their files are unlinked, so a crash in between leaves orphan
    files (deleted on recovery) — never a manifest naming missing files."""
    params = StorageParameters(segment_bytes=1024)
    path = str(tmp_path / "wal")
    w, r = open_wal(path, params)
    positions = []
    for i in range(1, 13):
        p = w.write(1, bytes([i]) * 300)
        w.note_round(i, p)
        positions.append(p)
    w.sync()
    victim_names = [s[0] for s in w.segments_snapshot()[:2]]
    victim_bytes = {
        name: open(os.path.join(path, name), "rb").read()
        for name in victim_names
    }
    reclaimed, removed = w.retire_below(9, keep_from_position=w.position())
    assert removed >= 2
    survivors = [e[0] for e in r.iter_until()]
    w.close()
    r.close()
    # Simulate the crash window: the unlinked victims come BACK as orphans
    # (equivalently: the crash happened right after the manifest rewrite).
    for name, data in victim_bytes.items():
        with open(os.path.join(path, name), "wb") as f:
            f.write(data)
    w2, r2 = open_wal(path, params)
    assert [e[0] for e in r2.iter_until()] == survivors
    for name in victim_names:
        assert not os.path.exists(os.path.join(path, name))  # orphans purged
    w2.close()
    r2.close()


# ---------------------------------------------------------------------------
# Checkpoint + GC + recovery through the real node stack (deterministic sims)


@pytest.mark.chaos
def test_checkpoint_boot_replays_only_the_tail(tmp_path):
    """Disk bounded + O(recent) boot: segments below the GC floor are
    deleted while the fleet commits, and a crash-restart boots from the
    newest checkpoint, replaying a small fraction of lifetime WAL bytes."""
    plan = FaultPlan(seed=7, crashes=[CrashFault(node=2, at_s=20.0, downtime_s=2.0)])
    report, harness = run_chaos_sim(
        plan, 4, 30.0, str(tmp_path), parameters=_params(), with_metrics=True
    )
    # Liveness through GC + checkpointing.
    assert all(harness.committed_height(a) > 100 for a in range(4))
    for authority in range(4):
        node = harness.nodes[authority]
        lifecycle = node.core.storage
        wal_dir = os.path.join(str(tmp_path), f"wal-{authority}")
        # GC actually deleted segments: the address space no longer starts
        # at zero and the reclaimed counter moved.
        assert node.core.wal_writer.first_base() > 0
        metrics = harness.metrics[authority]
        assert metrics.wal_reclaimed_bytes_total._value.get() > 0
        assert metrics.checkpoint_last_commit_index._value.get() > 0
        # Live disk is bounded well below lifetime bytes written.
        assert node.core.wal_writer.size_bytes() < node.core.wal_writer.position()
        assert len(checkpoint_files(wal_dir)) == 2  # pruned to the keep set
    # The crashed node recovered FROM A CHECKPOINT: it replayed only the
    # post-checkpoint tail, a small fraction of the lifetime log.
    restarted = harness.nodes[2].core.storage
    assert restarted.recovered_checkpoint_height > 0
    assert restarted.replay_start > 0
    lifetime = harness.nodes[2].core.wal_writer.position()
    assert restarted.replayed_bytes < lifetime / 5, (
        restarted.replayed_bytes, lifetime,
    )
    assert harness.metrics[2].crash_recovery_total._value.get() == 1.0
    # And it kept committing after the restart.
    assert harness.committed_height(2) > report.crash_events[0]["committed_height"]


@pytest.mark.chaos
def test_same_seed_storage_chaos_is_byte_identical(tmp_path):
    """Crash-during-roll / crash-during-checkpoint land WHEREVER the seeded
    schedule puts them (16 KiB segments roll every ~1 s; checkpoints every 5
    commits): same-seed runs produce byte-identical fault schedules and
    logs, and every node always recovers to a committing state."""
    plan = FaultPlan(
        seed=23,
        crashes=[
            CrashFault(node=1, at_s=6.0, downtime_s=2.0),
            CrashFault(node=3, at_s=9.0, downtime_s=2.0, torn_tail_bytes=11),
        ],
    )
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    report, harness = run_chaos_sim(
        plan, 4, 18.0, str(tmp_path / "a"), parameters=_params(),
        with_metrics=True,
    )
    replay, _ = run_chaos_sim(
        plan, 4, 18.0, str(tmp_path / "b"), parameters=_params(),
        with_metrics=True,
    )
    assert report.schedule_bytes == replay.schedule_bytes
    assert report.fault_log_bytes == replay.fault_log_bytes
    assert report.sequences == replay.sequences
    for event in report.crash_events:
        node = event["node"]
        assert harness.metrics[node].crash_recovery_total._value.get() == 1.0
        assert harness.committed_height(node) > event["committed_height"]


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    plan = FaultPlan(seed=5)
    report, harness = run_chaos_sim(
        plan, 4, 20.0, str(tmp_path), parameters=_params(), with_metrics=True
    )
    wal_dir = os.path.join(str(tmp_path), "wal-1")
    newest, older = checkpoint_files(wal_dir)[:2]
    # Torn checkpoint (crash mid-write survived the atomic rename somehow /
    # disk corruption): flip bytes in the newest one.
    with open(newest, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff\xff")
    from mysticeti_tpu.committee import Committee

    committee = Committee.new_test([1, 1, 1, 1])
    recovered, _obs, wal_writer, lifecycle = open_store(
        1, wal_dir, committee, _params()
    )
    older_height = int(os.path.basename(older).split(".")[1])
    assert lifecycle.recovered_checkpoint_height == older_height
    assert recovered.commit_height >= older_height  # tail replay catches up
    wal_writer.close()
    recovered.block_store.close()

    # Both checkpoints corrupt + GC'd history = genuinely unreplayable: the
    # boot refuses loudly instead of silently starting from a hole.
    with open(older, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(WalError, match="checkpoint"):
        open_store(1, wal_dir, committee, _params())


# ---------------------------------------------------------------------------
# Snapshot catch-up


@pytest.mark.chaos
def test_snapshot_catchup_rejoins_and_commits_fleet_sequence(tmp_path):
    """A node that missed ~200 commit heights (its history GC'd fleet-wide,
    so block-by-block pull from round zero is impossible) rejoins via the
    snapshot stream, adopts the fleet's commit baseline, and commits the
    SAME leader sequence at every shared height.  (The >= 1000-round regime
    rides in tools/storage_probe.py -> STORAGE_r08.json.)"""
    params = _params(snapshot_catchup=True, catchup_threshold_commits=50)
    plan = FaultPlan(
        seed=13, crashes=[CrashFault(node=3, at_s=3.0, downtime_s=30.0)]
    )
    report, harness = run_chaos_sim(
        plan, 4, 45.0, str(tmp_path), parameters=params, with_metrics=True
    )
    node3 = harness.nodes[3]
    lifecycle = node3.core.storage
    crashed_at = report.crash_events[0]["committed_height"]
    assert lifecycle.snapshots_adopted == 1
    # It genuinely skipped history: resumed well past where it crashed...
    anchors3 = harness.checker._anchors[3]
    resumed = min(h for h in sorted(anchors3) if h > crashed_at)
    assert resumed > crashed_at + params.storage.catchup_threshold_commits // 2
    # ...rejoined the committing fleet...
    heights = [harness.committed_height(a) for a in range(4)]
    assert min(heights) > max(heights) - 10
    assert harness.committed_height(3) > resumed + 50
    # ...and the committed-leader sequence is prefix-consistent with every
    # healthy node at every shared height (incl. the adopted anchor).
    anchors0 = harness.checker._anchors[0]
    shared = set(anchors0) & set(anchors3)
    assert len(shared) > 100
    assert all(anchors0[h] == anchors3[h] for h in shared)
    # The serving side shipped the bounded post-floor window, not history.
    served = sum(
        harness.nodes[a].snapshot_blocks_served
        + sum(
            d.snapshot_blocks_sent
            for d in harness.nodes[a]._disseminators.values()
        )
        for a in range(3)
    )
    assert served > 0


def test_manifest_and_checkpoint_roundtrip_units(tmp_path):
    from mysticeti_tpu.storage import SnapshotManifest, fold_leader_digest
    from mysticeti_tpu.types import BlockReference

    ref = BlockReference(2, 41, b"\x07" * 32)
    digest = fold_leader_digest(b"\x00" * 32, ref)
    manifest = SnapshotManifest(
        commit_height=41,
        last_committed_leader=ref,
        gc_round=21,
        chain_digest=digest,
        committed_refs=[ref, BlockReference(0, 40, b"\x01" * 32)],
    )
    again = SnapshotManifest.from_bytes(manifest.to_bytes())
    assert again == manifest
    # The digest chain is order-sensitive: folding the other ref differs.
    assert fold_leader_digest(b"\x00" * 32, manifest.committed_refs[1]) != digest


def test_block_manager_floor_drops_and_releases(tmp_path):
    from mysticeti_tpu.block_manager import BlockManager
    from mysticeti_tpu.block_store import BlockStore, BlockWriter
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.types import StatementBlock

    committee = Committee.new_test([1, 1, 1, 1])
    w, r = walf(str(tmp_path / "wal"))
    recovered, _ = BlockStore.open(0, r, w, committee)
    store = recovered.block_store
    manager = BlockManager(store, 4)
    writer = BlockWriter(w, store)
    genesis = [
        StatementBlock.new_genesis(a, committee.epoch)
        for a in committee.authority_indexes()
    ]
    # A block whose parents (round 9) we will never have.
    parents = [
        StatementBlock.build(a, 9, [g.reference for g in genesis], ())
        for a in committee.authority_indexes()
    ]
    orphan = StatementBlock.build(
        0, 10, [p.reference for p in parents], ()
    )
    processed, missing = manager.add_blocks([orphan], writer)
    assert not processed and missing  # parked, parents requested
    # Raising the floor to 10 settles the sub-floor parents and releases it.
    released, _missing2 = manager.set_gc_floor(10, writer)
    assert [b.reference for _pos, b in released] == [orphan.reference]
    assert all(not refs for refs in manager.missing)
    # Ancient blocks below the floor are dropped outright now...
    ancient, _ = manager.add_blocks(parents, writer)
    assert ancient == []
    # ...and read as settled at the dedup gate (never re-verified).
    assert manager.exists_or_pending(parents[0].reference)
    w.close()
    r.close()


def test_linearizer_floor_and_adoption():
    from mysticeti_tpu.consensus.linearizer import Linearizer
    from mysticeti_tpu.types import BlockReference

    lin = Linearizer(block_store=None)
    refs = [BlockReference(a, r, bytes([a]) * 32) for a in range(2) for r in (5, 30)]
    lin.committed.update(refs)
    lin.last_height = 3
    lin.set_gc_round(10)
    assert all(r.round >= 10 for r in lin.committed)
    adopt_refs = [BlockReference(1, 40, b"\x09" * 32)]
    lin.adopt_snapshot(90, adopt_refs, 25)
    assert lin.last_height == 90
    assert lin.gc_round == 25
    assert adopt_refs[0] in lin.committed


def test_storage_parameters_unification(tmp_path):
    # Legacy spellings migrate into the storage block...
    p = Parameters(enable_cleanup=False, store_retain_rounds=77)
    assert p.storage.enable_cleanup is False
    assert p.storage.retain_rounds == 77
    assert p.enable_cleanup is False and p.store_retain_rounds == 77
    # ...and the YAML round-trip keeps one canonical spelling.
    p2 = Parameters(storage=StorageParameters(gc_depth=123, snapshot_catchup=True))
    path = str(tmp_path / "parameters.yaml")
    p2.dump(path)
    raw = open(path).read()
    assert "gc_depth: 123" in raw and "enable_cleanup" not in raw.split("storage:")[0]
    p3 = Parameters.load(path)
    assert p3.storage.gc_depth == 123
    assert p3.storage.snapshot_catchup is True
    assert p3.store_retain_rounds == p3.storage.retain_rounds


def test_wal_inspect_tool(tmp_path):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    plan = FaultPlan(seed=3)
    run_chaos_sim(
        plan, 4, 14.0, str(tmp_path), parameters=_params(), with_metrics=True
    )
    wal_dir = os.path.join(str(tmp_path), "wal-0")

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "wal_inspect.py"), *args],
            capture_output=True, text=True,
        )

    healthy = run(wal_dir, "--json")
    assert healthy.returncode == 0, healthy.stdout + healthy.stderr
    doc = json.loads(healthy.stdout)
    assert doc["layout"] == "segmented"
    assert doc["checkpoints"] and doc["checkpoints"][0]["valid"]
    assert doc["census"]["block"]["entries"] > 0
    # Tear a SEALED segment: unreplayable -> exit 2 with a diagnosis.
    segments = sorted(
        n for n in os.listdir(wal_dir) if n.startswith("wal.")
    )
    victim = os.path.join(wal_dir, segments[0])
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 6)
    torn = run(wal_dir)
    assert torn.returncode == 2
    assert "SEALED" in torn.stdout
    # GC'd history with every checkpoint corrupted -> exit 3.
    for ckpt in checkpoint_files(wal_dir):
        with open(ckpt, "r+b") as f:
            f.seek(8)
            f.write(b"\x00" * 8)
    broken = run(wal_dir)
    assert broken.returncode in (2, 3)
    assert "UNREPLAYABLE" in broken.stdout or "SEALED" in broken.stdout
