"""Host attribution plane: subsystem-registry totality over the package,
attribution precedence units, byte-identical deterministic reports from a
seeded synthetic census, the published series, the blocking-call detector
(planted synchronous sleep on the core owner -> SLO alert + flight-recorder
event), the loop-lag probe, and the clean seeded 10-node sim with the host
SLOs armed producing ZERO false positives."""
import asyncio
import json
import os
import random
import sys
import time

import pytest

from mysticeti_tpu import profiling
from mysticeti_tpu.core_task import CoreTaskDispatcher
from mysticeti_tpu.flight_recorder import FlightRecorder
from mysticeti_tpu.health import HealthProbe, SLOThresholds
from mysticeti_tpu.hostattr import HostMonitor, LoopLagProbe
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.orchestrator.measurement import iter_series
from mysticeti_tpu.profiling import (
    FRAME_SUBSYSTEMS,
    SUBSYSTEMS,
    SubsystemAccountant,
    attribute,
    thread_class_of,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

pytestmark = pytest.mark.perf

PKG = os.path.join(REPO, "mysticeti_tpu")


# -- the declarative registry -------------------------------------------------


def test_subsystem_mapping_totality():
    """Every module in the package must resolve through SUBSYSTEMS — a new
    module cannot silently land its CPU time in "other" (the span-names
    lint idiom applied to the attribution registry)."""
    missing = []
    for dirpath, _dirnames, filenames in os.walk(PKG):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            module = fn[:-3]
            if module not in SUBSYSTEMS:
                missing.append(os.path.join(os.path.relpath(dirpath, REPO), fn))
    assert not missing, (
        f"modules without a SUBSYSTEMS row (add them in profiling.py): "
        f"{sorted(missing)}"
    )


def test_attribute_precedence():
    # Parked leaf -> idle, regardless of what is above it.
    assert attribute(
        [("selectors", "select", False), ("core_task", "_run", True)]
    ) == "event-loop-idle"
    # GC override beats the module map anywhere in the stack: a wal append
    # inside retire_below is GC cost.
    assert attribute([
        ("wal", "append", True),
        ("storage", "retire_below", True),
        ("core_task", "_run", True),
    ]) == "gc"
    # Leaf-most in-package frame decides; third-party frames are charged to
    # whichever package module called into them.
    assert attribute([
        ("numpy_core", "dot", False),
        ("serde", "parse_block", True),
        ("net_sync", "handle", True),
    ]) == "mesh-parse"
    assert attribute([("wal", "fsync", True)]) == "wal"
    # Nothing recognizable -> other (and the perf_attr gate caps its share).
    assert attribute([("mystery", "f", False)]) == "other"
    assert attribute([]) == "other"


def test_thread_classes():
    assert thread_class_of("MainThread") == "loop"
    assert thread_class_of("ThreadPoolExecutor-0_0") == "verifier"
    assert thread_class_of("wal-writer") == "wal"
    assert thread_class_of("dataplane-offload_0") == "offload"
    assert thread_class_of("mysterious") == "aux"


# -- the accountant -----------------------------------------------------------


def _seeded_census(seed, ticks=200):
    """A reproducible synthetic census: the determinism seam's test load."""
    rng = random.Random(seed)
    modules = sorted(SUBSYSTEMS)
    censuses = []
    for _ in range(ticks):
        samples = []
        for tc in ("loop", "verifier", "wal"):
            if rng.random() < 0.3:
                samples.append((tc, [("selectors", "select", False)]))
            else:
                samples.append(
                    (tc, [(rng.choice(modules), "work", True)])
                )
        censuses.append(samples)
    return censuses


def test_report_deterministic_from_seeded_census():
    reports = []
    for _ in range(2):
        acct = SubsystemAccountant()
        for census in _seeded_census(42):
            acct.ingest_census(census, 1.0 / 99.0)
        reports.append(acct.report_bytes())
    assert reports[0] == reports[1]
    doc = json.loads(reports[0])
    assert doc["census_ticks"] == 200
    # Every census module resolved through the registry: fully attributed.
    assert doc["attributed_ratio"] == 1.0
    assert 0.0 < doc["gil_convoy_ratio"] <= 1.0
    assert doc["subsystem_seconds"]


def test_accountant_math_and_convoy():
    acct = SubsystemAccountant()
    idle = [("selectors", "select", False)]
    busy = [("wal", "append", True)]
    # Tick 1: one busy thread (no convoy); tick 2: two busy (convoy).
    acct.ingest_census([("loop", busy), ("wal", idle)], 0.01)
    acct.ingest_census([("loop", busy), ("wal", busy)], 0.01)
    doc = acct.report()
    assert doc["census_ticks"] == 2 and doc["convoy_ticks"] == 1
    assert doc["gil_convoy_ratio"] == 0.5
    assert doc["cpu_seconds"]["wal/loop"] == pytest.approx(0.02)
    assert doc["cpu_seconds"]["wal/wal"] == pytest.approx(0.01)
    assert doc["subsystem_seconds"]["event-loop-idle"] == pytest.approx(0.01)
    # "other" time drags the attributed ratio down.
    acct.ingest_census([("loop", [("mystery", "f", False)])], 0.01)
    doc = acct.report()
    assert doc["attributed_ratio"] == pytest.approx(0.75)


def test_publish_exports_series():
    metrics = Metrics()
    acct = SubsystemAccountant()
    acct.bind(metrics, leaders_fn=lambda: 100)
    acct.ingest_census([("loop", [("wal", "append", True)])], 0.5)
    acct.publish()
    acct.publish()  # idempotent: deltas, not re-adds
    series = {
        (name, labels.get("subsystem"), labels.get("thread_class")): value
        for name, labels, value in iter_series(metrics.expose().decode())
    }
    assert series[("mysticeti_cpu_seconds_total", "wal", "loop")] == (
        pytest.approx(0.5)
    )
    # 0.5 s over 100 leaders = 5000 us/leader.
    assert series[("mysticeti_cpu_us_per_leader", "wal", None)] == (
        pytest.approx(5000.0)
    )


# -- folded-file salvage + flame diff (satellites) ---------------------------


def test_load_folded_salvages_tmp(tmp_path):
    # A node SIGKILL'd before its first complete flush leaves only the tmp
    # file; load_folded must fall back to it instead of dying.
    path = str(tmp_path / "prof.folded")
    with open(path + ".tmp", "w") as f:
        f.write("a;b 10\nc;d 3\ntorn-line-without-count")
    lines = profiling.load_folded(path)
    assert "a;b 10" in lines
    svg = profiling.flamegraph_svg(lines)  # torn line skipped, not fatal
    assert svg.startswith("<svg") and "a" in svg
    with pytest.raises(FileNotFoundError):
        profiling.load_folded(str(tmp_path / "absent.folded"))


def test_render_diff(tmp_path):
    base = tmp_path / "base.folded"
    new = tmp_path / "new.folded"
    base.write_text("main;wal:append 80\nmain;serde:parse 20\n")
    new.write_text("main;wal:append 20\nmain;serde:parse 80\n")
    out = profiling.render_diff(str(base), str(new))
    svg = open(out).read()
    assert svg.startswith("<svg")
    # Both directions of the delta palette present: serde grew (red side),
    # wal shrank (blue side).
    assert "+60.0 pts" in svg and "-60.0 pts" in svg


def test_mkflamegraph_diff_cli(tmp_path):
    import subprocess

    base = tmp_path / "base.folded"
    new = tmp_path / "new.folded"
    base.write_text("main;a 1\n")
    new.write_text("main;a 2\n")
    out = tmp_path / "diff.svg"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mkflamegraph.py"),
         "--diff", str(base), str(new), str(out)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    assert out.exists() and out.read_text().startswith("<svg")


# -- the blocking-call detector (acceptance) ---------------------------------


class _FakeWal:
    def pending(self):
        return False


class _FakeStore:
    def last_seen_by_authority(self, a):
        return 0


class _FakeCore:
    authority = 0
    wal_writer = _FakeWal()
    block_store = _FakeStore()

    def current_round(self):
        return 0


class _FakeObserver:
    class _Interp:
        last_height = 0

    commit_interpreter = _Interp()


def test_blocking_call_detector_catches_planted_sleep():
    """The planted >=50 ms synchronous hold on the core owner must surface
    as a blocking-call SLO alert AND a flight-recorder event; fast commands
    must not trip it (the zero-false-positive half rides the sim test)."""
    metrics = Metrics()
    recorder = FlightRecorder(authority=0)
    monitor = HostMonitor(
        metrics=metrics, recorder=recorder, blocking_threshold_ms=50.0
    )
    dispatcher = CoreTaskDispatcher(object())
    dispatcher.blocking_monitor = monitor

    def planted_sleep():
        time.sleep(0.06)  # the bug the dynamic lint twin exists to catch
        return "done"

    def fast():
        return "ok"

    async def drive():
        dispatcher.start()
        for _ in range(5):
            assert await dispatcher._call(fast) == "ok"
        assert await dispatcher._call(planted_sleep) == "done"
        dispatcher.stop()

    asyncio.run(drive())
    assert monitor.blocking_total == 1  # the sleep, not the fast commands
    events = [
        e for e in recorder.events() if e["kind"] == "blocking-call"
    ]
    assert len(events) == 1
    assert events[0]["site"] == "core:planted_sleep"
    assert events[0]["ms"] >= 50.0

    probe = HealthProbe(
        0, 4, metrics=metrics,
        slo=SLOThresholds(max_blocking_call_ms=50.0),
        clock=lambda: 0.0,
    )
    probe.attach(
        core=_FakeCore(), commit_observer=_FakeObserver(),
        host_monitor=monitor,
    )
    snapshot = probe.sample()
    kinds = [a["kind"] for a in snapshot.get("alerts", [])]
    assert kinds == ["blocking-call"]
    assert snapshot["host"]["last_blocking"]["site"] == "core:planted_sleep"
    # The drain re-arms the alert: a clean next sample clears it.
    s2 = probe.sample()
    assert not s2.get("alerts")


def test_loop_lag_probe_measures_a_blocked_loop():
    async def drive():
        probe = LoopLagProbe(interval_s=0.01).start()
        await asyncio.sleep(0.05)
        time.sleep(0.08)  # hold the loop: the next callback fires late
        await asyncio.sleep(0.03)
        probe.stop()
        return probe

    probe = asyncio.run(drive())
    assert probe.sample_count() >= 3
    assert probe.percentile(99) >= 0.05  # saw the 80 ms hold


def test_host_monitor_state_shape():
    monitor = HostMonitor(blocking_threshold_ms=50.0)
    state = monitor.state()
    assert state["loop_lag_samples"] == 0
    assert state["blocking_calls"] == 0
    assert state["last_blocking"] is None
    assert state["blocking_threshold_ms"] == 50.0
    assert monitor.drain_worst_blocking_ms() == 0.0
    # Sub-threshold command: not a blocking call.
    monitor.note_command("core:fast", 0.001)
    assert monitor.blocking_total == 0


# -- zero false positives under the clean seeded sim -------------------------


def test_clean_sim_no_host_false_positives(tmp_path):
    """A clean seeded 10-node sim with the host SLOs armed and the full
    wiring attached (HostMonitor on every probe AND every dispatcher) must
    raise ZERO loop-lag/blocking-call alerts: under virtual time the probe
    and the dispatcher measurement stay off by design, so host wall-clock
    hiccups cannot leak into the deterministic timeline."""
    from test_net_sync_sim import build_node

    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.config import Parameters
    from mysticeti_tpu.runtime.simulated import run_simulation
    from mysticeti_tpu.simulated_network import SimulatedNetwork

    n = 10
    alerts = []

    async def drive():
        committee = Committee.new_test([1] * n)
        signers = Committee.benchmark_signers(n)
        parameters = Parameters(leader_timeout_s=1.0)
        sim_net = SimulatedNetwork(n)
        nodes = [
            build_node(committee, signers, a, str(tmp_path), sim_net,
                       parameters)
            for a in range(n)
        ]
        slo = SLOThresholds(max_loop_lag_s=0.25, max_blocking_call_ms=50.0)
        probes = []
        for a, node in enumerate(nodes):
            monitor = HostMonitor(blocking_threshold_ms=50.0).start()
            node.dispatcher.blocking_monitor = monitor
            probe = HealthProbe(a, n, slo=slo).attach(
                core=node.core,
                commit_observer=node.syncer.commit_observer,
                host_monitor=monitor,
            )
            probes.append(probe)
        for node in nodes:
            await node.start()
        await sim_net.connect_all()
        for _ in range(20):
            await asyncio.sleep(1.0)
            for probe in probes:
                snapshot = probe.sample()
                alerts.extend(snapshot.get("alerts", []))
                # The sim's host block must be all-zero (determinism).
                host = snapshot["host"]
                assert host["loop_lag_samples"] == 0
                assert host["blocking_calls"] == 0
        for node in nodes:
            await node.stop()
        sim_net.close()
        return nodes

    nodes = run_simulation(drive(), seed=7)
    committed = [len(list(n_.syncer.commit_observer.committed_leaders))
                 for n_ in nodes]
    assert all(c > 0 for c in committed), committed  # the sim did real work
    host_kinds = [a["kind"] for a in alerts
                  if a["kind"] in ("loop-lag", "blocking-call")]
    assert host_kinds == [], host_kinds
