"""Parity tests for device scalar arithmetic mod L (ops/scalar.py).

Every function is checked against plain python-int arithmetic — the same
oracle discipline as the field/curve kernels (SURVEY §4 tier "crypto-parity").
"""
import pytest

pytestmark = pytest.mark.kernel

import hashlib
import random

import numpy as np
import jax.numpy as jnp

from mysticeti_tpu.ops import scalar as S
from mysticeti_tpu.ops import sha512 as H


def _limbs_from_int(x: int, n: int) -> np.ndarray:
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & S.MASK
        x >>= S.RADIX
    assert x == 0
    return out


def test_mod_l_random_512bit():
    rng = random.Random(1)
    vals = [rng.getrandbits(512) for _ in range(64)]
    # Adversarial corners: 0, 1, L-1, L, L+1, multiples of L, all-ones.
    vals += [0, 1, S.L - 1, S.L, S.L + 1, 2**512 - 1, 17 * S.L, S.L << 252]
    arr = np.stack([_limbs_from_int(v, 40) for v in vals])
    got = np.asarray(S.mod_L(jnp.asarray(arr)))
    for v, limbs in zip(vals, got):
        assert S.limbs_to_int(limbs) == v % S.L
        assert all(0 <= int(l) <= S.MASK for l in limbs)


def test_mod_l_matches_sha512_challenge():
    """End-to-end: sha512_96 digest -> LE words -> limbs -> mod L equals
    int.from_bytes(hashlib.sha512(m).digest(), 'little') % L."""
    rng = random.Random(2)
    msgs = [bytes(rng.randrange(256) for _ in range(96)) for _ in range(16)]
    words = jnp.asarray(H.pack_messages(msgs))
    digests = H.sha512_96(words)
    le = S.digest_words_to_le(digests)
    k = np.asarray(S.mod_L(S.words_to_limbs(le, 40)))
    for m, limbs in zip(msgs, k):
        want = int.from_bytes(hashlib.sha512(m).digest(), "little") % S.L
        assert S.limbs_to_int(limbs) == want


def test_words_to_limbs_roundtrip():
    rng = random.Random(3)
    vals = [rng.getrandbits(256) for _ in range(32)] + [0, 2**256 - 1]
    words = np.stack(
        [np.frombuffer(v.to_bytes(32, "little"), dtype="<u4") for v in vals]
    ).astype(np.uint32)
    limbs = np.asarray(S.words_to_limbs(jnp.asarray(words), 20))
    for v, row in zip(vals, limbs):
        assert S.limbs_to_int(row) == v


def test_windows4_matches_bit_slices():
    rng = random.Random(4)
    vals = [rng.getrandbits(253) for _ in range(16)] + [0, S.L - 1]
    arr = np.stack([_limbs_from_int(v, 20) for v in vals])
    wins = np.asarray(S.windows4(jnp.asarray(arr)))
    for v, row in zip(vals, wins):
        for w in range(64):
            assert int(row[w]) == (v >> (4 * w)) & 15


def test_lt_checks():
    vals = [0, 1, S.L - 1, S.L, S.L + 1, S.P - 1, S.P, S.P + 1, 2**255 - 1]
    arr = np.stack([_limbs_from_int(v, 20) for v in vals])
    lt_l = np.asarray(S.lt_L(jnp.asarray(arr)))
    lt_p = np.asarray(S.lt_P(jnp.asarray(arr)))
    for v, a, b in zip(vals, lt_l, lt_p):
        assert bool(a) == (v < S.L)
        assert bool(b) == (v < S.P)


def test_bswap32():
    x = jnp.asarray(np.array([0x01020304, 0xDEADBEEF, 0, 0xFFFFFFFF], np.uint32))
    got = np.asarray(S.bswap32(x))
    assert list(got) == [0x04030201, 0xEFBEADDE, 0, 0xFFFFFFFF]
