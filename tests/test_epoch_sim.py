"""Epoch close + finalization safety, on the deterministic simulator.

Parity with the reference sim tier (net_sync.rs:602-707):

* ``test_epoch_close`` — every node reaches SAFE_TO_CLOSE and shuts itself
  down through the epoch watch + grace period (net_sync.rs:466-494,602-642).
* ``test_epoch_commit_sequence_equality`` — EXACT commit-sequence equality
  across nodes within the closed epoch (net_sync.rs:643-661).
* ``test_finalization_safety`` — the offline :class:`FinalizationInterpreter`
  re-derivation agrees with the online pipeline: every finalized transaction
  has a certifying block inside the committed causal history
  (net_sync.rs:663-707).
"""
import asyncio
import os

import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.finalization_interpreter import FinalizationInterpreter
from mysticeti_tpu.runtime.simulated import run_simulation
from mysticeti_tpu.simulated_network import SimulatedNetwork

from test_net_sync_sim import build_node


async def _run_epoch_nodes(n, tmp_dir, rounds_in_epoch=10, timeout_s=300.0):
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = Parameters(
        leader_timeout_s=1.0,
        rounds_in_epoch=rounds_in_epoch,
        shutdown_grace_period_s=2.0,
    )
    sim_net = SimulatedNetwork(n)
    nodes = [
        build_node(committee, signers, a, tmp_dir, sim_net, parameters)
        for a in range(n)
    ]
    for node in nodes:
        await node.start()
    await sim_net.connect_all()
    # Epoch-aware shutdown stops each node on its own; wait for all of them.
    await asyncio.wait_for(
        asyncio.gather(*[node.await_completion() for node in nodes]),
        timeout=timeout_s,
    )
    sim_net.close()
    return nodes


def _committed(node):
    return list(node.syncer.commit_observer.committed_leaders)


def test_epoch_close(tmp_path):
    nodes = run_simulation(_run_epoch_nodes(4, str(tmp_path)), seed=17)
    for node in nodes:
        assert node.core.epoch_closed(), "node never reached SAFE_TO_CLOSE"
        # The epoch boundary was respected: commits exist and began before
        # the epoch cutoff triggered the change.
        assert len(_committed(node)) >= 3


def test_epoch_commit_sequence_equality(tmp_path):
    """Within a closed epoch the commit sequences are EXACTLY equal — not
    just prefix-consistent (net_sync.rs:643-661)."""
    nodes = run_simulation(_run_epoch_nodes(4, str(tmp_path)), seed=19)
    sequences = [_committed(n) for n in nodes]
    for seq in sequences[1:]:
        assert seq == sequences[0], f"diverged: {seq} vs {sequences[0]}"


def test_finalization_safety(tmp_path):
    """Offline re-interpretation of each node's stored DAG: every finalized
    transaction must have at least one certifying block linked from the last
    committed leader (the fast path never finalizes outside the committed
    history) — net_sync.rs:663-707."""
    nodes = run_simulation(_run_epoch_nodes(4, str(tmp_path)), seed=23)
    checked = 0
    for node in nodes:
        store = node.core.block_store
        committee = node.core.committee
        committed = _committed(node)
        assert committed, "no commits to check against"
        last_leader = store.get_block(committed[-1])
        assert last_leader is not None

        interpreter = FinalizationInterpreter(store, committee)
        finalized = interpreter.finalized_tx_certifying_blocks()
        assert finalized, "interpreter found no finalized transactions"
        for _tx, certifying in finalized:
            hit = False
            for ref in certifying:
                block = store.get_block(ref)
                if block is not None and store.linked(last_leader, block):
                    hit = True
                    break
            assert hit, f"finalized tx {_tx} has no committed certificate"
            checked += 1
    assert checked > 0


def test_finalization_safety_detects_perturbation(tmp_path):
    """The oracle has teeth: if the online pipeline were to under-commit
    (simulated by pointing the check at an EARLY committed leader), the
    cross-check fails — i.e. the assertion actually discriminates."""
    nodes = run_simulation(_run_epoch_nodes(4, str(tmp_path)), seed=29)
    node = nodes[0]
    store = node.core.block_store
    committee = node.core.committee
    committed = _committed(node)
    early_leader = store.get_block(committed[0])
    interpreter = FinalizationInterpreter(store, committee)
    finalized = interpreter.finalized_tx_certifying_blocks()
    # At least one finalized tx must NOT be covered by the very first
    # committed leader's history — otherwise the main test is vacuous.
    uncovered = 0
    for _tx, certifying in finalized:
        if not any(
            store.get_block(r) is not None
            and store.linked(early_leader, store.get_block(r))
            for r in certifying
        ):
            uncovered += 1
    assert uncovered > 0, "oracle cannot distinguish commit prefixes"
