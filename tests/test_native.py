"""Native extension parity: the C++ WAL scan/framing must agree byte-for-byte
with the pure-Python fallback, including the torn-tail recovery contract."""
import os
import struct
import zlib

import pytest

from mysticeti_tpu.native import native
from mysticeti_tpu import wal as W


pytestmark = pytest.mark.skipif(native is None, reason="native build unavailable")


def _entry(tag, payload):
    return struct.pack("<IIII", W.WAL_MAGIC, zlib.crc32(payload), len(payload), tag) + payload


def test_wal_scan_matches_layout():
    payloads = [(1, b"alpha"), (2, b""), (3, b"x" * 1000)]
    buf = b"".join(_entry(t, p) for t, p in payloads)
    got = native.wal_scan(buf, len(buf))
    assert [(p, t, ln) for p, t, _, ln in got] == [
        (0, 1, 5),
        (21, 2, 0),
        (37, 3, 1000),
    ]
    for (pos, tag, off, ln), (want_tag, want_payload) in zip(got, payloads):
        assert tag == want_tag
        assert buf[off : off + ln] == want_payload


def test_wal_scan_stops_at_tear_and_corruption():
    good = _entry(1, b"alpha")
    torn = _entry(2, b"beta")[:-2]  # truncated payload
    assert len(native.wal_scan(good + torn, len(good) + len(torn))) == 1
    corrupted = bytearray(_entry(2, b"beta"))
    corrupted[-1] ^= 1  # payload bit flip -> crc mismatch
    assert len(native.wal_scan(good + bytes(corrupted), len(good) + 21)) == 1
    # Bad magic.
    assert native.wal_scan(b"\x00" * 32, 32) == []


def test_frame_entry_matches_python_framing():
    parts = [b"hello ", b"world", b""]
    framed = native.frame_entry(9, parts)
    payload = b"".join(parts)
    assert framed == _entry(9, payload)


def test_writer_reader_roundtrip_both_paths(tmp_path):
    """The same WAL written with native framing replays identically through
    the native scan and the Python fallback iterator."""
    path = str(tmp_path / "wal")
    writer, reader = W.walf(path)
    positions = [
        writer.writev(5, (b"abc", b"def")),
        writer.write(6, b""),
        writer.write(7, os.urandom(5000)),
    ]
    entries_native = list(reader.iter_until())
    # Force the pure-Python path on the same file.
    old = W._native
    W._native = None
    try:
        _, reader2 = W.walf(path)
        entries_python = list(reader2.iter_until())
        reader2.close()
    finally:
        W._native = old
    assert [(p, t, b) for p, t, b in entries_native] == entries_python
    assert [p for p, _, _ in entries_native] == positions
    writer.close()
    reader.close()
