"""Threshold-aggregate verification (BASELINE config #5's technique).

Safety contract: NO forged block is ever accepted unless a quorum (2f+1
distinct-authority stake — beyond the fault model if all are dishonest) of
accepted blocks references it; every acceptance chain terminates at directly
signature-verified frontier blocks.
"""
import asyncio

import pytest

from mysticeti_tpu.block_validator import (
    BatchedSignatureVerifier,
    CpuSignatureVerifier,
    ThresholdAggregateVerifier,
)
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.types import Share, StatementBlock


@pytest.fixture
def setup():
    signers = Committee.benchmark_signers(4)
    committee = Committee.new_for_benchmarks(4)
    return committee, signers


class CountingInner(BatchedSignatureVerifier):
    def __init__(self, committee):
        super().__init__(
            committee, CpuSignatureVerifier(), max_batch=64, max_delay_s=0.001
        )
        self.seen = []

    async def verify_blocks(self, blocks):
        self.seen.extend(b.reference for b in blocks)
        return await super().verify_blocks(blocks)


def _dag(signers, rounds, per_round=4, forge=()):
    """Rounds of fully-connected blocks; ``forge`` = set of (round, authority)
    whose signature bytes are corrupted after signing."""
    genesis = [StatementBlock.new_genesis(a) for a in range(per_round)]
    prev = [g.reference for g in genesis]
    out = []
    for r in range(1, rounds + 1):
        layer = []
        for a in range(per_round):
            blk = StatementBlock.build(
                a, r, prev, [Share(bytes([r, a]))], signer=signers[a]
            )
            if (r, a) in forge:
                bad = bytes([blk.signature[0] ^ 1]) + blk.signature[1:]
                blk = StatementBlock(
                    blk.reference, blk.includes, blk.statements,
                    blk.meta_creation_time_ns, blk.epoch_marker, blk.epoch,
                    bad, _bytes=None,
                )
            layer.append(blk)
        out.extend(layer)
        prev = [b.reference for b in layer]
    return out


def test_interior_blocks_skip_direct_verification(setup):
    committee, signers = setup

    async def main():
        inner = CountingInner(committee)
        agg = ThresholdAggregateVerifier(committee, inner)
        blocks = _dag(signers, rounds=5)
        results = await agg.verify_blocks(blocks)
        assert all(results)
        # Only the frontier (last round, no in-batch endorsers) was
        # signature-verified directly.
        assert len(inner.seen) == 4
        assert all(ref.round == 5 for ref in inner.seen)
        assert agg.aggregated_total == 16

    asyncio.run(main())


def test_forged_frontier_rejected(setup):
    committee, signers = setup

    async def main():
        agg = ThresholdAggregateVerifier(committee, CountingInner(committee))
        blocks = _dag(signers, rounds=3, forge={(3, 1)})
        results = await agg.verify_blocks(blocks)
        by_ref = dict(zip((b.reference for b in blocks), results))
        for b in blocks:
            expected = not (b.round() == 3 and b.author() == 1)
            assert by_ref[b.reference] == expected, b.reference

    asyncio.run(main())


def test_forged_interior_without_quorum_rejected(setup):
    """A forged block endorsed by fewer than quorum distinct authorities is
    verified directly and rejected."""
    committee, signers = setup

    async def main():
        agg = ThresholdAggregateVerifier(committee, CountingInner(committee))
        blocks = _dag(signers, rounds=2, forge={(1, 2)})
        forged_ref = next(
            b.reference for b in blocks if b.round() == 1 and b.author() == 2
        )
        # Strip the forged block's endorsements below quorum: only one
        # round-2 block keeps it in its includes.
        filtered = []
        for b in blocks:
            if b.round() == 2 and b.author() != 0:
                b = StatementBlock.build(
                    b.author(), 2,
                    [r for r in b.includes if r != forged_ref],
                    list(b.statements), signer=signers[b.author()],
                )
            filtered.append(b)
        results = await agg.verify_blocks(filtered)
        by_ref = dict(zip((b.reference for b in filtered), results))
        assert by_ref[forged_ref] is False
        assert sum(results) == len(filtered) - 1

    asyncio.run(main())


def test_collapsed_endorsement_falls_back_to_direct(setup):
    """If a block's endorsers fail verification, it must not be rejected
    outright — it gets its own direct check (valid -> accepted)."""
    committee, signers = setup

    async def main():
        inner = CountingInner(committee)
        agg = ThresholdAggregateVerifier(committee, inner)
        # Round 1 valid, the ENTIRE round-2 frontier forged.
        blocks = _dag(signers, rounds=2, forge={(2, a) for a in range(4)})
        results = await agg.verify_blocks(blocks)
        by_ref = dict(zip((b.reference for b in blocks), results))
        for b in blocks:
            assert by_ref[b.reference] == (b.round() == 1), b.reference
        # Round-1 blocks went through the direct path (second pass).
        assert sum(1 for r in inner.seen if r.round == 1) == 4

    asyncio.run(main())


def test_singletons_bypass_aggregation(setup):
    committee, signers = setup

    async def main():
        inner = CountingInner(committee)
        agg = ThresholdAggregateVerifier(committee, inner)
        blk = _dag(signers, rounds=1)[0]
        assert await agg.verify_blocks([blk]) == [True]
        assert agg.aggregated_total == 0 and len(inner.seen) == 1

    asyncio.run(main())


def test_make_verifier_agg_kinds(monkeypatch):
    """"-agg" kinds enable COLLECTOR-level aggregation: the flush window
    pools blocks from every peer connection, which is where multi-author
    (quorum-capable) batches actually form — a frame-level wrapper only ever
    sees one peer's own single-author frames at steady state."""
    from mysticeti_tpu import block_validator as bv
    from mysticeti_tpu.validator import _make_verifier

    monkeypatch.setattr(bv.HybridSignatureVerifier, "warmup", lambda self: None)
    committee = Committee.new_for_benchmarks(4)
    v = _make_verifier("cpu-agg", committee)
    assert isinstance(v, bv.BatchedSignatureVerifier) and v.aggregate
    assert isinstance(v.verifier, bv.CpuSignatureVerifier)
    v = _make_verifier("tpu-agg", committee)
    assert isinstance(v, bv.BatchedSignatureVerifier) and v.aggregate
    assert isinstance(v.verifier, bv.HybridSignatureVerifier)
    v = _make_verifier("cpu", committee)
    assert isinstance(v, bv.BatchedSignatureVerifier) and not v.aggregate


class CountingSigVerifier(CpuSignatureVerifier):
    def __init__(self):
        self.dispatched = 0

    def verify_signatures(self, pks, digests, sigs):
        self.dispatched += len(sigs)
        return super().verify_signatures(pks, digests, sigs)


def test_collector_aggregation_skips_interior(setup):
    """Blocks arriving concurrently (as from many peer connections) pool in
    one flush window; only the frontier pays a signature dispatch."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        blocks = _dag(signers, rounds=5)
        results = await collector.verify_blocks(blocks)
        assert all(results)
        assert sig.dispatched == 4  # frontier only (round 5)
        assert collector.aggregated_total == 16
        assert collector.direct_total == 4

    asyncio.run(main())


def test_collector_aggregation_rejects_forged_frontier(setup):
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        blocks = _dag(signers, rounds=3, forge={(3, 1)})
        results = await collector.verify_blocks(blocks)
        by_ref = dict(zip((b.reference for b in blocks), results))
        for b in blocks:
            expected = not (b.round() == 3 and b.author() == 1)
            assert by_ref[b.reference] == expected, b.reference

    asyncio.run(main())


def test_cross_flush_endorsement_skips_late_parent(setup):
    """Catch-up regime: a block whose quorum of verified children was
    accepted in EARLIER flushes (peers' streams run at different round
    offsets) skips its signature dispatch even when it arrives alone."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        blocks = _dag(signers, rounds=3)
        late = next(b for b in blocks if b.round() == 1 and b.author() == 0)
        rest = [b for b in blocks if b is not late]
        assert all(await collector.verify_blocks(rest))
        dispatched_before = sig.dispatched
        assert await collector.verify_blocks([late]) == [True]
        assert sig.dispatched == dispatched_before  # skipped via the index
        assert collector.aggregated_total >= 1

        # A single-author chain's parent never reaches quorum in the index.
        solo = CountingSigVerifier()
        c2 = BatchedSignatureVerifier(
            committee, solo, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        genesis = [StatementBlock.new_genesis(a) for a in range(4)]
        parent = StatementBlock.build(
            1, 1, [g.reference for g in genesis], [Share(b"p")],
            signer=signers[1],
        )
        child = StatementBlock.build(
            1, 2, [parent.reference], [Share(b"c")], signer=signers[1]
        )
        assert all(await c2.verify_blocks([child]))
        before = solo.dispatched
        assert await c2.verify_blocks([parent]) == [True]
        assert solo.dispatched == before + 1  # direct check, no quorum

    asyncio.run(main())


def test_collector_aggregation_single_author_stream_never_skips(setup):
    """One peer's own-block push stream (single author) can never reach
    quorum endorsement — every block is verified directly."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        genesis = [StatementBlock.new_genesis(a) for a in range(4)]
        prev = [g.reference for g in genesis]
        chain = []
        for r in range(1, 9):
            blk = StatementBlock.build(
                0, r, prev, [Share(bytes([r]))], signer=signers[0]
            )
            chain.append(blk)
            prev = [blk.reference]
        results = await collector.verify_blocks(chain)
        assert all(results)
        assert sig.dispatched == len(chain)
        assert collector.aggregated_total == 0

    asyncio.run(main())


def test_validators_commit_with_aggregate_verifier(tmp_path):
    """4 localhost validators with the threshold-aggregate wrapper over the
    CPU oracle still commit, and the finalization-safety oracle's input (the
    committed sequences) stays consistent."""
    import socket

    from mysticeti_tpu.config import Identifier, Parameters, PrivateConfig
    from mysticeti_tpu.committee import Authority
    from mysticeti_tpu.validator import Validator

    def free_ports(n):
        socks, ports = [], []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:
            s.close()
        return ports

    async def main():
        ports = free_ports(8)
        identifiers = [
            Identifier("127.0.0.1", ports[2 * i], ports[2 * i + 1])
            for i in range(4)
        ]
        parameters = Parameters(identifiers=identifiers, leader_timeout_s=0.5)
        signers = Committee.benchmark_signers(4)
        committee = Committee([Authority(1, s.public_key) for s in signers])
        validators = [
            await Validator.start_benchmarking(
                i,
                committee,
                parameters,
                PrivateConfig.new_in_dir(i, str(tmp_path / f"v{i}")),
                signer=signers[i],
                tps=20,
                serve_metrics_endpoint=False,
                verifier="cpu-agg",
            )
            for i in range(4)
        ]
        try:

            async def poll():
                while True:
                    if all(len(v.committed_leaders()) >= 2 for v in validators):
                        return
                    await asyncio.sleep(0.2)

            await asyncio.wait_for(poll(), timeout=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def _include_block(signers, author, round_, includes, forge=False):
    blk = StatementBlock.build(
        author, round_, includes, [Share(bytes([round_, author]))],
        signer=signers[author],
    )
    if forge:
        bad = bytes([blk.signature[0] ^ 1]) + blk.signature[1:]
        blk = StatementBlock(
            blk.reference, blk.includes, blk.statements,
            blk.meta_creation_time_ns, blk.epoch_marker, blk.epoch,
            bad, _bytes=None,
        )
    return blk


def test_collector_defers_unresolved_to_next_flush(setup):
    """An interior block whose optimistic endorsement collapses (its
    endorsers' signatures fail) must NOT trigger a second serialized
    dispatch in the same flush (the round-4 tpu-agg saturation collapse);
    it rides the next window and resolves there."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        dispatches = []
        orig = sig.verify_signatures

        def spy(pks, digests, sigs_):
            dispatches.append(len(sigs_))
            return orig(pks, digests, sigs_)

        sig.verify_signatures = spy
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
        b = _include_block(signers, 0, 1, genesis)
        # Three round-2 endorsers of b, two with forged signatures: b is
        # optimistically quorum-endorsed (3 authors) but actually reaches
        # only stake 1 — unresolved.
        children = [
            _include_block(signers, a, 2, [b.reference], forge=(a in (2, 3)))
            for a in (1, 2, 3)
        ]
        results = await collector.verify_blocks([b] + children)
        assert results == [True, True, False, False]
        # Flush 1 dispatched ONLY the frontier (3 children); b deferred and
        # resolved by its own dispatch in flush 2 — never two serialized
        # dispatches in one flush.
        assert dispatches == [3, 1]
        assert collector.direct_total == 4

    asyncio.run(main())


def test_collector_force_dispatches_on_second_deferral(setup):
    """Liveness guard: a Byzantine author minting fresh forged endorsers
    every window must not park a block in 'maybe' forever."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        dispatches = []
        orig = sig.verify_signatures

        def spy(pks, digests, sigs_):
            dispatches.append(len(sigs_))
            return orig(pks, digests, sigs_)

        sig.verify_signatures = spy
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=10.0, aggregate=True
        )
        # Pin the window so flushes fire ONLY when the test drives them —
        # the adaptive window would otherwise flush the deferred block
        # before wave2 arrives.
        collector._effective_delay_s = lambda: 10.0
        genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
        b = _include_block(signers, 0, 1, genesis)
        wave1 = [
            _include_block(signers, a, 2, [b.reference], forge=(a in (2, 3)))
            for a in (1, 2, 3)
        ]
        task = asyncio.ensure_future(collector.verify_blocks([b] + wave1))
        await asyncio.sleep(0.01)
        # Flush 1: b is deferred (optimistic quorum via authors {1,2,3},
        # actual stake 1 once the forged endorsers fail).
        await collector._flush()
        assert not task.done()
        # Fresh forged endorsers arrive in b's second window: optimistic
        # endorsement again reaches quorum (prior-accepted author 1 + forged
        # in-batch authors {2,3}) and again collapses — without the guard b
        # would defer forever.
        wave2 = [
            _include_block(signers, a, 3, [b.reference], forge=True)
            for a in (2, 3)
        ]
        task2 = asyncio.ensure_future(collector.verify_blocks(wave2))
        await asyncio.sleep(0.01)
        await collector._flush()
        assert await task == [True, True, False, False]
        assert await task2 == [False, False]
        # Flush 1: frontier wave1 (3).  Flush 2: frontier wave2 (2), then
        # the FORCED direct dispatch for b (1) — deferral is bounded.
        assert dispatches == [3, 2, 1]

    asyncio.run(main())


def test_evicted_endorsement_never_resurrects(setup):
    """Lemma F, route 2 (docs/aggregate-verification.md): endorsement stake
    scattered across FIFO evictions must never accumulate to quorum.  Three
    distinct-author accepted includers of a forged ref exist over the run,
    but the index is evicted between them — the forged block must be
    direct-checked (and rejected), not laundered through rebuilt state."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        collector.ENDORSEMENT_MAX_ENTRIES = 2  # force aggressive eviction
        genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
        forged = _include_block(signers, 3, 1, genesis, forge=True)

        # Flush A: genuine accepted blocks by authors 0 and 1 include the
        # forged ref -> index {0, 1}.
        wave_a = [
            _include_block(signers, a, 2, [forged.reference]) for a in (0, 1)
        ]
        assert await collector.verify_blocks(wave_a) == [True, True]
        assert collector._prior_endorsers(forged.reference) == {0, 1}

        # Flush B: unrelated accepted blocks churn the FIFO past its cap —
        # the forged ref's entry is evicted.
        filler = _dag(signers, rounds=1)
        assert all(await collector.verify_blocks(filler))
        assert collector._prior_endorsers(forged.reference) == frozenset()

        # Flush C: author 2 includes the forged ref -> rebuilt entry is {2}
        # only; the historical {0, 1} must NOT merge back.
        wave_c = [_include_block(signers, 2, 2, [forged.reference])]
        assert await collector.verify_blocks(wave_c) == [True]
        assert collector._prior_endorsers(forged.reference) == {2}

        # The forged block arrives: total historical endorsers {0,1,2}
        # would be quorum (3), but only stake 1 is visible — direct check,
        # rejected.
        dispatched_before = sig.dispatched
        results = await collector.verify_blocks([forged])
        assert results == [False]
        assert sig.dispatched == dispatched_before + 1

    asyncio.run(main())


def test_same_author_endorsement_counts_once(setup):
    """Lemma F, route 3: one author endorsing a ref both via the cross-flush
    index and in-batch must count its stake ONCE — quorum must not be
    reachable by double counting."""
    committee, signers = setup

    async def main():
        sig = CountingSigVerifier()
        collector = BatchedSignatureVerifier(
            committee, sig, max_batch=64, max_delay_s=0.02, aggregate=True
        )
        genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
        forged = _include_block(signers, 3, 1, genesis, forge=True)
        # Prior flush: authors 0 and 1 include the forged ref -> index {0,1}.
        prior = [
            _include_block(signers, a, 2, [forged.reference]) for a in (0, 1)
        ]
        assert await collector.verify_blocks(prior) == [True, True]
        # Same batch as the forged block: authors 0 and 1 AGAIN include the
        # ref.  Double counting would yield stake 4 >= quorum 3; correct
        # dedup sees {0, 1} = 2 -> direct check -> rejected.
        again = [
            _include_block(signers, a, 3, [forged.reference]) for a in (0, 1)
        ]
        results = await collector.verify_blocks(again + [forged])
        assert results == [True, True, False]

    asyncio.run(main())
