"""BlockManager suspend/release tests (block_manager.rs:156-228 parity)."""
import random

from mysticeti_tpu.block_manager import BlockManager
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.utils.dag import Dag

from helpers import DagBlockWriter


def test_add_block_random_order(tmp_path):
    """All delivery orders over 100 seeds process every block exactly once."""
    committee = Committee.new_test([1, 1])
    dag = Dag.draw("A1:[A0, B0]; B1:[A0, B0]; B2:[A0, B1]; A2:[A1, B2]")
    blocks = dag.all_blocks()
    assert len(blocks) == 6  # 4 + 2 implicit genesis
    for seed in range(100):
        rng = random.Random(seed)
        order = blocks[:]
        rng.shuffle(order)
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{seed}")
        bm = BlockManager(writer.block_store, len(committee))
        processed_refs = set()
        for block in order:
            processed, _missing = bm.add_blocks([block], writer._writer)
            for _, p in processed:
                assert p.reference not in processed_refs, "processed twice"
                processed_refs.add(p.reference)
        assert not bm.block_references_waiting
        assert not bm.blocks_pending
        assert len(processed_refs) == len(blocks)
        assert writer.block_store.len_expensive() == len(blocks)


def test_add_block_missing_references(tmp_path):
    committee = Committee.new_test([1, 1])
    dag = Dag.draw("A1:[A0, B0]; B1:[A0, B0]; B2:[A0, B1]; A2:[A1, B1]")
    writer = DagBlockWriter(committee, str(tmp_path))
    bm = BlockManager(writer.block_store, len(committee))

    a2 = dag["A2"]
    # First sight of A2 reports its two missing parents.
    _, missing = bm.add_blocks([a2], writer._writer)
    assert len(missing) == 2
    # Re-adding reports nothing new.
    _, missing = bm.add_blocks([a2], writer._writer)
    assert not missing
    # B2 shares one already-reported parent; only B1's new dep (A0/B0 covered).
    b2 = dag["B2"]
    _, missing = bm.add_blocks([b2], writer._writer)
    assert len(missing) == 1
    # Delivering everything else resolves all.
    rest = [b for b in dag.all_blocks() if b.reference not in (a2.reference, b2.reference)]
    _, missing = bm.add_blocks(rest, writer._writer)
    assert not missing
    assert not bm.block_references_waiting
    assert not bm.blocks_pending
    assert writer.block_store.len_expensive() == len(dag)


def test_missing_blocks_by_authority(tmp_path):
    committee = Committee.new_test([1, 1])
    dag = Dag.draw("A1:[A0, B0]; B1:[A0, B0]; A2:[A1, B1]")
    writer = DagBlockWriter(committee, str(tmp_path))
    bm = BlockManager(writer.block_store, len(committee))
    bm.add_blocks([dag["A2"]], writer._writer)
    missing = bm.missing_blocks()
    assert dag["A1"].reference in missing[0]
    assert dag["B1"].reference in missing[1]
    # Parents arrive -> missing clears and A2 processes.
    bm.add_blocks(
        [dag["A1"], dag["B1"], dag["A0"], dag["B0"]], writer._writer
    )
    assert all(not s for s in bm.missing_blocks())
    assert writer.block_store.block_exists(dag["A2"].reference)
