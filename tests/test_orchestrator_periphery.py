"""Tests for the orchestrator periphery: logs analyzer, plot, settings, CLI."""
import json
import os

from mysticeti_tpu.orchestrator.logs import analyze_log_text, analyze_logs
from mysticeti_tpu.orchestrator.measurement import Measurement, MeasurementsCollection
from mysticeti_tpu.orchestrator.plot import plot_latency_throughput
from mysticeti_tpu.orchestrator.settings import Settings


def test_log_analyzer(tmp_path):
    (tmp_path / "node-0.log").write_text(
        "[00:00:01 A0] info validator: starting\n"
        "[00:00:02 A0] error net_sync: boom\n"
    )
    (tmp_path / "node-1.log").write_text(
        "ok line\nTraceback (most recent call last):\n  File x\nValueError: y\n"
    )
    analysis = analyze_logs(str(tmp_path))
    assert analysis.node_errors["node-0.log"] == 1
    assert analysis.node_crashes["node-1.log"] == 1
    assert not analysis.ok()
    assert "node-0.log" in analysis.display()
    assert analyze_log_text("all is well\n") == (0, 0)


def _collection(nodes, load, tps, latency):
    c = MeasurementsCollection(
        {"nodes": nodes, "load": load, "duration_s": 10.0,
         "faults": {"kind": "none", "faults": 0, "interval_s": 60.0}}
    )
    n = int(tps * 10)
    c.add(
        "0",
        Measurement(
            timestamp_s=10.0,
            benchmark_duration_s=10.0,
            count=n,
            sum_s=latency * n,
            squared_sum_s=latency * latency * n,
        ),
    )
    return c


def test_plot_writes_txt_and_png(tmp_path):
    cols = [
        _collection(4, 100, 95.0, 0.2),
        _collection(4, 200, 180.0, 0.35),
    ]
    out = str(tmp_path / "lt")
    written = plot_latency_throughput(cols, out)
    assert out + ".txt" in written
    text = open(out + ".txt").read()
    assert "4 nodes" in text
    # matplotlib is available in this environment
    assert out + ".png" in written
    assert os.path.getsize(out + ".png") > 0


def test_settings_roundtrip(tmp_path):
    s = Settings(runner="local", working_dir="wd", tps_per_node=42)
    path = str(tmp_path / "settings.json")
    s.save(path)
    loaded = Settings.load(path)
    assert loaded == s
    runner = loaded.make_runner()
    assert runner.tps_per_node == 42

    bad = Settings(runner="ssh", hosts=[])
    try:
        bad.validate()
        raise AssertionError("ssh with no hosts must fail validation")
    except ValueError:
        pass
    # Unknown keys in the file are tolerated (forward compatibility).
    with open(path, "w") as f:
        json.dump({"runner": "local", "future_field": 1}, f)
    assert Settings.load(path).runner == "local"


def test_orchestrator_cli_parses():
    """--help for the subcommand exits 0 (argparse raises SystemExit)."""
    import pytest

    from mysticeti_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["orchestrator", "--help"])
    assert exc.value.code == 0
