"""Tests for the orchestrator periphery: logs analyzer, plot, settings, CLI."""
import json
import os

from mysticeti_tpu.orchestrator.logs import analyze_log_text, analyze_logs
from mysticeti_tpu.orchestrator.measurement import Measurement, MeasurementsCollection
from mysticeti_tpu.orchestrator.plot import plot_latency_throughput
from mysticeti_tpu.orchestrator.settings import Settings


def test_log_analyzer(tmp_path):
    (tmp_path / "node-0.log").write_text(
        "[00:00:01 A0] info validator: starting\n"
        "[00:00:02 A0] error net_sync: boom\n"
    )
    (tmp_path / "node-1.log").write_text(
        "ok line\nTraceback (most recent call last):\n  File x\nValueError: y\n"
    )
    analysis = analyze_logs(str(tmp_path))
    assert analysis.node_errors["node-0.log"] == 1
    assert analysis.node_crashes["node-1.log"] == 1
    assert not analysis.ok()
    assert "node-0.log" in analysis.display()
    assert analyze_log_text("all is well\n") == (0, 0)


def _collection(nodes, load, tps, latency):
    c = MeasurementsCollection(
        {"nodes": nodes, "load": load, "duration_s": 10.0,
         "faults": {"kind": "none", "faults": 0, "interval_s": 60.0}}
    )
    n = int(tps * 10)
    c.add(
        "0",
        Measurement(
            timestamp_s=10.0,
            benchmark_duration_s=10.0,
            count=n,
            sum_s=latency * n,
            squared_sum_s=latency * latency * n,
        ),
    )
    return c


def test_plot_writes_txt_and_png(tmp_path):
    cols = [
        _collection(4, 100, 95.0, 0.2),
        _collection(4, 200, 180.0, 0.35),
    ]
    out = str(tmp_path / "lt")
    written = plot_latency_throughput(cols, out)
    assert out + ".txt" in written
    text = open(out + ".txt").read()
    assert "4 nodes" in text
    # matplotlib is available in this environment
    assert out + ".png" in written
    assert os.path.getsize(out + ".png") > 0


def test_settings_roundtrip(tmp_path):
    s = Settings(runner="local", working_dir="wd", tps_per_node=42)
    path = str(tmp_path / "settings.json")
    s.save(path)
    loaded = Settings.load(path)
    assert loaded == s
    runner = loaded.make_runner()
    assert runner.tps_per_node == 42

    bad = Settings(runner="ssh", hosts=[])
    try:
        bad.validate()
        raise AssertionError("ssh with no hosts must fail validation")
    except ValueError:
        pass
    # Unknown keys in the file are tolerated (forward compatibility).
    with open(path, "w") as f:
        json.dump({"runner": "local", "future_field": 1}, f)
    assert Settings.load(path).runner == "local"


def test_orchestrator_cli_parses():
    """--help for the subcommand exits 0 (argparse raises SystemExit)."""
    import pytest

    from mysticeti_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["orchestrator", "--help"])
    assert exc.value.code == 0


def test_monitoring_stack_deploy(tmp_path):
    from mysticeti_tpu.orchestrator.monitor import MonitoringStack, prometheus_config

    stack = MonitoringStack(str(tmp_path / "monitor"))
    cfg = stack.deploy(["127.0.0.1:1504", "127.0.0.1:1505"])
    text = open(cfg).read()
    assert "127.0.0.1:1504" in text and "scrape_interval: 5s" in text
    dash = tmp_path / "monitor" / "grafana" / "dashboards" / "mysticeti.json"
    assert dash.exists()
    content = json.loads(dash.read_text())
    assert any("latency_s_count" in p["targets"][0]["expr"] for p in content["panels"])
    ds = tmp_path / "monitor" / "grafana" / "provisioning" / "datasources" / "prometheus.yaml"
    assert "prometheus" in ds.read_text()


def test_monitoring_stack_spawns_grafana(tmp_path, monkeypatch):
    """start_grafana launches the binary against the generated provisioning
    tree (monitor.rs:86-104 parity), and stop() reaps it."""
    import os
    import stat

    from mysticeti_tpu.orchestrator.monitor import MonitoringStack

    fake_bin = tmp_path / "bin"
    fake_bin.mkdir()
    marker = tmp_path / "grafana-started"
    script = fake_bin / "grafana-server"
    script.write_text(
        "#!/bin/sh\n"
        f"echo \"$GF_PATHS_PROVISIONING\" > {marker}\n"
        "exec sleep 60\n"
    )
    script.chmod(script.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{fake_bin}:{os.environ['PATH']}")

    stack = MonitoringStack(str(tmp_path / "monitor"))
    stack.deploy(["127.0.0.1:1504"])
    assert stack.start_grafana()
    try:
        assert stack.grafana_proc is not None
        deadline = 50
        while not marker.exists() and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
        prov = marker.read_text().strip()
        assert prov.endswith("grafana/provisioning")
        # Dashboard provider path was rewritten from the container default to
        # the generated tree.
        provider_yaml = (
            tmp_path / "monitor" / "grafana" / "provisioning" / "dashboards"
            / "provider.yaml"
        ).read_text()
        assert "/etc/grafana/dashboards" not in provider_yaml
        assert str(tmp_path / "monitor" / "grafana" / "dashboards") in provider_yaml
    finally:
        stack.stop()
    assert stack.grafana_proc is None


def test_monitoring_stack_grafana_absent(tmp_path, monkeypatch):
    from mysticeti_tpu.orchestrator.monitor import MonitoringStack

    monkeypatch.setenv("PATH", str(tmp_path))  # nothing on PATH
    stack = MonitoringStack(str(tmp_path / "monitor"))
    stack.deploy(["127.0.0.1:1504"])
    assert stack.start_grafana() is False


def test_monitored_lock(tmp_path):
    import asyncio

    from mysticeti_tpu.utils.lock import MonitoredLock

    async def main():
        lock = MonitoredLock("test")
        async with lock:
            await asyncio.sleep(0.01)
        assert lock.hold_total_s >= 0.01
        # Contention is measured on the second waiter.
        async def holder():
            async with lock:
                await asyncio.sleep(0.02)

        task = asyncio.ensure_future(holder())
        await asyncio.sleep(0.001)
        async with lock:
            pass
        await task
        assert lock.wait_total_s >= 0.01

    asyncio.run(main())
