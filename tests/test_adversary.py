"""Byzantine adversary plane: attack injection, honest-path detection, and
the declarative resilience scenario matrix (adversary.py, scenarios.py).

The tier-1 acceptance sim is the seeded 10-node run with f=3 adversaries
concurrently equivocating, withholding, and signing invalidly: all honest
nodes commit a common leader prefix with zero SafetyChecker violations,
every counter-surfaced attack is detected and attributed, and the attack
schedule / detection ledger / committed sequences are byte-identical
across same-seed runs.  The full matrix (clean-twin throughput ratios
included) runs on the slow tier and in tools/scenario_matrix.py.
"""
import asyncio
import dataclasses
import json

import pytest

from mysticeti_tpu.adversary import (
    AdversarySpec,
    AttackLedger,
    equivocating_variant,
    tamper_signature,
)
from mysticeti_tpu.block_store import BlockStore, BlockWriter
from mysticeti_tpu.block_validator import (
    BatchedSignatureVerifier,
    CpuSignatureVerifier,
)
from mysticeti_tpu.chaos import FaultPlan, SafetyChecker, run_chaos_sim
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.flight_recorder import FlightRecorder
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.scenarios import (
    SimResignOracleVerifier,
    Scenario,
    default_matrix,
    oracle_verifier_factory,
    run_scenario,
    scenario_by_name,
    wan_latency_ranges,
)
from mysticeti_tpu.types import BlockReference, Share, StatementBlock, VerificationError
from mysticeti_tpu.wal import walf

pytestmark = pytest.mark.byzantine


# ---------------------------------------------------------------------------
# Spec / ledger / transform units


def test_adversary_spec_validates_and_roundtrips():
    spec = AdversarySpec(
        node=7, behavior="withhold", start_s=1.0, end_s=9.0,
        params=(("keep", 2.0),),
    )
    assert AdversarySpec.from_dict(spec.to_dict()) == spec
    assert spec.active(1.0) and spec.active(8.9)
    assert not spec.active(0.5) and not spec.active(9.0)
    assert spec.param("keep", 0) == 2.0
    with pytest.raises(ValueError, match="unknown adversary behavior"):
        AdversarySpec(node=0, behavior="teleport")


def test_fault_plan_carries_adversaries_through_json():
    plan = FaultPlan(
        seed=3,
        adversaries=[
            AdversarySpec(node=9, behavior="equivocate"),
            AdversarySpec(node=8, behavior="lag", params=(("lag_s", 0.4),)),
        ],
    )
    again = FaultPlan.from_json(plan.to_json())
    assert again == plan
    assert again.to_json() == plan.to_json()


def test_attack_ledger_bytes_are_canonical():
    async def main():
        ledger = AttackLedger()
        ledger.note("withhold", node=8, dst=1, blocks=2)
        ledger.note("equivocate", node=7, dst=5, blocks=1)
        return ledger

    ledger = asyncio.new_event_loop().run_until_complete(main())
    doc = json.loads(ledger.ledger_bytes())
    assert [e["kind"] for e in doc] == ["withhold", "equivocate"]
    assert ledger.counts() == {"withhold:8": 1, "equivocate:7": 1}


def test_tampered_signature_parses_but_fails_verification():
    """The invalid-signer twin: structure intact, digest self-consistent,
    signature exactly wrong — rejection happens at the verifier."""
    signer = Committee.benchmark_signers(4)[1]
    committee = Committee.new_for_benchmarks(4)
    own, others = committee.genesis_blocks(1)
    includes = [b.reference for b in [own] + others]
    block = StatementBlock.build(
        1, 1, includes, [Share(b"tx")], signer=signer
    )
    twin = StatementBlock.from_bytes(tamper_signature(block.to_bytes()))
    assert twin.author() == 1 and twin.round() == 1
    assert twin.reference.digest != block.reference.digest
    twin.verify_structure(committee)  # structure check passes
    oracle = SimResignOracleVerifier(committee)
    pk = committee.public_key_bytes()[1]
    assert oracle.verify_signatures(
        [pk], [block.signed_digest()], [block.signature]
    ) == [True]
    assert oracle.verify_signatures(
        [pk], [twin.signed_digest()], [twin.signature]
    ) == [False]


def test_equivocating_variant_is_valid_and_distinct():
    signer = Committee.benchmark_signers(4)[2]
    committee = Committee.new_for_benchmarks(4)
    own, others = committee.genesis_blocks(2)
    includes = [b.reference for b in [own] + others]
    block = StatementBlock.build(
        2, 1, includes, [Share(b"tx")], signer=signer
    )
    variant = StatementBlock.from_bytes(
        equivocating_variant(block.to_bytes(), signer)
    )
    assert (variant.author(), variant.round()) == (2, 1)
    assert variant.reference.digest != block.reference.digest
    assert variant.includes == block.includes
    assert variant.statements == block.statements
    variant.verify_structure(committee)
    oracle = SimResignOracleVerifier(committee)
    pk = committee.public_key_bytes()[2]
    assert oracle.verify_signatures(
        [pk], [variant.signed_digest()], [variant.signature]
    ) == [True]


# ---------------------------------------------------------------------------
# Equivocation detection in the honest path (block_store)


def test_block_store_counts_live_equivocation(tmp_path):
    committee = Committee.new_test([1, 1, 1, 1])
    metrics = Metrics()
    w, r = walf(str(tmp_path / "wal"))
    core, _obs = BlockStore.open(0, r, w, committee, metrics=metrics)
    store = core.block_store
    recorder = FlightRecorder(authority=0)
    store.recorder = recorder
    writer = BlockWriter(w, store)
    signer = Committee.benchmark_signers(4)[1]
    first = StatementBlock.build(1, 3, [], [Share(b"a")], signer=signer)
    sibling = StatementBlock.from_bytes(
        equivocating_variant(first.to_bytes(), signer)
    )
    writer.insert_block(first)
    assert store.equivocations_detected == {}
    # Re-inserting the SAME digest is not equivocation (WAL replay shape).
    writer.insert_block(first)
    assert store.equivocations_detected == {}
    writer.insert_block(sibling)
    assert store.equivocations_detected == {1: 1}
    assert metrics.mysticeti_equivocation_detected_total.labels(
        "1"
    )._value.get() == 1.0
    kinds = [e["kind"] for e in recorder.events()]
    assert "equivocation-detected" in kinds
    # A block from a DIFFERENT authority at the same round is not counted.
    other = StatementBlock.build(
        2, 3, [], [Share(b"c")], signer=Committee.benchmark_signers(4)[2]
    )
    writer.insert_block(other)
    assert store.equivocations_detected == {1: 1}


# ---------------------------------------------------------------------------
# SafetyChecker adversary attribution


class _Commit:
    def __init__(self, height, anchor, blocks=()):
        self.height = height
        self.anchor = anchor
        self.blocks = blocks


def test_safety_checker_attributes_adversary_divergence():
    a1 = BlockReference(0, 3, b"a" * 32)
    b1 = BlockReference(1, 3, b"b" * 32)
    checker = SafetyChecker()
    checker.mark_adversary(2)
    checker.observe(0, [_Commit(1, a1)])
    checker.observe(1, [_Commit(1, a1)])
    # The adversary forking against the honest golden sequence is
    # RECORDED, not fatal...
    checker.observe(2, [_Commit(1, b1)])
    checker.check()
    assert checker.adversary_divergence == [
        {"kind": "fork", "adversary": 2, "height": 1}
    ]
    # ...while honest-honest divergence still raises.
    from mysticeti_tpu.chaos import SafetyViolation

    with pytest.raises(SafetyViolation):
        checker.observe(1, [_Commit(2, a1)])
        checker.observe(0, [_Commit(2, b1)])
        checker.check()


def test_safety_checker_counts_committed_blocks_per_author():
    ref = BlockReference(0, 3, b"a" * 32)
    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    blocks = [
        StatementBlock.build(a, 1, [], [Share(bytes([a]))], signer=signers[a])
        for a in range(3)
    ]
    checker = SafetyChecker()
    checker.observe(0, [_Commit(1, ref, blocks=blocks)])
    # Height-deduped: a replay re-observation adds nothing.
    checker.observe(0, [_Commit(1, ref, blocks=blocks)])
    assert checker.committed_blocks[0] == {0: 1, 1: 1, 2: 1}
    assert checker.committed_tx[0] == {0: 1, 1: 1, 2: 1}


# ---------------------------------------------------------------------------
# Scenario plumbing


def test_wan_latency_ranges_split_by_region():
    ranges = wan_latency_ranges([0, 0, 1])
    assert ranges[(0, 1)] == (0.005, 0.015)
    assert ranges[(0, 2)] == (0.080, 0.160)
    assert (1, 1) not in ranges


def test_scenario_to_dict_is_a_reproduction_recipe():
    scenario = scenario_by_name("byzantine-at-f")
    doc = scenario.to_dict()
    plan = FaultPlan.from_dict(doc["plan"])
    assert plan == scenario.plan()
    assert {s["behavior"] for s in doc["plan"]["adversaries"]} == {
        "equivocate", "withhold", "invalid_sig",
    }
    names = [s.name for s in default_matrix()]
    assert len(names) == len(set(names)) and len(names) >= 5
    with pytest.raises(KeyError):
        scenario_by_name("nope")


def test_oracle_verifier_matches_real_semantics():
    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    block = StatementBlock.build(0, 1, [], [Share(b"x")], signer=signers[0])
    twin = StatementBlock.from_bytes(tamper_signature(block.to_bytes()))
    oracle = SimResignOracleVerifier(committee)
    cpu = CpuSignatureVerifier()
    pk = committee.public_key_bytes()[0]
    for candidate in (block, twin):
        args = ([pk], [candidate.signed_digest()], [candidate.signature])
        assert oracle.verify_signatures(*args) == cpu.verify_signatures(*args)
    # Unknown key -> reject, never a KeyError.
    stranger = Committee.benchmark_signers(8)[7]
    assert oracle.verify_signatures(
        [stranger.public_key.bytes], [block.signed_digest()],
        [block.signature],
    ) == [False]


# ---------------------------------------------------------------------------
# Verifier rejection path under the TPU seam (satellite 3): an
# invalid-signer batch must reject exactly the tampered records while the
# honest remainder commits, on the CPU oracle and the pipelined dispatch.


def _mixed_batch(n=8):
    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    blocks = [
        StatementBlock.build(
            a % 4, 1 + a // 4, [], [Share(bytes([a]))], signer=signers[a % 4]
        )
        for a in range(n)
    ]
    tampered_idx = {1, 4, 6}
    batch = [
        StatementBlock.from_bytes(tamper_signature(b.to_bytes()))
        if i in tampered_idx
        else b
        for i, b in enumerate(blocks)
    ]
    return committee, batch, tampered_idx


@pytest.mark.parametrize("depth", [1, 4])
def test_verifier_rejects_exactly_the_tampered_records(depth):
    """depth=1 is the serial CPU-oracle path; depth=4 exercises the staged
    dispatch pipeline (multiple windows in flight, straggler patching)."""
    committee, batch, tampered_idx = _mixed_batch()

    async def main():
        verifier = BatchedSignatureVerifier(
            committee, CpuSignatureVerifier(), max_batch=2, max_delay_s=0.005,
            pipeline_depth=depth,
        )
        return await asyncio.gather(
            *(verifier.verify(b) for b in batch), return_exceptions=True
        ), verifier

    results, collector = asyncio.run(main())
    for i, result in enumerate(results):
        if i in tampered_idx:
            assert isinstance(result, VerificationError), i
        else:
            assert result is None, (i, result)
    if depth > 1:
        assert collector.pipeline.max_inflight >= 2


# ---------------------------------------------------------------------------
# The tier-1 acceptance sim: 10 nodes, f=3 concurrent attack classes


def _byzantine_at_f(duration_s):
    scenario = scenario_by_name("byzantine-at-f")
    return dataclasses.replace(scenario, duration_s=duration_s)


def _run_attacked(scenario, wal_dir):
    return run_chaos_sim(
        scenario.plan(), scenario.nodes, scenario.duration_s, str(wal_dir),
        parameters=scenario.base_parameters(),
        latency_ranges=scenario.latency_ranges(),
        with_metrics=True,
        verifier_factory=oracle_verifier_factory(scenario.nodes),
    )


@pytest.mark.chaos
def test_byzantine_at_f_commits_safely_with_all_attacks_detected(tmp_path):
    """f=3 of 10 concurrently equivocating / withholding / invalid-signing:
    zero honest SafetyChecker violations, a common honest leader prefix,
    and every attack detected on its surface or accounted in the ledger."""
    scenario = _byzantine_at_f(4.0)
    report, harness = _run_attacked(scenario, tmp_path)

    adversaries = {spec.node for spec in scenario.adversaries}
    honest = {
        a: seq for a, seq in report.sequences.items() if a not in adversaries
    }
    longest = max(honest.values(), key=len)
    for seq in honest.values():
        assert seq == longest[: len(seq)]
    assert min(len(seq) for seq in honest.values()) >= 8

    # Injection happened for every declared behavior...
    for spec in scenario.adversaries:
        assert report.attack_counts.get(f"{spec.behavior}:{spec.node}", 0) > 0
    # ...and every counter-surfaced behavior was detected and ATTRIBUTED
    # by at least one honest node (withhold is silence-shaped: its
    # evidence is the ledger accounting asserted above).
    equivocation_seen = invalid_seen = 0
    for authority, census in report.detections.items():
        if authority in adversaries:
            continue
        equivocation_seen += census.get("equivocation", {}).get(
            "authority=7", 0
        )
        invalid_seen += census.get("invalid_blocks", {}).get(
            "authority=9,reason=signature", 0
        )
    assert equivocation_seen > 0
    assert invalid_seen > 0
    # The invalid signer's blocks never entered any honest DAG — only its
    # round-0 genesis block (constructed locally by every node, never on
    # the wire) can appear in a committed sub-dag...
    for authority, seq in honest.items():
        store_counts = report.committed_blocks.get(authority, {})
        assert store_counts.get(9, 0) <= 1
    # ...and no honest node was flagged as an equivocator.
    for authority, census in report.detections.items():
        for label in census.get("equivocation", {}):
            assert label == "authority=7", (authority, label)


@pytest.mark.chaos
def test_byzantine_sim_is_byte_identical_across_same_seed_runs(tmp_path):
    """Attack schedule, detection ledger, and committed sequences are
    canonical bytes — a same-seed re-run reproduces all three exactly."""
    scenario = Scenario(
        name="det",
        description="determinism twin",
        nodes=5,
        duration_s=3.0,
        seed=17,
        leader_timeout_s=0.3,
        adversaries=(
            AdversarySpec(node=3, behavior="equivocate"),
            AdversarySpec(node=4, behavior="invalid_sig"),
        ),
    )
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    first, _ = _run_attacked(scenario, tmp_path / "a")
    second, _ = _run_attacked(scenario, tmp_path / "b")
    assert first.attack_log_bytes == second.attack_log_bytes
    assert first.attack_log_bytes  # attacks actually fired
    assert first.detections_bytes() == second.detections_bytes()
    assert first.sequences == second.sequences
    assert first.committed_tx == second.committed_tx
    assert first.fault_log_bytes == second.fault_log_bytes


# ---------------------------------------------------------------------------
# The full matrix with clean twins (ratios) rides the slow tier; tier-1
# covers the matrix machinery end-to-end on one short scenario.


@pytest.mark.slow
def test_scenario_matrix_all_pass():
    """The acceptance matrix: >= 5 distinct scenarios, all passing (zero
    safety violations, every attack detected/accounted, honest committed
    throughput >= min_ratio x the same-seed clean twin)."""
    from mysticeti_tpu.scenarios import run_matrix

    doc = run_matrix()
    assert len(doc["scenarios"]) >= 5
    failures = [
        (v["scenario"]["name"], v.get("throughput_ratio"), v["safety_ok"])
        for v in doc["scenarios"]
        if not v["passed"]
    ]
    assert doc["all_pass"], failures


# ---------------------------------------------------------------------------
# Malformed-frame hardening on real sockets (satellite: a garbage length
# prefix or undecodable payload severs the delivering connection, counted
# and attributed — never an uncaught decode error in the protocol path).


async def _adversarial_socket(metrics):
    from mysticeti_tpu.network import TcpNetwork

    loop = asyncio.get_event_loop()
    accepted = loop.create_future()

    async def on_conn(reader, writer):
        accepted.set_result((reader, writer))

    server = await asyncio.start_server(on_conn, host="127.0.0.1", port=0)
    port = server.sockets[0].getsockname()[1]
    c_reader, c_writer = await asyncio.open_connection("127.0.0.1", port)
    s_reader, s_writer = await accepted
    net = TcpNetwork(0, [("127.0.0.1", 0), ("127.0.0.1", 0)], metrics)
    peer_task = asyncio.ensure_future(net._run_peer(1, s_reader, s_writer))
    conn = await net.connections.get()
    return server, c_writer, peer_task, conn


@pytest.mark.parametrize(
    "garbage",
    [
        pytest.param((0xFFFFFFFF).to_bytes(4, "little"), id="oversized-prefix"),
        pytest.param((1).to_bytes(4, "little") + b"\xfe", id="unknown-tag"),
        pytest.param(
            (8).to_bytes(4, "little") + b"\x01garbage", id="torn-payload"
        ),
    ],
)
def test_malformed_frame_severs_connection_and_counts(garbage):
    async def main():
        metrics = Metrics()
        server, c_writer, peer_task, conn = await _adversarial_socket(metrics)
        c_writer.write(garbage)
        await c_writer.drain()
        c_writer.write_eof()
        await asyncio.wait_for(peer_task, 5)
        assert metrics.mysticeti_malformed_frames_total.labels(
            "1"
        )._value.get() == 1.0
        c_writer.close()
        server.close()

    asyncio.run(main())
