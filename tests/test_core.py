"""Core engine tests — parity with core.rs:562-775 (simple exchange, randomized
exchange over seeds, WAL recovery)."""
import random

import pytest

from mysticeti_tpu.threshold_clock import threshold_clock_valid_non_genesis

from helpers import committee_and_cores, open_core
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters


def test_core_simple_exchange(tmp_path):
    _committee, cores = committee_and_cores(4, str(tmp_path))

    proposed_transactions = []
    blocks = []
    for core in cores:
        core.run_block_handler([])
        block = core.try_new_block()
        assert block is not None, "must propose after genesis"
        assert block.round() == 1
        proposed_transactions.extend(core.block_handler.proposed)
        core.block_handler.proposed.clear()
        blocks.append(block)
    assert len(proposed_transactions) == 4

    more_blocks = blocks[1:]
    first = blocks[:1]

    blocks_r2 = []
    for core in cores:
        core.add_blocks(first)
        assert core.try_new_block() is None  # no quorum yet
        core.add_blocks(more_blocks)
        block = core.try_new_block()
        assert block is not None, "must propose after full round"
        assert block.round() == 2
        blocks_r2.append(block)

    for core in cores:
        core.add_blocks(blocks_r2)
        block = core.try_new_block()
        assert block is not None
        assert block.round() == 3
        for txid in proposed_transactions:
            assert core.block_handler.is_certified(txid), (
                f"tx {txid} not certified by {core.authority}"
            )


@pytest.mark.parametrize("seed", range(20))
def test_randomized_simple_exchange(tmp_path, seed):
    rng = random.Random(seed)
    committee, cores = committee_and_cores(4, str(tmp_path))

    proposed_transactions = []
    pending = [[] for _ in range(4)]

    def push_all(except_authority, block):
        for i, q in enumerate(pending):
            if i != except_authority:
                q.append(block)

    for core in cores:
        core.run_block_handler([])
        block = core.try_new_block()
        assert block is not None and block.round() == 1
        assert threshold_clock_valid_non_genesis(block, committee)
        proposed_transactions.extend(core.block_handler.proposed)
        core.block_handler.proposed.clear()
        push_all(core.authority, block)

    for i in range(1000):
        authority = rng.randrange(4)
        core = cores[authority]
        this_pending = pending[authority]
        count = rng.randint(1, 3)
        blocks = []
        for _ in range(count):
            if not this_pending:
                break
            blocks.append(this_pending.pop(rng.randrange(len(this_pending))))
        if not blocks:
            continue
        core.add_blocks(blocks)
        block = core.try_new_block()
        if block is None:
            continue
        assert threshold_clock_valid_non_genesis(block, committee)
        push_all(core.authority, block)
        if i < 20:
            proposed_transactions.extend(core.block_handler.proposed)
            core.block_handler.proposed.clear()
        else:
            assert proposed_transactions
            if all(
                c.block_handler.is_certified(tx)
                for tx in proposed_transactions
                for c in cores
            ):
                return  # all certified everywhere
    pytest.fail(f"seed {seed}: not all transactions certified")


def test_core_recovery(tmp_path):
    tmp = str(tmp_path)
    _committee, cores = committee_and_cores(4, tmp)

    proposed_transactions = []
    blocks = []
    for core in cores:
        core.run_block_handler([])
        block = core.try_new_block()
        assert block is not None and block.round() == 1
        proposed_transactions.extend(core.block_handler.proposed)
        blocks.append(block)
    assert len(proposed_transactions) == 4
    for core in cores:
        core.write_state()
        core.wal_writer.close()
    del cores

    # Reopen all cores from their WALs.
    committee = Committee.new_test([1] * 4)
    signers = Committee.benchmark_signers(4)
    cores = [open_core(committee, a, tmp, signers[a]) for a in range(4)]

    first, more_blocks = blocks[:2], blocks[2:]
    blocks_r2 = []
    for core in cores:
        core.add_blocks(first)
        assert core.try_new_block() is None
        core.add_blocks(more_blocks)
        block = core.try_new_block()
        assert block is not None, "must propose after full round"
        assert block.round() == 2
        blocks_r2.append(block)

    # No write_state here: recovery must replay unprocessed blocks instead.
    for core in cores:
        core.wal_writer.close()
    del cores

    cores = [open_core(committee, a, tmp, signers[a]) for a in range(4)]
    for core in cores:
        core.add_blocks(blocks_r2)
        block = core.try_new_block()
        assert block is not None
        assert block.round() == 3
        for txid in proposed_transactions:
            assert core.block_handler.is_certified(txid), (
                f"tx {txid} not certified by {core.authority} after recovery"
            )
