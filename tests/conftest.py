"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, which exercises
the same mesh/collective compilation paths XLA uses on a real pod.  Must run
before jax is first imported anywhere in the test session.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin ignores the env var alone; pin the platform through the
# config API as well (must run before any backend is initialized).
import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache: the Ed25519 scan kernel costs ~60s to
# compile on CPU; cache it across test sessions.
# Persistent compilation cache: mysticeti_tpu.ops.ed25519 sets a per-uid,
# ownership-checked default when JAX_COMPILATION_CACHE_DIR is unset.

import pytest


def pytest_collection_modifyitems(config, items):
    # pytest.ini declares kernel tests tier 2 ("JAX kernel/mesh compile-heavy
    # tests (minutes; run tier 2)"); the tier-1 gate selects `-m 'not slow'`.
    # Marking kernel items slow here enforces that declared tiering — a cold
    # compilation cache otherwise blows the tier-1 wall-time budget — while
    # `-m kernel` still selects them for the tier-2 run.
    for item in items:
        if "kernel" in item.keywords:
            item.add_marker(pytest.mark.slow)
