"""REST cloud provider behind the ServerProvider seam, tested the way the
reference tests its cloud clients (client/mod.rs:111-160 TestClient): the
full testbed lifecycle runs against recorded request/response fixtures —
no network, real provider logic."""
import asyncio

import pytest

from mysticeti_tpu.orchestrator.providers import (
    Ec2Provider,
    FixtureTransport,
    ProviderError,
    RestCloudProvider,
)
from mysticeti_tpu.orchestrator.testbed import Testbed

BASE = "https://api.cloud.example/v2"


def _inst(iid, ip, power="running", label="mysticeti-tpu"):
    return {
        "id": iid, "main_ip": ip, "region": "ewr",
        "power_status": power, "label": label,
    }


def _fixtures():
    created = [_inst("abc1", "10.0.0.1"), _inst("abc2", "10.0.0.2")]
    return [
        {"method": "POST", "url": f"{BASE}/instances", "repeat": 1,
         "response": {"instance": created[0]}},
        {"method": "POST", "url": f"{BASE}/instances", "repeat": 1,
         "response": {"instance": created[1]}},
        {"method": "GET", "url": f"{BASE}/instances",
         "response": {"instances": created + [
             # Another tenant's machine: must be filtered out by label.
             _inst("zzz9", "10.9.9.9", label="other-project"),
         ]}},
        {"method": "POST", "url": f"{BASE}/instances/abc1/start", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc2/start", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc1/halt", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc2/halt", "response": {}},
        {"method": "DELETE", "url": f"{BASE}/instances/abc1", "response": {}},
        {"method": "DELETE", "url": f"{BASE}/instances/abc2", "response": {}},
    ]


def _provider(transport):
    return RestCloudProvider(BASE, token="tok-123", transport=transport)


def test_testbed_lifecycle_end_to_end():
    """deploy / status / start / stop / destroy through the Testbed CLI
    surface, against the fixture transport."""
    transport = FixtureTransport(_fixtures())
    tb = Testbed(_provider(transport))

    async def scenario():
        created = await tb.deploy(2, "ewr")
        assert [i.host for i in created] == ["10.0.0.1", "10.0.0.2"]
        insts = await tb.status()
        assert [i.id for i in insts] == ["abc1", "abc2"]  # label-filtered
        await tb.start()
        await tb.stop()
        await tb.destroy()

    asyncio.run(scenario())
    # The recorded wire conversation: create carries the full body; the
    # lifecycle ops hit the per-instance endpoints.
    assert transport.calls[0]["body"] == {
        "region": "ewr", "plan": "vc2-16c-64gb",
        "label": "mysticeti-tpu", "os_id": 1743,
    }
    methods = [(c["method"], c["url"].rsplit("/v2", 1)[1])
               for c in transport.calls]
    assert ("POST", "/instances/abc1/start") in methods
    assert ("POST", "/instances/abc2/halt") in methods
    assert ("DELETE", "/instances/abc1") in methods


def test_api_error_raises_provider_error():
    transport = FixtureTransport([
        {"method": "GET", "url": f"{BASE}/instances", "status": 401,
         "response": {"error": "invalid API token"}},
    ])
    with pytest.raises(ProviderError, match="401"):
        asyncio.run(_provider(transport).list_instances())


# -- AWS/EC2-surface provider (client/aws.rs:37-393 capability) ---------------

EC2 = "https://ec2.cloud.example"
AMIS = {"us-east-1": "ami-east", "eu-west-1": "ami-west"}


def _ec2_inst(iid, ip, state="running", az="us-east-1a", name="mysticeti-tpu"):
    return {
        "instance_id": iid,
        "public_ip": ip,
        "state": {"name": state},
        "placement": {"availability_zone": az},
        "tags": [{"key": "Name", "value": name}],
    }


def _ec2_fixtures():
    east = [
        _ec2_inst("i-e1", "3.0.0.1"),
        # Lifecycle states: pending counts as active inventory...
        _ec2_inst("i-e2", "3.0.0.2", state="pending"),
        # ...terminated/shutting-down never list; foreign tags filter out.
        _ec2_inst("i-zz", "3.9.9.9", state="terminated"),
        _ec2_inst("i-other", "3.8.8.8", name="someone-else"),
    ]
    west = [_ec2_inst("i-w1", "5.0.0.1", state="stopped", az="eu-west-1b")]
    return [
        # Security group: east must be created (describe finds none), west
        # already exists.
        {"method": "GET", "url": f"{EC2}/us-east-1/security-groups",
         "response": {"security_groups": []}},
        {"method": "POST", "url": f"{EC2}/us-east-1/security-groups",
         "response": {"group_id": "sg-123"}},
        {"method": "GET", "url": f"{EC2}/eu-west-1/security-groups",
         "response": {"security_groups": [{"group_name": "mysticeti-tpu"}]}},
        # RunInstances per region.
        {"method": "POST", "url": f"{EC2}/us-east-1/instances", "repeat": 1,
         "response": {"instances": east[:2]}},
        {"method": "POST", "url": f"{EC2}/eu-west-1/instances", "repeat": 1,
         "response": {"instances": west}},
        # DescribeInstances (region-scoped, reservation-nested).
        {"method": "GET", "url": f"{EC2}/us-east-1/instances",
         "response": {"reservations": [{"instances": east}]}},
        {"method": "GET", "url": f"{EC2}/eu-west-1/instances",
         "response": {"reservations": [{"instances": west}]}},
        # Lifecycle ops.
        {"method": "POST", "url": f"{EC2}/us-east-1/instances/i-e1/start",
         "response": {}},
        {"method": "POST", "url": f"{EC2}/us-east-1/instances/i-e2/start",
         "response": {}},
        {"method": "POST", "url": f"{EC2}/eu-west-1/instances/i-w1/start",
         "response": {}},
        {"method": "POST", "url": f"{EC2}/us-east-1/instances/i-e1/stop",
         "response": {}},
        {"method": "POST", "url": f"{EC2}/us-east-1/instances/i-e2/stop",
         "response": {}},
        {"method": "POST", "url": f"{EC2}/eu-west-1/instances/i-w1/stop",
         "response": {}},
        {"method": "DELETE", "url": f"{EC2}/us-east-1/instances/i-e1",
         "response": {}},
        {"method": "DELETE", "url": f"{EC2}/us-east-1/instances/i-e2",
         "response": {}},
        {"method": "DELETE", "url": f"{EC2}/eu-west-1/instances/i-w1",
         "response": {}},
    ]


def test_ec2_testbed_lifecycle_end_to_end():
    """deploy (both regions) / status / start / stop / destroy through the
    Testbed surface against recorded EC2-shaped fixtures: security-group
    ensure, regions x AMIs, lifecycle state mapping, tag ownership."""
    transport = FixtureTransport(_ec2_fixtures())
    provider = Ec2Provider(EC2, token="tok-ec2", amis=AMIS, transport=transport)
    tb = Testbed(provider)

    async def scenario():
        east = await tb.deploy(2, "us-east-1")
        assert [i.host for i in east] == ["3.0.0.1", "3.0.0.2"]
        west = await tb.deploy(1, "eu-west-1")
        assert [i.id for i in west] == ["i-w1"]
        insts = await tb.status()
        # terminated + foreign-tag instances filtered; stopped still listed.
        assert sorted(i.id for i in insts) == ["i-e1", "i-e2", "i-w1"]
        by_id = {i.id: i for i in insts}
        assert by_id["i-e2"].active  # pending counts as active
        assert not by_id["i-w1"].active  # stopped does not
        assert by_id["i-w1"].region == "eu-west-1b"
        await tb.start()
        await tb.stop()
        await tb.destroy()

    asyncio.run(scenario())
    # The wire conversation: security group ensured before the first
    # RunInstances, and the create body pins the region's AMI + tags.
    urls = [(c["method"], c["url"]) for c in transport.calls]
    assert urls.index(("GET", f"{EC2}/us-east-1/security-groups")) < urls.index(
        ("POST", f"{EC2}/us-east-1/instances")
    )
    assert ("POST", f"{EC2}/us-east-1/security-groups") in urls
    # West's group already existed: no create call for it.
    assert ("POST", f"{EC2}/eu-west-1/security-groups") not in urls
    run_body = next(
        c["body"] for c in transport.calls
        if c["url"] == f"{EC2}/us-east-1/instances" and c["method"] == "POST"
    )
    assert run_body["image_id"] == "ami-east"
    assert run_body["min_count"] == run_body["max_count"] == 2
    assert run_body["tags"] == [{"key": "Name", "value": "mysticeti-tpu"}]
    # Region-scoped lifecycle ops for every instance.
    assert ("POST", f"{EC2}/eu-west-1/instances/i-w1/start") in urls
    assert ("DELETE", f"{EC2}/us-east-1/instances/i-e1") in urls


def test_ec2_default_region_fallback_for_cli_placeholder():
    """`fleet deploy` passes the CLI's \"local\" placeholder when --region is
    omitted; the provider must fall back to its default region instead of
    failing the lookup (an explicit unknown region still errors)."""
    transport = FixtureTransport([
        {"method": "GET", "url": f"{EC2}/eu-west-1/security-groups",
         "response": {"security_groups": [{"group_name": "mysticeti-tpu"}]}},
        {"method": "POST", "url": f"{EC2}/eu-west-1/instances",
         "response": {"instances": [_ec2_inst("i-d1", "5.0.0.9",
                                              az="eu-west-1a")]}},
    ])
    provider = Ec2Provider(
        EC2, token="t", amis=AMIS, default_region="eu-west-1",
        transport=transport,
    )
    created = asyncio.run(provider.create_instances(1, "local"))
    assert [i.id for i in created] == ["i-d1"]
    body = transport.calls[-1]["body"]
    assert body["image_id"] == "ami-west"


def test_ec2_unknown_region_and_unknown_id():
    transport = FixtureTransport([
        {"method": "GET", "url": f"{EC2}/us-east-1/instances",
         "response": {"reservations": []}},
        {"method": "GET", "url": f"{EC2}/eu-west-1/instances",
         "response": {"reservations": []}},
    ])
    provider = Ec2Provider(EC2, token="t", amis=AMIS, transport=transport)
    with pytest.raises(ProviderError, match="no AMI"):
        asyncio.run(provider.create_instances(1, "ap-south-2"))
    # Unknown id: one inventory refresh, then a loud error.
    with pytest.raises(ProviderError, match="unknown instance id"):
        asyncio.run(provider.start_instances(["i-ghost"]))


def test_ec2_api_error_raises_provider_error():
    transport = FixtureTransport([
        {"method": "GET", "url": f"{EC2}/us-east-1/instances", "status": 403,
         "response": {"error": "UnauthorizedOperation"}},
    ])
    provider = Ec2Provider(
        EC2, token="t", amis={"us-east-1": "ami-east"}, transport=transport
    )
    with pytest.raises(ProviderError, match="403"):
        asyncio.run(provider.list_instances())


def test_settings_wires_the_ec2_provider(monkeypatch, tmp_path):
    from mysticeti_tpu.orchestrator.settings import Settings

    monkeypatch.setenv("CLOUD_API_TOKEN", "aws-env-token")
    s = Settings(
        provider="aws", provider_base_url=EC2, provider_amis=dict(AMIS),
        provider_instance_type="c5.large",
    )
    p = s.make_provider()
    assert isinstance(p, Ec2Provider)
    assert p.token == "aws-env-token"
    assert p.instance_type == "c5.large"
    assert p.regions == ["eu-west-1", "us-east-1"]
    # provider_region default ("ewr") is not an EC2 region in the AMI map:
    # the default region falls back to the first configured region.
    assert p.default_region == "eu-west-1"
    # Round-trips through JSON (amis survive; the secret never lands).
    path = str(tmp_path / "settings.json")
    s.save(path)
    assert "aws-env-token" not in open(path).read()
    loaded = Settings.load(path)
    assert isinstance(loaded.make_provider(), Ec2Provider)
    assert loaded.provider_amis == AMIS

    with pytest.raises(ValueError, match="provider_amis"):
        Settings(provider="aws", provider_base_url=EC2).validate()
    with pytest.raises(ValueError, match="provider_base_url"):
        Settings(provider="aws", provider_amis=AMIS).validate()
    # An explicitly-set region with no configured AMI is a loud config
    # error, never a silent fallback to another continent.
    with pytest.raises(ValueError, match="no entry in provider_amis"):
        Settings(
            provider="aws", provider_base_url=EC2, provider_amis=dict(AMIS),
            provider_region="us-west-2",
        ).validate()


def test_settings_wires_the_rest_provider(monkeypatch, tmp_path):
    from mysticeti_tpu.orchestrator.settings import Settings

    monkeypatch.setenv("CLOUD_API_TOKEN", "from-env")
    s = Settings(provider="rest", provider_base_url=BASE)
    p = s.make_provider()
    assert isinstance(p, RestCloudProvider)
    assert p.token == "from-env"
    # Round-trips through JSON without ever storing the secret.
    path = str(tmp_path / "settings.json")
    s.save(path)
    assert "from-env" not in open(path).read()
    assert isinstance(Settings.load(path).make_provider(), RestCloudProvider)

    with pytest.raises(ValueError, match="provider_base_url"):
        Settings(provider="rest").validate()
