"""REST cloud provider behind the ServerProvider seam, tested the way the
reference tests its cloud clients (client/mod.rs:111-160 TestClient): the
full testbed lifecycle runs against recorded request/response fixtures —
no network, real provider logic."""
import asyncio

import pytest

from mysticeti_tpu.orchestrator.providers import (
    FixtureTransport,
    ProviderError,
    RestCloudProvider,
)
from mysticeti_tpu.orchestrator.testbed import Testbed

BASE = "https://api.cloud.example/v2"


def _inst(iid, ip, power="running", label="mysticeti-tpu"):
    return {
        "id": iid, "main_ip": ip, "region": "ewr",
        "power_status": power, "label": label,
    }


def _fixtures():
    created = [_inst("abc1", "10.0.0.1"), _inst("abc2", "10.0.0.2")]
    return [
        {"method": "POST", "url": f"{BASE}/instances", "repeat": 1,
         "response": {"instance": created[0]}},
        {"method": "POST", "url": f"{BASE}/instances", "repeat": 1,
         "response": {"instance": created[1]}},
        {"method": "GET", "url": f"{BASE}/instances",
         "response": {"instances": created + [
             # Another tenant's machine: must be filtered out by label.
             _inst("zzz9", "10.9.9.9", label="other-project"),
         ]}},
        {"method": "POST", "url": f"{BASE}/instances/abc1/start", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc2/start", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc1/halt", "response": {}},
        {"method": "POST", "url": f"{BASE}/instances/abc2/halt", "response": {}},
        {"method": "DELETE", "url": f"{BASE}/instances/abc1", "response": {}},
        {"method": "DELETE", "url": f"{BASE}/instances/abc2", "response": {}},
    ]


def _provider(transport):
    return RestCloudProvider(BASE, token="tok-123", transport=transport)


def test_testbed_lifecycle_end_to_end():
    """deploy / status / start / stop / destroy through the Testbed CLI
    surface, against the fixture transport."""
    transport = FixtureTransport(_fixtures())
    tb = Testbed(_provider(transport))

    async def scenario():
        created = await tb.deploy(2, "ewr")
        assert [i.host for i in created] == ["10.0.0.1", "10.0.0.2"]
        insts = await tb.status()
        assert [i.id for i in insts] == ["abc1", "abc2"]  # label-filtered
        await tb.start()
        await tb.stop()
        await tb.destroy()

    asyncio.run(scenario())
    # The recorded wire conversation: create carries the full body; the
    # lifecycle ops hit the per-instance endpoints.
    assert transport.calls[0]["body"] == {
        "region": "ewr", "plan": "vc2-16c-64gb",
        "label": "mysticeti-tpu", "os_id": 1743,
    }
    methods = [(c["method"], c["url"].rsplit("/v2", 1)[1])
               for c in transport.calls]
    assert ("POST", "/instances/abc1/start") in methods
    assert ("POST", "/instances/abc2/halt") in methods
    assert ("DELETE", "/instances/abc1") in methods


def test_api_error_raises_provider_error():
    transport = FixtureTransport([
        {"method": "GET", "url": f"{BASE}/instances", "status": 401,
         "response": {"error": "invalid API token"}},
    ])
    with pytest.raises(ProviderError, match="401"):
        asyncio.run(_provider(transport).list_instances())


def test_settings_wires_the_rest_provider(monkeypatch, tmp_path):
    from mysticeti_tpu.orchestrator.settings import Settings

    monkeypatch.setenv("CLOUD_API_TOKEN", "from-env")
    s = Settings(provider="rest", provider_base_url=BASE)
    p = s.make_provider()
    assert isinstance(p, RestCloudProvider)
    assert p.token == "from-env"
    # Round-trips through JSON without ever storing the secret.
    path = str(tmp_path / "settings.json")
    s.save(path)
    assert "from-env" not in open(path).read()
    assert isinstance(Settings.load(path).make_provider(), RestCloudProvider)

    with pytest.raises(ValueError, match="provider_base_url"):
        Settings(provider="rest").validate()
