"""Deterministic virtual-time loop tests (simulator.rs:193-228 tier)."""
import asyncio
import time

import pytest

from mysticeti_tpu.runtime import simulated
from mysticeti_tpu.runtime.simulated import DeterministicLoop, run_simulation


def test_virtual_sleep_is_instant_and_exact():
    async def main():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.sleep(3600.0)  # one virtual hour
        return loop.time() - t0

    wall0 = time.monotonic()
    elapsed_virtual = run_simulation(main())
    wall = time.monotonic() - wall0
    assert abs(elapsed_virtual - 3600.0) < 1e-6
    assert wall < 5.0  # no real waiting


def test_timer_ordering_deterministic():
    async def main():
        loop = asyncio.get_running_loop()
        events = []

        async def ticker(name, period, count):
            for _ in range(count):
                await asyncio.sleep(period)
                events.append((round(loop.time(), 6), name))

        await asyncio.gather(ticker("a", 0.3, 5), ticker("b", 0.5, 3))
        return events

    first = run_simulation(main(), seed=1)
    second = run_simulation(main(), seed=1)
    assert first == second
    assert first[0] == (0.3, "a")
    assert (1.5, "b") in first


def test_asyncio_primitives_work():
    async def main():
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        ev = asyncio.Event()
        results = []

        async def producer():
            for i in range(3):
                await asyncio.sleep(0.1)
                await q.put(i)
            ev.set()

        async def consumer():
            while not (ev.is_set() and q.empty()):
                try:
                    item = await asyncio.wait_for(q.get(), timeout=0.05)
                    results.append((round(loop.time(), 6), item))
                except asyncio.TimeoutError:
                    continue
            return results

        _, res = await asyncio.gather(producer(), consumer())
        return res

    res = run_simulation(main())
    assert [item for _, item in res] == [0, 1, 2]
    assert res[0][0] >= 0.1


def test_seeded_rng_on_loop():
    async def main():
        loop = asyncio.get_running_loop()
        return [loop.rng.randrange(1000) for _ in range(5)]

    assert run_simulation(main(), seed=42) == run_simulation(main(), seed=42)
    assert run_simulation(main(), seed=42) != run_simulation(main(), seed=43)


def test_virtual_timeout():
    async def main():
        await asyncio.sleep(10**6)

    with pytest.raises(asyncio.TimeoutError):
        run_simulation(main(), timeout_s=100.0)


def test_utc_time_offset():
    async def main():
        from mysticeti_tpu import runtime

        t0 = runtime.timestamp_utc()
        await asyncio.sleep(12.5)
        return runtime.timestamp_utc() - t0

    assert abs(run_simulation(main()) - 12.5) < 1e-6
