"""Tests for the env-filtered logging + virtual-time sim formatter."""
import io
import logging

from mysticeti_tpu.runtime.simulated import DeterministicLoop
from mysticeti_tpu.tracing import (
    PACKAGE,
    SimAwareFormatter,
    current_authority,
    logger,
    setup_logging,
)


def _fresh_root():
    root = logging.getLogger(PACKAGE)
    for h in list(root.handlers):
        root.removeHandler(h)
    for lg in [root, logging.getLogger(f"{PACKAGE}.net_sync")]:
        lg.setLevel(logging.NOTSET)
    return root


def test_env_filter_levels():
    _fresh_root()
    stream = io.StringIO()
    setup_logging("net_sync=debug,warning", stream=stream, force=True)
    logger(f"{PACKAGE}.net_sync").debug("dbg visible")
    logger(f"{PACKAGE}.core").info("info hidden")
    logger(f"{PACKAGE}.core").warning("warn visible")
    out = stream.getvalue()
    assert "dbg visible" in out
    assert "info hidden" not in out
    assert "warn visible" in out
    _fresh_root()


def test_unset_spec_is_noop():
    root = _fresh_root()
    setup_logging(spec=None if "MYSTICETI_LOG" not in __import__("os").environ else "")
    assert not root.handlers


def test_force_reinstall_resets_stale_module_levels():
    """A force re-install must not inherit per-module levels from the
    previous spec: after switching 'net_sync=debug,warning' -> 'warning',
    net_sync debug records stay hidden."""
    _fresh_root()
    first = io.StringIO()
    setup_logging("net_sync=debug,warning", stream=first, force=True)
    assert logging.getLogger(f"{PACKAGE}.net_sync").level == logging.DEBUG
    second = io.StringIO()
    setup_logging("warning", stream=second, force=True)
    assert logging.getLogger(f"{PACKAGE}.net_sync").level == logging.NOTSET
    logger(f"{PACKAGE}.net_sync").debug("stale debug hidden")
    logger(f"{PACKAGE}.net_sync").warning("warn visible")
    out = second.getvalue()
    assert "stale debug hidden" not in out
    assert "warn visible" in out
    _fresh_root()


def test_formatter_caches_loop_class_module_level():
    """The DeterministicLoop import is resolved once and cached at module
    level, not re-imported per log record."""
    import mysticeti_tpu.tracing as tracing_mod
    from mysticeti_tpu.runtime.simulated import DeterministicLoop

    record = logging.LogRecord(
        f"{PACKAGE}.core", logging.INFO, __file__, 1, "hello", (), None
    )
    out = SimAwareFormatter().format(record)
    assert "core: hello" in out
    assert tracing_mod._DeterministicLoop is DeterministicLoop


def test_virtual_time_and_authority_prefix():
    _fresh_root()
    stream = io.StringIO()
    setup_logging("debug", stream=stream, force=True)
    loop = DeterministicLoop(seed=1)

    async def main():
        import asyncio

        current_authority.set(3)
        await asyncio.sleep(12.5)
        logger(f"{PACKAGE}.net_sync").info("hello from sim")

    loop.run_until_complete(main())
    out = stream.getvalue()
    assert "hello from sim" in out
    assert "A3]" in out, out
    assert "12.500s" in out, out
    assert "net_sync:" in out
    _fresh_root()
