"""Shared test fixtures: in-process cores wired to temp WALs (test_util.rs parity)."""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

from mysticeti_tpu.block_handler import TestBlockHandler
from mysticeti_tpu.block_store import BlockStore, BlockWriter
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.core import Core, CoreOptions
from mysticeti_tpu.wal import walf


def committee_and_cores(
    n: int, tmp_dir: str, parameters: Optional[Parameters] = None
) -> Tuple[Committee, List[Core]]:
    """N in-process cores with TestBlockHandlers over per-authority WALs
    (test_util.rs committee_and_cores)."""
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = parameters or Parameters()
    cores = []
    for authority in range(n):
        core = open_core(committee, authority, tmp_dir, signers[authority], parameters)
        cores.append(core)
    return committee, cores


def open_core(committee, authority, tmp_dir, signer, parameters=None):
    wal_path = os.path.join(tmp_dir, f"wal-{authority}")
    wal_writer, wal_reader = walf(wal_path)
    recovered, _observer = BlockStore.open(authority, wal_reader, wal_writer, committee)
    handler = TestBlockHandler(
        last_transaction=authority * 1_000_000, committee=committee, authority=authority
    )
    return Core(
        block_handler=handler,
        authority=authority,
        committee=committee,
        parameters=parameters or Parameters(),
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signer,
    )


def build_dag(committee, block_writer, start, stop):
    """Fully-connected DAG from ``start`` refs (or genesis) to round ``stop``
    (test_util.rs:436-485).  Returns the references of the last layer."""
    from mysticeti_tpu.types import StatementBlock

    if start is not None:
        assert start
        assert len({r.round for r in start}) == 1
        includes = list(start)
    else:
        genesis = [
            StatementBlock.new_genesis(a, committee.epoch)
            for a in committee.authority_indexes()
        ]
        block_writer.add_blocks(genesis)
        includes = [b.reference for b in genesis]

    starting_round = includes[0].round + 1
    for round_ in range(starting_round, stop + 1):
        blocks = [
            StatementBlock.build(a, round_, includes, (), epoch=committee.epoch)
            for a in committee.authority_indexes()
        ]
        block_writer.add_blocks(blocks)
        includes = [b.reference for b in blocks]
    return includes


def build_dag_layer(connections, block_writer):
    """One explicit layer: [(authority, parent refs)] (test_util.rs:487-511)."""
    from mysticeti_tpu.types import StatementBlock

    references = []
    for authority, parents in connections:
        round_ = parents[0].round + 1
        block = StatementBlock.build(authority, round_, parents, ())
        references.append(block.reference)
        block_writer.add_block(block)
    return references


class DagBlockWriter:
    """Standalone store + writer for committer tests (test_util.rs:377-432)."""

    def __init__(self, committee: Committee, tmp_dir: str, name: str = "tw-wal"):
        wal_path = os.path.join(tmp_dir, name)
        self.wal_writer, self.wal_reader = walf(wal_path)
        recovered, _ = BlockStore.open(0, self.wal_reader, self.wal_writer, committee)
        self.block_store = recovered.block_store
        self._writer = BlockWriter(self.wal_writer, self.block_store)

    def add_block(self, block):
        return self._writer.insert_block(block)

    def add_blocks(self, blocks):
        for b in blocks:
            self.add_block(b)
