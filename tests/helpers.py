"""Shared test fixtures: in-process cores wired to temp WALs (test_util.rs parity)."""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

from mysticeti_tpu.block_handler import TestBlockHandler
from mysticeti_tpu.block_store import BlockStore, BlockWriter
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.core import Core, CoreOptions
from mysticeti_tpu.wal import walf


def committee_and_cores(
    n: int, tmp_dir: str, parameters: Optional[Parameters] = None
) -> Tuple[Committee, List[Core]]:
    """N in-process cores with TestBlockHandlers over per-authority WALs
    (test_util.rs committee_and_cores)."""
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = parameters or Parameters()
    cores = []
    for authority in range(n):
        core = open_core(committee, authority, tmp_dir, signers[authority], parameters)
        cores.append(core)
    return committee, cores


def open_core(committee, authority, tmp_dir, signer, parameters=None):
    wal_path = os.path.join(tmp_dir, f"wal-{authority}")
    wal_writer, wal_reader = walf(wal_path)
    recovered, _observer = BlockStore.open(authority, wal_reader, wal_writer, committee)
    handler = TestBlockHandler(
        last_transaction=authority * 1_000_000, committee=committee, authority=authority
    )
    return Core(
        block_handler=handler,
        authority=authority,
        committee=committee,
        parameters=parameters or Parameters(),
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signer,
    )


class DagBlockWriter:
    """Standalone store + writer for committer tests (test_util.rs:377-432)."""

    def __init__(self, committee: Committee, tmp_dir: str, name: str = "tw-wal"):
        wal_path = os.path.join(tmp_dir, name)
        self.wal_writer, self.wal_reader = walf(wal_path)
        recovered, _ = BlockStore.open(0, self.wal_reader, self.wal_writer, committee)
        self.block_store = recovered.block_store
        self._writer = BlockWriter(self.wal_writer, self.block_store)

    def add_block(self, block):
        return self._writer.insert_block(block)

    def add_blocks(self, blocks):
        for b in blocks:
            self.add_block(b)
