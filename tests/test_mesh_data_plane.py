"""Broadcast-once mesh data plane (r10): encode-once fan-out, scatter-gather
write coalescing, Ping/Pong priority, and zero-copy receive.

The plane is endpoint-local by contract — every optimization must leave the
wire byte-identical to the pre-r10 encoder.  The golden corpus pins that for
all 12 message tags; the census tests pin the N-subscribers → 1-encode
economics; the socket tests drive the real ``TcpNetwork._run_peer`` loops
over a live localhost pair.
"""
import asyncio
import os

import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.metrics import Metrics
from mysticeti_tpu.network import (
    BlockNotFound,
    Blocks,
    Connection,
    EncodedFrame,
    Ping,
    Pong,
    RequestBlocks,
    RequestBlocksResponse,
    RequestSnapshot,
    RequestSnapshotStream,
    SnapshotResponse,
    SubscribeOthersFrom,
    SubscribeOwnFrom,
    TcpNetwork,
    TimestampedBlocks,
    _FrameReceiver,
    decode_message,
    encode_message,
    frame_payload,
    mesh_legacy,
)
from mysticeti_tpu.types import BlockReference, Share, StatementBlock

from helpers import DagBlockWriter, build_dag


# --- golden corpus: byte-identity across every message tag -----------------

_REF = BlockReference(3, 7, bytes(range(32)))
_REF2 = BlockReference(1, 9, bytes(range(100, 132)))

# (message, expected frame payload hex) — the hex was produced by the
# pre-broadcast-once encoder; the encoder (and therefore the wire) must
# never drift, whatever the send path does locally.
GOLDEN_CORPUS = [
    (SubscribeOwnFrom(5), "010500000000000000"),
    (
        Blocks((b"block-one", b"block-two-bytes")),
        "020200000009000000626c6f636b2d6f6e650f000000626c6f636b2d74776f2d"
        "6279746573",
    ),
    (
        RequestBlocks((_REF, _REF2)),
        "030200000003000000000000000700000000000000000102030405060708090a"
        "0b0c0d0e0f101112131415161718191a1b1c1d1e1f0100000000000000090000"
        "00000000006465666768696a6b6c6d6e6f707172737475767778797a7b7c7d7e"
        "7f80818283",
    ),
    (
        RequestBlocksResponse((b"resp-block",)),
        "04010000000a000000726573702d626c6f636b",
    ),
    (
        BlockNotFound((_REF,)),
        "050100000003000000000000000700000000000000000102030405060708090a"
        "0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
    ),
    (Ping(123456789), "0615cd5b0700000000"),
    (Pong(987654321), "07b168de3a00000000"),
    (SubscribeOthersFrom(2, 11), "0802000000000000000b00000000000000"),
    (RequestSnapshot(42), "092a00000000000000"),
    (SnapshotResponse(b"manifest-bytes"), "0a0e0000006d616e69666573742d6279746573"),
    (RequestSnapshotStream(17), "0b1100000000000000"),
    (
        TimestampedBlocks(
            (b"stamped-block",), sent_monotonic_ns=111, sent_wall_ns=222
        ),
        "0c6f00000000000000de00000000000000010000000d0000007374616d706564"
        "2d626c6f636b",
    ),
]


def test_golden_corpus_all_tags_byte_identical():
    """Every message tag 1-12: encode_message, the EncodedFrame cache path,
    and frame_payload all emit the pinned pre-r10 bytes, and the frame
    decodes back — from bytes AND from a memoryview (zero-copy mode)."""
    seen_tags = set()
    for msg, expected_hex in GOLDEN_CORPUS:
        expected = bytes.fromhex(expected_hex)
        assert encode_message(msg) == expected, type(msg).__name__
        assert EncodedFrame(msg).payload == expected
        assert frame_payload(EncodedFrame(msg)) == expected
        assert frame_payload(msg) == expected
        seen_tags.add(expected[0])
        # Roundtrip, both input modes.
        assert decode_message(expected) == msg
        view_decoded = decode_message(memoryview(bytearray(expected)))
        assert view_decoded == msg
    assert seen_tags == set(range(1, 13))


def test_encoded_frame_is_lazy_and_caches():
    """The sim delivers EncodedFrame objects without ever serializing; the
    TCP write path encodes once and reuses the bytes."""
    msg = Blocks((b"payload",))
    frame = EncodedFrame(msg)
    assert frame._payload is None  # nothing encoded yet
    first = frame.payload
    assert first == encode_message(msg)
    assert frame.payload is first  # cached, not re-encoded


def test_decode_message_views_are_zero_copy_until_materialized():
    """Block payloads decoded from a memoryview are sub-views of the frame
    buffer; StatementBlock.from_bytes materializes exactly one bytes that
    survives buffer reuse."""
    committee = Committee.new_test([1] * 4)
    signers = Committee.benchmark_signers(4)
    genesis = [StatementBlock.new_genesis(a).reference for a in range(4)]
    block = StatementBlock.build(
        0, 1, genesis, [Share(b"tx" * 50)], signer=signers[0]
    )
    frame = bytearray(encode_message(Blocks((block.to_bytes(),))))
    msg = decode_message(memoryview(frame))
    assert type(msg.blocks[0]) is memoryview
    decoded = StatementBlock.from_bytes(msg.blocks[0])
    del msg  # release the view before clobbering
    frame[:] = b"\x00" * len(frame)  # simulate buffer reuse
    assert decoded.to_bytes() == block.to_bytes()
    decoded.verify(committee)  # digest/signature contract intact


# --- Ping/Pong priority lane ----------------------------------------------


def test_ping_jumps_saturated_send_queue():
    async def main():
        conn = Connection(peer=2)
        while conn.try_send(Blocks((b"bulk",))):
            pass  # saturate the bounded queue
        assert conn.sender.full()
        # Must neither block nor drop, and must come out FIRST.
        await asyncio.wait_for(conn.send(Ping(7)), timeout=0.5)
        assert isinstance(conn.sender.get_nowait(), Ping)
        # Pong rides the same lane (the echo side of the probe).
        await asyncio.wait_for(conn.send(Pong(8)), timeout=0.5)
        assert isinstance(conn.sender.get_nowait(), Pong)

    asyncio.run(main())


def test_urgent_lane_is_capped_against_ping_floods():
    """The priority lane ignores the bulk bound but has its OWN cap: a
    peer flooding Pings (each answered with a front-queued Pong) cannot
    grow the send queue without limit while refusing to read."""
    from mysticeti_tpu.network import _SendQueue

    async def main():
        metrics = Metrics()
        conn = Connection(peer=7, metrics=metrics)
        accepted = 0
        for i in range(1000):
            if conn.try_send(Pong(i)):
                accepted += 1
        assert accepted == _SendQueue.URGENT_CAP
        assert conn.sender.qsize() == _SendQueue.URGENT_CAP
        dropped = metrics.connection_send_drops_total.labels("7")._value.get()
        assert dropped == 1000 - _SendQueue.URGENT_CAP
        # Draining the lane frees it again (the counter tracks pops).
        for _ in range(_SendQueue.URGENT_CAP):
            assert isinstance(conn.sender.get_nowait(), Pong)
        assert conn.try_send(Ping(0))
        # await-send drops over-cap probes instead of queueing them.
        await conn.send(Pong(1))
        while True:
            try:
                conn.sender.get_nowait()
            except asyncio.QueueEmpty:
                break

    asyncio.run(main())


def test_try_send_drops_are_counted():
    async def main():
        metrics = Metrics()
        conn = Connection(peer=5, metrics=metrics)
        while conn.try_send(Blocks((b"bulk",))):
            pass  # the exiting (False) call counts the first drop
        counter = metrics.connection_send_drops_total.labels("5")
        base = counter._value.get()
        assert base >= 1
        assert not conn.try_send(Blocks((b"dropped",)))
        assert not conn.try_send(Blocks((b"dropped",)))
        assert counter._value.get() == base + 2
        # Urgent frames never count as drops — they jump the bound.
        assert conn.try_send(Ping(1))
        assert counter._value.get() == base + 2

    asyncio.run(main())


# --- encode-once fan-out census -------------------------------------------


class _Notify:
    """Minimal stand-in for net_sync.Notify (subscribe/notify/generation)."""

    def __init__(self):
        self._event = asyncio.Event()
        self.generation = 0

    def subscribe(self):
        return self._event

    def notify(self):
        self.generation += 1
        event, self._event = self._event, asyncio.Event()
        event.set()


def test_encode_reuse_census(tmp_path):
    """N subscribers at one cursor: 1 build, N-1 reuses, identical frame
    object on every queue; a new block (generation bump) forces a rebuild."""
    from mysticeti_tpu.synchronizer import BlockDisseminator, FrameCache

    committee = Committee.new_test([1] * 4)
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 3)  # rounds 1-3 for every authority

    async def main():
        metrics = Metrics()
        cache = FrameCache(metrics)
        notify = _Notify()
        n_subs = 5
        conns = [Connection(peer=i + 1) for i in range(n_subs)]
        dissems = [
            BlockDisseminator(
                c, writer.block_store, notify, metrics=metrics,
                frame_cache=cache,
            )
            for c in conns
        ]
        for d in dissems:
            d.subscribe_own_from(0)
        frames = []
        for c in conns:
            # Every subscriber ships a frame covering rounds 1-3.
            frames.append(await asyncio.wait_for(c.sender.get(), timeout=2.0))
        for d in dissems:
            d.stop()
        assert all(f is frames[0] for f in frames)
        assert isinstance(frames[0], EncodedFrame)
        # All five queues carried the IDENTICAL immutable frame object.
        assert cache.builds == 1, cache.builds
        assert cache.reuses == n_subs - 1, cache.reuses
        reuse_series = metrics.dissemination_encode_reuse_total
        assert reuse_series._value.get() == n_subs - 1
        # A store change bumps the generation: the next frame is rebuilt,
        # never served stale from the cache.
        build_dag(
            committee, writer,
            [b.reference for b in writer.block_store.get_blocks_by_round(3)],
            4,
        )
        notify.notify()
        d2 = BlockDisseminator(
            Connection(peer=9), writer.block_store, notify, metrics=metrics,
            frame_cache=cache,
        )
        frame2, cursor2, count2 = d2._push_frame("own", None, 3)
        assert cursor2 == 4 and count2 == 1
        assert cache.builds == 2

    asyncio.run(main())


def test_frame_cache_identity_across_subscribers(tmp_path):
    """The cache returns the same EncodedFrame object (not equal copies) so
    a 3.12+ transport can hold one buffer N times without N serializations."""
    from mysticeti_tpu.synchronizer import BlockDisseminator, FrameCache

    committee = Committee.new_test([1] * 4)
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 2)

    async def main():
        cache = FrameCache()
        notify = _Notify()
        mk = lambda: BlockDisseminator(
            Connection(peer=1), writer.block_store, notify,
            frame_cache=cache,
        )
        a = mk()._push_frame("own", None, 0)
        b = mk()._push_frame("own", None, 0)
        assert a[0] is b[0]
        # Different cursors are different frames.
        c = mk()._push_frame("own", None, 1)
        assert c[0] is not a[0] and c[1] == 2
        # Helper streams have their own key space.
        h = mk()._push_frame("others", 2, 0)
        assert h[0] is not a[0] and h[2] > 0

    asyncio.run(main())


def test_frame_cache_bounded():
    from mysticeti_tpu.synchronizer import FrameCache

    cache = FrameCache()
    for i in range(3 * FrameCache.CAPACITY):
        cache.put(("own", None, i, 100, False, 0), (object(), i, 1))
    assert len(cache._frame_entries) == FrameCache.CAPACITY


# --- live socket pair: coalescing, priority, zero-copy receive -------------


async def _socket_pair():
    """(client reader, client writer, server reader, server writer) over a
    real localhost TCP connection."""
    loop = asyncio.get_event_loop()
    accepted = loop.create_future()

    async def on_conn(reader, writer):
        accepted.set_result((reader, writer))

    server = await asyncio.start_server(on_conn, host="127.0.0.1", port=0)
    port = server.sockets[0].getsockname()[1]
    c_reader, c_writer = await asyncio.open_connection("127.0.0.1", port)
    s_reader, s_writer = await accepted
    return server, c_reader, c_writer, s_reader, s_writer


async def _read_raw_frame(reader):
    header = await reader.readexactly(4)
    return await reader.readexactly(int.from_bytes(header, "little"))


def test_write_coalescing_ships_ping_first_and_byte_identical():
    """Drive the real _run_peer write loop: a saturated bulk backlog plus a
    Ping must reach the wire ping-first, every frame byte-identical to the
    plain encoder, with the coalescing/wire-bytes counters advancing."""

    async def main():
        metrics = Metrics()
        net = TcpNetwork(0, [("127.0.0.1", 0), ("127.0.0.1", 0)], metrics)
        server, c_reader, c_writer, s_reader, s_writer = await _socket_pair()
        peer_task = asyncio.ensure_future(net._run_peer(1, s_reader, s_writer))
        conn = await net.connections.get()
        bulk = [Blocks((bytes([i]) * 200,)) for i in range(50)]
        # Enqueue without yielding: the write loop sees one batch.
        for m in bulk:
            assert conn.try_send(m)
        await conn.send(Ping(1234))  # priority lane, still same batch
        frames = []
        while len([f for f in frames if f[0] != 6]) < 50 or not any(
            f == encode_message(Ping(1234)) for f in frames
        ):
            frames.append(await asyncio.wait_for(_read_raw_frame(c_reader), 5))
        # Drain any straggling startup ping so the byte census is exact.
        try:
            while True:
                frames.append(
                    await asyncio.wait_for(_read_raw_frame(c_reader), 0.2)
                )
        except asyncio.TimeoutError:
            pass
        # The probe ping precedes EVERY bulk frame (the peer task's own
        # startup ping may ride ahead — also urgent, also fine).
        ping_at = frames.index(encode_message(Ping(1234)))
        first_bulk = min(i for i, f in enumerate(frames) if f[0] != 6)
        assert ping_at < first_bulk, (ping_at, first_bulk)
        # Bulk order preserved, every frame byte-identical to the encoder.
        assert [f for f in frames if f[0] != 6] == [
            encode_message(m) for m in bulk
        ]
        total = sum(len(f) + 4 for f in frames)
        sent = metrics.mesh_wire_bytes_total.labels("sent")._value.get()
        assert sent == total
        # At least the 50-frame batch coalesced (scheduling may split the
        # first wakeup off, never below this floor).
        assert metrics.mesh_frames_coalesced_total._value.get() >= 49
        peer_task.cancel()
        c_writer.close()
        server.close()

    asyncio.run(main())


def test_zero_copy_receive_through_run_peer():
    """The receiving _run_peer parses frames via the BufferedProtocol path:
    block payloads surface as memoryviews, survive deep pipelining (buffer
    reuse is refcount-guarded), and Pings are answered on the priority lane."""

    async def main():
        metrics = Metrics()
        net = TcpNetwork(0, [("127.0.0.1", 0), ("127.0.0.1", 0)], metrics)
        server, c_reader, c_writer, s_reader, s_writer = await _socket_pair()
        peer_task = asyncio.ensure_future(net._run_peer(1, s_reader, s_writer))
        conn = await net.connections.get()

        payloads = [os.urandom(300) for _ in range(40)]
        parts = []
        for p in payloads:
            enc = encode_message(Blocks((p,)))
            parts += [len(enc).to_bytes(4, "little"), enc]
        parts += [
            len(encode_message(Ping(77))).to_bytes(4, "little"),
            encode_message(Ping(77)),
        ]
        c_writer.writelines(parts)
        await c_writer.drain()

        msgs = []
        for _ in range(40):
            msgs.append(await asyncio.wait_for(conn.recv(), 5))
        # Zero-copy mode delivered views (proves the BufferedProtocol path
        # attached, not the readexactly fallback)...
        assert all(type(m.blocks[0]) is memoryview for m in msgs)
        # ...and every payload is intact even though 40 frames crossed one
        # reusable buffer while earlier views were still alive.
        assert [bytes(m.blocks[0]) for m in msgs] == payloads
        received = metrics.mesh_wire_bytes_total.labels("received")._value.get()
        assert received == sum(len(p) for p in parts)
        # The Ping was consumed by the read loop and echoed as a Pong
        # (the peer task's own startup Ping may arrive first — skip it).
        while True:
            frame = await asyncio.wait_for(_read_raw_frame(c_reader), 5)
            if frame[0] != 6:
                break
        assert decode_message(frame) == Pong(77)
        peer_task.cancel()
        c_writer.close()
        server.close()

    asyncio.run(main())


def test_receive_buffer_shrinks_after_jumbo_frame():
    """A multi-MB frame grows the per-connection assembly buffer; once the
    backlog clears it swaps back to MIN_BUF instead of pinning the jumbo
    allocation for the life of the connection."""

    async def main():
        server, c_reader, c_writer, s_reader, s_writer = await _socket_pair()
        recv = _FrameReceiver.attach(s_reader, s_writer)
        assert recv is not None
        jumbo = Blocks((os.urandom(3_000_000),))
        enc = encode_message(jumbo)
        c_writer.writelines([len(enc).to_bytes(4, "little"), enc])
        await c_writer.drain()
        got = decode_message(await asyncio.wait_for(recv.read_frame(), 10))
        assert bytes(got.blocks[0]) == jumbo.blocks[0]
        del got
        assert len(recv._buf) == _FrameReceiver.MIN_BUF
        # The link keeps working on the fresh buffer.
        small = Blocks((b"x" * 100,))
        enc = encode_message(small)
        c_writer.writelines([len(enc).to_bytes(4, "little"), enc])
        await c_writer.drain()
        assert decode_message(await asyncio.wait_for(recv.read_frame(), 5)) == small
        c_writer.close()
        server.close()

    asyncio.run(main())


def test_legacy_env_disables_new_plane(monkeypatch):
    monkeypatch.setenv("MYSTICETI_MESH_LEGACY", "1")
    assert mesh_legacy()

    async def main():
        # attach() refuses, so the stream fallback runs.
        server, c_reader, c_writer, s_reader, s_writer = await _socket_pair()
        assert _FrameReceiver.attach(s_reader, s_writer) is None
        s_writer.close()
        c_writer.close()
        server.close()

    asyncio.run(main())
    monkeypatch.delenv("MYSTICETI_MESH_LEGACY")
    assert not mesh_legacy()


# --- ingest batching audit -------------------------------------------------


def test_ingest_whole_frame_batching(tmp_path):
    """A frame of K blocks crosses the core owner exactly twice: one
    processed() dedup command for the whole batch, one add_blocks() for the
    accepted batch — never a per-block hop."""
    from mysticeti_tpu.runtime.simulated import run_simulation

    committee = Committee.new_test([1] * 4)
    signers = Committee.benchmark_signers(4)

    async def scenario():
        from mysticeti_tpu.block_handler import TestBlockHandler
        from mysticeti_tpu.block_store import BlockStore
        from mysticeti_tpu.commit_observer import TestCommitObserver
        from mysticeti_tpu.config import Parameters
        from mysticeti_tpu.core import Core, CoreOptions
        from mysticeti_tpu.net_sync import NetworkSyncer
        from mysticeti_tpu.wal import walf

        wal_writer, wal_reader = walf(os.path.join(str(tmp_path), "wal-0"))
        recovered, observer_recovered = BlockStore.open(
            0, wal_reader, wal_writer, committee
        )
        handler = TestBlockHandler(
            last_transaction=0, committee=committee, authority=0
        )
        core = Core(
            block_handler=handler, authority=0, committee=committee,
            parameters=Parameters(), recovered=recovered,
            wal_writer=wal_writer, options=CoreOptions.test(),
            signer=signers[0],
        )
        observer = TestCommitObserver(
            core.block_store, committee, recovered_state=observer_recovered
        )

        class _Net:
            connections: asyncio.Queue = asyncio.Queue()

            async def stop(self):
                pass

        node = NetworkSyncer(core, observer, _Net())
        calls = {"processed": [], "add_blocks": []}
        real_processed = node.dispatcher.processed
        real_add = node.dispatcher.add_blocks

        async def processed(refs):
            calls["processed"].append(len(refs))
            return await real_processed(refs)

        async def add_blocks(blocks, connected):
            calls["add_blocks"].append(len(blocks))
            return await real_add(blocks, connected)

        node.dispatcher.processed = processed
        node.dispatcher.add_blocks = add_blocks
        await node.start()
        conn = Connection(peer=1)
        await _Net.connections.put(conn)
        await asyncio.sleep(0.1)

        genesis = [
            StatementBlock.new_genesis(a, committee.epoch).reference
            for a in range(4)
        ]
        blocks = [
            StatementBlock.build(
                a, 1, genesis, [Share(b"t%d" % a)], signer=signers[a],
                epoch=committee.epoch,
            )
            for a in (1, 2, 3)
        ]
        base_processed = len(calls["processed"])
        base_add = len(calls["add_blocks"])
        await conn.receiver.put(
            Blocks(tuple(b.to_bytes() for b in blocks))
        )
        await asyncio.sleep(1.0)
        assert calls["processed"][base_processed:] == [3]
        assert calls["add_blocks"][base_add:] == [3]
        await node.stop()

    run_simulation(scenario(), seed=42)
