"""Machine-checks of docs/commit-rule.md's epoch-change claims (round-4
verdict: the spec and `epoch_close.py` agreed only by prose).  Each numbered
claim in the doc's "Epoch change" section is driven against the real code;
the doc text itself is parsed so renaming a state or weakening the quorum
phrase without updating the spec fails a test."""
import os
import re

import pytest

from mysticeti_tpu import epoch_close
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.epoch_close import BEGIN_CHANGE, OPEN, SAFE_TO_CLOSE, EpochManager
from mysticeti_tpu.types import Share, StatementBlock

DOC = open(
    os.path.join(os.path.dirname(__file__), "..", "docs", "commit-rule.md")
).read()
EPOCH_SECTION = DOC.split("## Epoch change", 1)[1]


def test_doc_states_match_implementation():
    """'Open -> BeginChange -> SafeToClose' is the implemented machine."""
    assert "Open" in EPOCH_SECTION
    assert "BeginChange" in EPOCH_SECTION
    assert "SafeToClose" in EPOCH_SECTION
    assert (OPEN, BEGIN_CHANGE, SAFE_TO_CLOSE) == (0, 1, 2)
    m = EpochManager()
    assert m.status == OPEN and not m.changing() and not m.closed()
    m.epoch_change_begun()
    assert m.status == BEGIN_CHANGE and m.changing() and not m.closed()


def test_claim_1_trigger_is_committed_leader_round():
    """Claim 1: BeginChange fires when a committed leader's round exceeds
    rounds_in_epoch — the trigger lives on Core's commit path."""
    import inspect

    from mysticeti_tpu.core import Core

    src = inspect.getsource(Core.try_commit)
    assert "rounds_in_epoch" in src and "epoch_change_begun" in src


def test_claim_2_changing_proposals_carry_marker_and_no_payload(tmp_path):
    """Claim 2: during the change, proposals set epoch_marker=1 and stop
    carrying transaction payloads.  Core 0 is the control (no change begun:
    marker 0, payload present); core 1 proposes mid-change."""
    from tests.helpers import committee_and_cores

    committee, cores = committee_and_cores(4, str(tmp_path))
    genesis = [StatementBlock.new_genesis(a) for a in range(4)]
    control = cores[0]
    control.add_blocks([b for a, b in enumerate(genesis) if a != 0])
    block = control.try_new_block()
    assert block is not None and block.epoch_marker == 0
    assert any(isinstance(s, Share) for s in block.statements)

    changing = cores[1]
    changing.add_blocks([b for a, b in enumerate(genesis) if a != 1])
    changing.epoch_manager.epoch_change_begun()
    block = changing.try_new_block()
    assert block is not None
    assert block.epoch_marker == 1
    assert not any(isinstance(s, Share) for s in block.statements)
    for c in cores:
        c.wal_writer.close()


def test_claim_3_safe_to_close_needs_quorum_of_distinct_marker_authors():
    """Claim 3: SafeToClose exactly when COMMITTED marker blocks reach 2f+1
    distinct-authority stake; repeats from one author never count twice."""
    committee = Committee.new_test([1, 1, 1, 1])  # quorum = 3
    signers = Committee.benchmark_signers(4)

    def marker_block(author, round_):
        return StatementBlock.build(
            author, round_, [], (), epoch_marker=1, signer=signers[author]
        )

    m = EpochManager()
    m.epoch_change_begun()
    m.observe_committed_block(marker_block(0, 1), committee)
    m.observe_committed_block(marker_block(0, 2), committee)  # same author
    m.observe_committed_block(marker_block(1, 1), committee)
    assert not m.closed()  # stake 2 < 3 despite three blocks
    # A block WITHOUT the marker contributes nothing.
    plain = StatementBlock.build(2, 1, [], (), signer=signers[2])
    m.observe_committed_block(plain, committee)
    assert not m.closed()
    m.observe_committed_block(marker_block(2, 2), committee)
    assert m.closed() and m.closing_time() > 0


def test_claim_4_grace_period_wiring():
    """Claim 4: after close the node serves for shutdown_grace_period_s
    before shutting down (net_sync's epoch watch)."""
    import inspect

    from mysticeti_tpu.config import Parameters
    from mysticeti_tpu.net_sync import NetworkSyncer

    assert hasattr(Parameters(), "shutdown_grace_period_s")
    src = inspect.getsource(NetworkSyncer._epoch_watch_task)
    assert "shutdown_grace_period_s" in src and "epoch_closed" in src
    assert "stop" in src  # grace elapses, THEN the node shuts down


def test_doc_quorum_phrase_matches_code_threshold():
    """The doc promises a 2f+1 quorum; the implementation aggregates with
    the committee QUORUM threshold."""
    assert re.search(r"quorum \(2f\+1\)", EPOCH_SECTION)
    from mysticeti_tpu.committee import QUORUM

    assert EpochManager().change_aggregator.kind is QUORUM
