"""WAL unit tests — parity with the reference's wal round-trip / reopen / torn-tail
coverage (wal.rs:375-522)."""
import os

import pytest

from mysticeti_tpu.wal import (
    HEADER_SIZE,
    POSITION_MAX,
    WalError,
    WalReader,
    walf,
)


def test_write_read_roundtrip(tmp_path):
    w, r = walf(str(tmp_path / "wal"))
    p1 = w.write(1, b"hello")
    p2 = w.write(2, b"")
    p3 = w.writev(3, (b"a" * 10, b"b" * 20))
    assert p1 == 0
    assert p2 == HEADER_SIZE + 5
    assert r.read(p1) == (1, b"hello")
    assert r.read(p2) == (2, b"")
    assert r.read(p3) == (3, b"a" * 10 + b"b" * 20)


def test_iter_until(tmp_path):
    w, r = walf(str(tmp_path / "wal"))
    entries = [(i, bytes([i]) * i) for i in range(1, 10)]
    positions = [w.write(tag, data) for tag, data in entries]
    got = list(r.iter_until(w.position()))
    assert [(pos, tag, data) for pos, (tag, data) in zip(positions, entries)] == got


def test_reopen(tmp_path):
    path = str(tmp_path / "wal")
    w, r = walf(path)
    p1 = w.write(7, b"persisted")
    w.sync()
    w.close()
    r.close()

    w2, r2 = walf(path)
    assert r2.read(p1) == (7, b"persisted")
    p2 = w2.write(8, b"appended")
    assert p2 > p1
    assert [t for _, t, _ in r2.iter_until(w2.position())] == [7, 8]


def test_torn_tail_stops_replay(tmp_path):
    path = str(tmp_path / "wal")
    w, r = walf(path)
    w.write(1, b"good")
    p2 = w.write(2, b"to-be-torn-xxxxxxxxxxxx")
    w.close()
    r.close()
    # Simulate a crash mid-write: truncate into the middle of the second entry.
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 8)

    w2, r2 = walf(path)
    replayed = list(r2.iter_until(w2.position()))
    assert [(t, d) for _, t, d in replayed] == [(1, b"good")]
    # Appending after the torn entry goes to the (truncated) end of file.
    p3 = w2.write(3, b"after")
    assert p3 == size - 8


def test_corrupt_payload_detected(tmp_path):
    path = str(tmp_path / "wal")
    w, r = walf(path)
    p = w.write(1, b"AAAABBBB")
    w.flush()  # corruption-on-disk scenario: the entry must be ON disk
    with open(path, "r+b") as f:
        f.seek(p + HEADER_SIZE + 2)
        f.write(b"X")
    with pytest.raises(WalError):
        r.read(p)


def test_syncer_thread_handle(tmp_path):
    import threading

    w, _r = walf(str(tmp_path / "wal"))
    w.write(1, b"x")
    syncer = w.syncer()
    t = threading.Thread(target=syncer.sync)
    t.start()
    t.join()
    syncer.close()


def test_reader_sees_growth(tmp_path):
    """The mmap must be refreshed as the writer appends (window remap path)."""
    w, r = walf(str(tmp_path / "wal"))
    p1 = w.write(1, b"first")
    assert r.read(p1) == (1, b"first")
    p2 = w.write(2, b"second" * 1000)
    assert r.read(p2) == (2, b"second" * 1000)
    r.cleanup()
    assert r.read(p1) == (1, b"first")


def test_async_and_sync_writers_produce_identical_files(tmp_path):
    """The async writer thread is an IO offload, not a format change: the
    same appends must produce byte-identical logs."""
    import os

    from mysticeti_tpu.wal import WalWriter

    entries = [(i % 5, bytes([i]) * (1000 * i + 1)) for i in range(1, 20)]
    paths = []
    for mode, async_writes in (("sync", False), ("async", True)):
        path = str(tmp_path / f"wal-{mode}")
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        w = WalWriter(fd, 0, path, async_writes=async_writes)
        positions = [w.writev(tag, (payload,)) for tag, payload in entries]
        w.close()
        paths.append((path, positions))
    (p1, pos1), (p2, pos2) = paths
    assert pos1 == pos2
    assert open(p1, "rb").read() == open(p2, "rb").read()


def test_reader_serves_inflight_entries_before_they_hit_disk(tmp_path):
    """Read-after-write must hold while an append is still queued in the
    writer thread (block_store reads recently appended entries)."""
    import os

    from mysticeti_tpu.wal import walf

    writer, reader = walf(str(tmp_path / "wal"))
    # Stop the drain thread: every append now stays in-flight forever,
    # deterministically exercising the read-through.
    writer._queue.put(None)
    writer._thread.join(timeout=5)
    payload = b"queued-but-not-on-disk" * 100
    pos = writer.writev(7, (payload,))
    assert os.fstat(writer._fd).st_size == 0  # nothing landed
    tag, got = reader.read(pos)
    assert (tag, bytes(got)) == (7, payload)


def test_replay_sees_queued_appends(tmp_path):
    from mysticeti_tpu.wal import walf

    writer, reader = walf(str(tmp_path / "wal"))
    positions = [writer.writev(3, (bytes([i]) * 100,)) for i in range(10)]
    # No explicit flush: iter_until must drain the paired writer first.
    seen = [(pos, tag, bytes(payload))
            for pos, tag, payload in reader.iter_until()]
    assert [s[0] for s in seen] == positions
    assert all(tag == 3 for _, tag, _ in seen)
    writer.close()


def test_own_proposal_drains_async_append_queue(tmp_path):
    """ADVICE r5 durability window: a proposal must not leave the WAL append
    parked in process memory when it becomes externally visible — the core
    drains the writer queue (flush, no fsync) before handing the block to
    dissemination, so a plain process crash cannot un-propose a broadcast
    block (restart-equivocation)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from helpers import committee_and_cores

    _committee, cores = committee_and_cores(4, str(tmp_path))
    core = cores[0]
    assert core.wal_writer._async  # the deployed default: async appends
    core.run_block_handler([])
    block = core.try_new_block()
    assert block is not None
    # Every acknowledged append — the proposal included — has left the
    # in-flight queue by the time try_new_block returns.
    assert core.wal_writer._inflight == {}
    # And a reader replaying the log NOW (the crash-recovery view, no
    # flush assist) sees the proposal entry on disk.
    from mysticeti_tpu.wal import WalReader

    reader = WalReader(os.path.join(str(tmp_path), "wal-0"))
    try:
        payloads = [payload for _, _, payload in reader.iter_until()]
        assert any(block.to_bytes() in p for p in payloads)
    finally:
        reader.close()
