"""SHA-512 kernel parity with hashlib over the 96-byte (R||A||M) block shape."""
import pytest

pytestmark = pytest.mark.kernel

import hashlib
import random

import numpy as np

import jax
import jax.numpy as jnp

from mysticeti_tpu.ops import sha512 as S


def test_sha512_96_matches_hashlib():
    rng = random.Random(11)
    messages = [bytes(rng.randrange(256) for _ in range(96)) for _ in range(32)]
    messages += [b"\x00" * 96, b"\xff" * 96]
    packed = jnp.asarray(S.pack_messages(messages))
    digests = S.digest_bytes(np.asarray(jax.jit(S.sha512_96)(packed)))
    for msg, got in zip(messages, digests):
        assert got == hashlib.sha512(msg).digest(), msg.hex()


def test_sha512_96_is_the_ed25519_challenge_shape():
    """The exact production shape: R || A || blake2b-256 block digest."""
    from mysticeti_tpu import crypto
    from mysticeti_tpu.types import StatementBlock

    signer = crypto.Signer.from_seed(b"sha512-kernel-test-seed-00000000")
    block = StatementBlock.build(0, 1, [], (), signer=signer)
    r_bytes = block.signature[:32]
    a_bytes = signer.public_key.bytes
    m = block.signed_digest()
    msg = r_bytes + a_bytes + m
    packed = jnp.asarray(S.pack_messages([msg]))
    [digest] = S.digest_bytes(np.asarray(jax.jit(S.sha512_96)(packed)))
    assert digest == hashlib.sha512(msg).digest()
