"""Pipelined commit-rule gold suite — ``consensus/tests/pipelined_committer_tests.rs``.
With pipelining every round is some committer's leader round."""
import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder

from helpers import DagBlockWriter, build_dag, build_dag_layer

WAVE = DEFAULT_WAVE_LENGTH


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def make_committer(committee, writer):
    return (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(WAVE)
        .with_pipeline(True)
        .build()
    )


def test_direct_commit(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, WAVE)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == committee.elect_leader(1, 0)


def test_idempotence(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 5)
    committer = make_committer(committee, writer)
    committed = committer.try_commit(AuthorityRound(0, 0))
    assert committed
    last = committed[-1]
    sequence = committer.try_commit(AuthorityRound(last.authority, last.round))
    assert sequence == []


def test_multiple_direct_commit(committee, tmp_path):
    last_committed = AuthorityRound(0, 0)
    for n in range(1, 11):
        enough_blocks = n + (WAVE - 1)
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{n}")
        build_dag(committee, writer, None, enough_blocks)
        committer = make_committer(committee, writer)
        sequence = committer.try_commit(last_committed)
        assert len(sequence) == 1
        assert sequence[0].kind == LeaderStatus.COMMIT
        assert sequence[0].block.author() == committee.elect_leader(n, 0)
        last = sequence[-1]
        last_committed = AuthorityRound(last.authority, last.round)


def test_direct_commit_late_call(committee, tmp_path):
    n = 10
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, n + WAVE - 1)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == n
    for i, status in enumerate(sequence):
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(i + 1, 0)


def test_no_genesis_commit(committee, tmp_path):
    for r in range(WAVE):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{r}")
        build_dag(committee, writer, None, r)
        committer = make_committer(committee, writer)
        assert committer.try_commit(AuthorityRound(0, 0)) == []


def test_no_leader(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    # Round 1's leader missing: build round 1 without it, then to its decision round.
    references = build_dag(committee, writer, None, 0)
    leader_round_1 = 1
    leader_1 = committee.elect_leader(leader_round_1, 0)
    connections = [
        (a, references) for a in committee.authority_indexes() if a != leader_1
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, leader_round_1 + WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
    assert sequence[0].round == leader_round_1


def test_direct_skip(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = 1
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]
    build_dag(committee, writer, references_without_leader_1, leader_round_1 + WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
    assert sequence[0].round == leader_round_1


def test_indirect_commit(committee, tmp_path):
    """Leader 1 gets 2f+1 votes but only f+1 certificates: direct rule cannot
    decide, a later anchor commits it indirectly
    (pipelined_committer_tests.rs:285)."""
    quorum = committee.quorum_threshold()
    validity = committee.validity_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_1 = 1
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]

    voters = list(committee.authority_indexes())[:quorum]
    non_voters = list(committee.authority_indexes())[quorum:]
    references_with_votes = build_dag_layer(
        [(a, references_1) for a in voters], writer
    )
    references_without_votes = build_dag_layer(
        [(a, references_without_leader_1) for a in non_voters], writer
    )

    references_3 = []
    certifiers = list(committee.authority_indexes())[:validity]
    rest = list(committee.authority_indexes())[validity:]
    references_3 += build_dag_layer(
        [(a, references_with_votes) for a in certifiers], writer
    )
    mixed = (references_without_votes + references_with_votes)[:quorum]
    references_3 += build_dag_layer([(a, mixed) for a in rest], writer)

    decision_round = 2 * WAVE + 1
    build_dag(committee, writer, references_3, decision_round)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 5
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == leader_1


def test_indirect_skip(committee, tmp_path):
    """Only f+1 validators link to the 4th leader: its own committer cannot
    decide, and the anchor finds no certificate — skip it indirectly, commit
    the leaders before and after (pipelined_committer_tests.rs:385)."""
    validity = committee.validity_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_4 = WAVE + 1
    references_4 = build_dag(committee, writer, None, leader_round_4)
    leader_4 = committee.elect_leader(leader_round_4, 0)
    references_without_leader_4 = [
        r for r in references_4 if r.authority != leader_4
    ]

    linkers = list(committee.authority_indexes())[:validity]
    others = list(committee.authority_indexes())[validity:]
    references_5 = build_dag_layer(
        [(a, references_4) for a in linkers], writer
    ) + build_dag_layer(
        [(a, references_without_leader_4) for a in others], writer
    )

    decision_round_7 = 3 * WAVE
    build_dag(committee, writer, references_5, decision_round_7)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 7

    for i in range(3):
        status = sequence[i]
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(i + 1, 0)
    assert sequence[3].kind == LeaderStatus.SKIP
    assert sequence[3].authority == leader_4
    assert sequence[3].round == leader_round_4
    for i in range(4, 7):
        status = sequence[i]
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(i + 1, 0)


def test_undecided(committee, tmp_path):
    """One vote for the first leader: neither committed nor skipped
    (pipelined_committer_tests.rs:482)."""
    quorum = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_1 = 1
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader = [
        r for r in references_1 if r.authority != leader_1
    ]

    indexes = list(committee.authority_indexes())
    connections = [(indexes[0], references_1)] + [
        (a, references_without_leader) for a in indexes[1:quorum]
    ]
    references = build_dag_layer(connections, writer)

    build_dag(committee, writer, references, WAVE)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert sequence == []
