"""Pipelined commit-rule gold suite — ``consensus/tests/pipelined_committer_tests.rs``.
With pipelining every round is some committer's leader round."""
import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder

from helpers import DagBlockWriter, build_dag, build_dag_layer

WAVE = DEFAULT_WAVE_LENGTH


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def make_committer(committee, writer):
    return (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(WAVE)
        .with_pipeline(True)
        .build()
    )


def test_direct_commit(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, WAVE)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == committee.elect_leader(1, 0)


def test_idempotence(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 5)
    committer = make_committer(committee, writer)
    committed = committer.try_commit(AuthorityRound(0, 0))
    assert committed
    last = committed[-1]
    sequence = committer.try_commit(AuthorityRound(last.authority, last.round))
    assert sequence == []


def test_multiple_direct_commit(committee, tmp_path):
    last_committed = AuthorityRound(0, 0)
    for n in range(1, 11):
        enough_blocks = n + (WAVE - 1)
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{n}")
        build_dag(committee, writer, None, enough_blocks)
        committer = make_committer(committee, writer)
        sequence = committer.try_commit(last_committed)
        assert len(sequence) == 1
        assert sequence[0].kind == LeaderStatus.COMMIT
        assert sequence[0].block.author() == committee.elect_leader(n, 0)
        last = sequence[-1]
        last_committed = AuthorityRound(last.authority, last.round)


def test_direct_commit_late_call(committee, tmp_path):
    n = 10
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, n + WAVE - 1)
    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == n
    for i, status in enumerate(sequence):
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(i + 1, 0)


def test_no_genesis_commit(committee, tmp_path):
    for r in range(WAVE):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{r}")
        build_dag(committee, writer, None, r)
        committer = make_committer(committee, writer)
        assert committer.try_commit(AuthorityRound(0, 0)) == []


def test_no_leader(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    # Round 1's leader missing: build round 1 without it, then to its decision round.
    references = build_dag(committee, writer, None, 0)
    leader_round_1 = 1
    leader_1 = committee.elect_leader(leader_round_1, 0)
    connections = [
        (a, references) for a in committee.authority_indexes() if a != leader_1
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, leader_round_1 + WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
    assert sequence[0].round == leader_round_1


def test_direct_skip(committee, tmp_path):
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = 1
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]
    build_dag(committee, writer, references_without_leader_1, leader_round_1 + WAVE - 1)

    committer = make_committer(committee, writer)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 1
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
    assert sequence[0].round == leader_round_1
