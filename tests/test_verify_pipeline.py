"""Verification pipelines, two layers:

* the per-connection receive pipeline (net_sync.py): a slow verifier must
  not serialize the receive path, and duplicate blocks inside the pipeline
  window must not be re-verified;
* the staged DISPATCH pipeline (verify_pipeline.py + the batching
  collector): pack/device/fetch overlap with a bounded in-flight window, so
  N fixed-latency dispatch windows finish in measurably less wall time than
  N x the fixed latency — with per-future results intact under interleaved
  flushes and breaker-mediated degradation on mid-pipeline backend failure.
"""
import asyncio
import threading
import time

import pytest

from mysticeti_tpu.block_validator import BlockVerifier
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.types import Share, StatementBlock


class SlowCountingVerifier(BlockVerifier):
    """Counts concurrent verify_blocks calls; each takes ``delay_s``."""

    def __init__(self, delay_s: float = 0.05) -> None:
        self.delay_s = delay_s
        self.calls = 0
        self.in_flight = 0
        self.max_in_flight = 0
        self.seen_refs = []

    async def verify_blocks(self, blocks):
        self.calls += 1
        self.seen_refs.extend(b.reference for b in blocks)
        self.in_flight += 1
        self.max_in_flight = max(self.max_in_flight, self.in_flight)
        await asyncio.sleep(self.delay_s)
        self.in_flight -= 1
        return [True] * len(blocks)


class FakeConnection:
    """Minimal Connection surface for _connection_task: scripted recv()."""

    def __init__(self, peer, messages):
        self.peer = peer
        self._messages = list(messages)
        self.sent = []

    async def recv(self):
        if not self._messages:
            await asyncio.sleep(0.3)  # then let the task be torn down
            return None
        msg = self._messages.pop(0)
        await asyncio.sleep(0)  # yield so pipeline stages interleave
        return msg

    async def send(self, msg):
        self.sent.append(msg)

    def try_send(self, msg):
        self.sent.append(msg)
        return True

    def close(self):
        pass


@pytest.fixture
def syncer_env(tmp_path):
    """A NetworkSyncer with a scripted connection, no real network."""
    import os

    from mysticeti_tpu.block_handler import TestBlockHandler
    from mysticeti_tpu.block_store import BlockStore
    from mysticeti_tpu.commit_observer import TestCommitObserver
    from mysticeti_tpu.core import Core, CoreOptions
    from mysticeti_tpu.net_sync import NetworkSyncer
    from mysticeti_tpu.wal import walf

    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    wal_writer, wal_reader = walf(os.path.join(str(tmp_path), "wal-0"))
    recovered, observer_recovered = BlockStore.open(
        0, wal_reader, wal_writer, committee
    )
    core = Core(
        block_handler=TestBlockHandler(0, committee, 0),
        authority=0,
        committee=committee,
        parameters=Parameters(leader_timeout_s=10.0),
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signers[0],
    )
    observer = TestCommitObserver(core.block_store, committee)

    class _NoNet:
        def __init__(self):
            self.connections = asyncio.Queue()  # accept loop idles on this

        async def stop(self):
            pass

    def make(verifier):
        return NetworkSyncer(
            core, observer, _NoNet(),
            parameters=Parameters(leader_timeout_s=10.0),
            block_verifier=verifier,
        )

    return committee, signers, make


def _peer_blocks(signers, rounds):
    """Valid-DAG layers: 3 authors per round (a quorum of 4), fully
    connected, so every block passes the threshold-clock structure check."""
    genesis = [StatementBlock.new_genesis(i) for i in range(4)]
    prev = [g.reference for g in genesis]
    out = []
    for r in range(1, rounds + 1):
        layer = [
            StatementBlock.build(
                a, r, prev, [Share(bytes([r, a]))], signer=signers[a]
            )
            for a in range(1, 4)
        ]
        out.extend(layer)
        prev = [b.reference for b in layer]
    return out


def test_pipeline_overlaps_slow_verification(syncer_env):
    """With a 50 ms verifier, N single-block messages must overlap their
    verification (serialized would take N*50 ms and max_in_flight == 1)."""
    from mysticeti_tpu.network import Blocks

    from mysticeti_tpu.runtime.simulated import run_simulation

    committee, signers, make = syncer_env
    verifier = SlowCountingVerifier(0.05)

    blocks = _peer_blocks(signers, 3)  # 9 blocks
    msgs = [Blocks((b.to_bytes(),)) for b in blocks]

    async def main():
        ns = make(verifier)
        await ns.start()
        conn = FakeConnection(1, msgs)
        task = asyncio.ensure_future(ns._connection_task(conn))
        # Virtual time: 9 x 50 ms serialized would need 450 ms.
        await asyncio.sleep(0.2)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await ns.stop()

    run_simulation(main(), seed=1)
    assert verifier.calls == 9
    assert verifier.max_in_flight >= 4, verifier.max_in_flight


def test_pipeline_dedups_in_flight_duplicates(syncer_env):
    """The same block retransmitted while its first copy is still being
    verified must not be verified twice."""
    from mysticeti_tpu.network import Blocks

    from mysticeti_tpu.runtime.simulated import run_simulation

    committee, signers, make = syncer_env
    verifier = SlowCountingVerifier(0.05)

    blk = _peer_blocks(signers, 1)[0]
    msgs = [Blocks((blk.to_bytes(),)) for _ in range(5)]

    async def main():
        ns = make(verifier)
        await ns.start()
        conn = FakeConnection(1, msgs)
        task = asyncio.ensure_future(ns._connection_task(conn))
        await asyncio.sleep(0.25)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await ns.stop()

    run_simulation(main(), seed=1)
    assert verifier.seen_refs.count(blk.reference) == 1, verifier.seen_refs


# ---------------------------------------------------------------------------
# The staged dispatch pipeline (verify_pipeline.py + BatchedSignatureVerifier)


class FixedLatencyVerifier:
    """Stub SignatureVerifier: every dispatch takes exactly ``delay_s`` of
    real (executor-thread) time; per-item verdicts come from a reject set so
    interleaved flushes are checkable.  Samples the inflight gauge from
    INSIDE the dispatch — the honest way to observe concurrency."""

    def __init__(self, delay_s, metrics=None, reject_digests=()):
        from mysticeti_tpu.block_validator import SignatureVerifier

        self.delay_s = delay_s
        self.metrics = metrics
        self.reject = set(reject_digests)
        self.calls = 0
        self.gauge_seen = []
        self._lock = threading.Lock()

    def padded_batch(self, n):
        return n

    def warmup(self):
        pass

    def verify_signatures_async(self, public_keys, digests, signatures):
        from mysticeti_tpu.verify_pipeline import DeferredDispatch

        return DeferredDispatch(
            self.verify_signatures, public_keys, digests, signatures
        )

    def verify_signatures(self, public_keys, digests, signatures):
        with self._lock:
            self.calls += 1
            if self.metrics is not None:
                self.gauge_seen.append(
                    self.metrics.verify_pipeline_inflight._value.get()
                )
        time.sleep(self.delay_s)
        return [d not in self.reject for d in digests]


def _signed_blocks(n):
    """n distinct valid blocks over a 4-authority benchmark committee."""
    from mysticeti_tpu.committee import Committee
    from mysticeti_tpu.types import Share

    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    blocks = [
        StatementBlock.build(
            a % 4, 1 + a // 4, [], [Share(bytes([a]))], signer=signers[a % 4]
        )
        for a in range(n)
    ]
    return committee, blocks


def _run_windows(committee, blocks, verifier, depth, metrics):
    """Drive N windows (max_batch=2) through the collector; returns
    (wall_seconds, results list aligned with blocks)."""
    from mysticeti_tpu.block_validator import BatchedSignatureVerifier

    collector = BatchedSignatureVerifier(
        committee, verifier, max_batch=2, max_delay_s=5.0,
        metrics=metrics, pipeline_depth=depth,
    )

    async def main():
        started = time.monotonic()
        results = await asyncio.gather(
            *(collector.verify(b) for b in blocks), return_exceptions=True
        )
        return time.monotonic() - started, results, collector

    return asyncio.run(main())


def test_staged_pipeline_overlaps_fixed_latency_dispatches():
    """Acceptance: with depth >= 2, N windows of fixed-latency work finish
    in measurably less wall time than N x the latency; the serial (depth-1)
    baseline is asserted in the same test, and verify_pipeline_inflight
    reaches >= 2 while dispatches are actually running."""
    from mysticeti_tpu.metrics import Metrics

    delay, windows = 0.08, 4
    committee, blocks = _signed_blocks(2 * windows)

    serial_metrics = Metrics()
    serial_wall, serial_results, serial_collector = _run_windows(
        committee, blocks, FixedLatencyVerifier(delay, serial_metrics),
        depth=1, metrics=serial_metrics,
    )
    assert all(r is None for r in serial_results)
    # Serial baseline: one dispatch at a time, N x delay end to end.
    assert serial_wall >= windows * delay * 0.95, serial_wall
    assert serial_collector.pipeline.max_inflight == 1

    metrics = Metrics()
    verifier = FixedLatencyVerifier(delay, metrics)
    wall, results, collector = _run_windows(
        committee, blocks, verifier, depth=4, metrics=metrics,
    )
    assert all(r is None for r in results)
    assert verifier.calls == windows
    # Overlap: strictly and measurably faster than the serial baseline.
    assert wall < serial_wall * 0.75, (wall, serial_wall)
    assert wall < windows * delay * 0.8, (wall, windows * delay)
    # The bounded window actually held >= 2 dispatches in flight, visible
    # both at the engine and on the scraped gauge DURING a dispatch.
    assert collector.pipeline.max_inflight >= 2
    assert max(verifier.gauge_seen) >= 2, verifier.gauge_seen
    # ...and the gauge returns to zero once the work drains.
    assert metrics.verify_pipeline_inflight._value.get() == 0
    scrape = metrics.expose().decode()
    assert "verify_pipeline_stage_seconds" in scrape


def test_pipeline_resolves_correct_futures_under_interleaved_flushes():
    """Verdicts must land on the RIGHT per-block futures even when several
    flush windows are in flight at once and complete out of order."""
    from mysticeti_tpu.metrics import Metrics
    from mysticeti_tpu.types import VerificationError

    committee, blocks = _signed_blocks(12)
    bad = {b.signed_digest() for b in blocks[::3]}  # every third block
    metrics = Metrics()
    verifier = FixedLatencyVerifier(0.03, metrics, reject_digests=bad)
    _, results, collector = _run_windows(
        committee, blocks, verifier, depth=4, metrics=metrics,
    )
    assert collector.pipeline.max_inflight >= 2
    for block, result in zip(blocks, results):
        if block.signed_digest() in bad:
            assert isinstance(result, VerificationError), block.reference
        else:
            assert result is None, (block.reference, result)


def test_mid_pipeline_backend_failure_degrades_with_zero_lost_futures():
    """A backend dying while dispatches are in flight trips the existing
    circuit breaker at FETCH time; every affected batch re-verifies on the
    oracle — zero futures lost, zero spurious rejections."""
    from mysticeti_tpu.block_validator import HybridSignatureVerifier
    from mysticeti_tpu.metrics import Metrics

    class DyingBackend(FixedLatencyVerifier):
        def __init__(self, delay_s, die_after):
            super().__init__(delay_s)
            self.die_after = die_after

        def verify_signatures(self, public_keys, digests, signatures):
            with self._lock:
                self.calls += 1
                call = self.calls
            time.sleep(self.delay_s)
            if call > self.die_after:
                raise ConnectionError("accelerator tunnel dropped")
            return [True] * len(signatures)

    committee, blocks = _signed_blocks(12)
    metrics = Metrics()
    # die_after=0: EVERY dispatch fails at fetch while others are in
    # flight.  (die_after=1 made the end-state racy: the one successful
    # dispatch could complete LAST and close the breaker the failures had
    # just tripped.)
    tpu = DyingBackend(0.03, die_after=0)
    cpu = FixedLatencyVerifier(0.0)
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=cpu, threshold=1,
                                     metrics=metrics)
    _, results, collector = _run_windows(
        committee, blocks, hybrid, depth=4, metrics=metrics,
    )
    # Zero lost futures, zero spurious rejections.
    assert all(r is None for r in results), results
    assert hybrid.breaker_open
    assert metrics.verifier_fallback_total._value.get() >= 1.0
    assert cpu.calls >= 1  # degraded batches re-verified on the oracle


def test_verify_pipeline_depth_adapts_to_fixed_cost():
    from mysticeti_tpu.verify_pipeline import VerifyPipeline

    cost = {"s": 0.0}
    p = VerifyPipeline(fixed_cost_fn=lambda: cost["s"])
    assert p.depth() == VerifyPipeline.MIN_DEPTH  # co-located: nothing to hide
    cost["s"] = 0.01
    assert p.depth() == 3
    cost["s"] = 0.120  # tunneled chip
    assert p.depth() == VerifyPipeline.MAX_DEPTH
    assert VerifyPipeline(depth=7).depth() == 7  # pinned overrides


def test_cancelled_flush_mid_submit_abandons_the_handle():
    """A flush task cancelled while suspended on the submit executor hop
    must still release the dispatch handle's backend state (pooled service
    connection, the breaker's exclusive probe flag) — the executor job
    outlives the cancellation and its handle would otherwise leak."""
    from mysticeti_tpu.block_validator import BatchedSignatureVerifier

    events = {"abandoned": 0, "fetched": 0}
    submit_started = threading.Event()
    release_submit = threading.Event()

    class Handle:
        def result(self):
            events["fetched"] += 1
            return [True]

        def abandon(self):
            events["abandoned"] += 1

    class SlowSubmitVerifier:
        def verify_signatures_async(self, pks, digests, sigs):
            submit_started.set()
            release_submit.wait(5)
            return Handle()

        def verify_signatures(self, pks, digests, sigs):
            return [True] * len(sigs)

    committee, blocks = _signed_blocks(1)
    collector = BatchedSignatureVerifier(
        committee, SlowSubmitVerifier(), max_batch=10, max_delay_s=5.0
    )

    async def main():
        loop = asyncio.get_running_loop()
        future = loop.create_future()
        task = asyncio.ensure_future(
            collector._flush([(blocks[0], future)])
        )
        await asyncio.to_thread(submit_started.wait, 5)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        release_submit.set()  # the executor job now lands its handle
        for _ in range(100):
            if events["abandoned"]:
                break
            await asyncio.sleep(0.01)

    asyncio.run(main())
    assert events["abandoned"] == 1
    assert events["fetched"] == 0
