"""Per-connection verification pipeline (net_sync.py): a slow verifier must
not serialize the receive path, and duplicate blocks inside the pipeline
window must not be re-verified."""
import asyncio

import pytest

from mysticeti_tpu.block_validator import BlockVerifier
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.types import Share, StatementBlock


class SlowCountingVerifier(BlockVerifier):
    """Counts concurrent verify_blocks calls; each takes ``delay_s``."""

    def __init__(self, delay_s: float = 0.05) -> None:
        self.delay_s = delay_s
        self.calls = 0
        self.in_flight = 0
        self.max_in_flight = 0
        self.seen_refs = []

    async def verify_blocks(self, blocks):
        self.calls += 1
        self.seen_refs.extend(b.reference for b in blocks)
        self.in_flight += 1
        self.max_in_flight = max(self.max_in_flight, self.in_flight)
        await asyncio.sleep(self.delay_s)
        self.in_flight -= 1
        return [True] * len(blocks)


class FakeConnection:
    """Minimal Connection surface for _connection_task: scripted recv()."""

    def __init__(self, peer, messages):
        self.peer = peer
        self._messages = list(messages)
        self.sent = []

    async def recv(self):
        if not self._messages:
            await asyncio.sleep(0.3)  # then let the task be torn down
            return None
        msg = self._messages.pop(0)
        await asyncio.sleep(0)  # yield so pipeline stages interleave
        return msg

    async def send(self, msg):
        self.sent.append(msg)

    def try_send(self, msg):
        self.sent.append(msg)
        return True

    def close(self):
        pass


@pytest.fixture
def syncer_env(tmp_path):
    """A NetworkSyncer with a scripted connection, no real network."""
    import os

    from mysticeti_tpu.block_handler import TestBlockHandler
    from mysticeti_tpu.block_store import BlockStore
    from mysticeti_tpu.commit_observer import TestCommitObserver
    from mysticeti_tpu.core import Core, CoreOptions
    from mysticeti_tpu.net_sync import NetworkSyncer
    from mysticeti_tpu.wal import walf

    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    wal_writer, wal_reader = walf(os.path.join(str(tmp_path), "wal-0"))
    recovered, observer_recovered = BlockStore.open(
        0, wal_reader, wal_writer, committee
    )
    core = Core(
        block_handler=TestBlockHandler(0, committee, 0),
        authority=0,
        committee=committee,
        parameters=Parameters(leader_timeout_s=10.0),
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signers[0],
    )
    observer = TestCommitObserver(core.block_store, committee)

    class _NoNet:
        def __init__(self):
            self.connections = asyncio.Queue()  # accept loop idles on this

        async def stop(self):
            pass

    def make(verifier):
        return NetworkSyncer(
            core, observer, _NoNet(),
            parameters=Parameters(leader_timeout_s=10.0),
            block_verifier=verifier,
        )

    return committee, signers, make


def _peer_blocks(signers, rounds):
    """Valid-DAG layers: 3 authors per round (a quorum of 4), fully
    connected, so every block passes the threshold-clock structure check."""
    genesis = [StatementBlock.new_genesis(i) for i in range(4)]
    prev = [g.reference for g in genesis]
    out = []
    for r in range(1, rounds + 1):
        layer = [
            StatementBlock.build(
                a, r, prev, [Share(bytes([r, a]))], signer=signers[a]
            )
            for a in range(1, 4)
        ]
        out.extend(layer)
        prev = [b.reference for b in layer]
    return out


def test_pipeline_overlaps_slow_verification(syncer_env):
    """With a 50 ms verifier, N single-block messages must overlap their
    verification (serialized would take N*50 ms and max_in_flight == 1)."""
    from mysticeti_tpu.network import Blocks

    from mysticeti_tpu.runtime.simulated import run_simulation

    committee, signers, make = syncer_env
    verifier = SlowCountingVerifier(0.05)

    blocks = _peer_blocks(signers, 3)  # 9 blocks
    msgs = [Blocks((b.to_bytes(),)) for b in blocks]

    async def main():
        ns = make(verifier)
        await ns.start()
        conn = FakeConnection(1, msgs)
        task = asyncio.ensure_future(ns._connection_task(conn))
        # Virtual time: 9 x 50 ms serialized would need 450 ms.
        await asyncio.sleep(0.2)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await ns.stop()

    run_simulation(main(), seed=1)
    assert verifier.calls == 9
    assert verifier.max_in_flight >= 4, verifier.max_in_flight


def test_pipeline_dedups_in_flight_duplicates(syncer_env):
    """The same block retransmitted while its first copy is still being
    verified must not be verified twice."""
    from mysticeti_tpu.network import Blocks

    from mysticeti_tpu.runtime.simulated import run_simulation

    committee, signers, make = syncer_env
    verifier = SlowCountingVerifier(0.05)

    blk = _peer_blocks(signers, 1)[0]
    msgs = [Blocks((blk.to_bytes(),)) for _ in range(5)]

    async def main():
        ns = make(verifier)
        await ns.start()
        conn = FakeConnection(1, msgs)
        task = asyncio.ensure_future(ns._connection_task(conn))
        await asyncio.sleep(0.25)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await ns.stop()

    run_simulation(main(), seed=1)
    assert verifier.seen_refs.count(blk.reference) == 1, verifier.seen_refs
