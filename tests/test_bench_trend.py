"""Perf-trend plane (tools/bench_trend.py): schema-drift normalization,
the append-only index, the regression gate, and the live bench.py append."""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.bench_trend import (  # noqa: E402
    append_record,
    load_index,
    main,
    merge_index,
    normalize,
    regression_report,
)

pytestmark = pytest.mark.tracing


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


# -- normalization across the r1-r5 schema drift -----------------------------


def test_normalize_bench_wrapper_and_null(tmp_path):
    ok = normalize(_write(tmp_path, "BENCH_r01.json", {
        "n": 1, "rc": 0,
        "parsed": {"metric": "ed25519_verifies_per_sec", "value": 46747.5,
                   "unit": "sig/s", "vs_baseline": 0.09},
    }))
    assert ok == [{
        "round": 1, "source": "BENCH_r01.json",
        "metric": "ed25519_verifies_per_sec", "value": 46747.5,
        "unit": "sig/s", "vs_baseline": 0.09,
    }]
    # parsed=null (a failed round) records the GAP, never silence.
    null = normalize(_write(tmp_path, "BENCH_r05.json",
                            {"n": 5, "rc": 1, "parsed": None}))
    assert null[0]["value"] is None and "parsed=null" in null[0]["note"]


def test_normalize_fleet_shapes(tmp_path):
    # Fleet metrics are namespaced by artifact FAMILY: a TPU-fleet peak
    # must never share a regression trajectory with a CPU search.
    flat = normalize(_write(tmp_path, "MAXLOAD_r02.json", {
        "metric": "max_sustainable_load_tx_s", "verifier": "cpu", "nodes": 4,
        "max_sustainable_load_tx_s": 12800, "peak_committed_tx_s": 9754.5,
    }))
    assert {r["metric"] for r in flat} == {
        "MAXLOAD.max_sustainable_load_tx_s", "MAXLOAD.peak_committed_tx_s"
    }
    nested = normalize(_write(tmp_path, "MAXLOAD_TPU_r03.json", {
        "fleet_runs": {
            "cpu_search": {"verifier": "cpu", "peak_committed_tx_s": 19614.1},
            "hybrid_fixed": {"verifier": "tpu", "committed_tx_s": 10826.7},
        },
    }))
    assert {r["metric"] for r in nested} == {
        "MAXLOAD_TPU.cpu_search.peak_committed_tx_s",
        "MAXLOAD_TPU.hybrid_fixed.committed_tx_s",
    }
    runs = normalize(_write(tmp_path, "TENNODE_r05.json", {
        "runs": [
            {"verifier": "cpu", "committed_tx_s": 1703.0},
            {"verifier": "cpu", "committed_tx_s": 1500.0},
        ],
    }))
    assert runs == [{
        "round": 5, "source": "TENNODE_r05.json",
        "metric": "TENNODE.committed_tx_s",
        "value": 1703.0, "unit": "tx/s", "verifier": "cpu", "nodes": None,
    }]
    tax = normalize(_write(tmp_path, "MAXLOAD_TAX_r06.json", {
        "tpu_over_cpu": 1.17, "cpu_peak_committed_tx_s": 3104.9,
        "tpu_peak_committed_tx_s": 3643.3,
    }))
    assert {r["metric"] for r in tax} == {
        "tpu_over_cpu", "cpu_peak_committed_tx_s", "tpu_peak_committed_tx_s"
    }
    samples = normalize(_write(tmp_path, "BENCH_SAMPLES_r02.json", {
        "samples_utc": [{"time": "10:30", "value": 300.0},
                        {"time": "18:00", "value": 100.0}],
    }))
    assert {(r["metric"], r["value"]) for r in samples} == {
        ("bench_samples_best", 300.0), ("bench_samples_worst", 100.0)
    }
    unknown = normalize(_write(tmp_path, "BENCH_weird_r07.json",
                               {"someday": "maybe"}))
    assert unknown[0]["metric"] == "unparsed"


# -- the regression gate -----------------------------------------------------


def test_regression_gate_exit_codes(tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json",
           {"parsed": {"metric": "m", "value": 100.0, "unit": "u"}})
    _write(tmp_path, "BENCH_r02.json",
           {"parsed": {"metric": "m", "value": 95.0, "unit": "u"}})
    out = str(tmp_path / "BENCH_TREND.json")
    # 5% down: within the 10% tolerance.
    assert main(["--repo", str(tmp_path), "--out", out]) == 0
    report = capsys.readouterr().out
    assert "-5.0% vs best prior" in report
    # A >10% drop in a NEW round fails the gate.
    _write(tmp_path, "BENCH_r03.json",
           {"parsed": {"metric": "m", "value": 80.0, "unit": "u"}})
    assert main(["--repo", str(tmp_path), "--out", out]) == 2
    assert "REGRESSIONS" in capsys.readouterr().out


def test_index_is_append_only_and_idempotent(tmp_path):
    _write(tmp_path, "BENCH_r01.json",
           {"parsed": {"metric": "m", "value": 10.0, "unit": "u"}})
    out = str(tmp_path / "BENCH_TREND.json")
    main(["--repo", str(tmp_path), "--out", out])
    first = load_index(out)
    main(["--repo", str(tmp_path), "--out", out])
    second = load_index(out)
    assert first["records"] == second["records"]  # re-scan adds nothing
    # A live append survives the next artifact scan.
    append_record({"metric": "m", "value": 11.0, "unit": "u"}, path=out)
    main(["--repo", str(tmp_path), "--out", out])
    kinds = [(r["source"], r.get("seq")) for r in load_index(out)["records"]]
    assert ("bench.py(live)", 1) in kinds
    # A second live run is a DISTINCT record (seq bumps), not a dedup hit.
    append_record({"metric": "m", "value": 12.0, "unit": "u"}, path=out)
    assert ("bench.py(live)", 2) in [
        (r["source"], r.get("seq")) for r in load_index(out)["records"]
    ]


def test_live_records_shown_but_never_gate():
    records = [
        {"round": None, "source": "bench.py(live)", "metric": "m",
         "value": 50.0, "unit": "u", "seq": 1},
        {"round": 2, "source": "BENCH_r02.json", "metric": "m",
         "value": 200.0, "unit": "u"},
        {"round": 1, "source": "BENCH_r01.json", "metric": "m",
         "value": 100.0, "unit": "u"},
    ]
    lines, regressions = regression_report(records, 0.10)
    text = "\n".join(lines)
    assert text.index("r01") < text.index("r02") < text.index("live#1")
    # The low live record rides the report but must NOT gate (a laptop run
    # or a documented zero-record would flip CI red otherwise): the newest
    # ROUND (r02) is the best round, so no regression.
    assert regressions == []
    # A genuinely regressed ROUND still gates with live noise present.
    records.append({"round": 3, "source": "BENCH_r03.json", "metric": "m",
                    "value": 60.0, "unit": "u"})
    _lines, regressions = regression_report(records, 0.10)
    assert regressions and "BENCH_r03.json" in regressions[0]


def test_merge_dedups_by_key():
    index = {"records": [{"source": "a", "metric": "m", "value": 1.0}]}
    added = merge_index(index, [
        {"source": "a", "metric": "m", "value": 1.0},
        {"source": "a", "metric": "n", "value": 2.0},
    ])
    assert added == 1 and len(index["records"]) == 2


# -- acceptance: the real repo artifacts parse -------------------------------


def test_repo_artifacts_yield_nonempty_trajectory(capsys):
    """ISSUE 9 acceptance: bench_trend over the existing rounds emits a
    non-empty trajectory with at least 5 rounds parsed."""
    main(["--repo", REPO, "--no-write"])
    out = capsys.readouterr().out
    header = out.splitlines()[0]
    assert "bench trend:" in header
    rounds = int(header.split("record(s),")[1].split("metric(s),")[1]
                 .split("round(s)")[0].strip())
    assert rounds >= 5, header


# -- SCENARIO family (the resilience matrix) ---------------------------------


def _scenario_doc(passed_map, determinism=True):
    return {
        "metric": "scenario_matrix",
        "scenarios": [
            {
                "scenario": {"name": name, "min_ratio": 0.8},
                "passed": ok,
                "throughput_ratio": 0.84 if ok else 0.41,
                "safety_ok": True,
            }
            for name, ok in passed_map.items()
        ],
        "determinism": (
            {"scenario": "byzantine-at-f", "byte_identical": True}
            if determinism
            else {}
        ),
    }


def test_normalize_scenario_matrix(tmp_path):
    records = normalize(_write(tmp_path, "SCENARIO_r12.json", _scenario_doc(
        {"byzantine-at-f": True, "wan-geo-profile": True}
    )))
    by_metric = {r["metric"]: r for r in records}
    row = by_metric["SCENARIO.byzantine-at-f.passed"]
    assert row["value"] == 1.0 and row["unit"] == "pass"
    assert row["ratio"] == 0.84 and row["min_ratio"] == 0.8
    assert by_metric["SCENARIO.determinism_byte_identical"]["value"] == 1.0
    # A failing scenario scores 0.0 — a pass->fail flip between rounds is
    # a 100% drop, exactly what the generic gate fires on.
    failed = normalize(_write(tmp_path, "SCENARIO_r13.json", _scenario_doc(
        {"byzantine-at-f": False}, determinism=False
    )))
    assert failed[0]["metric"] == "SCENARIO.byzantine-at-f.passed"
    assert failed[0]["value"] == 0.0


def test_scenario_pass_fail_flip_gates(tmp_path, capsys):
    _write(tmp_path, "SCENARIO_r12.json",
           _scenario_doc({"byzantine-at-f": True}, determinism=False))
    assert main(["--repo", str(tmp_path)]) == 0
    capsys.readouterr()
    _write(tmp_path, "SCENARIO_r13.json",
           _scenario_doc({"byzantine-at-f": False}, determinism=False))
    assert main(["--repo", str(tmp_path)]) == 2
    out = capsys.readouterr().out
    assert "SCENARIO.byzantine-at-f.passed" in out
    # Ratio noise between PASSING rounds never gates: the scored value is
    # the verdict, the ratio only context.
