"""Tests for the DAG data model — mirrors types.rs:891-1035 coverage plus serde
round-trips (bincode round-trips are implicit in the reference; our format is our own
so it needs explicit tests)."""
import pytest

from mysticeti_tpu import crypto
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.serde import Reader, SerdeError, Writer
from mysticeti_tpu.types import (
    AuthoritySet,
    BlockReference,
    Share,
    StatementBlock,
    TransactionLocator,
    TransactionLocatorRange,
    VerificationError,
    Vote,
    VoteRange,
)
from mysticeti_tpu.utils.dag import Dag


def make_ref(authority=0, round_=1, fill=0xAB):
    return BlockReference(authority, round_, bytes([fill] * 32))


class TestAuthoritySet:
    def test_insert_contains(self):
        s = AuthoritySet()
        for i in (0, 1, 5, 63, 64, 127, 128, 511):
            assert not s.contains(i)
            assert s.insert(i)
            assert s.contains(i)
            assert not s.insert(i)  # duplicate returns False
        assert sorted(s.present()) == [0, 1, 5, 63, 64, 127, 128, 511]
        assert len(s) == 8

    def test_max_size(self):
        s = AuthoritySet()
        with pytest.raises(ValueError):
            s.insert(512)

    def test_clear(self):
        s = AuthoritySet()
        s.insert(3)
        s.clear()
        assert not s.contains(3)
        assert len(s) == 0


class TestSerde:
    def test_roundtrip_primitives(self):
        w = Writer()
        w.u8(7).u32(1 << 30).u64(1 << 60).bytes(b"hello").fixed(b"xy")
        r = Reader(w.finish())
        assert r.u8() == 7
        assert r.u32() == 1 << 30
        assert r.u64() == 1 << 60
        assert r.bytes() == b"hello"
        assert r.fixed(2) == b"xy"
        r.expect_done()

    def test_truncated(self):
        r = Reader(b"\x01")
        with pytest.raises(SerdeError):
            r.u32()


class TestStatementBlock:
    def test_build_and_decode_roundtrip(self):
        signer = crypto.Signer.from_seed(b"\x01" * 32)
        parent = StatementBlock.new_genesis(1)
        block = StatementBlock.build(
            authority=0,
            round_=1,
            includes=[parent.reference],
            statements=[
                Share(b"tx-payload"),
                Vote(TransactionLocator(parent.reference, 0)),
                Vote(TransactionLocator(parent.reference, 1), accept=False),
                VoteRange(TransactionLocatorRange(parent.reference, 2, 9)),
            ],
            meta_creation_time_ns=123456789,
            signer=signer,
        )
        decoded = StatementBlock.from_bytes(block.to_bytes())
        assert decoded.reference == block.reference
        assert decoded.includes == block.includes
        assert decoded.statements == block.statements
        assert decoded.meta_creation_time_ns == 123456789
        assert decoded.signature == block.signature
        assert decoded.to_bytes() == block.to_bytes()

    def test_digest_covers_signature(self):
        """crypto.rs:77-84 layering: same content, different signer → different digest,
        but identical signed_bytes prefix."""
        s1 = crypto.Signer.from_seed(b"\x01" * 32)
        s2 = crypto.Signer.from_seed(b"\x02" * 32)
        b1 = StatementBlock.build(0, 1, (), (), signer=s1)
        b2 = StatementBlock.build(0, 1, (), (), signer=s2)
        assert b1.signed_bytes() == b2.signed_bytes()
        assert b1.digest() != b2.digest()

    def test_genesis_deterministic(self):
        assert (
            StatementBlock.new_genesis(2).reference
            == StatementBlock.new_genesis(2).reference
        )

    def test_verify_good_block(self):
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis], [Share(b"t")], signer=signers[0]
        )
        block.verify(committee)  # should not raise

    def test_verify_bad_signature(self):
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        # authority 0's block signed with authority 1's key
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis], (), signer=signers[1]
        )
        with pytest.raises(VerificationError, match="signature"):
            block.verify(committee)

    def test_verify_include_round_monotonicity(self):
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        high = StatementBlock.build(
            1, 5, [g.reference for g in genesis], (), signer=signers[1]
        )
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis] + [high.reference], (),
            signer=signers[0],
        )
        with pytest.raises(VerificationError, match="round"):
            block.verify(committee)

    def test_verify_threshold_clock(self):
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        # only 2/4 includes at round 0 — below quorum
        block = StatementBlock.build(
            0, 1, [genesis[0].reference, genesis[1].reference], (), signer=signers[0]
        )
        with pytest.raises(VerificationError, match="[Tt]hreshold clock"):
            block.verify(committee)

    def test_verify_wrong_epoch(self):
        committee = Committee.new_for_benchmarks(4, epoch=1)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis], (), epoch=0, signer=signers[0]
        )
        with pytest.raises(VerificationError, match="epoch"):
            block.verify(committee)

    def test_verify_tampered_payload(self):
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis], [Share(b"AAAA")], signer=signers[0]
        )
        raw = bytearray(block.to_bytes())
        idx = bytes(raw).index(b"AAAA")
        raw[idx] = ord(b"B")
        tampered = StatementBlock.from_bytes(bytes(raw))
        # digest recomputes fine (covers tampered bytes) but the signature must fail
        with pytest.raises(VerificationError, match="signature"):
            tampered.verify(committee)


class TestVoteRangeBounds:
    def test_unbounded_range_rejected(self):
        """A Byzantine block must not induce iteration over 2^64 offsets."""
        committee = Committee.new_for_benchmarks(4)
        signers = Committee.benchmark_signers(4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis],
            [VoteRange(TransactionLocatorRange(genesis[0].reference, 0, 2**63))],
            signer=signers[0],
        )
        with pytest.raises(SerdeError, match="too"):
            block.verify(committee)

    def test_reasonable_range_ok(self):
        rng = TransactionLocatorRange(make_ref(), 0, 10000)
        rng.verify()


class TestVoteStrictness:
    def test_accept_with_conflict_rejected(self):
        with pytest.raises(ValueError, match="conflict"):
            Vote(TransactionLocator(make_ref(), 0), accept=True,
                 conflict=TransactionLocator(make_ref(), 1))

    def test_reject_conflict_roundtrip(self):
        block = StatementBlock.build(
            0, 1, (), [Vote(TransactionLocator(make_ref(), 0), accept=False,
                            conflict=TransactionLocator(make_ref(1), 7))],
        )
        decoded = StatementBlock.from_bytes(block.to_bytes())
        assert decoded.statements == block.statements
        assert decoded.statements[0].conflict.offset == 7

    def test_invalid_vote_byte_rejected(self):
        """Non-canonical wire bytes must raise, not silently coerce."""
        block = StatementBlock.build(
            0, 1, (), [Vote(TransactionLocator(make_ref(), 0), accept=True)],
        )
        raw = bytearray(block.to_bytes())
        # vote byte sits right after: u64 auth + u64 round + u32 n_inc + u32 n_st
        # + u8 tag + (u64+u64+32 digest) locator + u64 offset
        vote_byte_idx = 8 + 8 + 4 + 4 + 1 + (8 + 8 + 32) + 8
        assert raw[vote_byte_idx] == 0  # VOTE_ACCEPT
        raw[vote_byte_idx] = 2
        with pytest.raises(SerdeError, match="vote byte"):
            StatementBlock.from_bytes(bytes(raw))


class TestDagDsl:
    def test_draw(self):
        dag = Dag.draw("A1:[A0,B0,C0]; B1:[A0,B0,C0,D0]; A2:[A1,B1]")
        assert len(dag) == 3 + 4  # three drawn + four implicit genesis
        a2 = dag["A2"]
        assert a2.author_round() == (0, 2)
        assert a2.includes == (dag["A1"].reference, dag["B1"].reference)

    def test_draw_undefined_ref(self):
        with pytest.raises(ValueError, match="undefined"):
            Dag.draw("A2:[A1]")
