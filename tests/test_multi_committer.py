"""Multi-leader commit-rule gold suite — ``consensus/tests/multi_committer_tests.rs``."""
import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder

from helpers import DagBlockWriter, build_dag, build_dag_layer

WAVE = DEFAULT_WAVE_LENGTH


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def make_committer(committee, writer, number_of_leaders):
    return (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(WAVE)
        .with_number_of_leaders(number_of_leaders)
        .build()
    )


def test_direct_commit(committee, tmp_path):
    for number_of_leaders in range(1, len(committee)):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{number_of_leaders}")
        build_dag(committee, writer, None, 5)
        committer = make_committer(committee, writer, number_of_leaders)
        sequence = committer.try_commit(AuthorityRound(0, 0))
        assert len(sequence) == number_of_leaders
        for leader_offset, status in enumerate(sequence):
            expected = committee.elect_leader(WAVE, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_idempotence(committee, tmp_path):
    for number_of_leaders in range(1, len(committee)):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{number_of_leaders}")
        build_dag(committee, writer, None, 5)
        committer = make_committer(committee, writer, number_of_leaders)
        committed = committer.try_commit(AuthorityRound(0, 0))
        assert committed
        last = committed[-1]
        sequence = committer.try_commit(AuthorityRound(last.authority, last.round))
        assert sequence == []


def test_multiple_direct_commit(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    last_committed = AuthorityRound(0, 0)
    for n in range(1, 11):
        enough_blocks = WAVE * (n + 1) - 1
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{n}")
        build_dag(committee, writer, None, enough_blocks)
        committer = make_committer(committee, writer, number_of_leaders)
        sequence = committer.try_commit(last_committed)
        assert len(sequence) == number_of_leaders
        leader_round = n * WAVE
        for leader_offset, status in enumerate(sequence):
            expected = committee.elect_leader(leader_round, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected
        last = sequence[-1]
        last_committed = AuthorityRound(last.authority, last.round)


def test_direct_commit_partial_round(committee, tmp_path):
    """Resuming from mid-round commits only the remaining leaders of that round."""
    number_of_leaders = committee.quorum_threshold()
    first_leader_round = WAVE
    first_leader = committee.elect_leader(first_leader_round, 0)
    last_committed = AuthorityRound(first_leader, first_leader_round)

    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 2 * WAVE - 1)
    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(last_committed)
    assert len(sequence) == number_of_leaders - 1
    for i, status in enumerate(sequence):
        leader_offset = i + 1
        expected = committee.elect_leader(first_leader_round, leader_offset)
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == expected


def test_direct_commit_late_call(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    n = 10
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, WAVE * (n + 1) - 1)
    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders * n
    for i in range(n):
        chunk = sequence[i * number_of_leaders : (i + 1) * number_of_leaders]
        leader_round = (i + 1) * WAVE
        for leader_offset, status in enumerate(chunk):
            expected = committee.elect_leader(leader_round, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_no_genesis_commit(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    for r in range(2 * WAVE - 1):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{r}")
        build_dag(committee, writer, None, r)
        committer = make_committer(committee, writer, number_of_leaders)
        assert committer.try_commit(AuthorityRound(0, 0)) == []


def test_no_leader(committee, tmp_path):
    """The missing first leader is skipped; other leaders of the round commit."""
    number_of_leaders = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))
    references = build_dag(committee, writer, None, WAVE - 1)
    leader_round_1 = WAVE
    leader_1 = committee.elect_leader(leader_round_1, 0)
    connections = [
        (a, references) for a in committee.authority_indexes() if a != leader_1
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, 2 * WAVE - 1)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders
    for leader_offset, status in enumerate(sequence):
        expected = committee.elect_leader(leader_round_1, leader_offset)
        if expected == leader_1:
            assert status.kind == LeaderStatus.SKIP
            assert status.authority == expected
            assert status.round == leader_round_1
        else:
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_direct_skip(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]
    build_dag(committee, writer, references_without_leader_1, 2 * WAVE - 1)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1
