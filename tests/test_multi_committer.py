"""Multi-leader commit-rule gold suite — ``consensus/tests/multi_committer_tests.rs``."""
import pytest

from mysticeti_tpu.committee import Committee
from mysticeti_tpu.consensus import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus
from mysticeti_tpu.consensus.universal_committer import UniversalCommitterBuilder

from helpers import DagBlockWriter, build_dag, build_dag_layer

WAVE = DEFAULT_WAVE_LENGTH


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def make_committer(committee, writer, number_of_leaders):
    return (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(WAVE)
        .with_number_of_leaders(number_of_leaders)
        .build()
    )


def test_direct_commit(committee, tmp_path):
    for number_of_leaders in range(1, len(committee)):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{number_of_leaders}")
        build_dag(committee, writer, None, 5)
        committer = make_committer(committee, writer, number_of_leaders)
        sequence = committer.try_commit(AuthorityRound(0, 0))
        assert len(sequence) == number_of_leaders
        for leader_offset, status in enumerate(sequence):
            expected = committee.elect_leader(WAVE, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_idempotence(committee, tmp_path):
    for number_of_leaders in range(1, len(committee)):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{number_of_leaders}")
        build_dag(committee, writer, None, 5)
        committer = make_committer(committee, writer, number_of_leaders)
        committed = committer.try_commit(AuthorityRound(0, 0))
        assert committed
        last = committed[-1]
        sequence = committer.try_commit(AuthorityRound(last.authority, last.round))
        assert sequence == []


def test_multiple_direct_commit(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    last_committed = AuthorityRound(0, 0)
    for n in range(1, 11):
        enough_blocks = WAVE * (n + 1) - 1
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{n}")
        build_dag(committee, writer, None, enough_blocks)
        committer = make_committer(committee, writer, number_of_leaders)
        sequence = committer.try_commit(last_committed)
        assert len(sequence) == number_of_leaders
        leader_round = n * WAVE
        for leader_offset, status in enumerate(sequence):
            expected = committee.elect_leader(leader_round, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected
        last = sequence[-1]
        last_committed = AuthorityRound(last.authority, last.round)


def test_direct_commit_partial_round(committee, tmp_path):
    """Resuming from mid-round commits only the remaining leaders of that round."""
    number_of_leaders = committee.quorum_threshold()
    first_leader_round = WAVE
    first_leader = committee.elect_leader(first_leader_round, 0)
    last_committed = AuthorityRound(first_leader, first_leader_round)

    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 2 * WAVE - 1)
    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(last_committed)
    assert len(sequence) == number_of_leaders - 1
    for i, status in enumerate(sequence):
        leader_offset = i + 1
        expected = committee.elect_leader(first_leader_round, leader_offset)
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == expected


def test_direct_commit_late_call(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    n = 10
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, WAVE * (n + 1) - 1)
    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders * n
    for i in range(n):
        chunk = sequence[i * number_of_leaders : (i + 1) * number_of_leaders]
        leader_round = (i + 1) * WAVE
        for leader_offset, status in enumerate(chunk):
            expected = committee.elect_leader(leader_round, leader_offset)
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_no_genesis_commit(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    for r in range(2 * WAVE - 1):
        writer = DagBlockWriter(committee, str(tmp_path), name=f"wal-{r}")
        build_dag(committee, writer, None, r)
        committer = make_committer(committee, writer, number_of_leaders)
        assert committer.try_commit(AuthorityRound(0, 0)) == []


def test_no_leader(committee, tmp_path):
    """The missing first leader is skipped; other leaders of the round commit."""
    number_of_leaders = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))
    references = build_dag(committee, writer, None, WAVE - 1)
    leader_round_1 = WAVE
    leader_1 = committee.elect_leader(leader_round_1, 0)
    connections = [
        (a, references) for a in committee.authority_indexes() if a != leader_1
    ]
    references = build_dag_layer(connections, writer)
    build_dag(committee, writer, references, 2 * WAVE - 1)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders
    for leader_offset, status in enumerate(sequence):
        expected = committee.elect_leader(leader_round_1, leader_offset)
        if expected == leader_1:
            assert status.kind == LeaderStatus.SKIP
            assert status.authority == expected
            assert status.round == leader_round_1
        else:
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == expected


def test_direct_skip(committee, tmp_path):
    number_of_leaders = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))
    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]
    build_dag(committee, writer, references_without_leader_1, 2 * WAVE - 1)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == number_of_leaders
    assert sequence[0].kind == LeaderStatus.SKIP
    assert sequence[0].authority == leader_1


def test_indirect_commit(committee, tmp_path):
    """Leader 1 of wave 1 reaches only f+1 certificates at the decision round
    (not 2f+1): the direct rule cannot commit it, but the wave-2 anchor finds
    a certified link — indirect commit (multi_committer_tests.rs:370)."""
    number_of_leaders = committee.quorum_threshold()
    quorum = committee.quorum_threshold()
    validity = committee.validity_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader_1 = [
        r for r in references_1 if r.authority != leader_1
    ]

    # Only 2f+1 validators vote for leader 1.
    voters = list(committee.authority_indexes())[:quorum]
    non_voters = list(committee.authority_indexes())[quorum:]
    references_with_votes = build_dag_layer(
        [(a, references_1) for a in voters], writer
    )
    references_without_votes = build_dag_layer(
        [(a, references_without_leader_1) for a in non_voters], writer
    )

    # Only f+1 validators certify leader 1.
    references_3 = []
    certifiers = list(committee.authority_indexes())[:validity]
    rest = list(committee.authority_indexes())[validity:]
    references_3 += build_dag_layer(
        [(a, references_with_votes) for a in certifiers], writer
    )
    mixed = (references_without_votes + references_with_votes)[:quorum]
    references_3 += build_dag_layer([(a, mixed) for a in rest], writer)

    decision_round_3 = 3 * WAVE - 1
    build_dag(committee, writer, references_3, decision_round_3)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 2 * number_of_leaders
    assert sequence[0].kind == LeaderStatus.COMMIT
    assert sequence[0].block.author() == leader_1


def test_indirect_skip(committee, tmp_path):
    """Only f+1 validators link to the first leader of wave 2: undecided by
    the direct rule, and the wave-3 anchor finds no certificate — indirect
    skip, while the other wave-2 leaders commit (multi_committer_tests.rs:469)."""
    number_of_leaders = committee.quorum_threshold()
    validity = committee.validity_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_2 = 2 * WAVE
    references_2 = build_dag(committee, writer, None, leader_round_2)
    leader_2 = committee.elect_leader(leader_round_2, 0)
    references_without_leader_2 = [
        r for r in references_2 if r.authority != leader_2
    ]

    linkers = list(committee.authority_indexes())[:validity]
    others = list(committee.authority_indexes())[validity:]
    references = build_dag_layer(
        [(a, references_2) for a in linkers], writer
    ) + build_dag_layer(
        [(a, references_without_leader_2) for a in others], writer
    )

    decision_round_3 = 4 * WAVE - 1
    build_dag(committee, writer, references, decision_round_3)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert len(sequence) == 3 * number_of_leaders

    # Wave 1: all leaders commit.
    for n in range(number_of_leaders):
        status = sequence[n]
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(WAVE, n)
    # Wave 2: first leader skipped, the rest commit.
    for n in range(number_of_leaders):
        status = sequence[number_of_leaders + n]
        if n == 0:
            assert status.kind == LeaderStatus.SKIP
            assert status.authority == leader_2
            assert status.round == leader_round_2
        else:
            assert status.kind == LeaderStatus.COMMIT
            assert status.block.author() == committee.elect_leader(leader_round_2, n)
    # Wave 3: all leaders commit.
    for n in range(number_of_leaders):
        status = sequence[2 * number_of_leaders + n]
        assert status.kind == LeaderStatus.COMMIT
        assert status.block.author() == committee.elect_leader(3 * WAVE, n)


def test_undecided(committee, tmp_path):
    """One vote for the leader: not enough support to commit, not enough
    blame to skip — nothing commits (multi_committer_tests.rs:592)."""
    number_of_leaders = committee.quorum_threshold()
    quorum = committee.quorum_threshold()
    writer = DagBlockWriter(committee, str(tmp_path))

    leader_round_1 = WAVE
    references_1 = build_dag(committee, writer, None, leader_round_1)
    leader_1 = committee.elect_leader(leader_round_1, 0)
    references_without_leader = [
        r for r in references_1 if r.authority != leader_1
    ]

    indexes = list(committee.authority_indexes())
    connections = [(indexes[0], references_1)] + [
        (a, references_without_leader) for a in indexes[1:quorum]
    ]
    references = build_dag_layer(connections, writer)

    decision_round_1 = 2 * WAVE - 1
    build_dag(committee, writer, references, decision_round_1)

    committer = make_committer(committee, writer, number_of_leaders)
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert sequence == []


def test_hundred_authority_committer(tmp_path):
    """BASELINE config #5 scale at the committer tier: 100 authorities with
    stake-weighted election over a fully-connected DAG. Validates
    AuthoritySet, the weighted elector, and the direct-commit rule at a
    committee size the whole-stack sim cannot cheaply reach."""
    from helpers import DagBlockWriter, build_dag

    committee = Committee.new_for_benchmarks(100)
    writer = DagBlockWriter(committee, str(tmp_path))
    build_dag(committee, writer, None, 2 * DEFAULT_WAVE_LENGTH + 2)
    committer = (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(DEFAULT_WAVE_LENGTH)
        .with_number_of_leaders(2)
        .with_pipeline(True)
        .build()
    )
    sequence = committer.try_commit(AuthorityRound(0, 0))
    assert sequence, "no commits at 100 authorities"
    assert all(s.kind == LeaderStatus.COMMIT for s in sequence)
    # Elected leaders come from the weighted sampler over the full set.
    leaders = {s.block.author() for s in sequence}
    assert all(0 <= a < 100 for a in leaders)
    # Determinism: a second committer over the same store agrees.
    committer2 = (
        UniversalCommitterBuilder(committee, writer.block_store)
        .with_wave_length(DEFAULT_WAVE_LENGTH)
        .with_number_of_leaders(2)
        .with_pipeline(True)
        .build()
    )
    sequence2 = committer2.try_commit(AuthorityRound(0, 0))
    assert [(s.authority, s.round) for s in sequence] == [
        (s.authority, s.round) for s in sequence2
    ]
