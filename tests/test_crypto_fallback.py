"""The pure-Python RFC 8032 fallback oracle (mysticeti_tpu._ed25519_py).

These tests target the fallback module *directly* (not through crypto.py's
backend selection), so its strict accept/reject semantics stay covered in
tier-1 even on machines where the ``cryptography`` package is installed —
and especially on the tier-1 environment where the fallback IS the oracle
every other test leans on.
"""
import hashlib

import pytest

from mysticeti_tpu import _ed25519_py as F
from mysticeti_tpu import crypto


def test_rfc8032_selftest_vector():
    F.selftest()


def _keypair(seed: bytes):
    key = F.Ed25519PrivateKey.from_private_bytes(seed)
    return key, key.public_key()


def test_sign_verify_roundtrip_and_rejects():
    key, pub = _keypair(hashlib.blake2b(b"fallback-seed", digest_size=32).digest())
    msg = b"the quick brown fox"
    sig = key.sign(msg)
    pub.verify(sig, msg)  # accepts

    with pytest.raises(F.InvalidSignature):
        pub.verify(sig, msg + b"!")  # wrong message
    corrupted = bytearray(sig)
    corrupted[3] ^= 0x40
    with pytest.raises(F.InvalidSignature):
        pub.verify(bytes(corrupted), msg)  # corrupted R
    corrupted = bytearray(sig)
    corrupted[40] ^= 0x01
    with pytest.raises(F.InvalidSignature):
        pub.verify(bytes(corrupted), msg)  # corrupted S
    _, other = _keypair(bytes(32))
    with pytest.raises(F.InvalidSignature):
        other.verify(sig, msg)  # wrong key


def test_rejects_noncanonical_s():
    """s' = s + L is congruent mod L but non-canonical: RFC 8032 / OpenSSL
    reject it (malleability defense), and the oracle must agree with the
    kernels that are tested against it."""
    key, pub = _keypair(bytes(range(32)))
    msg = b"malleability"
    sig = key.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    assert s < F.L
    forged = sig[:32] + (s + F.L).to_bytes(32, "little")
    with pytest.raises(F.InvalidSignature):
        pub.verify(forged, msg)


def test_rejects_noncanonical_point_encodings():
    key, pub = _keypair(b"\x11" * 32)
    msg = b"encodings"
    sig = key.sign(msg)
    # Non-canonical A: y >= p.
    bad_pk = F.Ed25519PublicKey.from_public_bytes(bytes([0xFF] * 31 + [0x7F]))
    with pytest.raises(F.InvalidSignature):
        bad_pk.verify(sig, msg)
    # Non-canonical R likewise.
    forged = bytes([0xFF] * 31 + [0x7F]) + sig[32:]
    with pytest.raises(F.InvalidSignature):
        pub.verify(forged, msg)


def test_crypto_surface_works_with_active_backend():
    """Whichever backend crypto.py selected, the Signer/PublicKey surface
    holds: deterministic seeds, digest-layered sign/verify, bool returns."""
    signer = crypto.Signer.from_seed(b"surface-test-seed")
    again = crypto.Signer.from_seed(b"surface-test-seed")
    assert signer.public_key == again.public_key
    digest = crypto.blake2b_256(b"payload")
    sig = signer.sign(digest)
    assert signer.public_key.verify(sig, digest) is True
    assert signer.public_key.verify(sig, crypto.blake2b_256(b"other")) is False
    assert isinstance(crypto.HAVE_CRYPTOGRAPHY, bool)
