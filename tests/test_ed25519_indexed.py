"""Committee-indexed verification path: KeyTable + pack_blob_indexed +
verify_batch_table must be bit-identical to the generic fused path (and to
the CPU oracle) — only the wire format differs.

The signer set of a validator is its committee, so the public key rides as an
index into a device-resident table (26 words/sig instead of 33).  Reference
unit of work: crypto.rs:174-189 (full SHA-512 + Ed25519 verify per block).
"""
import random

import numpy as np
import pytest
from mysticeti_tpu.crypto import Ed25519PrivateKey

from mysticeti_tpu.ops import ed25519 as E

pytestmark = [pytest.mark.kernel,
              pytest.mark.filterwarnings("ignore::DeprecationWarning")]


@pytest.fixture(scope="module")
def keyring():
    rng = random.Random(99)
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(6)
    ]
    return rng, keys


def _batch(rng, keys, n, tamper_every=None):
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = bytes(rng.randrange(256) for _ in range(32))
        s = k.sign(m)
        good = True
        if tamper_every and i % tamper_every == 0:
            s = bytes([s[0] ^ 1]) + s[1:]
            good = False
        pks.append(k.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(s)
        expect.append(good)
    return pks, msgs, sigs, np.array(expect)


def test_indexed_matches_generic_and_expected(keyring):
    rng, keys = keyring
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
    pks, msgs, sigs, expect = _batch(rng, keys, 200, tamper_every=9)
    out = E.verify_batch_table(table, pks, msgs, sigs)
    assert (out == expect).all()
    assert (out == E.verify_batch(pks, msgs, sigs)).all()


def test_indexed_unknown_pk_falls_back(keyring):
    rng, keys = keyring
    # table misses the last key: its items route through the generic path
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys[:-1]])
    pks, msgs, sigs, expect = _batch(rng, keys, 60, tamper_every=7)
    out = E.verify_batch_table(table, pks, msgs, sigs)
    assert (out == expect).all()


def test_indexed_rejects_malformed_lengths(keyring):
    rng, keys = keyring
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys])
    pks, msgs, sigs, expect = _batch(rng, keys, 8)
    msgs[3] = b"short"
    sigs[5] = sigs[5][:-1]
    out = E.verify_batch_table(table, pks, msgs, sigs)
    expect[3] = expect[5] = False
    assert (out == expect).all()


def test_key_table_validation():
    with pytest.raises(ValueError):
        E.KeyTable([])
    with pytest.raises(ValueError):
        E.KeyTable([b"too-short"])


def test_indexed_blob_layout(keyring):
    rng, keys = keyring
    pk = keys[0].public_key().public_bytes_raw()
    m = bytes(range(32))
    s = keys[0].sign(m)
    blob = E.pack_blob_indexed(np.array([4]), [m], [s])
    assert blob.shape == (1, 26) and blob.dtype == np.uint32
    assert blob[0, 24] == 4 and blob[0, 25] == 1
    # R words are the big-endian view of the first 32 sig bytes
    want_r = np.frombuffer(s[:32], ">u4").astype(np.uint32)
    assert (blob[0, :8] == want_r).all()
    # M words big-endian, s words little-endian
    assert (blob[0, 8:16] == np.frombuffer(m, ">u4").astype(np.uint32)).all()
    assert (blob[0, 16:24] == np.frombuffer(s[32:], "<u4").astype(np.uint32)).all()
