"""RangeMap tests — property-based coverage mirroring range_map.rs's unit tests,
checked against a naive dict-of-points model."""
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st

from mysticeti_tpu.range_map import RangeMap


def set_range(rm, start, end, value):
    rm.mutate_range(start, end, lambda s, e, old: value)


class TestBasic:
    def test_insert_and_get(self):
        rm = RangeMap()
        set_range(rm, 5, 10, "x")
        assert rm.get(4) is None
        assert rm.get(5) == "x"
        assert rm.get(9) == "x"
        assert rm.get(10) is None

    def test_split_on_partial_overwrite(self):
        rm = RangeMap()
        set_range(rm, 0, 10, "a")
        set_range(rm, 3, 6, "b")
        assert [rm.get(i) for i in range(10)] == (
            ["a"] * 3 + ["b"] * 3 + ["a"] * 4
        )
        assert len(rm) == 3

    def test_delete_via_none(self):
        rm = RangeMap()
        set_range(rm, 0, 10, "a")
        rm.mutate_range(2, 8, lambda s, e, old: None)
        assert rm.get(1) == "a"
        assert rm.get(5) is None
        assert rm.get(9) == "a"

    def test_gap_callback_sees_none(self):
        rm = RangeMap()
        set_range(rm, 5, 7, "a")
        seen = []
        rm.mutate_range(3, 9, lambda s, e, old: seen.append((s, e, old)) or old)
        assert seen == [(3, 5, None), (5, 7, "a"), (7, 9, None)]

    def test_multiple_fragments(self):
        rm = RangeMap()
        set_range(rm, 0, 2, "a")
        set_range(rm, 4, 6, "b")
        set_range(rm, 8, 10, "c")
        seen = []
        rm.mutate_range(1, 9, lambda s, e, old: seen.append((s, e, old)) or old)
        assert seen == [
            (1, 2, "a"), (2, 4, None), (4, 6, "b"), (6, 8, None), (8, 9, "c"),
        ]

    def test_empty_range_noop(self):
        rm = RangeMap()
        rm.mutate_range(5, 5, lambda s, e, old: "x")
        assert rm.is_empty()


@st.composite
def operations(draw):
    ops = []
    for _ in range(draw(st.integers(0, 12))):
        start = draw(st.integers(0, 30))
        end = draw(st.integers(0, 30))
        value = draw(st.one_of(st.none(), st.integers(0, 3)))
        ops.append((start, end, value))
    return ops


class TestPropertyBased:
    @settings(max_examples=200, deadline=None)
    @given(operations())
    def test_matches_point_model(self, ops):
        rm = RangeMap()
        model = {}
        for start, end, value in ops:
            rm.mutate_range(start, end, lambda s, e, old, v=value: v)
            for k in range(start, end):
                if value is None:
                    model.pop(k, None)
                else:
                    model[k] = value
        for k in range(0, 31):
            assert rm.get(k) == model.get(k), f"mismatch at {k}"
        # entries must be sorted, disjoint, non-empty
        prev_end = None
        for s, e, v in rm.items():
            assert s < e
            assert v is not None
            if prev_end is not None:
                assert s >= prev_end
            prev_end = e
