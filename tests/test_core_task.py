"""Dispatcher fail-stop semantics (ADVICE r4): transient command failures
keep the owner loop alive; a persistent run of failures halts the node
instead of letting it run on possibly-corrupt state."""
import asyncio

import pytest

from mysticeti_tpu.core_task import CoreTaskDispatcher


def _dispatcher(fatal=None):
    return CoreTaskDispatcher(syncer=None, fatal_handler=fatal).start()


def _boom():
    raise RuntimeError("corrupt state")


def _boom2():
    raise RuntimeError("corrupt state elsewhere")


def test_single_failure_propagates_and_loop_survives():
    async def scenario():
        d = _dispatcher()
        with pytest.raises(RuntimeError, match="corrupt state"):
            await d._call(_boom)
        # Loop is still alive and serving.
        assert await d._call(lambda: 42) == 42
        assert not d._task.done()
        d.stop()

    asyncio.run(scenario())


def test_success_resets_the_failure_run():
    async def scenario():
        d = _dispatcher()
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES - 1):
            await d._queue.put((_boom, (), None, False))  # no live caller
        assert await d._call(lambda: "ok") == "ok"
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES - 1):
            await d._queue.put((_boom, (), None, False))
        assert await d._call(lambda: "ok") == "ok"
        assert not d._task.done()
        d.stop()

    asyncio.run(scenario())


def test_observed_failures_never_halt_the_owner():
    """ADVICE r5: a client retry-looping one failing command observes every
    exception itself — no amount of CALLER-OBSERVED failures may SIGTERM the
    node (the old counter fired after 16)."""
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES * 2):
            with pytest.raises(RuntimeError, match="corrupt state"):
                await d._call(_boom)
        assert not d._task.done()
        assert await d._call(lambda: 7) == 7
        d.stop()
        await asyncio.sleep(0)
        assert fired == []

    asyncio.run(scenario())


def test_persistent_failure_halts_the_owner_and_fires_fatal_handler():
    """Only failures NO live caller observes count toward the fail-stop
    halt, and the run must span more than one command type: that is the
    poisoned-store signature (every mutation fails), not caller churn."""
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for i in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES):
            await d._queue.put((_boom if i % 2 else _boom2, (), None, False))
        while not d._task.done():
            await asyncio.sleep(0)
        with pytest.raises(RuntimeError, match="corrupt state"):
            d._task.result()
        await asyncio.sleep(0)  # done-callback runs on the loop
        # The node must TERMINATE, not zombie on with a dead owner — the
        # default handler SIGTERMs the process; tests record instead.
        assert fired == [True]

    asyncio.run(scenario())


def test_single_command_unobserved_run_never_halts():
    """A cancelled-await retry loop hammering ONE failing command reads as
    unobserved too — without the distinct-type requirement it would SIGTERM
    the node exactly like the caller churn ADVICE r5 exempted."""
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES * 2):
            await d._queue.put((_boom, (), None, False))
        assert await d._call(lambda: 3) == 3  # owner alive and serving
        assert not d._task.done()
        d.stop()
        await asyncio.sleep(0)
        assert fired == []

    asyncio.run(scenario())


def test_observed_internal_failures_reach_the_halt():
    """A poisoned store fails every command, but network-driven commands
    always have live callers observing the exception — the halt must still
    be reachable via the node's OWN periodic commands (cleanup/get_missing/
    force_new_block), which a remote client cannot drive: their failures
    count even when observed."""
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for i in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES):
            with pytest.raises(RuntimeError, match="corrupt state"):
                await d._call(_boom if i % 2 else _boom2, internal=True)
        while not d._task.done():
            await asyncio.sleep(0)
        await asyncio.sleep(0)  # done-callback runs on the loop
        assert fired == [True]

    asyncio.run(scenario())


def test_observed_internal_single_kind_never_halts():
    """One flaky internal command (e.g. cleanup hitting a transient store
    error every period) is not the poisoned-store signature: without kind
    diversity the owner stays up."""
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES * 2):
            with pytest.raises(RuntimeError, match="corrupt state"):
                await d._call(_boom, internal=True)
        assert await d._call(lambda: 9) == 9
        assert not d._task.done()
        d.stop()
        await asyncio.sleep(0)
        assert fired == []

    asyncio.run(scenario())


def test_clean_stop_does_not_fire_fatal_handler():
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        assert await d._call(lambda: 1) == 1
        d.stop()
        await asyncio.sleep(0)
        assert fired == []

    asyncio.run(scenario())
