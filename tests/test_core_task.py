"""Dispatcher fail-stop semantics (ADVICE r4): transient command failures
keep the owner loop alive; a persistent run of failures halts the node
instead of letting it run on possibly-corrupt state."""
import asyncio

import pytest

from mysticeti_tpu.core_task import CoreTaskDispatcher


def _dispatcher(fatal=None):
    return CoreTaskDispatcher(syncer=None, fatal_handler=fatal).start()


def _boom():
    raise RuntimeError("corrupt state")


def test_single_failure_propagates_and_loop_survives():
    async def scenario():
        d = _dispatcher()
        with pytest.raises(RuntimeError, match="corrupt state"):
            await d._call(_boom)
        # Loop is still alive and serving.
        assert await d._call(lambda: 42) == 42
        assert not d._task.done()
        d.stop()

    asyncio.run(scenario())


def test_success_resets_the_failure_run():
    async def scenario():
        d = _dispatcher()
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES - 1):
            with pytest.raises(RuntimeError):
                await d._call(_boom)
        assert await d._call(lambda: "ok") == "ok"
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES - 1):
            with pytest.raises(RuntimeError):
                await d._call(_boom)
        assert not d._task.done()
        d.stop()

    asyncio.run(scenario())


def test_persistent_failure_halts_the_owner_and_fires_fatal_handler():
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        for _ in range(CoreTaskDispatcher.MAX_CONSECUTIVE_FAILURES):
            with pytest.raises(RuntimeError):
                await d._call(_boom)
        await asyncio.sleep(0)  # let the owner task finish raising
        assert d._task.done()
        with pytest.raises(RuntimeError, match="corrupt state"):
            d._task.result()
        await asyncio.sleep(0)  # done-callback runs on the loop
        # The node must TERMINATE, not zombie on with a dead owner — the
        # default handler SIGTERMs the process; tests record instead.
        assert fired == [True]

    asyncio.run(scenario())


def test_clean_stop_does_not_fire_fatal_handler():
    fired = []

    async def scenario():
        d = _dispatcher(fatal=lambda: fired.append(True))
        assert await d._call(lambda: 1) == 1
        d.stop()
        await asyncio.sleep(0)
        assert fired == []

    asyncio.run(scenario())
