"""Sharded verification over a virtual 8-device CPU mesh (multi-chip dry-run)."""
import numpy as np
import pytest

pytestmark = pytest.mark.kernel

from mysticeti_tpu.crypto import Ed25519PrivateKey

import jax


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs a multi-device mesh")
def test_sharded_verify_matches_single_device():
    from mysticeti_tpu.ops import ed25519 as E
    from mysticeti_tpu.parallel import make_mesh, sharded_verify_batch

    import random

    rng = random.Random(42)
    pks, msgs, sigs = [], [], []
    for i in range(16):
        key = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        pk = key.public_key().public_bytes_raw()
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        if i % 4 == 3:
            sig = bytearray(sig)
            sig[7] ^= 0xFF
            sig = bytes(sig)
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)

    mesh = make_mesh(8)
    ok, total = sharded_verify_batch(mesh, pks, msgs, sigs)
    single = E.verify_batch(pks, msgs, sigs)
    assert (ok == single).all()
    assert total == int(single.sum())
    assert total == 12  # 4 corrupted out of 16


def test_sharded_fused_verify_matches_oracle():
    """Fused raw-bytes sharded path on the virtual 8-device mesh: per-item
    bits match the oracle and the psum'd count is exact."""
    import random

    import numpy as np
    from mysticeti_tpu.crypto import Ed25519PrivateKey

    from mysticeti_tpu.parallel import make_mesh, sharded_verify_batch_fused

    rng = random.Random(21)
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(13):  # odd size: exercises bucket padding across shards
        key = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = key.sign(msg)
        ok = True
        if i % 5 == 3:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            ok = False
        pks.append(key.public_key().public_bytes_raw())
        msgs.append(msg)
        sigs.append(sig)
        expect.append(ok)
    mesh = make_mesh(8)
    got, total = sharded_verify_batch_fused(mesh, pks, msgs, sigs)
    assert list(got) == expect
    assert total == sum(expect)


def test_sharded_indexed_verify_matches_oracle():
    """Committee-indexed sharded path (table replicated, blob sharded):
    bit-identical to the generic sharded path incl. unknown-key fallback."""
    import random

    from mysticeti_tpu.ops import ed25519 as E
    from mysticeti_tpu.parallel.mesh import (
        make_mesh,
        sharded_verify_batch_fused,
        sharded_verify_batch_indexed,
    )

    rng = random.Random(17)
    keys = [
        Ed25519PrivateKey.from_private_bytes(
            bytes(rng.randrange(256) for _ in range(32))
        )
        for _ in range(5)
    ]
    table = E.KeyTable([k.public_key().public_bytes_raw() for k in keys[:4]])
    pks, msgs, sigs = [], [], []
    for i in range(64):
        k = keys[i % 5]  # key 4 is unknown to the table
        m = bytes(rng.randrange(256) for _ in range(32))
        s = k.sign(m)
        if i % 6 == 0:
            s = bytes([s[0] ^ 1]) + s[1:]
        pks.append(k.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(s)
    mesh = make_mesh(8)
    ok_idx, total_idx = sharded_verify_batch_indexed(mesh, table, pks, msgs, sigs)
    ok_gen, total_gen = sharded_verify_batch_fused(mesh, pks, msgs, sigs)
    assert (ok_idx == ok_gen).all()
    assert total_idx == total_gen == int(ok_gen.sum())
