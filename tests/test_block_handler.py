"""Block handler drain semantics (block_handler.rs SOFT_MAX regime)."""
import pytest

from mysticeti_tpu import block_handler as bh
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.types import Share


def _handler():
    committee = Committee.new_for_benchmarks(4)
    return bh.BenchmarkFastPathBlockHandler(committee, 0)


def test_soft_max_slices_oversize_submissions(monkeypatch):
    """A submission chunk larger than the SOFT_MAX budget is sliced, not
    admitted whole: the cap is a per-block transaction cap (block_handler.rs
    SOFT_MAX), and the generator's 100 ms chunks (tps/10 transactions) would
    otherwise blow past it on every proposal."""
    monkeypatch.setattr(bh, "SOFT_MAX_PROPOSED_PER_BLOCK", 16)
    h = _handler()
    h.submit([bytes([i % 256]) * 32 for i in range(100)])
    stmts = h.handle_blocks([], require_response=True)
    shares = [s for s in stmts if isinstance(s, Share)]
    assert len(shares) == 16
    # The remainder stays queued; the next proposal budget drains more.
    h.pending_transactions = 0  # as handle_proposal does after proposing
    stmts = h.handle_blocks([], require_response=True)
    assert len([s for s in stmts if isinstance(s, Share)]) == 16


def test_soft_max_exact_budget_not_sliced(monkeypatch):
    monkeypatch.setattr(bh, "SOFT_MAX_PROPOSED_PER_BLOCK", 16)
    h = _handler()
    h.submit([b"x" * 32 for _ in range(10)])
    h.submit([b"y" * 32 for _ in range(6)])
    stmts = h.handle_blocks([], require_response=True)
    assert len([s for s in stmts if isinstance(s, Share)]) == 16


def test_env_cap_validation(monkeypatch):
    monkeypatch.setenv("MYSTICETI_MAX_BLOCK_TX", "64")
    assert bh._soft_max_from_env() == 64
    monkeypatch.setenv("MYSTICETI_MAX_BLOCK_TX", "0")
    with pytest.raises(ValueError, match="out of range"):
        bh._soft_max_from_env()
    monkeypatch.setenv("MYSTICETI_MAX_BLOCK_TX", "99999")
    with pytest.raises(ValueError, match="out of range"):
        bh._soft_max_from_env()
    monkeypatch.setenv("MYSTICETI_MAX_BLOCK_TX", "lots")
    with pytest.raises(ValueError, match="integer"):
        bh._soft_max_from_env()
    monkeypatch.delenv("MYSTICETI_MAX_BLOCK_TX")
    assert bh._soft_max_from_env() == bh.MAX_PROPOSED_PER_BLOCK


def test_log_range_matches_per_locator_format(tmp_path):
    """log_range (bulk certified-log write) emits byte-identical lines to
    the per-locator log() path — consumers parse one format."""
    from mysticeti_tpu.log import TransactionLog
    from mysticeti_tpu.types import StatementBlock, TransactionLocator

    blk = StatementBlock.new_genesis(3)
    a = TransactionLog.start(str(tmp_path / "a.log"))
    for off in range(5, 9):
        a.log(TransactionLocator(blk.reference, off))
    a.flush()
    b = TransactionLog.start(str(tmp_path / "b.log"))
    b.log_range(blk.reference, 5, 9)
    b.flush()
    assert (tmp_path / "a.log").read_bytes() == (tmp_path / "b.log").read_bytes()
    # and the prefix cache stays coherent for a subsequent singular log()
    b.log(TransactionLocator(blk.reference, 9))
    b.flush()
    assert (tmp_path / "b.log").read_text().splitlines()[-1].endswith(",9")
