"""PreciseHistogram window semantics (stat.rs:8-100 drain-per-sweep parity).

Round-4 verdict: the old buffer stopped appending at ``max_samples`` and the
reporter never cleared, so at fleet load the published p50/p90/p99 described
the first ~2.5 s of the run forever.  These tests pin the fix: reservoir
sampling within a window + drain on report.
"""
import asyncio
import json

from mysticeti_tpu.metrics import Metrics, PreciseHistogram, serve_metrics


def test_percentiles_track_shifted_distribution_after_200k():
    h = PreciseHistogram(max_samples=10_000)
    # Window 1: 200k observations around 1.0 — far beyond the buffer cap.
    for i in range(200_000):
        h.observe(1.0 + (i % 100) / 1000.0)
    pcts = h.pcts((50, 90, 99))
    assert 1.0 <= pcts[50] <= 1.1
    h.clear()
    # Window 2: the distribution shifts to ~5.0.  A frozen buffer would keep
    # reporting ~1.0; the drained reservoir must follow the shift.
    for i in range(200_000):
        h.observe(5.0 + (i % 100) / 1000.0)
    pcts = h.pcts((50, 90, 99))
    assert 5.0 <= pcts[50] <= 5.1
    assert 5.0 <= pcts[99] <= 5.1
    # Cumulative average still spans both windows.
    assert 2.9 < h.avg() < 3.2
    assert h.count == 400_000


def test_reservoir_is_representative_within_one_window():
    h = PreciseHistogram(max_samples=1_000)
    # One window whose character changes after the buffer fills: 100k warmup
    # samples at 10.0 then 100k steady-state at 1.0.  Appending-only capture
    # would report p50=10 (pure warmup); a uniform reservoir over the window
    # reports the ~50/50 mixture.
    for _ in range(100_000):
        h.observe(10.0)
    for _ in range(100_000):
        h.observe(1.0)
    mixed = sum(1 for s in h.samples if s == 1.0) / len(h.samples)
    assert 0.35 < mixed < 0.65
    assert len(h.samples) == 1_000


def test_report_precise_drains_and_keeps_last_value_on_quiet_window():
    m = Metrics()
    for _ in range(100):
        m.transaction_committed_latency.observe(2.0)
    m.report_precise()
    g = m._pct_gauge.labels("transaction_committed_latency", "50")
    assert g._value.get() == 2.0
    assert m.transaction_committed_latency.samples == []
    # Quiet window: no new samples — the gauge keeps its last published value.
    m.report_precise()
    assert g._value.get() == 2.0
    # Next busy window at a different level: the gauge follows.
    for _ in range(100):
        m.transaction_committed_latency.observe(7.0)
    m.report_precise()
    assert g._value.get() == 7.0


REFERENCE_SERIES_MAP = [
    # (reference metrics.rs:36-87 field, our scrape name)
    ("benchmark_duration", "benchmark_duration"),
    ("latency_s", "latency_s"),
    ("latency_squared_s", "latency_squared_s"),
    ("committed_leaders_total", "committed_leaders_total"),
    ("leader_timeout_total", "leader_timeout_total"),
    ("inter_block_latency_s", "inter_block_latency_s"),
    ("block_store_unloaded_blocks", "block_store_unloaded_blocks"),
    ("block_store_loaded_blocks", "block_store_loaded_blocks"),
    ("block_store_entries", "block_store_entries"),
    ("block_store_cleanup_util", "utilization_timer"),  # proc label
    ("wal_mappings", "wal_mappings"),
    ("core_lock_util", "utilization_timer"),  # proc="core:*"
    ("core_lock_enqueued", "core_lock_enqueued"),
    ("core_lock_dequeued", "core_lock_dequeued"),
    ("block_handler_pending_certificates", "block_handler_pending_certificates"),
    ("block_handler_cleanup_util", "utilization_timer"),
    ("commit_handler_pending_certificates", "commit_handler_pending_certificates"),
    ("missing_blocks", "missing_blocks_total"),
    ("blocks_suspended", "blocks_suspended"),
    ("block_sync_requests_sent", "block_sync_requests_sent"),
    ("block_sync_requests_received", "block_sync_requests_received"),
    ("transaction_certified_latency", "histogram_pct"),  # name label
    ("certificate_committed_latency", "histogram_pct"),
    ("transaction_committed_latency", "histogram_pct"),
    ("proposed_block_size_bytes", "histogram_pct"),
    ("proposed_block_transaction_count", "histogram_pct"),
    ("proposed_block_vote_count", "histogram_pct"),
    ("connection_latency_sender", "connection_latency"),
    ("connected_nodes", "connected_nodes"),
    ("utilization_timer", "utilization_timer"),
    ("threshold_clock_round", "threshold_clock_round"),
    ("commit_round", "commit_round"),
    ("blocks_per_commit_count", "histogram_pct"),
    ("sub_dags_per_commit_count", "histogram_pct"),
    ("block_commit_latency", "histogram_pct"),
    ("block_receive_latency", "block_receive_latency"),
    ("add_block_latency", "add_block_latency"),
    ("quorum_receive_latency", "histogram_pct"),
    ("ready_new_block", "ready_new_block"),
    # Beyond the reference: the TPU verifier series + wal size.
    ("-", "verified_signatures_total"),
    ("-", "verify_batch_size"),
    ("-", "wal_size_bytes"),
]


def test_scrape_contains_full_reference_inventory():
    """Every series in the reference's Metrics struct (metrics.rs:36-87) has
    a scrapeable counterpart here — the series-for-series map is also
    recorded in PARITY.md."""
    m = Metrics()
    # Label-less series appear in an empty scrape; labeled/vec series appear
    # once touched — touch one child each so the scrape carries them all.
    m.latency_s.labels("shared")
    m.latency_squared_s.labels("shared")
    m.committed_leaders_total.labels("0", "committed")
    m.inter_block_latency_s.labels("shared")
    m.block_sync_requests_sent.labels("1")
    m.block_sync_requests_received.labels("1")
    m.connection_latency.labels("1")
    m.block_receive_latency.labels("0")
    m.add_block_latency.labels("0")
    m.utilization_timer_us.labels("core:add_blocks")
    m.ready_new_block.labels("leader")
    m.verified_signatures_total.labels("cpu", "accepted")
    m.quorum_receive_latency.observe(0.1)
    for name in (
        "transaction_certified_latency", "certificate_committed_latency",
        "transaction_committed_latency", "proposed_block_size_bytes",
        "proposed_block_transaction_count", "proposed_block_vote_count",
        "blocks_per_commit_count", "sub_dags_per_commit_count",
        "block_commit_latency",
    ):
        m._precise[name].observe(1.0)
    m.report_precise()
    scrape = m.expose().decode()
    for ref_field, ours in REFERENCE_SERIES_MAP:
        assert ours in scrape, f"{ref_field} -> {ours} missing from scrape"
    # The precise channels ride histogram_pct{name=...}: check each label.
    for name in sorted(m._precise):
        assert f'name="{name}"' in scrape, name
    # The verifier hot-path inventory (batch shape, padding, routing,
    # service queue) — labeled series need one touched child to appear.
    m.verify_padding_wasted_total.labels("cpu")
    m.verify_route_total.labels("cpu")
    m.verifier_service_inflight.labels("c0")
    scrape = m.expose().decode()
    for series in (
        "verify_dispatch_batch_size",
        "verify_padding_wasted_total",
        "verify_route_total",
        "verify_route_estimate_error_s",
        "verifier_service_queue_depth",
        "verifier_service_inflight",
    ):
        assert series in scrape, series


def test_healthz_route_alongside_metrics():
    """The metrics endpoint answers /healthz with 200 + uptime and keeps
    serving the prometheus scrape on /metrics."""

    async def scenario():
        metrics = Metrics()
        server = await serve_metrics(metrics, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]

        async def get(path):
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
            await writer.drain()
            data = await reader.read()
            writer.close()
            return data

        health = await get("/healthz")
        scrape = await get("/metrics")
        server.close()
        await server.wait_closed()
        return health, scrape

    health, scrape = asyncio.run(scenario())
    head, _, body = health.partition(b"\r\n\r\n")
    assert b"200 OK" in head and b"application/json" in head
    payload = json.loads(body)
    assert payload["status"] == "ok"
    assert payload["uptime_s"] >= 0.0
    assert b"200 OK" in scrape
    assert b"benchmark_duration" in scrape
