"""Batched signature-verification seam tests: the collector's size/deadline
policy and the end-to-end validator path with real signature checking."""
import asyncio

import pytest

from mysticeti_tpu.block_validator import (
    BatchedSignatureVerifier,
    CpuSignatureVerifier,
)
from mysticeti_tpu.committee import Authority, Committee
from mysticeti_tpu.crypto import Signer
from mysticeti_tpu.types import StatementBlock, VerificationError


@pytest.fixture
def committee_and_signers():
    signers = Committee.benchmark_signers(4)
    committee = Committee([Authority(1, s.public_key) for s in signers])
    return committee, signers


class CountingVerifier(CpuSignatureVerifier):
    def __init__(self):
        self.calls = []

    def verify_signatures(self, public_keys, digests, signatures):
        self.calls.append(len(signatures))
        return super().verify_signatures(public_keys, digests, signatures)


def test_batch_collector_deadline(committee_and_signers):
    """Blocks arriving under max_batch are flushed by the deadline, as one call."""
    committee, signers = committee_and_signers

    async def main():
        backend = CountingVerifier()
        verifier = BatchedSignatureVerifier(
            committee, backend, max_batch=100, max_delay_s=0.02
        )
        blocks = [
            StatementBlock.build(a, 1, [], (), signer=signers[a]) for a in range(4)
        ]
        await asyncio.gather(*(verifier.verify(b) for b in blocks))
        assert backend.calls == [4], backend.calls

    asyncio.run(main())


def test_batch_collector_size_trigger(committee_and_signers):
    committee, signers = committee_and_signers

    async def main():
        backend = CountingVerifier()
        verifier = BatchedSignatureVerifier(
            committee, backend, max_batch=2, max_delay_s=10.0
        )
        blocks = [
            StatementBlock.build(a, 1, [], (), signer=signers[a]) for a in range(4)
        ]
        await asyncio.gather(*(verifier.verify(b) for b in blocks))
        assert sum(backend.calls) == 4
        assert max(backend.calls) <= 2

    asyncio.run(main())


def test_batch_collector_rejects_bad_signature(committee_and_signers):
    committee, signers = committee_and_signers

    async def main():
        verifier = BatchedSignatureVerifier(
            committee, CpuSignatureVerifier(), max_batch=10, max_delay_s=0.01
        )
        good = StatementBlock.build(0, 1, [], (), signer=signers[0])
        forged = StatementBlock.build(1, 1, [], (), signer=signers[0])  # wrong key
        results = await asyncio.gather(
            verifier.verify(good), verifier.verify(forged), return_exceptions=True
        )
        assert results[0] is None
        assert isinstance(results[1], VerificationError)

    asyncio.run(main())


def test_validators_with_cpu_signature_verification(tmp_path):
    """4 localhost validators with full signature verification through the
    batching collector still commit (BASELINE config #1)."""
    import socket

    from mysticeti_tpu.config import Identifier, Parameters, PrivateConfig
    from mysticeti_tpu.validator import Validator

    def free_ports(n):
        socks, ports = [], []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:
            s.close()
        return ports

    async def main():
        ports = free_ports(8)
        identifiers = [
            Identifier("127.0.0.1", ports[2 * i], ports[2 * i + 1]) for i in range(4)
        ]
        parameters = Parameters(identifiers=identifiers, leader_timeout_s=0.5)
        signers = Committee.benchmark_signers(4)
        committee = Committee([Authority(1, s.public_key) for s in signers])
        validators = [
            await Validator.start_benchmarking(
                i,
                committee,
                parameters,
                PrivateConfig.new_in_dir(i, str(tmp_path / f"v{i}")),
                signer=signers[i],
                tps=20,
                serve_metrics_endpoint=False,
                verifier="cpu",
            )
            for i in range(4)
        ]
        try:

            async def poll():
                while True:
                    if all(len(v.committed_leaders()) >= 2 for v in validators):
                        return
                    await asyncio.sleep(0.2)

            await asyncio.wait_for(poll(), timeout=60)
        finally:
            for v in validators:
                await v.stop()

    asyncio.run(main())


def test_hybrid_verifier_routes_by_batch_size():
    """Small batches take the CPU oracle, large ones the TPU backend; the
    threshold is the measured crossover, capped by the CPU time budget."""
    from mysticeti_tpu.block_validator import (
        HybridSignatureVerifier,
        SignatureVerifier,
    )

    class Recorder(SignatureVerifier):
        def __init__(self):
            self.calls = []

        def verify_signatures(self, pks, digests, sigs):
            self.calls.append(len(sigs))
            return [True] * len(sigs)

    tpu, cpu = Recorder(), Recorder()
    hybrid = HybridSignatureVerifier(tpu=tpu, cpu=cpu)
    # Pretend calibration: 100 ms accelerator round-trip, 100 µs/sig CPU.
    hybrid.tpu_dispatch_s = 0.100
    hybrid.cpu_per_sig_s = 100e-6
    # Pure-speed crossover would be 1000, but past the CPU budget (10 ms,
    # i.e. >100 sigs) batches offload to free the host core — the
    # accelerator's 100 ms turnaround is within MAX_OFFLOAD_LATENCY_S.
    assert hybrid.threshold() == 101

    args = lambda n: ([b"\0" * 32] * n, [b"\1" * 32] * n, [b"\2" * 64] * n)
    hybrid.verify_signatures(*args(5))
    assert cpu.calls == [5] and tpu.calls == []
    assert hybrid.backend_label == "hybrid-cpu"
    hybrid.verify_signatures(*args(256))
    assert tpu.calls == [256]
    assert hybrid.backend_label == "hybrid-tpu"
    # EMAs update from routed dispatches (values sane, not outliers)
    assert 0 < hybrid.tpu_dispatch_s < 0.2
    assert hybrid.verify_signatures([], [], []) == []


def test_hybrid_verifier_fixed_threshold_and_default():
    from mysticeti_tpu.block_validator import HybridSignatureVerifier

    h = HybridSignatureVerifier(threshold=7)
    assert h.threshold() == 7
    h2 = HybridSignatureVerifier()
    assert h2.threshold() == h2.DEFAULT_THRESHOLD  # uncalibrated


def test_hybrid_verifier_end_to_end_cpu_backends(committee_and_signers):
    """Hybrid with two CPU oracles behind it is behaviorally identical to the
    plain CPU path: good blocks pass, forged blocks fail, either route."""
    committee, signers = committee_and_signers
    from mysticeti_tpu.block_validator import HybridSignatureVerifier

    async def main():
        for threshold in (0, 100):  # force tpu-route and cpu-route
            hybrid = HybridSignatureVerifier(
                tpu=CpuSignatureVerifier(),
                cpu=CpuSignatureVerifier(),
                threshold=threshold,
            )
            verifier = BatchedSignatureVerifier(
                committee, hybrid, max_batch=10, max_delay_s=0.01
            )
            good = StatementBlock.build(0, 1, [], (), signer=signers[0])
            forged = StatementBlock.build(1, 1, [], (), signer=signers[0])
            results = await asyncio.gather(
                verifier.verify(good),
                verifier.verify(forged),
                return_exceptions=True,
            )
            assert results[0] is None
            assert isinstance(results[1], VerificationError)

    asyncio.run(main())


def test_adaptive_batching_window_tracks_dispatch_latency():
    """A remote accelerator (~100ms/dispatch) must widen the collection
    window to a fraction of the observed dispatch latency, so back-to-back
    tiny dispatches don't queue; a fast verifier keeps the 5ms floor."""
    import asyncio
    import time as _time

    from mysticeti_tpu.block_validator import (
        BatchedSignatureVerifier,
        SignatureVerifier,
    )
    from mysticeti_tpu.committee import Committee

    class SlowVerifier(SignatureVerifier):
        def verify_signatures(self, pks, digests, sigs):
            _time.sleep(0.05)
            return [True] * len(sigs)

    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)
    from mysticeti_tpu.types import Share, StatementBlock

    genesis = [StatementBlock.new_genesis(i).reference for i in range(4)]
    blk = StatementBlock.build(0, 1, genesis, [Share(b"tx")], signer=signers[0])

    async def main():
        v = BatchedSignatureVerifier(committee, SlowVerifier(), max_delay_s=0.005)
        assert v._effective_delay_s() == 0.005  # floor before any dispatch
        await v.verify(blk)
        await v.flush_now()
        assert v._dispatch_ema_s >= 0.05
        assert v._effective_delay_s() == pytest.approx(0.2 * v._dispatch_ema_s)
        # the window is capped: a compile stall cannot push it past the max
        v._dispatch_ema_s = 30.0
        assert v._effective_delay_s() == v.MAX_ADAPTIVE_DELAY_S
        # and outlier dispatches never enter the EMA
        assert v.EMA_OUTLIER_S < 30.0

    asyncio.run(main())


def test_collection_window_adapts_both_directions():
    """Round-4 weak #5: the fixed 5 ms window added pure latency at light
    load when dispatches are sub-ms (hybrid CPU route).  The window is now
    20% of the dispatch EMA, clamped — wide for remote accelerators, sub-ms
    for cheap local dispatch, max_delay_s only before calibration."""
    from mysticeti_tpu.block_validator import BatchedSignatureVerifier
    from mysticeti_tpu.committee import Committee

    c = BatchedSignatureVerifier(Committee.new_for_benchmarks(4))
    assert c._effective_delay_s() == c.max_delay_s  # pre-calibration
    c._dispatch_ema_s = 0.0005  # light-load CPU route
    assert c._effective_delay_s() == c.MIN_ADAPTIVE_DELAY_S
    c._dispatch_ema_s = 0.030  # saturated CPU batch
    assert abs(c._effective_delay_s() - 0.006) < 1e-9
    c._dispatch_ema_s = 0.100  # tunneled accelerator
    assert abs(c._effective_delay_s() - 0.020) < 1e-9
    c._dispatch_ema_s = 10.0  # pathological: stays clamped
    assert c._effective_delay_s() == c.MAX_ADAPTIVE_DELAY_S


def test_collection_window_adapts_to_arrival_rate():
    """ISSUE 6 tentpole #3: the window only pays off when more arrivals are
    coming.  With ~2+ expected arrivals inside a full window the ceiling
    holds; below that the wait scales down linearly to the floor — a lone
    steady-state block stops paying the full batch window."""
    from mysticeti_tpu.block_validator import BatchedSignatureVerifier
    from mysticeti_tpu.committee import Committee

    c = BatchedSignatureVerifier(Committee.new_for_benchmarks(4))
    ceiling = c.max_delay_s  # pre-calibration ceiling (5 ms default)
    # Unseeded arrival EMA: full window (same-tick bursts keep this shape).
    assert c._effective_delay_s() == ceiling
    # Dense arrivals (gap << window): the full window still batches.
    c._arrival_gap_ema_s = 0.0005
    assert c._effective_delay_s() == ceiling
    # ~1 expected arrival per window: wait scales to half the ceiling.
    c._arrival_gap_ema_s = ceiling
    assert c._effective_delay_s() == pytest.approx(ceiling / 2)
    # Sparse arrivals (gap >> window): floor — no batch is coming.
    c._arrival_gap_ema_s = 0.5
    assert c._effective_delay_s() == c.MIN_ADAPTIVE_DELAY_S
    # The arrival scaling rides ON the dispatch-cost ceiling: a remote
    # accelerator's widened window still collapses when arrivals stop.
    c._dispatch_ema_s = 0.100  # tunneled chip -> 20 ms ceiling
    c._arrival_gap_ema_s = 0.002
    assert abs(c._effective_delay_s() - 0.020) < 1e-9
    c._arrival_gap_ema_s = 0.5
    assert c._effective_delay_s() == c.MIN_ADAPTIVE_DELAY_S


def test_collector_tracks_arrival_gaps_and_publishes_window(
    committee_and_signers,
):
    """verify() feeds the loop-clocked inter-arrival gap EMA (capped, so an
    idle stretch reads as low rate without poisoning the EMA) and each armed
    window is published on verify_collector_window_seconds."""
    from mysticeti_tpu.metrics import Metrics

    committee, signers = committee_and_signers
    metrics = Metrics()

    async def main():
        backend = CountingVerifier()
        v = BatchedSignatureVerifier(
            committee, backend, max_batch=100, max_delay_s=0.02,
            metrics=metrics,
        )
        blocks = [
            StatementBlock.build(a, 1, [], (), signer=signers[a])
            for a in range(4)
        ]
        first = asyncio.ensure_future(v.verify(blocks[0]))
        await asyncio.sleep(0.004)
        rest = [asyncio.ensure_future(v.verify(b)) for b in blocks[1:]]
        await asyncio.gather(first, *rest)
        await v.flush_now()
        # One real ~4 ms gap seeded the EMA; the same-tick trio pulled it
        # down (0.8 decay per zero sample).
        assert 0.0 < v._arrival_gap_ema_s <= 0.004 + 0.02
        assert metrics.verify_collector_window_seconds._value.get() > 0.0
        # The cap bounds what one idle stretch can inject.
        v._last_arrival_t = None
        v._arrival_gap_ema_s = 0.0
        loop = asyncio.get_running_loop()
        v._last_arrival_t = loop.time() - 500.0  # pretend: long idle
        await v.verify(blocks[0])
        assert v._arrival_gap_ema_s <= v.ARRIVAL_GAP_CAP_S
        await v.flush_now()

    asyncio.run(main())


def test_router_shortcircuit_counter(committee_and_signers):
    """Batches the cost-model router keeps on the oracle never touch the
    accelerator backend, and each one counts on
    verify_shortcircuit_total{reason="router"}."""
    from mysticeti_tpu.block_validator import (
        HybridSignatureVerifier,
        SignatureVerifier,
    )
    from mysticeti_tpu.crypto import blake2b_256
    from mysticeti_tpu.metrics import Metrics

    class NeverBackend(SignatureVerifier):
        def verify_signatures(self, *args):
            raise AssertionError("router-rejected batch reached the backend")

    _, signers = committee_and_signers
    metrics = Metrics()
    h = HybridSignatureVerifier(
        tpu=NeverBackend(), cpu=CountingVerifier(), metrics=metrics
    )
    digest = blake2b_256(b"router-test")
    sig = signers[0].sign(digest)
    pk = signers[0].public_key.bytes
    # Below DEFAULT_THRESHOLD: the router keeps it in-process.
    assert h.verify_signatures([pk] * 2, [digest] * 2, [sig] * 2) == [
        True, True,
    ]
    count = metrics.verify_shortcircuit_total.labels("router")._value.get()
    assert count == 1


def test_pin_probe_abandon_releases_exclusivity(committee_and_signers):
    """A flush cancelled between submit and fetch abandons its probe-
    carrying handle: the shared probe-exclusivity flag is released (no
    permanently blocked probes), the pin stands, and a completed probe
    whose re-HELLO reports an UNKNOWN backend (pre-r6 service) unpins —
    unknown must never stay pinned."""
    from mysticeti_tpu.block_validator import (
        HybridSignatureVerifier,
        SignatureVerifier,
        _PinProbeDispatch,
    )
    from mysticeti_tpu.crypto import blake2b_256

    class StubRemote(SignatureVerifier):
        advertised_backend = "cpu"
        rehello_result = ("cpu", None)

        def rehello(self):
            return self.rehello_result

        def verify_signatures(self, *args):
            raise AssertionError("pinned batch reached the remote backend")

    _, signers = committee_and_signers
    digest = blake2b_256(b"pin-abandon")
    pks = [signers[0].public_key.bytes] * 2
    digests, sigs = [digest] * 2, [signers[0].sign(digest)] * 2
    remote = StubRemote()
    clock = {"t": 0.0}
    h = HybridSignatureVerifier(tpu=remote, cpu=CountingVerifier())
    h._breaker_clock = lambda: clock["t"]
    h._sync_pin_with_advertisement()
    assert h.pinned_backend == "cpu"
    clock["t"] = 100.0  # past the probe deadline
    handle = h.verify_signatures_async(pks, digests, sigs)
    assert isinstance(handle, _PinProbeDispatch)
    assert h._breaker_probing  # the handle owns the exclusive slot
    handle.abandon()
    assert not h._breaker_probing, "abandon leaked the probe flag"
    assert h.pinned_backend == "cpu"  # an abandoned probe is no evidence
    # The next window's probe still runs — and an unknown-backend answer
    # (old server replaced the advertiser) unpins.
    remote.rehello_result = (None, None)
    clock["t"] = 10_000.0
    handle = h.verify_signatures_async(pks, digests, sigs)
    assert isinstance(handle, _PinProbeDispatch)
    assert handle.result() == [True, True]
    assert h.pinned_backend is None
    assert not h._breaker_probing


def test_hybrid_never_offloads_to_a_degraded_backend():
    """Round-5 NODE_BENCH finding: a host whose JAX backend degraded to CPU
    measures seconds per dispatch — the budget-relief offload must refuse it
    (light-load latency collapsed ~25x when it didn't)."""
    from mysticeti_tpu.block_validator import HybridSignatureVerifier

    h = HybridSignatureVerifier()
    h.cpu_per_sig_s = 125e-6
    h.tpu_dispatch_s = 1.5  # degraded: pad-to-bucket on jax-CPU
    # 256 sigs: 32 ms of CPU is over budget, but 1.5 s of "accelerator"
    # would stall consensus -> stay on the oracle.
    assert not h._route_to_tpu(256)
    assert not h._route_to_tpu(4096)
    # A real accelerator (tunneled ~150 ms fixed) takes the same batch.
    h.tpu_dispatch_s = 0.150
    assert h._route_to_tpu(256)
    # ...unless its LEARNED marginal cost makes the turnaround stall-grade.
    h.tpu_per_sig_s = 0.005
    assert not h._route_to_tpu(256)
    # Light load always stays local either way.
    h.tpu_per_sig_s = 0.0
    assert not h._route_to_tpu(3)


def test_hybrid_ema_splits_residual_between_fixed_and_marginal():
    """ADVICE r5: one slow dispatch used to feed its FULL residual to both
    cost parameters in the same update (each against the other's pre-update
    value), inflating the summed model by ~double the residual.  With the
    50/50 split the summed model moves by exactly one EMA step of the
    residual — a transient can no longer wrongly veto the saturation
    offload."""
    from mysticeti_tpu.block_validator import (
        HybridSignatureVerifier,
        SignatureVerifier,
    )

    class Stub(SignatureVerifier):
        def verify_signatures(self, pks, digests, sigs):
            return [True] * len(sigs)

    h = HybridSignatureVerifier(tpu=Stub(), cpu=Stub())
    h.tpu_dispatch_s = 0.1
    h.tpu_per_sig_s = 0.0005
    n = 100
    before = h._tpu_time(n)
    residual = 0.2
    h._absorb_tpu_sample(before + residual, n)
    after = h._tpu_time(n)
    assert after > before  # the model does track the slow sample...
    # ...but by ONE EMA step (alpha=0.2) of the residual, not two.
    assert after - before == pytest.approx(0.2 * residual, rel=1e-6)
    # Symmetric on the way down, and outliers never enter.
    h._absorb_tpu_sample(h._tpu_time(n) - 0.1, n)
    assert h._tpu_time(n) < after
    frozen = (h.tpu_dispatch_s, h.tpu_per_sig_s)
    h._absorb_tpu_sample(h.EMA_OUTLIER_S + 1.0, n)
    assert (h.tpu_dispatch_s, h.tpu_per_sig_s) == frozen
