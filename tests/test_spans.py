"""Block-lifecycle span tracing: tracer unit semantics, the 10-node
deterministic-sim acceptance path (MYSTICETI_TRACE -> valid, reproducible
Chrome trace-event JSON + trace_report breakdown), and the verifier-path
telemetry scrape via the /metrics endpoint."""
import asyncio
import json
import os
import sys

from mysticeti_tpu import spans
from mysticeti_tpu.block_handler import TestBlockHandler
from mysticeti_tpu.block_store import BlockStore
from mysticeti_tpu.commit_observer import TestCommitObserver
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.config import Parameters
from mysticeti_tpu.core import Core, CoreOptions
from mysticeti_tpu.net_sync import NetworkSyncer
from mysticeti_tpu.runtime.simulated import run_simulation
from mysticeti_tpu.simulated_network import SimulatedNetwork
from mysticeti_tpu.spans import PIPELINE_STAGES, SpanTracer, format_ref
from mysticeti_tpu.types import BlockReference
from mysticeti_tpu.wal import walf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _ref(authority=3, round_=7, tag=b"\x01"):
    return BlockReference(authority, round_, tag.ljust(32, b"\x00"))


# -- tracer unit semantics ----------------------------------------------------

def test_begin_end_records_completed_span():
    tracer = SpanTracer()
    ref = _ref()
    tracer.begin_span("dag_add", ref, authority=0, t=1.0)
    # A duplicate begin must NOT shrink the measured wait.
    tracer.begin_span("dag_add", ref, authority=0, t=2.0)
    tracer.end_span("dag_add", ref, authority=0, t=3.5)
    # Unmatched end: silently ignored.
    tracer.end_span("dag_add", _ref(tag=b"\x02"), authority=0, t=4.0)
    events = tracer.chrome_trace()["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == 1
    assert xs[0]["name"] == "dag_add"
    assert xs[0]["ts"] == 1_000_000 and xs[0]["dur"] == 2_500_000
    assert xs[0]["args"]["block"] == format_ref(ref)


def test_tracks_are_split_by_authority_and_named():
    tracer = SpanTracer()
    ref = _ref()
    tracer.record_span("receive", ref, 0.0, t1=0.5, authority=2)
    tracer.record_span("receive", ref, 0.0, t1=0.5, authority=5)
    trace = tracer.chrome_trace()
    names = {
        e["tid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert names[2] == "A2" and names[5] == "A5"
    assert {e["tid"] for e in trace["traceEvents"] if e["ph"] == "X"} == {2, 5}


def test_write_is_atomic_and_valid_json(tmp_path):
    tracer = SpanTracer()
    tracer.record_span("commit", _ref(), 1.0, t1=2.0, authority=1)
    path = str(tmp_path / "trace.json")
    tracer.write(path)
    assert not os.path.exists(path + ".tmp")
    data = json.loads(open(path).read())
    assert any(e["ph"] == "X" for e in data["traceEvents"])


def test_event_cap_drops_instead_of_growing():
    tracer = SpanTracer()
    tracer.MAX_EVENTS = 3
    for i in range(5):
        tracer.record_span("commit", _ref(tag=bytes([i + 1])), 0.0, t1=1.0,
                           authority=0)
    assert len([e for e in tracer.chrome_trace()["traceEvents"]
                if e["ph"] == "X"]) == 3
    assert tracer.dropped == 2


# -- the 10-node deterministic-sim acceptance path ---------------------------

class _SimNodeNetwork:
    def __init__(self, queue):
        self.connections = queue

    async def stop(self):
        pass


def _build_node(committee, signers, authority, tmp_dir, sim_net, parameters):
    wal_writer, wal_reader = walf(os.path.join(tmp_dir, f"wal-{authority}"))
    recovered, observer_recovered = BlockStore.open(
        authority, wal_reader, wal_writer, committee
    )
    handler = TestBlockHandler(
        last_transaction=authority * 1_000_000,
        committee=committee,
        authority=authority,
    )
    core = Core(
        block_handler=handler,
        authority=authority,
        committee=committee,
        parameters=parameters,
        recovered=recovered,
        wal_writer=wal_writer,
        options=CoreOptions.test(),
        signer=signers[authority],
    )
    observer = TestCommitObserver(
        core.block_store, committee, recovered_state=observer_recovered
    )
    return NetworkSyncer(
        core,
        observer,
        _SimNodeNetwork(sim_net.node_connections[authority]),
        parameters=parameters,
    )


async def _run_nodes(n, tmp_dir, virtual_seconds):
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = Parameters(leader_timeout_s=1.0)
    sim_net = SimulatedNetwork(n)
    nodes = [
        _build_node(committee, signers, a, tmp_dir, sim_net, parameters)
        for a in range(n)
    ]
    for node in nodes:
        await node.start()
    await sim_net.connect_all()
    await asyncio.sleep(virtual_seconds)
    for node in nodes:
        await node.stop()
    sim_net.close()
    return nodes


def _traced_sim_run(tmp_dir, seed):
    """One traced 10-node sim: returns (trace bytes, committed leader refs)."""
    tracer = spans.start_from_env()
    assert tracer is not None
    try:
        nodes = run_simulation(_run_nodes(10, tmp_dir, 8.0), seed=seed)
    finally:
        spans.stop_from_env()
    committed = [
        list(node.syncer.commit_observer.committed_leaders) for node in nodes
    ]
    with open(os.environ["MYSTICETI_TRACE"].replace("%p", str(os.getpid())),
              "rb") as f:
        return f.read(), committed


def test_ten_node_sim_trace_report_and_verifier_metrics(tmp_path, monkeypatch, capsys):
    """The acceptance path in one test: a 10-node deterministic sim under
    MYSTICETI_TRACE yields a valid Chrome trace (all pipeline stages present
    for a committed block, per-track monotone virtual timestamps,
    byte-identical across two same-seed runs), trace_report prints the
    per-stage breakdown from it, and the verifier batch-size/padding/route
    series are scrapeable over the /metrics HTTP endpoint."""
    trace_path = tmp_path / "trace-%p.json"
    monkeypatch.setenv("MYSTICETI_TRACE", str(trace_path))

    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    raw_a, committed = _traced_sim_run(str(tmp_path / "a"), seed=17)
    raw_b, _ = _traced_sim_run(str(tmp_path / "b"), seed=17)

    # Determinism: virtual-clocked spans of a seeded sim are byte-identical.
    assert raw_a == raw_b

    data = json.loads(raw_a)
    events = data["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert xs, "sim produced no spans"

    # Every pipeline stage is present for one mid-sequence committed leader.
    sequences = [seq for seq in committed if seq]
    assert sequences and all(len(s) >= 20 for s in sequences), [
        len(s) for s in committed
    ]
    leader = sequences[0][len(sequences[0]) // 2]
    label = format_ref(leader)
    stages_for_leader = {e["name"] for e in xs if e["args"]["block"] == label}
    assert set(PIPELINE_STAGES) <= stages_for_leader, (
        label, sorted(stages_for_leader)
    )

    # Virtual timestamps: non-negative, monotone per authority track.
    last_ts = {}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert e["ts"] >= last_ts.get(e["tid"], 0), e
        last_ts[e["tid"]] = e["ts"]
    # One named track per simulated authority.
    track_names = {
        e["args"]["name"] for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert {f"A{i}" for i in range(10)} <= track_names

    # trace_report prints a per-stage latency breakdown from the file.
    from tools.trace_report import main as report_main

    path = str(trace_path).replace("%p", str(os.getpid()))
    assert report_main([path]) == 0
    out = capsys.readouterr().out
    for stage in PIPELINE_STAGES:
        assert stage in out, out
    assert "p50_ms" in out and "p99_ms" in out

    # Verifier-path telemetry reaches the /metrics endpoint (real asyncio:
    # the collector + hybrid router + HTTP server need threads/sockets the
    # simulator forbids).
    scrape = asyncio.run(_verifier_metrics_scrape())
    assert "verify_dispatch_batch_size" in scrape
    assert 'verify_padding_wasted_total{backend="hybrid-tpu"}' in scrape
    assert 'verify_route_total{route="tpu"}' in scrape
    assert 'verify_route_total{route="cpu"}' in scrape
    assert "verify_batch_size" in scrape


async def _verifier_metrics_scrape() -> str:
    from mysticeti_tpu import crypto
    from mysticeti_tpu.block_validator import (
        BatchedSignatureVerifier,
        CpuSignatureVerifier,
        HybridSignatureVerifier,
    )
    from mysticeti_tpu.metrics import Metrics, serve_metrics
    from mysticeti_tpu.types import Share, StatementBlock

    metrics = Metrics()
    committee = Committee.new_for_benchmarks(4)
    signers = Committee.benchmark_signers(4)

    class FakeTpu(CpuSignatureVerifier):
        """CPU oracle pretending to be a bucket-padded accelerator."""

        def padded_batch(self, n):
            return 256 if n <= 256 else n

    # Route 1 (tpu): threshold=1 sends the block batch to the "accelerator".
    hybrid = HybridSignatureVerifier(
        tpu=FakeTpu(), cpu=CpuSignatureVerifier(), threshold=1,
        metrics=metrics,
    )
    collector = BatchedSignatureVerifier(committee, hybrid, metrics=metrics)
    genesis = [StatementBlock.new_genesis(i) for i in range(4)]
    prev = [g.reference for g in genesis]
    blocks = [
        StatementBlock.build(a, 1, prev, [Share(bytes([a]))], signer=signers[a])
        for a in range(1, 4)
    ]
    oks = await collector.verify_blocks(blocks)
    assert all(oks)
    # Route 2 (cpu): a sky-high threshold keeps the batch on the oracle.
    hybrid_cpu = HybridSignatureVerifier(
        tpu=FakeTpu(), cpu=CpuSignatureVerifier(), threshold=1 << 30,
        metrics=metrics,
    )
    signer = crypto.Signer.from_seed(bytes(32))
    digest = crypto.blake2b_256(b"route-probe")
    hybrid_cpu.verify_signatures(
        [signer.public_key.bytes], [digest], [signer.sign(digest)]
    )

    server = await serve_metrics(metrics, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(b"GET /metrics HTTP/1.1\r\nHost: t\r\n\r\n")
    await writer.drain()
    payload = await reader.read()
    writer.close()
    server.close()
    await server.wait_closed()
    return payload.decode()
