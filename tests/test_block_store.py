"""Block store tests — index/recovery/ancestry parity with block_store.rs:575-647."""
import pytest

from mysticeti_tpu.block_store import (
    BlockStore,
    BlockWriter,
    CommitData,
    OwnBlockData,
    WAL_ENTRY_COMMIT,
    WAL_ENTRY_PAYLOAD,
    WAL_ENTRY_STATE,
)
from mysticeti_tpu.committee import Committee
from mysticeti_tpu.serde import Writer
from mysticeti_tpu.state import Include, Payload, encode_payload
from mysticeti_tpu.types import Share, StatementBlock
from mysticeti_tpu.utils.dag import Dag
from mysticeti_tpu.wal import POSITION_MAX, walf


@pytest.fixture
def committee():
    return Committee.new_test([1, 1, 1, 1])


def open_store(tmp_path, committee, authority=0, name="wal"):
    w, r = walf(str(tmp_path / name))
    core, observer = BlockStore.open(authority, r, w, committee)
    return w, r, core, observer


def test_insert_and_queries(tmp_path, committee):
    w, _r, core, _obs = open_store(tmp_path, committee)
    store = core.block_store
    writer = BlockWriter(w, store)
    dag = Dag.draw("A1:[A0,B0,C0]; B1:[A0,B0,C0]; A2:[A1,B1]")
    for blk in dag.all_blocks():
        writer.insert_block(blk)

    a1 = dag["A1"]
    assert store.block_exists(a1.reference)
    assert store.get_block(a1.reference) == a1
    assert store.highest_round() == 2
    assert {b.author() for b in store.get_blocks_by_round(1)} == {0, 1}
    assert store.get_blocks_at_authority_round(0, 1) == [a1]
    assert store.block_exists_at_authority_round(1, 1)
    assert not store.block_exists_at_authority_round(2, 1)
    assert store.all_blocks_exists_at_authority_round([0, 1], 1)
    assert not store.all_blocks_exists_at_authority_round([0, 1, 2], 1)
    assert store.last_seen_by_authority(0) == 2
    assert store.last_seen_by_authority(1) == 1
    assert store.last_own_block_ref() == dag["A2"].reference


def test_ancestry(tmp_path, committee):
    w, _r, core, _obs = open_store(tmp_path, committee)
    store = core.block_store
    writer = BlockWriter(w, store)
    dag = Dag.draw(
        "A1:[A0,B0,C0]; B1:[A0,B0,C0]; C1:[A0,B0,C0];"
        "A2:[A1,B1]; B2:[B1,C1]"
    )
    for blk in dag.all_blocks():
        writer.insert_block(blk)

    assert store.linked(dag["A2"], dag["A1"])
    assert store.linked(dag["A2"], dag["B1"])
    assert not store.linked(dag["A2"], dag["C1"])
    assert store.linked(dag["B2"], dag["C1"])
    round1 = store.linked_to_round(dag["A2"], 1)
    assert {b.reference for b in round1} == {dag["A1"].reference, dag["B1"].reference}
    genesis = store.linked_to_round(dag["A2"], 0)
    assert len(genesis) == 3


def test_dissemination_cursors(tmp_path, committee):
    w, _r, core, _obs = open_store(tmp_path, committee)
    store = core.block_store
    writer = BlockWriter(w, store)
    dag = Dag.draw(
        "A1:[A0,B0,C0]; B1:[A0,B0,C0]; A2:[A1,B1]; B2:[A1,B1]; A3:[A2,B2]"
    )
    for blk in dag.all_blocks():
        writer.insert_block(blk)

    own = store.get_own_blocks(0, 10)
    assert [b.round() for b in own] == [1, 2, 3]
    assert store.get_own_blocks(2, 10) == [dag["A3"]]
    assert store.get_own_blocks(0, 2) == [dag["A1"], dag["A2"]]
    others = store.get_others_blocks(0, 1, 10)
    assert [b.round() for b in others] == [1, 2]


def test_cache_unload_and_reload(tmp_path, committee):
    w, _r, core, _obs = open_store(tmp_path, committee)
    store = core.block_store
    writer = BlockWriter(w, store)
    dag = Dag.draw("A1:[A0,B0,C0]; B1:[A0,B0,C0]; A2:[A1,B1]")
    for blk in dag.all_blocks():
        writer.insert_block(blk)

    unloaded = store.cleanup(1)
    assert unloaded > 0
    # Unloaded entries reload transparently from the WAL mmap.
    assert store.get_block(dag["A1"].reference) == dag["A1"]
    assert store.get_blocks_by_round(1)[0].to_bytes() == dag["A1"].to_bytes()


def test_recovery_replay(tmp_path, committee):
    path_args = (tmp_path, committee)
    w, r, core, _obs = open_store(*path_args)
    store = core.block_store
    writer = BlockWriter(w, store)
    dag = Dag.draw("A1:[A0,B0,C0]; B1:[A0,B0,C0]; C1:[A0,B0,C0]; A2:[A1,B1,C1]")
    genesis = [b for b in dag.all_blocks() if b.round() == 0]
    for blk in dag.all_blocks():
        if blk.author_round() == (0, 2):
            continue
        writer.insert_block(blk)
    # Own proposal A2 consumes all pending entries before it.
    payload_pos = w.write(WAL_ENTRY_PAYLOAD, encode_payload((Share(b"tx1"),)))
    own = OwnBlockData(next_entry=payload_pos, block=dag["A2"])
    writer.insert_own_block(own)
    state_pos = w.write(WAL_ENTRY_STATE, b"handler-state")
    # Commit entry: one commit data + aggregator state.
    cd = CommitData(dag["A1"].reference, [dag["A1"].reference], height=1)
    cw = Writer()
    cw.u32(1)
    cd.encode(cw)
    cw.bytes(b"agg-state")
    w.write(WAL_ENTRY_COMMIT, cw.finish())
    w.sync()
    w.close()
    r.close()

    w2, r2 = walf(str(tmp_path / "wal"))
    core2, obs2 = BlockStore.open(0, r2, w2, committee)
    store2 = core2.block_store
    assert store2.len_expensive() == len(dag)
    assert store2.get_block(dag["A2"].reference) == dag["A2"]
    assert store2.highest_round() == 2
    assert store2.last_own_block_ref() == dag["A2"].reference
    # Pending: everything before the own block was consumed; payload entry remains.
    kinds = [type(st).__name__ for _, st in core2.pending]
    assert kinds == ["Payload"]
    assert core2.last_own_block is not None
    assert core2.last_own_block.block == dag["A2"]
    assert core2.last_own_block.next_entry == payload_pos
    # State snapshot cleared unprocessed blocks.
    assert core2.state == b"handler-state"
    assert core2.unprocessed_blocks == []
    assert core2.last_committed_leader == dag["A1"].reference
    assert len(obs2.sub_dags) == 1
    assert obs2.sub_dags[0].height == 1
    assert obs2.state == b"agg-state"


def test_recovery_without_state_snapshot_replays_blocks(tmp_path, committee):
    w, r, core, _obs = open_store(tmp_path, committee)
    writer = BlockWriter(w, core.block_store)
    dag = Dag.draw("A1:[A0,B0,C0]; B1:[A0,B0,C0]")
    for blk in dag.all_blocks():
        writer.insert_block(blk)
    w.sync()
    w.close()
    r.close()

    w2, r2 = walf(str(tmp_path / "wal"))
    core2, _ = BlockStore.open(0, r2, w2, committee)
    # No state snapshot: all blocks must be re-run through the handler.
    assert len(core2.unprocessed_blocks) == len(dag)
    assert len(core2.pending) == len(dag)
    assert all(isinstance(st, Include) for _, st in core2.pending)
