"""Tests for committee thresholds, election, aggregation — mirrors
committee.rs:530-553 plus wider coverage of the fast-path certification engine."""
import pytest

from mysticeti_tpu.committee import (
    Committee,
    QUORUM,
    StakeAggregator,
    TransactionAggregator,
    VALIDITY,
    VoteRangeBuilder,
    shared_ranges,
)
from mysticeti_tpu.types import (
    Share,
    StatementBlock,
    TransactionLocator,
    TransactionLocatorRange,
    Vote,
    VoteRange,
)


class TestThresholds:
    def test_quorum_validity(self):
        c = Committee.new_test([1, 1, 1, 1])
        assert c.total_stake == 4
        assert c.quorum_threshold() == 3  # > 2/3 of 4
        assert c.validity_threshold() == 2  # > 1/3 of 4
        assert not c.is_quorum(2)
        assert c.is_quorum(3)
        assert not c.is_valid(1)
        assert c.is_valid(2)

    def test_uneven_stake(self):
        c = Committee.new_test([100, 200, 300, 400])
        assert c.total_stake == 1000
        assert c.is_quorum(667)
        assert not c.is_quorum(666)
        assert c.is_valid(334)
        assert not c.is_valid(333)

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            Committee.new_test([])

    def test_zero_stake_rejected(self):
        with pytest.raises(ValueError):
            Committee.new_test([1, 0, 1])


class TestLeaderElection:
    def test_round_robin(self):
        c = Committee.new_test([1, 1, 1, 1])
        assert [c.elect_leader(r) for r in range(5)] == [0, 1, 2, 3, 0]
        assert c.elect_leader(1, offset=2) == 3

    def test_stake_based_distinct_per_offset(self):
        """committee.rs:530-546 stake_aware_leader_election."""
        c = Committee.new_test([100, 200, 300, 400, 500])
        leaders = {c.elect_leader_stake_based(10, off) for off in range(5)}
        assert len(leaders) == 5  # all distinct

    def test_stake_based_deterministic(self):
        c = Committee.new_test([100, 200, 300, 400, 500])
        for r in range(1, 20):
            assert c.elect_leader_stake_based(r, 0) == c.elect_leader_stake_based(r, 0)

    def test_stake_based_weighting(self):
        """An authority with overwhelming stake should win most rounds."""
        c = Committee.new_test([1, 1, 1, 10000])
        wins = sum(1 for r in range(1, 101) if c.elect_leader_stake_based(r, 0) == 3)
        assert wins > 90

    def test_genesis_round_leader_zero(self):
        c = Committee.new_test([5, 1, 1, 1])
        assert c.elect_leader_stake_based(0, 0) == 0


class TestStakeAggregator:
    def test_quorum(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(QUORUM)
        assert not agg.add(0, c)
        assert not agg.add(0, c)  # duplicate vote doesn't double-count
        assert not agg.add(1, c)
        assert agg.add(2, c)

    def test_validity(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(VALIDITY)
        assert not agg.add(0, c)
        assert agg.add(1, c)

    def test_encode_decode(self):
        from mysticeti_tpu.serde import Reader, Writer

        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(QUORUM)
        agg.add(1, c)
        agg.add(3, c)
        w = Writer()
        agg.encode(w)
        back = StakeAggregator.decode(Reader(w.finish()))
        assert back.kind == QUORUM
        assert back.stake == agg.stake
        assert sorted(back.voters()) == [1, 3]


def _block_with_shares(authority, n_tx, signers=None):
    genesis = [StatementBlock.new_genesis(i) for i in range(4)]
    return StatementBlock.build(
        authority, 1, [g.reference for g in genesis],
        [Share(bytes([i])) for i in range(n_tx)],
    )


class TestTransactionAggregator:
    def test_fast_path_certification(self):
        """Author's share is an implicit vote; 2 more votes certify (4-committee)."""
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 5)
        processed = agg.process_block(block, None, c)
        assert processed == []  # shares only register
        assert len(agg) == 1

        rng = TransactionLocatorRange(block.reference, 0, 5)
        out = []
        agg.vote(rng, 1, c, out)
        assert out == []
        agg.vote(rng, 2, c, out)  # third distinct authority → quorum
        assert len(out) == 5
        assert agg.is_empty()
        assert agg.is_processed(TransactionLocator(block.reference, 3))

    def test_author_self_vote_not_double_counted(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 1)
        agg.process_block(block, None, c)
        out = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 1), 0, c, out)
        assert out == []  # author voting again adds no stake

    def test_partial_range_votes(self):
        """Votes over sub-ranges split the aggregation correctly (RangeMap)."""
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 10)
        agg.process_block(block, None, c)
        out = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 6), 1, c, out)
        agg.vote(TransactionLocatorRange(block.reference, 3, 10), 2, c, out)
        # only [3,6) has author + 1 + 2 = quorum
        assert sorted(k.offset for k in out) == [3, 4, 5]
        assert not agg.is_empty()
        out2 = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 3), 2, c, out2)
        assert sorted(k.offset for k in out2) == [0, 1, 2]

    def test_vote_for_unknown_transaction_raises(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        ref = StatementBlock.new_genesis(0).reference
        with pytest.raises(RuntimeError, match="unknown"):
            agg.vote(TransactionLocatorRange(ref, 0, 1), 1, c, [])

    def test_process_block_emits_vote_ranges(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 3)
        response = []
        agg.process_block(block, response, c)
        assert len(response) == 1
        assert isinstance(response[0], VoteRange)
        assert response[0].range == TransactionLocatorRange(block.reference, 0, 3)

    def test_process_block_tallies_vote_statements(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        share_block = _block_with_shares(0, 2)
        agg.process_block(share_block, None, c)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        vb1 = StatementBlock.build(
            1, 1, [g.reference for g in genesis],
            [VoteRange(TransactionLocatorRange(share_block.reference, 0, 2))],
        )
        vb2 = StatementBlock.build(
            2, 1, [g.reference for g in genesis],
            [Vote(TransactionLocator(share_block.reference, 0)),
             Vote(TransactionLocator(share_block.reference, 1))],
        )
        assert agg.process_block(vb1, None, c) == []
        processed = agg.process_block(vb2, None, c)
        assert sorted(k.offset for k in processed) == [0, 1]

    def test_state_roundtrip(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 8)
        agg.process_block(block, None, c)
        agg.vote(TransactionLocatorRange(block.reference, 0, 4), 1, c, [])
        snapshot = agg.state()

        restored = TransactionAggregator(QUORUM)
        restored.with_state(snapshot)
        restored.processed = set(agg.processed)
        # one more vote certifies [0,4) in the restored copy too
        out = []
        restored.vote(TransactionLocatorRange(block.reference, 0, 4), 2, c, out)
        assert sorted(k.offset for k in out) == [0, 1, 2, 3]


class TestSharedRanges:
    def test_contiguous_runs(self):
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        ref = genesis[0].reference
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis],
            [Share(b"a"), Share(b"b"),
             Vote(TransactionLocator(ref, 0)),
             Share(b"c")],
        )
        ranges = shared_ranges(block)
        assert [(r.offset_start_inclusive, r.offset_end_exclusive) for r in ranges] == [
            (0, 2), (3, 4),
        ]


class TestVoteRangeBuilder:
    def test_reference_sequence(self):
        """committee.rs:530-541 vote_range_builder_test."""
        b = VoteRangeBuilder()
        assert b.add(1) is None
        assert b.add(2) is None
        assert b.add(4) == (1, 3)
        assert b.add(6) == (4, 5)
        assert b.finish() == (6, 7)

    def test_empty(self):
        assert VoteRangeBuilder().finish() is None
